#!/bin/bash
# One-shot TPU evidence capture for when the relay comes alive.
# The relay dies unpredictably (TPU_EVIDENCE_r04.md), so this runs the
# cheapest/highest-value probes first and commits nothing itself — run
# it, then check in whatever it produced.
#
#   bash capture_tpu_window.sh [outdir]
#
# Produces in outdir (default .):
#   BENCH_r04_tpu_live.json      bench.py JSON (mode table, chain est, e2e)
#   PALLAS_VALIDATION.json       Pallas-HLL vs jnp estimator on real TPU
#                                (written by pallas_validate.py into the
#                                repo dir, then copied to outdir)
#   tpu_window_*.log             output for each step
set -u
cd "$(dirname "$0")"
OUT="${1:-.}"
TS=$(date -u +%Y%m%dT%H%M%SZ)

alive=$(timeout 90 python -c "
from veneur_tpu.utils.platform import tunnel_alive
print('yes' if tunnel_alive() else 'no')" 2>/dev/null | tail -1)
if [ "$alive" != "yes" ]; then
    echo "relay dead; nothing captured"
    exit 1
fi
echo "relay alive at $TS — capturing"

# 1. Pallas validation first: cheapest, never captured on real TPU yet.
#    Writes PALLAS_VALIDATION.json itself on success.
timeout 420 python native/pallas_validate.py \
    > "$OUT/tpu_window_pallas_$TS.log" 2>&1
rc=$?
[ -f PALLAS_VALIDATION.json ] && [ "$OUT" != "." ] \
    && cp PALLAS_VALIDATION.json "$OUT/"
echo "pallas_validate rc=$rc (artifact: PALLAS_VALIDATION.json)"

# 2. The north-star bench: exec/fetch split, fetch-mode probe, chain
#    estimator, e2e under the best mode.
BENCH_BUDGET_S=500 timeout 560 python bench.py \
    > "$OUT/BENCH_r04_tpu_live.json.tmp" 2> "$OUT/tpu_window_bench_$TS.log"
rc=$?
if [ $rc -eq 0 ] && grep -q '"platform": "tpu"' "$OUT/BENCH_r04_tpu_live.json.tmp"; then
    mv "$OUT/BENCH_r04_tpu_live.json.tmp" "$OUT/BENCH_r04_tpu_live.json"
    echo "bench captured: $(cat "$OUT/BENCH_r04_tpu_live.json")"
else
    echo "bench rc=$rc or not platform=tpu; keeping .tmp for forensics"
fi
