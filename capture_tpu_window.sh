#!/bin/bash
# One-shot TPU evidence capture for when the relay comes alive (round 5).
# The relay dies unpredictably (TPU_EVIDENCE_r04.md), so this runs the
# cheapest/highest-value probes first and commits nothing itself — run
# it, then check in whatever it produced.
#
#   bash capture_tpu_window.sh [outdir]
#
# Produces in outdir (default .):
#   PALLAS_VALIDATION.json       Pallas-HLL vs jnp estimator on real TPU
#   BENCH_r06_tpu_live.json      bench.py JSON (mode table, chain est,
#                                e2e under the winning fetch mode, and
#                                the compress merge-path vs full-sort
#                                A/B pair on real TPU — the capture that
#                                retires VENEUR_TPU_TDIGEST_FULL_SORT)
#   BENCH_c8_tpu.json            bench_suite c8 ingest stages with the
#                                REAL TPU dispatch path (s4/s5 pump
#                                rates — never captured on TPU; VERDICT
#                                r4 item 2a)
#   tpu_window_*.log             output for each step
set -u
OUT="${1:-.}"
# resolve OUT before cd so a relative outdir means "relative to the
# caller", then make sure it exists — a failed redirect would silently
# waste the relay window
OUT="$(mkdir -p "$OUT" && cd "$OUT" && pwd)" || exit 1
cd "$(dirname "$0")"
TS=$(date -u +%Y%m%dT%H%M%SZ)

alive=$(timeout 150 python -c "
from veneur_tpu.utils.platform import tunnel_healthy
print('yes' if tunnel_healthy(timeout_s=120) else 'no')" 2>/dev/null | tail -1)
if [ "$alive" != "yes" ]; then
    echo "relay dead or unhealthy; nothing captured"
    exit 1
fi
echo "relay healthy at $TS — capturing"

# 1. Pallas validation first: cheapest, never captured on real TPU yet
#    (VERDICT r4 item 5). Writes PALLAS_VALIDATION.json itself.
timeout 360 python native/pallas_validate.py \
    > "$OUT/tpu_window_pallas_$TS.log" 2>&1
rc=$?
if [ $rc -eq 0 ] && [ -f PALLAS_VALIDATION.json ]; then
    [ "$OUT" != "$(pwd)" ] && cp PALLAS_VALIDATION.json "$OUT/"
    echo "pallas_validate OK (artifact: PALLAS_VALIDATION.json)"
else
    echo "pallas_validate rc=$rc — no fresh artifact (a pre-existing"\
         "PALLAS_VALIDATION.json, if any, is from an EARLIER window)"
fi

# 2. The north-star bench (VERDICT r4 item 1): exec/fetch split,
#    fetch-mode probe (sync/staged/host/async + compact outputs), chain
#    estimator, e2e under the best mode. Headline is machine-honest:
#    value carries the defensible number even when the relay poisons the
#    raw e2e (bench.py headline logic).
BENCH_BUDGET_S=500 timeout 560 python bench.py \
    > "$OUT/BENCH_r06_tpu_live.json.tmp" 2> "$OUT/tpu_window_bench_$TS.log"
rc=$?
if [ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)"' "$OUT/BENCH_r06_tpu_live.json.tmp"; then
    mv "$OUT/BENCH_r06_tpu_live.json.tmp" "$OUT/BENCH_r06_tpu_live.json"
    echo "bench captured: $(cat "$OUT/BENCH_r06_tpu_live.json")"
else
    echo "bench rc=$rc or not platform=tpu; keeping .tmp for forensics"
fi

# 3. TPU pump rates (VERDICT r4 item 2a): bench_suite c8 with the real
#    TPU dispatch path. The CPU-platform s4/s5 numbers are
#    XLA-dispatch-bound and unrepresentative; this is the measurement
#    the 10M/s scaling model has been missing.
timeout 540 python bench_suite.py --config 8 \
    --json-out "$OUT/BENCH_c8_tpu.json.tmp" \
    > "$OUT/tpu_window_c8_$TS.log" 2>&1
rc=$?
if [ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)"' "$OUT/BENCH_c8_tpu.json.tmp"; then
    mv "$OUT/BENCH_c8_tpu.json.tmp" "$OUT/BENCH_c8_tpu.json"
    echo "c8 TPU stages captured (artifact: BENCH_c8_tpu.json)"
else
    echo "c8 rc=$rc or not platform=tpu; keeping .tmp for forensics"
fi
# 4. Flight-recorder phase attribution + profiler capture on real TPU
#    (ISSUE 6): run bench_suite c13 on the TPU path (phase coverage,
#    /debug/flush same-tick check, recorder overhead), the live
#    analogue of the CPU rows in BENCH_SUITE_r07.json. Against a RUNNING
#    server started with `debug_flush_profile: true`, the on-demand
#    xprof window is one curl away:
#        curl "http://$HTTP_ADDR/debug/flush/profile?ticks=3"
#        curl "http://$HTTP_ADDR/debug/flush" | python -m json.tool
#    (the first schedules a jax.profiler capture around the next 3
#    flush ticks into debug_flush_profile_dir; the second returns the
#    phase timelines for exactly those ticks.)
timeout 540 python bench_suite.py --config 13 \
    --json-out "$OUT/BENCH_c13_tpu.json.tmp" \
    > "$OUT/tpu_window_c13_$TS.log" 2>&1
rc=$?
if [ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)"' "$OUT/BENCH_c13_tpu.json.tmp"; then
    mv "$OUT/BENCH_c13_tpu.json.tmp" "$OUT/BENCH_c13_tpu.json"
    echo "c13 TPU flight-recorder rows captured (BENCH_c13_tpu.json)"
else
    echo "c13 rc=$rc or not platform=tpu; keeping .tmp for forensics"
fi

# 5. Fused Pallas kernels on real TPU (ISSUE 15): bench_suite c20 runs
#    the exec-only fused-vs-XLA A/B with the COMPILED Mosaic kernel
#    (c20_fused_arm_is_compiled=1 confirms Mosaic accepted it; 0 means
#    the probe refused and the arm fell back — check the log for the
#    Mosaic error, that's the capture). This is the measurement the
#    CPU-interpret rows in BENCH_SUITE_r14.json are a stand-in for:
#    the HBM-round-trip win is structural there (one pallas_call per
#    bucket) and becomes a wall-clock number here. It also gates the
#    still-pending merge-path capture (step 2's compress A/B pair)
#    that retires VENEUR_TPU_TDIGEST_FULL_SORT.
timeout 540 python bench_suite.py --config 20 \
    --json-out "$OUT/BENCH_c20_tpu.json.tmp" \
    > "$OUT/tpu_window_c20_$TS.log" 2>&1
rc=$?
if [ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)"' "$OUT/BENCH_c20_tpu.json.tmp"; then
    mv "$OUT/BENCH_c20_tpu.json.tmp" "$OUT/BENCH_c20_tpu.json"
    echo "c20 fused-kernel TPU A/B captured (BENCH_c20_tpu.json)"
else
    echo "c20 rc=$rc or not platform=tpu; keeping .tmp for forensics"
fi
# Fused-vs-XLA PHASE TIMELINES against a live server: start one with
# `tpu_fused_kernels: auto` + `debug_flush_profile: true`, then
#     curl "http://$HTTP_ADDR/debug/flush/profile?ticks=3"
#     curl "http://$HTTP_ADDR/debug/flush" | python -m json.tool
# and read sketch_engines.kernels (histogram_arm/set_arm/fallback_total
# name the arm every executable was ACTUALLY built with) next to the
# device.exec phase rows; flip the knob to `off`, restart, re-curl —
# the two /debug/flush captures are the fused-vs-XLA phase timeline
# pair this window should check in.
echo "window capture complete at $(date -u +%Y%m%dT%H%M%SZ)"
