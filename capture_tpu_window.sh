#!/bin/bash
# One-shot TPU evidence capture for when the relay comes alive.
# The relay dies unpredictably (TPU_EVIDENCE_r04.md), so this runs the
# cheapest/highest-value probes first and commits nothing itself — run
# it, then check in whatever it produced.
#
#   bash capture_tpu_window.sh [outdir]
#
# Produces in outdir (default .):
#   BENCH_r04_tpu_live.json      bench.py JSON (mode table, chain est, e2e)
#   PALLAS_VALIDATION.json       Pallas-HLL vs jnp estimator on real TPU
#                                (written by pallas_validate.py into the
#                                repo dir, then copied to outdir)
#   tpu_window_*.log             output for each step
set -u
OUT="${1:-.}"
# resolve OUT before cd so a relative outdir means "relative to the
# caller", then make sure it exists — a failed redirect would silently
# waste the relay window
OUT="$(mkdir -p "$OUT" && cd "$OUT" && pwd)" || exit 1
cd "$(dirname "$0")"
TS=$(date -u +%Y%m%dT%H%M%SZ)

alive=$(timeout 150 python -c "
from veneur_tpu.utils.platform import tunnel_healthy
print('yes' if tunnel_healthy(timeout_s=120) else 'no')" 2>/dev/null | tail -1)
if [ "$alive" != "yes" ]; then
    echo "relay dead or unhealthy; nothing captured"
    exit 1
fi
echo "relay healthy at $TS — capturing"

# 1. Pallas validation first: cheapest, never captured on real TPU yet.
#    Writes PALLAS_VALIDATION.json itself on success.
timeout 420 python native/pallas_validate.py \
    > "$OUT/tpu_window_pallas_$TS.log" 2>&1
rc=$?
if [ $rc -eq 0 ] && [ -f PALLAS_VALIDATION.json ]; then
    [ "$OUT" != "$(pwd)" ] && cp PALLAS_VALIDATION.json "$OUT/"
    echo "pallas_validate OK (artifact: PALLAS_VALIDATION.json)"
else
    echo "pallas_validate rc=$rc — no fresh artifact (a pre-existing"\
         "PALLAS_VALIDATION.json, if any, is from an EARLIER window)"
fi

# 2. The north-star bench: exec/fetch split, fetch-mode probe, chain
#    estimator, e2e under the best mode.
BENCH_BUDGET_S=500 timeout 560 python bench.py \
    > "$OUT/BENCH_r04_tpu_live.json.tmp" 2> "$OUT/tpu_window_bench_$TS.log"
rc=$?
if [ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)"' "$OUT/BENCH_r04_tpu_live.json.tmp"; then
    mv "$OUT/BENCH_r04_tpu_live.json.tmp" "$OUT/BENCH_r04_tpu_live.json"
    echo "bench captured: $(cat "$OUT/BENCH_r04_tpu_live.json")"
else
    echo "bench rc=$rc or not platform=tpu; keeping .tmp for forensics"
fi
