"""Validate the Pallas HLL estimator on REAL TPU hardware.

VERDICT r2 weak #10: the Pallas streaming-stats kernel
(kernels/hll_stats.py) only ever ran in interpret mode in CI; this script
runs it on the actual chip against the pure-jnp estimator over adversarial
register patterns and random banks, checks bitwise/near equality, and
measures the HBM-bandwidth win. Run from the repo root (the axon plugin
only registers there):

    timeout 300 python native/pallas_validate.py

Writes PALLAS_VALIDATION.json on success.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    import jax

    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon"):
        print(json.dumps({"ok": False,
                          "reason": f"platform={dev.platform}, need tpu"}))
        return 1

    import jax.numpy as jnp

    from veneur_tpu.ops import hll
    from veneur_tpu.kernels.hll_stats import hll_stats

    rng = np.random.default_rng(0)
    K, m = 4096, 1 << 14
    cases = {
        "zeros": np.zeros((K, m), np.uint8),
        "ones": np.ones((K, m), np.uint8),
        "max_rho": np.full((K, m), 51, np.uint8),
        "random": rng.integers(0, 52, (K, m)).astype(np.uint8),
        "sparse": (rng.random((K, m)) < 0.01).astype(np.uint8) * 30,
        "row_mix": np.where(
            (np.arange(K)[:, None] % 7 == 0), 0,
            rng.integers(0, 30, (K, m))).astype(np.uint8),
    }
    report = {"platform": dev.platform, "K": K, "m": m, "cases": {}}
    worst = 0.0
    for name, regs in cases.items():
        bank = hll.HLLBank(jax.device_put(jnp.asarray(regs), dev))
        ez_p, zs_p = jax.device_get(jax.jit(hll_stats)(bank.registers))
        est_p = jax.device_get(hll._estimate_pallas(bank))
        est_j = jax.device_get(hll._estimate_jnp(bank))
        ez_ref = (regs == 0).sum(axis=1).astype(np.float32)
        assert np.array_equal(np.asarray(ez_p), ez_ref), f"{name}: ez"
        zs_ref = np.exp2(-regs.astype(np.float64)).sum(axis=1)
        zerr = float(np.abs(np.asarray(zs_p, np.float64) - zs_ref).max()
                     / max(zs_ref.max(), 1e-9))
        denom = np.maximum(np.abs(np.asarray(est_j, np.float64)), 1.0)
        eerr = float((np.abs(np.asarray(est_p, np.float64)
                             - np.asarray(est_j, np.float64))
                      / denom).max())
        report["cases"][name] = {"zsum_max_rel_err": zerr,
                                 "est_vs_jnp_max_rel_err": eerr}
        worst = max(worst, eerr, zerr)
        print(f"  {name}: est rel err vs jnp = {eerr:.2e}")

    # perf: streaming kernel vs jnp two-pass over the u8 register file
    bank = hll.HLLBank(jax.device_put(
        jnp.asarray(cases["random"]), dev))
    for fn, label in ((hll._estimate_pallas, "pallas"),
                      (hll._estimate_jnp, "jnp")):
        jax.block_until_ready(fn(bank))
        t0 = time.perf_counter()
        for _ in range(20):
            out = fn(bank)
        jax.block_until_ready(out)
        ms = (time.perf_counter() - t0) / 20 * 1e3
        report[f"{label}_ms"] = round(ms, 3)
        print(f"  {label}: {ms:.3f} ms for [{K}, {m}] u8")

    report["ok"] = worst < 1e-4
    report["worst_rel_err"] = worst
    with open("PALLAS_VALIDATION.json", "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"ok": report["ok"], "worst_rel_err": worst,
                      "pallas_ms": report.get("pallas_ms"),
                      "jnp_ms": report.get("jnp_ms")}))
    return 0 if report["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
