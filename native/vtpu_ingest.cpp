// vtpu_ingest — native high-rate DogStatsD ingest bridge.
//
// The TPU-native analogue of veneur's ingest front half
// (server.go sym: Server.ReadMetricSocket, Server.HandleMetricPacket;
// samplers/parser.go sym: ParseMetric; worker.go sym: Worker.ProcessMetric's
// dispatch-by-digest): SO_REUSEPORT UDP reader threads, a byte-level
// DogStatsD parser, a sharded MetricKey-interning hash table assigning
// device bank slots, and per-bank sample rings that the Python pump drains
// into fixed-shape batches for the XLA scatter kernels.
//
// Conformance contract: for every line this parser accepts, the produced
// (name, type, joined_tags, digest, value, rate, scope) must be
// bit-identical with veneur_tpu/ingest/parser.py. Lines it cannot prove
// bit-identical handling for (events, service checks, invalid UTF-8,
// numeric tokens with '_' or whitespace that CPython's float() would
// accept) are routed to the "other" queue for the Python slow path
// instead of being guessed at.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- constants

enum Bank : int { B_HISTO = 0, B_COUNTER = 1, B_GAUGE = 2, B_SET = 3 };
constexpr int NUM_BANKS = 4;

enum MType : uint8_t {
  MT_COUNTER = 0,
  MT_GAUGE = 1,
  MT_TIMER = 2,
  MT_HISTOGRAM = 3,
  MT_SET = 4,
};

// Scope values match ingest/parser.py MIXED_SCOPE / LOCAL_ONLY / GLOBAL_ONLY.
enum Scope : uint8_t { SC_MIXED = 0, SC_LOCAL = 1, SC_GLOBAL = 2 };

constexpr int NUM_SHARDS = 16;

const char* const MTYPE_NAMES[5] = {"counter", "gauge", "timer", "histogram",
                                    "set"};

inline int bank_of(MType t) {
  switch (t) {
    case MT_COUNTER: return B_COUNTER;
    case MT_GAUGE: return B_GAUGE;
    case MT_TIMER:
    case MT_HISTOGRAM: return B_HISTO;
    case MT_SET: return B_SET;
  }
  return B_HISTO;
}

// ---------------------------------------------------------------- hashing
// FNV-1a, identical to utils/hashing.py (itself parity with the fnv32a in
// samplers/parser.go) so proxies/tests agree about key identity.

constexpr uint32_t FNV32_OFFSET = 0x811C9DC5u;
constexpr uint32_t FNV32_PRIME = 0x01000193u;
constexpr uint64_t FNV64_OFFSET = 0xCBF29CE484222325ull;
constexpr uint64_t FNV64_PRIME = 0x00000100000001B3ull;

inline uint32_t fnv1a_32(const uint8_t* p, size_t n, uint32_t h) {
  for (size_t i = 0; i < n; i++) h = (h ^ p[i]) * FNV32_PRIME;
  return h;
}

inline uint64_t fnv1a_64(const uint8_t* p, size_t n, uint64_t h) {
  for (size_t i = 0; i < n; i++) h = (h ^ p[i]) * FNV64_PRIME;
  return h;
}

inline uint64_t fmix64(uint64_t h) {  // murmur3 finalizer (hashing.py fmix64)
  h ^= h >> 33;
  h *= 0xFF51AFD7ED558CCDull;
  h ^= h >> 33;
  h *= 0xC4CEB9FE1A85EC53ull;
  h ^= h >> 33;
  return h;
}

// fnv1a over name + type-name + joined tags — the one definition of
// metric identity, shared by the statsd parse tail, the SSF sample
// path, and the indicator timer (parity: utils/hashing.py
// metric_digest)
inline uint32_t metric_digest32(const uint8_t* name, size_t name_len,
                                int mtype,
                                const std::string& joined_tags) {
  const char* tn = MTYPE_NAMES[mtype];
  uint32_t h = fnv1a_32(name, name_len, FNV32_OFFSET);
  h = fnv1a_32(reinterpret_cast<const uint8_t*>(tn), strlen(tn), h);
  h = fnv1a_32(reinterpret_cast<const uint8_t*>(joined_tags.data()),
               joined_tags.size(), h);
  return h;
}

// ---------------------------------------------------------------- utf8
// Strict UTF-8 validation: CPython's decoder only leaves bytes unchanged
// (decode('utf-8','replace') then re-encode) when the input is strictly
// valid, so "strictly valid" is exactly the fast-path condition.

bool utf8_valid(const uint8_t* s, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t b = s[i];
    if (b < 0x80) {
      i++;
    } else if ((b >> 5) == 0x6) {  // 110xxxxx
      if (b < 0xC2 || i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
      i += 2;
    } else if ((b >> 4) == 0xE) {  // 1110xxxx
      if (i + 2 >= n) return false;
      uint8_t b1 = s[i + 1], b2 = s[i + 2];
      if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80) return false;
      if (b == 0xE0 && b1 < 0xA0) return false;        // overlong
      if (b == 0xED && b1 > 0x9F) return false;        // surrogates
      i += 3;
    } else if ((b >> 3) == 0x1E) {  // 11110xxx
      if (b > 0xF4 || i + 3 >= n) return false;
      uint8_t b1 = s[i + 1], b2 = s[i + 2], b3 = s[i + 3];
      if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80 ||
          (b3 & 0xC0) != 0x80)
        return false;
      if (b == 0xF0 && b1 < 0x90) return false;        // overlong
      if (b == 0xF4 && b1 > 0x8F) return false;        // > U+10FFFF
      i += 4;
    } else {
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------- numbers
// CPython float() compatibility triage for a numeric token:
//   OK     — strtod agrees with float() (charset-restricted decimal forms)
//   ERROR  — float() would raise (both sides reject)
//   SLOW   — float() may accept forms strtod can't ('_' digit grouping,
//            exotic whitespace trimming) → route the line to Python.

enum NumVerdict { NUM_OK = 0, NUM_ERROR = 1, NUM_SLOW = 2 };

NumVerdict parse_pyfloat(const uint8_t* p, size_t n, double* out) {
  if (n == 0) return NUM_ERROR;
  for (size_t i = 0; i < n; i++) {
    uint8_t c = p[i];
    if ((c >= '0' && c <= '9') || c == '+' || c == '-' || c == '.' ||
        c == 'e' || c == 'E')
      continue;
    if (c == '_' || c == ' ' || (c >= 0x09 && c <= 0x0D)) return NUM_SLOW;
    return NUM_ERROR;  // 'x', 'p', letters, NUL, UTF-8 ws… float() raises too
  }
  char buf[64];
  if (n >= sizeof(buf)) return NUM_SLOW;  // absurd token; let Python decide
  memcpy(buf, p, n);
  buf[n] = '\0';
  char* end = nullptr;
  errno = 0;
  double v = strtod(buf, &end);
  if (end != buf + n) return NUM_ERROR;  // e.g. "1e", "--1", "."
  *out = v;                              // may be ±inf on overflow, like float()
  return NUM_OK;
}

// ---------------------------------------------------------------- parser

enum ParseVerdict {
  P_METRIC = 0,   // parsed a metric sample
  P_ERROR = 1,    // ParseError on both implementations
  P_OTHER = 2,    // event / service check / slow path → Python
};

struct ParsedMetric {
  MType mtype;
  uint8_t scope;
  double value;        // numeric types
  double rate;
  uint32_t digest;
  std::string name;        // raw bytes (validated UTF-8)
  std::string joined_tags; // sorted, comma-joined
  std::string member;      // set member bytes
};

// Parse one line. `scratch` vectors are caller-provided to avoid per-line
// allocation on the hot path.
ParseVerdict parse_line(
    const uint8_t* data, size_t len, ParsedMetric* m,
    std::vector<std::pair<const uint8_t*, size_t>>* secs,
    std::vector<std::pair<const uint8_t*, size_t>>* tags,
    const std::vector<std::string>* exclude = nullptr) {
  if (len == 0) return P_ERROR;
  if (len >= 3 && memcmp(data, "_e{", 3) == 0) return P_OTHER;
  if (len >= 4 && memcmp(data, "_sc|", 4) == 0) return P_OTHER;
  if (!utf8_valid(data, len)) return P_OTHER;  // replace-decode divergence

  const uint8_t* colon =
      static_cast<const uint8_t*>(memchr(data, ':', len));
  if (colon == nullptr || colon == data) return P_ERROR;
  const uint8_t* name = data;
  size_t name_len = static_cast<size_t>(colon - data);
  const uint8_t* rest = colon + 1;
  size_t rest_len = len - name_len - 1;

  // split rest on '|'
  secs->clear();
  {
    const uint8_t* p = rest;
    size_t remain = rest_len;
    for (;;) {
      const uint8_t* bar =
          static_cast<const uint8_t*>(memchr(p, '|', remain));
      if (bar == nullptr) {
        secs->emplace_back(p, remain);
        break;
      }
      secs->emplace_back(p, static_cast<size_t>(bar - p));
      remain -= static_cast<size_t>(bar - p) + 1;
      p = bar + 1;
    }
  }
  if (secs->size() < 2) return P_ERROR;  // missing type

  const uint8_t* valstr = (*secs)[0].first;
  size_t val_len = (*secs)[0].second;
  const uint8_t* typestr = (*secs)[1].first;
  size_t type_len = (*secs)[1].second;

  MType mtype;
  bool is_dist = false;
  if (type_len == 1) {
    switch (typestr[0]) {
      case 'c': mtype = MT_COUNTER; break;
      case 'g': mtype = MT_GAUGE; break;
      case 'h': mtype = MT_HISTOGRAM; break;
      case 's': mtype = MT_SET; break;
      case 'd': mtype = MT_HISTOGRAM; is_dist = true; break;
      default: return P_ERROR;
    }
  } else if (type_len == 2 && typestr[0] == 'm' && typestr[1] == 's') {
    mtype = MT_TIMER;
  } else {
    return P_ERROR;
  }

  double value = 0.0;
  if (mtype == MT_SET) {
    m->member.assign(reinterpret_cast<const char*>(valstr), val_len);
  } else {
    if (val_len == 0) return P_ERROR;
    NumVerdict nv = parse_pyfloat(valstr, val_len, &value);
    if (nv == NUM_SLOW) return P_OTHER;
    if (nv == NUM_ERROR) return P_ERROR;
    if (!std::isfinite(value)) return P_ERROR;
  }

  double rate = 1.0;
  uint8_t scope = is_dist ? SC_GLOBAL : SC_MIXED;
  bool seen_rate = false, seen_tags = false;
  tags->clear();

  for (size_t si = 2; si < secs->size(); si++) {
    const uint8_t* sec = (*secs)[si].first;
    size_t sec_len = (*secs)[si].second;
    if (sec_len == 0) return P_ERROR;
    if (sec[0] == '@') {
      if (seen_rate) return P_ERROR;
      seen_rate = true;
      NumVerdict nv = parse_pyfloat(sec + 1, sec_len - 1, &rate);
      if (nv == NUM_SLOW) return P_OTHER;
      if (nv == NUM_ERROR) return P_ERROR;
      if (!(rate > 0.0 && rate <= 1.0)) return P_ERROR;
      if ((mtype == MT_GAUGE || mtype == MT_SET) && rate != 1.0)
        return P_ERROR;
    } else if (sec[0] == '#') {
      if (seen_tags) return P_ERROR;
      seen_tags = true;
      const uint8_t* p = sec + 1;
      size_t remain = sec_len - 1;
      for (;;) {
        const uint8_t* comma =
            remain ? static_cast<const uint8_t*>(memchr(p, ',', remain))
                   : nullptr;
        size_t tlen = comma ? static_cast<size_t>(comma - p) : remain;
        if (tlen == 15 && memcmp(p, "veneurlocalonly", 15) == 0) {
          scope = SC_LOCAL;
        } else if (tlen == 16 && memcmp(p, "veneurglobalonly", 16) == 0) {
          scope = SC_GLOBAL;
        } else if (tlen > 0) {
          tags->emplace_back(p, tlen);
        }
        if (!comma) break;
        remain -= tlen + 1;
        p = comma + 1;
      }
      if (exclude && !exclude->empty()) {
        // tags_exclude semantics (config.go): drop tags whose NAME
        // (before ':', or the whole tag) matches, BEFORE the key is
        // built, so excluded-tag variants aggregate together
        tags->erase(
            std::remove_if(
                tags->begin(), tags->end(),
                [&](const std::pair<const uint8_t*, size_t>& t) {
                  const uint8_t* colon = static_cast<const uint8_t*>(
                      memchr(t.first, ':', t.second));
                  size_t nlen = colon
                      ? static_cast<size_t>(colon - t.first) : t.second;
                  for (const std::string& ex : *exclude)
                    if (ex.size() == nlen &&
                        memcmp(ex.data(), t.first, nlen) == 0)
                      return true;
                  return false;
                }),
            tags->end());
      }
      // byte-wise sort == code-point sort for valid UTF-8
      std::sort(tags->begin(), tags->end(),
                [](const std::pair<const uint8_t*, size_t>& a,
                   const std::pair<const uint8_t*, size_t>& b) {
                  int c = memcmp(a.first, b.first,
                                 a.second < b.second ? a.second : b.second);
                  if (c != 0) return c < 0;
                  return a.second < b.second;
                });
    } else {
      return P_ERROR;
    }
  }

  if (name_len == 0) return P_ERROR;

  m->mtype = mtype;
  m->scope = scope;
  m->value = value;
  m->rate = rate;
  m->name.assign(reinterpret_cast<const char*>(name), name_len);
  m->joined_tags.clear();
  for (size_t i = 0; i < tags->size(); i++) {
    if (i) m->joined_tags.push_back(',');
    m->joined_tags.append(reinterpret_cast<const char*>((*tags)[i].first),
                          (*tags)[i].second);
  }

  m->digest = metric_digest32(name, name_len, mtype, m->joined_tags);
  return P_METRIC;
}

// ---------------------------------------------------------------- rings

struct Ring {
  std::mutex mu;
  std::vector<int32_t> slots;
  std::vector<float> a;
  std::vector<float> b;
  std::vector<int32_t> c;
  size_t cap = 0, head = 0, count = 0;
  uint64_t drops = 0;

  void init(size_t capacity) {
    cap = capacity;
    slots.resize(cap);
    a.resize(cap);
    b.resize(cap);
    c.resize(cap);
  }

  // bulk append; drops (and counts) what doesn't fit — veneur's
  // full-worker-channel backpressure drop, not blocking.
  void push(const int32_t* s, const float* av, const float* bv,
            const int32_t* cv, size_t n) {
    std::lock_guard<std::mutex> g(mu);
    size_t space = cap - count;
    if (n > space) {
      drops += n - space;
      n = space;
    }
    size_t tail = (head + count) % cap;
    size_t first = std::min(n, cap - tail);
    memcpy(&slots[tail], s, first * sizeof(int32_t));
    memcpy(&a[tail], av, first * sizeof(float));
    memcpy(&b[tail], bv, first * sizeof(float));
    memcpy(&c[tail], cv, first * sizeof(int32_t));
    if (n > first) {
      memcpy(&slots[0], s + first, (n - first) * sizeof(int32_t));
      memcpy(&a[0], av + first, (n - first) * sizeof(float));
      memcpy(&b[0], bv + first, (n - first) * sizeof(float));
      memcpy(&c[0], cv + first, (n - first) * sizeof(int32_t));
    }
    count += n;
  }

  size_t pop(int32_t* s, float* av, float* bv, int32_t* cv, size_t max_n) {
    std::lock_guard<std::mutex> g(mu);
    size_t n = std::min(count, max_n);
    size_t first = std::min(n, cap - head);
    memcpy(s, &slots[head], first * sizeof(int32_t));
    memcpy(av, &a[head], first * sizeof(float));
    memcpy(bv, &b[head], first * sizeof(float));
    memcpy(cv, &c[head], first * sizeof(int32_t));
    if (n > first) {
      memcpy(s + first, &slots[0], (n - first) * sizeof(int32_t));
      memcpy(av + first, &a[0], (n - first) * sizeof(float));
      memcpy(bv + first, &b[0], (n - first) * sizeof(float));
      memcpy(cv + first, &c[0], (n - first) * sizeof(int32_t));
    }
    head = (head + n) % cap;
    count -= n;
    return n;
  }
};

// ---------------------------------------------------------------- interner

struct NewKey {
  uint8_t bank, mtype, scope;
  int32_t slot;
  std::string name, tags;
};

struct Shard {
  std::mutex mu;
  // key string: name '\x1f' type-name '\x1f' joined_tags
  std::unordered_map<std::string, int32_t> map[NUM_BANKS];
};

struct BankMeta {
  int32_t capacity = 0;
  std::vector<std::atomic<uint32_t>> last_interval;
  std::vector<std::atomic<uint8_t>> scope;
  std::mutex free_mu;
  std::vector<int32_t> free_slots;
  std::atomic<uint32_t> interval{0};
  std::atomic<uint64_t> drops_no_slot{0};
  std::atomic<int64_t> key_count{0};

  void init(int32_t cap) {
    capacity = cap;
    last_interval = std::vector<std::atomic<uint32_t>>(cap);
    scope = std::vector<std::atomic<uint8_t>>(cap);
    for (int32_t i = 0; i < cap; i++) {
      last_interval[i].store(0, std::memory_order_relaxed);
      scope[i].store(0, std::memory_order_relaxed);
    }
    free_slots.reserve(cap);
    for (int32_t i = cap - 1; i >= 0; i--) free_slots.push_back(i);
  }
};

// ---------------------------------------------------------------- bridge

constexpr int RING_WAYS = 8;  // sub-rings per bank: writers shard by
                              // thread, so producers don't serialize
                              // against each other or the drain memcpy

struct Bridge {
  BankMeta banks[NUM_BANKS];
  Shard shards[NUM_SHARDS];
  Ring rings[NUM_BANKS][RING_WAYS];
  int hll_precision = 14;
  int idle_ttl = 16;
  // bumped on every advance_interval (evictions may reassign slots);
  // thread-local key caches check it and self-invalidate
  std::atomic<uint64_t> intern_epoch{0};
  // process-unique identity: thread_local LocalStages outlive any one
  // Bridge, so their memos must be scoped to the bridge they were
  // filled from — and a raw pointer is not enough (a new Bridge can be
  // allocated at a freed one's address with a matching epoch)
  uint64_t instance_id = 0;

  std::mutex newkeys_mu;
  std::deque<NewKey> newkeys;

  // set ONCE before readers start (no synchronization on the hot path)
  std::vector<std::string> tags_exclude;
  // indicator-span duration timer name ("" = disabled); set before start
  std::string indicator_timer;
  std::atomic<uint64_t> ssf_spans{0}, ssf_fallbacks{0};

  std::mutex other_mu;
  std::deque<std::string> other;
  size_t other_cap = 65536;
  uint64_t other_drops = 0;

  // SSF datagrams the native fast path could not express (STATUS
  // samples): raw bytes for the Python span pipeline, plus the native
  // SSF listener's own counters/port
  std::mutex ssf_other_mu;
  std::deque<std::string> ssf_other;
  size_t ssf_other_cap = 65536;
  uint64_t ssf_other_drops = 0;
  std::atomic<uint64_t> ssf_errors{0};
  int ssf_bound_port = 0;
  int ssf_max_dgram = 16384;

  std::atomic<uint64_t> packets{0}, lines{0}, samples{0}, parse_errors{0},
      slow_routed{0};

  std::vector<int> socks;
  std::vector<std::thread> readers;
  std::atomic<bool> stop{false};
  int bound_port = 0;
  int max_packet = 8192;
};

// per-thread parse + staging state
struct LocalStage {
  std::vector<std::pair<const uint8_t*, size_t>> secs, tags;
  ParsedMetric m;
  std::string keybuf;
  // key -> slot memo, valid within one (bridge, intern epoch):
  // steady-state hot keys skip the sharded map (and its mutex)
  // entirely; a thread that served a different bridge self-invalidates
  std::unordered_map<std::string, int32_t> key_cache[NUM_BANKS];
  uint64_t cache_epoch = ~0ull;
  uint64_t cache_owner = 0;  // Bridge::instance_id the memo belongs to
  std::vector<int32_t> slots[NUM_BANKS];
  std::vector<float> a[NUM_BANKS];
  std::vector<float> b[NUM_BANKS];
  std::vector<int32_t> c[NUM_BANKS];

  int way = -1;

  void flush(Bridge* br) {
    if (way < 0) {
      static std::atomic<int> next_way{0};
      way = next_way.fetch_add(1, std::memory_order_relaxed) % RING_WAYS;
    }
    for (int bk = 0; bk < NUM_BANKS; bk++) {
      if (!slots[bk].empty()) {
        br->rings[bk][way].push(slots[bk].data(), a[bk].data(),
                                b[bk].data(), c[bk].data(),
                                slots[bk].size());
        slots[bk].clear();
        a[bk].clear();
        b[bk].clear();
        c[bk].clear();
      }
    }
  }
};

inline void touch_meta(BankMeta& bank, int32_t slot, uint8_t scope);

void build_key(const ParsedMetric& m, std::string* keybuf) {
  keybuf->clear();
  keybuf->append(m.name);
  keybuf->push_back('\x1f');
  keybuf->append(MTYPE_NAMES[m.mtype]);
  keybuf->push_back('\x1f');
  keybuf->append(m.joined_tags);
}

int32_t intern_key(Bridge* br, const ParsedMetric& m,
                   const std::string& keybuf) {
  int bk = bank_of(m.mtype);
  BankMeta& bank = br->banks[bk];
  Shard& sh = br->shards[m.digest & (NUM_SHARDS - 1)];

  int32_t slot;
  {
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.map[bk].find(keybuf);
    if (it != sh.map[bk].end()) {
      slot = it->second;
    } else {
      {
        std::lock_guard<std::mutex> fg(bank.free_mu);
        if (bank.free_slots.empty()) {
          bank.drops_no_slot.fetch_add(1, std::memory_order_relaxed);
          return -1;
        }
        slot = bank.free_slots.back();
        bank.free_slots.pop_back();
      }
      sh.map[bk].emplace(keybuf, slot);
      bank.key_count.fetch_add(1, std::memory_order_relaxed);
      NewKey nk;
      nk.bank = static_cast<uint8_t>(bk);
      nk.mtype = static_cast<uint8_t>(m.mtype);
      nk.scope = m.scope;
      nk.slot = slot;
      nk.name = m.name;
      nk.tags = m.joined_tags;
      std::lock_guard<std::mutex> ng(br->newkeys_mu);
      br->newkeys.push_back(std::move(nk));
    }
  }
  touch_meta(bank, slot, m.scope);
  return slot;
}

// Refresh per-slot liveness/scope. Read-mostly: unconditional stores on
// a hot slot ping-pong its cache line between reader cores; in steady
// state the values don't change, so check first and only write on
// difference.
inline void touch_meta(BankMeta& bank, int32_t slot, uint8_t scope) {
  uint32_t cur = bank.interval.load(std::memory_order_relaxed);
  if (bank.last_interval[slot].load(std::memory_order_relaxed) != cur)
    bank.last_interval[slot].store(cur, std::memory_order_relaxed);
  if (bank.scope[slot].load(std::memory_order_relaxed) != scope)
    bank.scope[slot].store(scope, std::memory_order_relaxed);
}

void route_other(Bridge* br, const uint8_t* line, size_t len) {
  std::lock_guard<std::mutex> g(br->other_mu);
  if (br->other.size() >= br->other_cap) {
    br->other_drops++;
    return;
  }
  br->other.emplace_back(reinterpret_cast<const char*>(line), len);
}

void stage_parsed(Bridge* br, LocalStage* st, const ParsedMetric& m);

void handle_line(Bridge* br, LocalStage* st, const uint8_t* line,
                 size_t len) {
  br->lines.fetch_add(1, std::memory_order_relaxed);
  ParseVerdict v = parse_line(
      line, len, &st->m, &st->secs, &st->tags,
      br->tags_exclude.empty() ? nullptr : &br->tags_exclude);
  if (v == P_ERROR) {
    br->parse_errors.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  if (v == P_OTHER) {
    br->slow_routed.fetch_add(1, std::memory_order_relaxed);
    route_other(br, line, len);
    return;
  }
  stage_parsed(br, st, st->m);
}

// Intern + stage one parsed metric through the thread's LocalStage —
// the tail of handle_line, shared with the SSF span fast path.
void stage_parsed(Bridge* br, LocalStage* st, const ParsedMetric& m) {
  uint64_t ep = br->intern_epoch.load(std::memory_order_acquire);
  if (st->cache_epoch != ep || st->cache_owner != br->instance_id) {
    for (auto& c : st->key_cache) c.clear();
    st->cache_epoch = ep;
    st->cache_owner = br->instance_id;
  }
  int cbk = bank_of(m.mtype);
  build_key(m, &st->keybuf);
  int32_t slot;
  auto cit = st->key_cache[cbk].find(st->keybuf);
  if (cit != st->key_cache[cbk].end()) {
    slot = cit->second;
    touch_meta(br->banks[cbk], slot, m.scope);
  } else {
    slot = intern_key(br, m, st->keybuf);
    if (slot >= 0) st->key_cache[cbk].emplace(st->keybuf, slot);
  }
  if (slot < 0) return;
  int bk = bank_of(m.mtype);
  br->samples.fetch_add(1, std::memory_order_relaxed);
  switch (bk) {
    case B_HISTO:
    case B_COUNTER:
      st->slots[bk].push_back(slot);
      st->a[bk].push_back(static_cast<float>(m.value));
      st->b[bk].push_back(static_cast<float>(1.0 / m.rate));
      st->c[bk].push_back(0);
      break;
    case B_GAUGE:
      // last-write-wins sequence numbers are assigned by the engine at
      // dispatch time (ingest_gauge_batch), under the same lock as the
      // flush swap — ring order is arrival order
      st->slots[bk].push_back(slot);
      st->a[bk].push_back(static_cast<float>(m.value));
      st->b[bk].push_back(0.0f);
      st->c[bk].push_back(0);
      break;
    case B_SET: {
      // member hash identical to hashing.py set_member_hash + the rho
      // computation in pipeline.py _process_locked
      int p = br->hll_precision;
      uint64_t h = fmix64(fnv1a_64(
          reinterpret_cast<const uint8_t*>(m.member.data()),
          m.member.size(), FNV64_OFFSET));
      uint32_t idx = static_cast<uint32_t>(h >> (64 - p));
      uint64_t rest = (h << p) | ((1ull << p) - 1);
      int rho = __builtin_clzll(rest) + 1;
      st->slots[bk].push_back(slot);
      st->a[bk].push_back(static_cast<float>(rho));
      st->b[bk].push_back(0.0f);
      st->c[bk].push_back(static_cast<int32_t>(idx));
      break;
    }
  }
}

void handle_buffer(Bridge* br, LocalStage* st, const uint8_t* data,
                   size_t len) {
  size_t i = 0;
  while (i < len) {
    const uint8_t* nl =
        static_cast<const uint8_t*>(memchr(data + i, '\n', len - i));
    size_t ll = nl ? static_cast<size_t>(nl - (data + i)) : len - i;
    if (ll > 0) handle_line(br, st, data + i, ll);
    i += ll + 1;
  }
}

// ---------------------------------------------------------------- ssf
// Native span->metrics fast path: decode one SSF datagram (the
// protobuf subset of ssf/protos/ssf.proto) and stage its embedded
// samples straight into the rings — the C++ twin of
// sinks/ssfmetrics.py (sample_to_metric + indicator_timer; parity:
// sinks/ssfmetrics/metrics.go sym: metricExtractionSink). Spans the
// fast path cannot express faithfully (STATUS samples, which become
// service checks in Python) make the WHOLE datagram fall back to the
// Python path — never a partial native landing.

// Unknown-field group nesting deeper than this makes the native parser
// hand the datagram to the Python fallback decoder instead of erroring:
// the Python protobuf runtime accepts deeper well-formed nesting, so
// rejecting here would be a parity divergence (round-5 advisory).
// MUST stay equal to ssf/framing.py PB_SKIP_MAX_DEPTH (vlint NA02).
constexpr int kPbSkipMaxDepth = 16;

struct PbReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  bool deep = false;  // failed ONLY by exceeding kPbSkipMaxDepth

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  bool tag(uint32_t* field, uint32_t* wt) {
    if (p >= end) return false;
    uint64_t t = varint();
    if (!ok) return false;
    *field = static_cast<uint32_t>(t >> 3);
    *wt = static_cast<uint32_t>(t & 7);
    // wire-format limits the decoders we must agree with enforce:
    // field numbers are 1..2^29-1 (0 and oversized tags are illegal)
    if (*field == 0 || (t >> 3) > 536870911ull) {
      ok = false;
      return false;
    }
    return true;
  }

  bool bytes(const uint8_t** s, size_t* n) {
    uint64_t len = varint();
    if (!ok || len > static_cast<uint64_t>(end - p)) {
      ok = false;
      return false;
    }
    *s = p;
    *n = static_cast<size_t>(len);
    p += len;
    return true;
  }

  float f32() {
    if (end - p < 4) {
      ok = false;
      return 0.0f;
    }
    float v;
    memcpy(&v, p, 4);
    p += 4;
    return v;
  }

  void skip(uint32_t field, uint32_t wt, int depth = 0) {
    switch (wt) {
      case 0: varint(); break;
      case 1: p = (end - p >= 8) ? p + 8 : (ok = false, end); break;
      case 2: {
        const uint8_t* s;
        size_t n;
        bytes(&s, &n);
        break;
      }
      case 3: {
        // START_GROUP in an unknown field: the decoders we must agree
        // with accept well-formed groups (matching END_GROUP number),
        // reject unterminated/mismatched ones. Past the depth cap the
        // datagram falls back to Python (deep flag) rather than being
        // rejected — the fallback decoder accepts deeper nesting.
        if (depth >= kPbSkipMaxDepth) {
          ok = false;
          deep = true;
          return;
        }
        uint32_t f2, w2;
        while (true) {
          if (!tag(&f2, &w2)) {
            ok = false;  // EOF inside a group
            return;
          }
          if (w2 == 4) {
            if (f2 != field) ok = false;
            return;
          }
          skip(f2, w2, depth + 1);
          if (!ok) return;
        }
      }
      case 5: p = (end - p >= 4) ? p + 4 : (ok = false, end); break;
      default: ok = false;  // bare END_GROUP (4) or invalid 6/7
    }
  }
};

// parse one map<string,string> entry {1: key, 2: value} into raw
// (key, value) — kept raw so map semantics (last entry wins per key)
// can be applied before formatting
bool parse_tag_entry(const uint8_t* s, size_t n,
                     std::pair<std::string, std::string>* out,
                     bool* deep = nullptr) {
  PbReader r{s, s + n};
  const uint8_t *k = nullptr, *v = nullptr;
  size_t kn = 0, vn = 0;
  uint32_t f, wt;
  while (r.tag(&f, &wt)) {
    if (f == 1 && wt == 2) {
      r.bytes(&k, &kn);
    } else if (f == 2 && wt == 2) {
      r.bytes(&v, &vn);
    } else {
      r.skip(f, wt);
    }
    if (!r.ok) break;
  }
  if (deep != nullptr) *deep = *deep || r.deep;
  if (!r.ok) return false;
  // proto3 `string` fields must be valid UTF-8 — the Python decoder
  // rejects the whole message otherwise, and the key records these
  // bytes land in are strict-decoded downstream
  if (!utf8_valid(k, kn) || !utf8_valid(v, vn)) return false;
  // a map entry may omit field 1 or 2 entirely, leaving k/v nullptr:
  // clear() the target instead of assign(nullptr, 0), which is UB
  // (round-5 advisory NA01)
  if (k) out->first.assign(reinterpret_cast<const char*>(k), kn);
  else out->first.clear();
  if (v) out->second.assign(reinterpret_cast<const char*>(v), vn);
  else out->second.clear();
  return true;
}

struct SsfSample {
  // proto3 enums are int32: varints truncate to the low 32 bits,
  // signed — matching the Python decoder (a 2^32+4 wire value IS
  // STATUS there, and must be here too)
  int32_t metric = 0;
  std::string name, message, unit;
  float value = 0.0f;
  float rate = 0.0f;
  int32_t scope = 0;
  std::vector<std::pair<std::string, std::string>> tags;  // raw k, v
};

// A known field whose wire type doesn't match its declaration is
// treated as an unknown field and skipped — proto3 parser semantics,
// which the Python decoder follows; diverging here would make the two
// paths accept different byte streams.
bool parse_ssf_sample(const uint8_t* s, size_t n, SsfSample* out,
                      bool* deep = nullptr) {
  PbReader r{s, s + n};
  uint32_t f, wt;
  while (r.tag(&f, &wt)) {
    const uint8_t* b;
    size_t bn;
    if (f == 1 && wt == 0) {                                  // Metric
      out->metric = static_cast<int32_t>(r.varint());
    } else if (f == 2 && wt == 2) {                           // name
      if (!r.bytes(&b, &bn) || !utf8_valid(b, bn)) return false;
      out->name.assign(reinterpret_cast<const char*>(b), bn);
    } else if (f == 3 && wt == 5) {                           // value
      out->value = r.f32();
    } else if (f == 5 && wt == 2) {                           // message
      if (!r.bytes(&b, &bn) || !utf8_valid(b, bn)) return false;
      out->message.assign(reinterpret_cast<const char*>(b), bn);
    } else if (f == 7 && wt == 5) {                           // rate
      out->rate = r.f32();
    } else if (f == 8 && wt == 2) {                           // tags
      if (!r.bytes(&b, &bn)) return false;
      out->tags.emplace_back();
      if (!parse_tag_entry(b, bn, &out->tags.back(), deep)) return false;
    } else if (f == 9 && wt == 2) {                           // unit
      if (!r.bytes(&b, &bn) || !utf8_valid(b, bn)) return false;
      out->unit.assign(reinterpret_cast<const char*>(b), bn);
    } else if (f == 10 && wt == 0) {                          // Scope
      out->scope = static_cast<int32_t>(r.varint());
    } else {
      r.skip(f, wt);
    }
    if (!r.ok) {
      if (deep != nullptr) *deep = *deep || r.deep;
      return false;
    }
  }
  if (deep != nullptr) *deep = *deep || r.deep;
  return r.ok;
}

// time-unit scale to milliseconds (ssf/__init__.py TIME_UNITS; "\xc2\xb5s"
// is UTF-8 "µs")
bool time_unit_ms(const std::string& u, double* scale_ms) {
  if (u == "ns") *scale_ms = 1e-6;
  else if (u == "\xc2\xb5s" || u == "us") *scale_ms = 1e-3;
  else if (u == "ms") *scale_ms = 1.0;
  else if (u == "s") *scale_ms = 1e3;
  else return false;
  return true;
}

// Fill a ParsedMetric from one decoded sample; mirrors
// sample_to_metric. Returns false when the sample is skipped (no name
// / unknown type) — the Python twin returns None for those.
bool sample_to_parsed(const SsfSample& s, ParsedMetric* m) {
  if (s.name.empty()) return false;
  switch (s.metric) {
    case 0: m->mtype = MT_COUNTER; break;
    case 1: m->mtype = MT_GAUGE; break;
    case 2: m->mtype = MT_HISTOGRAM; break;
    case 3: m->mtype = MT_SET; break;
    default: return false;  // STATUS is pre-filtered; unknown skipped
  }
  m->value = s.value;
  double scale_ms;
  if (m->mtype == MT_HISTOGRAM && time_unit_ms(s.unit, &scale_ms)) {
    m->mtype = MT_TIMER;
    m->value = static_cast<double>(s.value) * scale_ms;
  }
  m->rate = (s.rate != 0.0f) ? s.rate : 1.0;
  m->scope = (s.scope >= 0 && s.scope <= 2)
                 ? static_cast<uint8_t>(s.scope)
                 : static_cast<uint8_t>(SC_MIXED);
  m->name = s.name;
  if (m->mtype == MT_SET) m->member = s.message;
  // proto3 map semantics: for duplicate keys on the wire, the LAST
  // entry wins (what the Python decoder's dict does) — dedupe on the
  // raw key before formatting, or the native and fallback paths would
  // build different metric identities for the same datagram
  std::vector<std::string> formatted;
  formatted.reserve(s.tags.size());
  for (size_t i = 0; i < s.tags.size(); i++) {
    bool overwritten = false;
    for (size_t j = i + 1; j < s.tags.size(); j++)
      if (s.tags[j].first == s.tags[i].first) {
        overwritten = true;
        break;
      }
    if (overwritten) continue;
    std::string f = s.tags[i].first;
    if (!s.tags[i].second.empty()) {
      f.push_back(':');
      f.append(s.tags[i].second);
    }
    formatted.push_back(std::move(f));
  }
  // sorted, comma-joined — UTF-8 byte order equals code point order,
  // so std::sort matches Python's sorted()
  std::sort(formatted.begin(), formatted.end());
  m->joined_tags.clear();
  for (size_t i = 0; i < formatted.size(); i++) {
    if (i) m->joined_tags.push_back(',');
    m->joined_tags.append(formatted[i]);
  }
  m->digest = metric_digest32(
      reinterpret_cast<const uint8_t*>(m->name.data()), m->name.size(),
      m->mtype, m->joined_tags);
  return true;
}

// Decode + stage one SSF datagram. Returns 1 when handled natively,
// 0 when the caller must use the Python path (STATUS samples present,
// or unknown-field nesting past kPbSkipMaxDepth — the Python decoder
// accepts deeper well-formed groups, so erroring would diverge),
// -1 on malformed protobuf (counted; caller should count an ssf error).
int handle_ssf(Bridge* br, LocalStage* st, const uint8_t* data,
               size_t len) {
  PbReader r{data, data + len};
  std::vector<SsfSample> samples;
  bool indicator = false, error = false, deep = false;
  int64_t start_ts = 0, end_ts = 0;
  std::string service;
  uint32_t f, wt;
  std::pair<std::string, std::string> scratch_tag;
  auto fail = [&]() -> int {
    if (deep || r.deep) {
      br->ssf_fallbacks.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
    return -1;
  };
  while (r.tag(&f, &wt)) {
    const uint8_t* b;
    size_t bn;
    if (f == 5 && wt == 0) {
      start_ts = static_cast<int64_t>(r.varint());
    } else if (f == 6 && wt == 0) {
      end_ts = static_cast<int64_t>(r.varint());
    } else if (f == 7 && wt == 0) {
      error = r.varint() != 0;
    } else if (f == 8 && wt == 2) {                        // service
      if (!r.bytes(&b, &bn) || !utf8_valid(b, bn)) return -1;
      service.assign(reinterpret_cast<const char*>(b), bn);
    } else if (f == 9 && wt == 2) {
      // span-level tags: unused by the metric extraction, but KNOWN to
      // the schema — the Python decoder parses and validates every
      // known submessage/string field, so the native path must reject
      // what it would reject (a skipped-but-malformed entry was a
      // fuzz-found false accept)
      if (!r.bytes(&b, &bn)) return -1;
      if (!parse_tag_entry(b, bn, &scratch_tag, &deep)) return fail();
    } else if (f == 10 && wt == 0) {
      indicator = r.varint() != 0;
    } else if (f == 11 && wt == 2) {                       // span name
      if (!r.bytes(&b, &bn) || !utf8_valid(b, bn)) return -1;
    } else if (f == 12 && wt == 2) {                       // metrics
      if (!r.bytes(&b, &bn)) return -1;
      samples.emplace_back();
      if (!parse_ssf_sample(b, bn, &samples.back(), &deep))
        return fail();
    } else {
      r.skip(f, wt);
    }
    if (!r.ok) return fail();
  }
  if (!r.ok) return fail();
  // STATUS samples become service checks in Python — whole-datagram
  // fallback so one span never lands half-natively
  for (const SsfSample& s : samples)
    if (s.metric == 4) {
      br->ssf_fallbacks.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
  br->ssf_spans.fetch_add(1, std::memory_order_relaxed);
  ParsedMetric m;
  for (const SsfSample& s : samples)
    if (sample_to_parsed(s, &m)) stage_parsed(br, st, m);
  if (indicator && !br->indicator_timer.empty() && start_ts && end_ts) {
    // indicator_timer(): duration timer tagged service/error
    m.mtype = MT_TIMER;
    m.value = static_cast<double>(end_ts >= start_ts ? end_ts - start_ts
                                                     : 0) / 1e6;
    m.rate = 1.0;
    m.scope = SC_MIXED;
    m.name = br->indicator_timer;
    std::string etag = error ? "error:true" : "error:false";
    std::string stag = "service:" + service;
    m.joined_tags = etag < stag ? etag + "," + stag : stag + "," + etag;
    m.digest = metric_digest32(
        reinterpret_cast<const uint8_t*>(m.name.data()), m.name.size(),
        m.mtype, m.joined_tags);
    stage_parsed(br, st, m);
  }
  return 1;
}

// recvmmsg burst machinery shared by the statsd and SSF reader loops.
struct RecvBatch {
  static constexpr int VLEN = 64;
  std::vector<std::vector<uint8_t>> bufs;
  std::vector<mmsghdr> msgs;
  std::vector<iovec> iovs;

  explicit RecvBatch(size_t max_dgram)
      : bufs(VLEN), msgs(VLEN), iovs(VLEN) {
    for (int i = 0; i < VLEN; i++) {
      bufs[i].resize(max_dgram);
      iovs[i].iov_base = bufs[i].data();
      iovs[i].iov_len = bufs[i].size();
      memset(&msgs[i], 0, sizeof(mmsghdr));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
  }
};

void reader_loop(Bridge* br, int sock) {
  LocalStage st;
  RecvBatch rb(br->max_packet);
  pollfd pfd{sock, POLLIN, 0};
  while (!br->stop.load(std::memory_order_relaxed)) {
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int n = recvmmsg(sock, rb.msgs.data(), RecvBatch::VLEN, MSG_DONTWAIT,
                     nullptr);
    if (n <= 0) continue;
    br->packets.fetch_add(n, std::memory_order_relaxed);
    for (int i = 0; i < n; i++)
      handle_buffer(br, &st, rb.bufs[i].data(), rb.msgs[i].msg_len);
    st.flush(br);
  }
}

void route_ssf_other(Bridge* br, const uint8_t* data, size_t len) {
  std::lock_guard<std::mutex> g(br->ssf_other_mu);
  if (br->ssf_other.size() >= br->ssf_other_cap) {
    br->ssf_other_drops++;
    return;
  }
  br->ssf_other.emplace_back(reinterpret_cast<const char*>(data), len);
}

// The SSF span listener: one datagram = one SSFSpan protobuf, decoded
// and staged natively; fallback datagrams queue for the Python span
// pipeline (Server.ReadSSFPacketSocket's C++ twin).
void ssf_reader_loop(Bridge* br, int sock) {
  LocalStage st;
  RecvBatch rb(br->ssf_max_dgram);
  pollfd pfd{sock, POLLIN, 0};
  while (!br->stop.load(std::memory_order_relaxed)) {
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int n = recvmmsg(sock, rb.msgs.data(), RecvBatch::VLEN, MSG_DONTWAIT,
                     nullptr);
    if (n <= 0) continue;
    br->packets.fetch_add(n, std::memory_order_relaxed);
    for (int i = 0; i < n; i++) {
      int rc = handle_ssf(br, &st, rb.bufs[i].data(), rb.msgs[i].msg_len);
      if (rc == 0)
        route_ssf_other(br, rb.bufs[i].data(), rb.msgs[i].msg_len);
      else if (rc < 0)
        br->ssf_errors.fetch_add(1, std::memory_order_relaxed);
    }
    st.flush(br);
  }
}

}  // namespace

// ================================================================ C ABI

extern "C" {

void* vtpu_create(int32_t histo_slots, int32_t counter_slots,
                  int32_t gauge_slots, int32_t set_slots,
                  int32_t hll_precision, int32_t idle_ttl,
                  int32_t ring_capacity, int32_t max_packet) {
  Bridge* br = new Bridge();
  static std::atomic<uint64_t> next_instance{1};
  br->instance_id = next_instance.fetch_add(1, std::memory_order_relaxed);
  int32_t caps[NUM_BANKS] = {histo_slots, counter_slots, gauge_slots,
                             set_slots};
  for (int i = 0; i < NUM_BANKS; i++) {
    br->banks[i].init(caps[i]);
    for (int w = 0; w < RING_WAYS; w++)
      br->rings[i][w].init(
          static_cast<size_t>(ring_capacity) / RING_WAYS + 1);
  }
  br->hll_precision = hll_precision;
  br->idle_ttl = idle_ttl;
  br->max_packet = max_packet;
  return br;
}

void vtpu_destroy(void* h) {
  Bridge* br = static_cast<Bridge*>(h);
  br->stop.store(true);
  for (auto& t : br->readers)
    if (t.joinable()) t.join();
  for (int s : br->socks) close(s);
  delete br;
}

// Feed one raw packet (possibly multiple '\n'-separated lines) from the
// calling thread — the test/slow-path entry, same code as the readers.
void vtpu_handle_packet(void* h, const uint8_t* data, int32_t len) {
  Bridge* br = static_cast<Bridge*>(h);
  thread_local LocalStage st;
  br->packets.fetch_add(1, std::memory_order_relaxed);
  handle_buffer(br, &st, data, static_cast<size_t>(len));
  st.flush(br);
}

// Decode one SSF span datagram and stage its embedded samples natively.
// Returns 1 = handled, 0 = caller must use the Python span path for
// this datagram, -1 = malformed protobuf.
int32_t vtpu_handle_ssf(void* h, const uint8_t* data, int32_t len) {
  Bridge* br = static_cast<Bridge*>(h);
  thread_local LocalStage st;
  int rc = handle_ssf(br, &st, data, static_cast<size_t>(len));
  if (rc == 1) st.flush(br);
  return rc;
}

// Configure the indicator-span duration timer (config key
// indicator_span_timer_name). Must be called before readers start.
void vtpu_set_indicator_timer(void* h, const char* name) {
  Bridge* br = static_cast<Bridge*>(h);
  br->indicator_timer = name ? name : "";
}

// Start n SO_REUSEPORT UDP reader threads on host:port. Returns bound
// port (useful with port 0) or -errno.
static int32_t open_udp_readers(Bridge* br, const char* host,
                                int32_t port, int32_t n_readers,
                                int32_t rcvbuf,
                                void (*loop)(Bridge*, int)) {
  bool v6 = strchr(host, ':') != nullptr;
  int bound = -1;
  for (int r = 0; r < n_readers; r++) {
    int fd = socket(v6 ? AF_INET6 : AF_INET, SOCK_DGRAM, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
#ifdef SO_REUSEPORT
    setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
#endif
    if (rcvbuf > 0)
      setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof(rcvbuf));
    int rc;
    if (v6) {
      sockaddr_in6 sa{};
      sa.sin6_family = AF_INET6;
      sa.sin6_port = htons(static_cast<uint16_t>(bound > 0 ? bound : port));
      if (inet_pton(AF_INET6, host, &sa.sin6_addr) != 1) {
        close(fd);
        return -EINVAL;  // hostnames must be resolved by the caller
      }
      rc = bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa));
      if (rc == 0 && bound < 0) {
        socklen_t sl = sizeof(sa);
        getsockname(fd, reinterpret_cast<sockaddr*>(&sa), &sl);
        bound = ntohs(sa.sin6_port);
      }
    } else {
      sockaddr_in sa{};
      sa.sin_family = AF_INET;
      sa.sin_port = htons(static_cast<uint16_t>(bound > 0 ? bound : port));
      if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) {
        close(fd);
        return -EINVAL;  // hostnames must be resolved by the caller
      }
      rc = bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa));
      if (rc == 0 && bound < 0) {
        socklen_t sl = sizeof(sa);
        getsockname(fd, reinterpret_cast<sockaddr*>(&sa), &sl);
        bound = ntohs(sa.sin_port);
      }
    }
    if (rc != 0) {
      int e = errno;
      close(fd);
      return -e;
    }
    br->socks.push_back(fd);
    br->readers.emplace_back(loop, br, fd);
  }
  return bound;
}

int32_t vtpu_start_udp(void* h, const char* host, int32_t port,
                       int32_t n_readers, int32_t rcvbuf) {
  Bridge* br = static_cast<Bridge*>(h);
  int32_t bound = open_udp_readers(br, host, port, n_readers, rcvbuf,
                                   reader_loop);
  if (bound >= 0) br->bound_port = bound;
  return bound;
}

// Start the native SSF span listener (one datagram = one SSFSpan).
// max_dgram sizes the receive buffers (trace_max_length_bytes).
int32_t vtpu_start_ssf_udp(void* h, const char* host, int32_t port,
                           int32_t n_readers, int32_t rcvbuf,
                           int32_t max_dgram) {
  Bridge* br = static_cast<Bridge*>(h);
  if (max_dgram > 0) br->ssf_max_dgram = max_dgram;
  int32_t bound = open_udp_readers(br, host, port, n_readers, rcvbuf,
                                   ssf_reader_loop);
  if (bound >= 0) br->ssf_bound_port = bound;
  return bound;
}

// Drain fallback SSF datagrams (STATUS-carrying spans) as u32le
// length-prefixed records for the Python span pipeline.
int32_t vtpu_drain_ssf_other(void* h, uint8_t* buf, int32_t buf_len) {
  Bridge* br = static_cast<Bridge*>(h);
  std::lock_guard<std::mutex> g(br->ssf_other_mu);
  int32_t off = 0;
  while (!br->ssf_other.empty()) {
    const std::string& s = br->ssf_other.front();
    int32_t need = 4 + static_cast<int32_t>(s.size());
    if (off + need > buf_len) break;
    uint32_t sl = static_cast<uint32_t>(s.size());
    memcpy(buf + off, &sl, 4);
    off += 4;
    memcpy(buf + off, s.data(), sl);
    off += sl;
    br->ssf_other.pop_front();
  }
  return off;
}

void vtpu_stop(void* h) {
  Bridge* br = static_cast<Bridge*>(h);
  br->stop.store(true);
  for (auto& t : br->readers)
    if (t.joinable()) t.join();
  br->readers.clear();
  for (int s : br->socks) close(s);
  br->socks.clear();
}

// Drain up to max_n staged samples for `bank` into caller arrays.
// histo/counter: a=values  b=weights;  gauge: a=values  c=seqs;
// set: a=rho  c=register index.
int32_t vtpu_poll(void* h, int32_t bank, int32_t max_n, int32_t* slots,
                  float* a, float* b, int32_t* c) {
  Bridge* br = static_cast<Bridge*>(h);
  size_t got = 0;
  for (int w = 0; w < RING_WAYS && got < static_cast<size_t>(max_n); w++)
    got += br->rings[bank][w].pop(slots + got, a + got, b + got, c + got,
                                  static_cast<size_t>(max_n) - got);
  return static_cast<int32_t>(got);
}

// Drain newly-interned keys as packed records:
//   bank u8 | mtype u8 | scope u8 | slot i32le | name_len u16le | name |
//   tags_len u16le | tags
// Returns bytes written; 0 when empty. Records are never split.
int32_t vtpu_drain_new_keys(void* h, uint8_t* buf, int32_t buf_len) {
  Bridge* br = static_cast<Bridge*>(h);
  std::lock_guard<std::mutex> g(br->newkeys_mu);
  int32_t off = 0;
  while (!br->newkeys.empty()) {
    const NewKey& nk = br->newkeys.front();
    int32_t need = 3 + 4 + 2 + static_cast<int32_t>(nk.name.size()) + 2 +
                   static_cast<int32_t>(nk.tags.size());
    if (off + need > buf_len) break;
    buf[off++] = nk.bank;
    buf[off++] = nk.mtype;
    buf[off++] = nk.scope;
    memcpy(buf + off, &nk.slot, 4);
    off += 4;
    uint16_t nl = static_cast<uint16_t>(nk.name.size());
    memcpy(buf + off, &nl, 2);
    off += 2;
    memcpy(buf + off, nk.name.data(), nl);
    off += nl;
    uint16_t tl = static_cast<uint16_t>(nk.tags.size());
    memcpy(buf + off, &tl, 2);
    off += 2;
    memcpy(buf + off, nk.tags.data(), tl);
    off += tl;
    br->newkeys.pop_front();
  }
  return off;
}

// Drain slow-path lines (events, service checks, py-float oddities) as
// u16le length-prefixed raw byte strings. Returns bytes written.
int32_t vtpu_drain_other(void* h, uint8_t* buf, int32_t buf_len) {
  Bridge* br = static_cast<Bridge*>(h);
  std::lock_guard<std::mutex> g(br->other_mu);
  int32_t off = 0;
  while (!br->other.empty()) {
    const std::string& s = br->other.front();
    int32_t need = 2 + static_cast<int32_t>(s.size());
    if (off + need > buf_len) break;
    uint16_t sl = static_cast<uint16_t>(s.size());
    memcpy(buf + off, &sl, 2);
    off += 2;
    memcpy(buf + off, s.data(), sl);
    off += sl;
    br->other.pop_front();
  }
  return off;
}

// Bulk-read per-slot scopes for `bank` (flush-time snapshot).
void vtpu_slot_scopes(void* h, int32_t bank, uint8_t* out, int32_t n) {
  Bridge* br = static_cast<Bridge*>(h);
  BankMeta& bm = br->banks[bank];
  int32_t lim = std::min(n, bm.capacity);
  for (int32_t i = 0; i < lim; i++)
    out[i] = bm.scope[i].load(std::memory_order_relaxed);
}

// Advance `bank`'s interval counter and evict keys idle > idle_ttl
// intervals (KeyInterner.advance_interval's eviction). Returns number
// evicted. Gauge advance also resets the per-interval gauge sequence.
int32_t vtpu_advance_interval(void* h, int32_t bank) {
  Bridge* br = static_cast<Bridge*>(h);
  BankMeta& bm = br->banks[bank];
  uint32_t now = bm.interval.fetch_add(1, std::memory_order_relaxed) + 1;
  // any eviction below may reassign slots: invalidate thread-local key
  // caches up front (publishes before the frees become visible)
  br->intern_epoch.fetch_add(1, std::memory_order_acq_rel);
  if (br->idle_ttl <= 0 || now < static_cast<uint32_t>(br->idle_ttl))
    return 0;
  uint32_t horizon = now - static_cast<uint32_t>(br->idle_ttl);
  int32_t evicted = 0;
  for (int s = 0; s < NUM_SHARDS; s++) {
    Shard& sh = br->shards[s];
    std::lock_guard<std::mutex> g(sh.mu);
    for (auto it = sh.map[bank].begin(); it != sh.map[bank].end();) {
      int32_t slot = it->second;
      if (bm.last_interval[slot].load(std::memory_order_relaxed) < horizon) {
        {
          std::lock_guard<std::mutex> fg(bm.free_mu);
          bm.free_slots.push_back(slot);
        }
        bm.key_count.fetch_add(-1, std::memory_order_relaxed);
        it = sh.map[bank].erase(it);
        evicted++;
      } else {
        ++it;
      }
    }
  }
  return evicted;
}

// Intern one key from the Python side (the slow path / ssfmetrics bridge /
// global-tier Combine all reach interning through here in native mode).
// Returns the slot, or -1 when the bank is full.
int32_t vtpu_intern(void* h, int32_t mtype, int32_t scope,
                    const uint8_t* name, int32_t name_len,
                    const uint8_t* tags, int32_t tags_len) {
  Bridge* br = static_cast<Bridge*>(h);
  thread_local ParsedMetric m;
  thread_local std::string keybuf;
  m.mtype = static_cast<MType>(mtype);
  m.scope = static_cast<uint8_t>(scope);
  m.name.assign(reinterpret_cast<const char*>(name),
                static_cast<size_t>(name_len));
  m.joined_tags.assign(reinterpret_cast<const char*>(tags),
                       static_cast<size_t>(tags_len));
  uint32_t hh = fnv1a_32(name, static_cast<size_t>(name_len), FNV32_OFFSET);
  const char* tn = MTYPE_NAMES[mtype];
  hh = fnv1a_32(reinterpret_cast<const uint8_t*>(tn), strlen(tn), hh);
  hh = fnv1a_32(tags, static_cast<size_t>(tags_len), hh);
  m.digest = hh;
  build_key(m, &keybuf);
  return intern_key(br, m, keybuf);
}

// Install the tags_exclude list: '\n'-joined tag names. MUST be called
// before vtpu_start_udp (readers snapshot nothing; the list is read
// lock-free on the hot path).
void vtpu_set_tags_exclude(void* h, const uint8_t* packed, int32_t len) {
  Bridge* br = static_cast<Bridge*>(h);
  br->tags_exclude.clear();
  size_t start = 0;
  std::string all(reinterpret_cast<const char*>(packed),
                  static_cast<size_t>(len));
  while (start <= all.size() && len > 0) {
    size_t nl = all.find('\n', start);
    size_t end = (nl == std::string::npos) ? all.size() : nl;
    if (end > start) br->tags_exclude.emplace_back(all, start, end - start);
    if (nl == std::string::npos) break;
    start = nl + 1;
  }
}

int64_t vtpu_key_count(void* h, int32_t bank) {
  return static_cast<Bridge*>(h)->banks[bank].key_count.load();
}

// stats[0..8] = packets, lines, samples, parse_errors, slow_routed,
//               drops_no_slot(sum), ring_drops(sum), other_drops,
//               pending_other
void vtpu_stats(void* h, uint64_t* out) {
  Bridge* br = static_cast<Bridge*>(h);
  out[0] = br->packets.load();
  out[1] = br->lines.load();
  out[2] = br->samples.load();
  out[3] = br->parse_errors.load();
  out[4] = br->slow_routed.load();
  uint64_t no_slot = 0, ring_drops = 0;
  for (int i = 0; i < NUM_BANKS; i++) {
    no_slot += br->banks[i].drops_no_slot.load();
    for (int w = 0; w < RING_WAYS; w++) {
      std::lock_guard<std::mutex> g(br->rings[i][w].mu);
      ring_drops += br->rings[i][w].drops;
    }
  }
  out[5] = no_slot;
  out[6] = ring_drops;
  out[9] = br->ssf_spans.load();
  out[10] = br->ssf_fallbacks.load();
  out[11] = br->ssf_errors.load();
  {
    std::lock_guard<std::mutex> sg(br->ssf_other_mu);
    out[12] = br->ssf_other_drops;
    out[13] = br->ssf_other.size();
  }
  std::lock_guard<std::mutex> g(br->other_mu);
  out[7] = br->other_drops;
  out[8] = br->other.size();
}

// -------- conformance/testing helpers (stateless parse of one line) -----
// Returns the ParseVerdict. On P_METRIC fills the packed record:
//   mtype u8 | scope u8 | rate f64le | value f64le | digest u32le |
//   name_len u16le | name | tags_len u16le | tags |
//   member_len u16le | member
int32_t vtpu_parse_one(const uint8_t* data, int32_t len, uint8_t* buf,
                       int32_t buf_len, int32_t* out_len) {
  std::vector<std::pair<const uint8_t*, size_t>> secs, tags;
  ParsedMetric m;
  ParseVerdict v = parse_line(data, static_cast<size_t>(len), &m, &secs,
                              &tags);
  *out_len = 0;
  if (v != P_METRIC) return v;
  int32_t need = 1 + 1 + 8 + 8 + 4 + 2 +
                 static_cast<int32_t>(m.name.size()) + 2 +
                 static_cast<int32_t>(m.joined_tags.size()) + 2 +
                 static_cast<int32_t>(m.member.size());
  if (need > buf_len) return P_ERROR;
  int32_t off = 0;
  buf[off++] = m.mtype;
  buf[off++] = m.scope;
  memcpy(buf + off, &m.rate, 8);
  off += 8;
  memcpy(buf + off, &m.value, 8);
  off += 8;
  memcpy(buf + off, &m.digest, 4);
  off += 4;
  uint16_t nl = static_cast<uint16_t>(m.name.size());
  memcpy(buf + off, &nl, 2);
  off += 2;
  memcpy(buf + off, m.name.data(), nl);
  off += nl;
  uint16_t tl = static_cast<uint16_t>(m.joined_tags.size());
  memcpy(buf + off, &tl, 2);
  off += 2;
  memcpy(buf + off, m.joined_tags.data(), tl);
  off += tl;
  uint16_t ml = static_cast<uint16_t>(m.member.size());
  memcpy(buf + off, &ml, 2);
  off += 2;
  memcpy(buf + off, m.member.data(), ml);
  off += ml;
  *out_len = off;
  return P_METRIC;
}

// Parse-only throughput probe: parse the given newline-separated buffer
// `iters` times with no interning/staging; returns seconds elapsed.
double vtpu_bench_parse(const uint8_t* data, int32_t len, int32_t iters) {
  std::vector<std::pair<const uint8_t*, size_t>> secs, tags;
  ParsedMetric m;
  timespec t0, t1;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  for (int32_t it = 0; it < iters; it++) {
    size_t i = 0;
    size_t n = static_cast<size_t>(len);
    while (i < n) {
      const uint8_t* nl =
          static_cast<const uint8_t*>(memchr(data + i, '\n', n - i));
      size_t ll = nl ? static_cast<size_t>(nl - (data + i)) : n - i;
      if (ll > 0) parse_line(data + i, ll, &m, &secs, &tags);
      i += ll + 1;
    }
  }
  clock_gettime(CLOCK_MONOTONIC, &t1);
  return (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;
}

int32_t vtpu_bound_port(void* h) {
  return static_cast<Bridge*>(h)->bound_port;
}

int32_t vtpu_ssf_bound_port(void* h) {
  return static_cast<Bridge*>(h)->ssf_bound_port;
}

}  // extern "C"
