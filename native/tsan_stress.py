"""TSAN stress harness for the native ingest bridge.

The concurrency test story for the C++ bridge (SURVEY §5 — the rebuild's
analogue of the reference's `go test -race`): exercise every cross-thread
path at once — SO_REUSEPORT UDP readers, the Python caller's
thread_local staging (two bridges to cover the bridge-scoped memo),
concurrent ring drains (the pump path), new-key/slow-path drains, and
interval advancement with eviction — under ThreadSanitizer.

Run (from repo root; deliberately does NOT import jax/pytest — TSAN
makes them unusably slow):

    make -C native tsan
    LD_PRELOAD=$(g++ -print-file-name=libtsan.so) \
    VENEUR_TPU_NATIVE_LIB=native/build/libvtpu_ingest_tsan.so \
    TSAN_OPTIONS=exitcode=66 python native/tsan_stress.py

Exit 0 + "tsan stress ok" and no "WARNING: ThreadSanitizer" output means
a clean run; TSAN itself exits 66 on a detected race.
"""

import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from veneur_tpu.ingest import native  # noqa: E402

DURATION_S = float(os.environ.get("TSAN_STRESS_S", "5"))


def main() -> int:
    bridges = [native.NativeBridge(
        histo_slots=256, counter_slots=256, gauge_slots=128,
        set_slots=64, hll_precision=10, idle_ttl=2,
        ring_capacity=65536, max_packet=8192) for _ in range(2)]
    port = bridges[0].start_udp("127.0.0.1", 0, n_readers=2)

    stop = threading.Event()

    def sender():
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        i = 0
        while not stop.is_set():
            s.sendto(
                (f"t{i % 97}:{i % 31}|ms|#env:prod\n"
                 f"c{i % 53}:1|c|@0.5\nu:{i % 1009}|s").encode(),
                ("127.0.0.1", port))
            i += 1

    def direct_caller():
        # alternates bridges from ONE thread: the bridge-scoped
        # thread_local memo must never cross-pollinate
        i = 0
        while not stop.is_set():
            bridges[i % 2].handle_packet(
                f"d{i % 41}:{i % 7}|ms\ng:{i}|g".encode())
            i += 1

    import numpy as np

    def pump(br):
        slots = np.zeros(4096, np.int32)
        a = np.zeros(4096, np.float32)
        b = np.zeros(4096, np.float32)
        c = np.zeros(4096, np.int32)
        polled = 0
        while not stop.is_set():
            for bank in ("histo", "counter", "gauge", "set"):
                polled += max(0, br.poll(bank, slots, a, b, c))
            br.drain_new_keys()
            br.drain_other()
            time.sleep(0.001)
        return polled

    def ticker(br):
        while not stop.is_set():
            for bank in ("histo", "counter", "gauge", "set"):
                br.advance_interval(bank)
            br.slot_scopes("histo")
            br.stats()
            time.sleep(0.05)

    threads = [threading.Thread(target=f, daemon=True) for f in (
        sender, sender, direct_caller,
        lambda: pump(bridges[0]), lambda: pump(bridges[1]),
        lambda: ticker(bridges[0]), lambda: ticker(bridges[1]))]
    for t in threads:
        t.start()
    time.sleep(DURATION_S)
    stop.set()
    for t in threads:
        t.join(5)
    stats = bridges[0].stats()
    for br in bridges:
        br.close()
    assert stats["packets"] > 0 and stats["lines"] > 0, stats
    print(f"tsan stress ok: {stats['lines']} lines through "
          f"{len(threads)} threads")
    return 0


if __name__ == "__main__":
    sys.exit(main())
