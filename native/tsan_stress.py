"""TSAN stress harness for the native ingest bridge.

The concurrency test story for the C++ bridge (SURVEY §5 — the rebuild's
analogue of the reference's `go test -race`): exercise every cross-thread
path at once — SO_REUSEPORT UDP readers, the Python caller's
thread_local staging (two bridges to cover the bridge-scoped memo),
concurrent ring drains (the pump path), new-key/slow-path drains, and
interval advancement with eviction — under ThreadSanitizer.

Run (from repo root; deliberately does NOT import jax/pytest — TSAN
makes them unusably slow):

    make -C native tsan
    LD_PRELOAD=$(g++ -print-file-name=libtsan.so) \
    VENEUR_TPU_NATIVE_LIB=native/build/libvtpu_ingest_tsan.so \
    TSAN_OPTIONS="exitcode=66 suppressions=native/tsan.supp" \
    python native/tsan_stress.py

(the suppression covers only glibc's TLS-teardown false positive —
see native/tsan.supp)

Exit 0 + "tsan stress ok" and no "WARNING: ThreadSanitizer" output means
a clean run; TSAN itself exits 66 on a detected race.
"""

import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from veneur_tpu.ingest import native  # noqa: E402

DURATION_S = float(os.environ.get("TSAN_STRESS_S", "5"))


def main() -> int:
    bridges = [native.NativeBridge(
        histo_slots=256, counter_slots=256, gauge_slots=128,
        set_slots=64, hll_precision=10, idle_ttl=2,
        ring_capacity=65536, max_packet=8192) for _ in range(2)]
    port = bridges[0].start_udp("127.0.0.1", 0, n_readers=2)

    stop = threading.Event()

    def sender():
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        i = 0
        while not stop.is_set():
            s.sendto(
                (f"t{i % 97}:{i % 31}|ms|#env:prod\n"
                 f"c{i % 53}:1|c|@0.5\nu:{i % 1009}|s").encode(),
                ("127.0.0.1", port))
            i += 1

    def direct_caller():
        # alternates bridges from ONE thread: the bridge-scoped
        # thread_local memo must never cross-pollinate
        i = 0
        while not stop.is_set():
            bridges[i % 2].handle_packet(
                f"d{i % 41}:{i % 7}|ms\ng:{i}|g".encode())
            i += 1

    # pre-built SSF datagrams (protobuf import is cheap; jax stays out)
    from veneur_tpu.ssf.protos import ssf_pb2

    def mk_ssf(i):
        sp = ssf_pb2.SSFSpan()
        sp.version = 1
        sp.indicator = bool(i % 3 == 0)
        sp.service = "tsan"
        sp.start_timestamp = 10**18
        sp.end_timestamp = 10**18 + i
        m = sp.metrics.add()
        m.metric = [ssf_pb2.SSFSample.COUNTER, ssf_pb2.SSFSample.GAUGE,
                    ssf_pb2.SSFSample.HISTOGRAM,
                    ssf_pb2.SSFSample.SET][i % 4]
        m.name = f"s{i % 37}"
        m.value = float(i % 13)
        if m.metric == ssf_pb2.SSFSample.SET:
            m.message = f"mem{i % 29}"
        if i % 5 == 0:
            m.tags["env"] = "prod"
        return sp.SerializeToString()

    ssf_datagrams = [mk_ssf(i) for i in range(128)]
    # every 8th datagram carries a STATUS sample -> exercises the
    # fallback (ssf_other) queue under concurrency
    for i in range(0, 128, 8):
        sp = ssf_pb2.SSFSpan()
        s = sp.metrics.add()
        s.metric = ssf_pb2.SSFSample.STATUS
        s.name = "tsan.check"
        s.status = 1
        ssf_datagrams[i] = sp.SerializeToString()
    bridges[0].set_indicator_timer("tsan.indicator")
    ssf_port = bridges[0].start_ssf_udp("127.0.0.1", 0, n_readers=2)

    def ssf_caller():
        # the native SSF decode+stage path, concurrent with UDP
        # readers, pollers, and interval ticks on the same bridge
        i = 0
        while not stop.is_set():
            bridges[0].handle_ssf(ssf_datagrams[i % 128])
            i += 1

    def ssf_sender():
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        i = 0
        while not stop.is_set():
            s.sendto(ssf_datagrams[i % 128], ("127.0.0.1", ssf_port))
            i += 1

    import numpy as np

    def pump(br):
        slots = np.zeros(4096, np.int32)
        a = np.zeros(4096, np.float32)
        b = np.zeros(4096, np.float32)
        c = np.zeros(4096, np.int32)
        polled = 0
        while not stop.is_set():
            for bank in ("histo", "counter", "gauge", "set"):
                polled += max(0, br.poll(bank, slots, a, b, c))
            br.drain_new_keys()
            br.drain_other()
            br.drain_ssf_other()
            time.sleep(0.001)
        return polled

    def ticker(br):
        while not stop.is_set():
            for bank in ("histo", "counter", "gauge", "set"):
                br.advance_interval(bank)
            br.slot_scopes("histo")
            br.stats()
            time.sleep(0.05)

    threads = [threading.Thread(target=f, daemon=True) for f in (
        sender, sender, direct_caller, ssf_caller, ssf_sender,
        lambda: pump(bridges[0]), lambda: pump(bridges[1]),
        lambda: ticker(bridges[0]), lambda: ticker(bridges[1]))]
    for t in threads:
        t.start()
    time.sleep(DURATION_S)
    stop.set()
    for t in threads:
        t.join(5)
    stats = bridges[0].stats()
    for br in bridges:
        br.close()
    assert stats["packets"] > 0 and stats["lines"] > 0, stats
    print(f"tsan stress ok: {stats['lines']} lines through "
          f"{len(threads)} threads")
    return 0


if __name__ == "__main__":
    sys.exit(main())
