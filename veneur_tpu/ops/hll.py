"""Batched HyperLogLog bank — set-cardinality sketches on TPU.

The reference's samplers.Set (samplers/samplers.go sym: Set.Sample /
Set.Combine) wraps a vendored axiomhq/hyperloglog with 2^14 registers;
inserts hash the member string and take max(register, rho); merge is
elementwise register max; estimation uses the LogLog-Beta bias-corrected
harmonic mean.

Here K sets live as one u8[K, m] register matrix. Hashing happens on the
host (the device never sees strings — see veneur_tpu.utils.hashing);
the device ops are scatter-max (insert), elementwise max (merge — which is
also how cross-chip union rides ICI as a single collective), and a
row-reduction (estimate).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class HLLBank(NamedTuple):
    registers: jax.Array  # u8[K, m], m = 2^precision

    @property
    def num_slots(self):
        return self.registers.shape[0]

    @property
    def num_registers(self):
        return self.registers.shape[1]


def init(num_slots: int, precision: int = 14) -> HLLBank:
    return HLLBank(registers=jnp.zeros((num_slots, 1 << precision), jnp.uint8))


def host_hash_to_updates(hashes64, precision: int = 14):
    """Split host-side 64-bit member hashes into (register index, rho).

    index = top `precision` bits; rho = 1 + leading zeros of the remaining
    bits (capped so it fits u8) — the standard HLL decomposition the
    vendored sketch uses.
    """
    import numpy as np
    h = np.asarray(hashes64, np.uint64)
    idx = (h >> np.uint64(64 - precision)).astype(np.int32)
    rest = (h << np.uint64(precision)) | np.uint64((1 << precision) - 1)
    # clz via exact float64 log2 of the top 53 bits (rho is capped at
    # 64 - precision + 1, so truncating the low 11 bits never matters).
    y = (rest >> np.uint64(11)).astype(np.float64)
    clz = np.where(y > 0, 52.0 - np.floor(np.log2(np.maximum(y, 1.0))), 64.0)
    rho = np.minimum(clz + 1.0, 64 - precision + 1).astype(np.uint8)
    return idx, rho


@partial(jax.jit, donate_argnames=("bank",))
def insert(bank: HLLBank, slots, reg_idx, rho) -> HLLBank:
    """Batched Set.Sample: registers[slot, idx] = max(., rho).
    slot == -1 marks padding (dropped via OOB scatter)."""
    K = bank.num_slots
    row = jnp.where(slots >= 0, slots, K)
    return HLLBank(
        registers=bank.registers.at[row, reg_idx].max(
            rho.astype(jnp.uint8), mode="drop"))


@partial(jax.jit, donate_argnames=("bank",))
def merge_rows(bank: HLLBank, slots, registers) -> HLLBank:
    """Batched Set.Combine: union forwarded sketches into local slots.
    `registers` is u8[n, m]; slots[n] == -1 padding."""
    K = bank.num_slots
    row = jnp.where(slots >= 0, slots, K)
    return HLLBank(
        registers=bank.registers.at[row, :].max(registers, mode="drop"))


def merge_banks(a: HLLBank, b: HLLBank) -> HLLBank:
    """Slot-aligned union of two whole banks (the ICI collective is
    jax.lax.pmax of registers over the mesh axis — same op)."""
    return HLLBank(registers=jnp.maximum(a.registers, b.registers))


# LogLog-Beta coefficients for p=14 (m=16384), as used by the vendored
# axiomhq/hyperloglog estimator.
_BETA14 = (-0.370393911, 0.070471823, 0.17393686, 0.16339839,
           -0.09237745, 0.03738027, -0.005384159, 0.00042419)


def _use_pallas() -> bool:
    """Run the streaming Pallas stats kernel on real TPUs (single-pass
    HBM traffic over the u8 register file); plain jnp elsewhere.
    VENEUR_TPU_NO_PALLAS=1 forces the jnp path."""
    import os
    if os.environ.get("VENEUR_TPU_NO_PALLAS", "") not in ("", "0"):
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def will_use_pallas(num_registers: int) -> bool:
    """True when estimate() will take the Pallas kernel for banks of
    this register width. Exposed so mesh program builders can PLACE the
    estimate consistently with this choice: the Pallas kernel belongs
    inside shard_map (device-local block compute, the recommended
    pallas-under-shard_map pattern), while the jnp estimator belongs in
    the plain-jit epilogue (its reductions hit the documented slow
    lowering inside manually-partitioned regions — see
    parallel/mesh.py:_build_flush)."""
    return _use_pallas() and num_registers % 512 == 0


def estimate(bank: HLLBank, force_jnp: bool = False) -> jax.Array:
    """Batched cardinality estimate, one f32 per slot.

    LogLog-Beta estimator: m * alpha * (m - ez) / (beta(ez) + sum 2^-reg),
    with beta a degree-7 polynomial in ln(ez + 1). Valid across the whole
    range (no linear-counting switchover needed).

    `force_jnp` pins the pure-jnp path for callers that manage kernel
    placement themselves (the engine's fused flush builds separate
    executables per choice).
    """
    if not force_jnp and will_use_pallas(bank.num_registers):
        return _estimate_pallas(bank)
    return _estimate_jnp(bank)


@jax.jit
def _estimate_pallas(bank: HLLBank) -> jax.Array:
    from ..kernels.hll_stats import hll_stats
    ez, zsum = hll_stats(bank.registers)
    return _estimate_from_stats(bank, ez, zsum)


@jax.jit
def _estimate_jnp(bank: HLLBank) -> jax.Array:
    regs = bank.registers.astype(jnp.float32)
    ez = jnp.sum(bank.registers == 0, axis=1).astype(jnp.float32)
    zsum = jnp.sum(jnp.exp2(-regs), axis=1)
    return _estimate_from_stats(bank, ez, zsum)


def _estimate_from_stats(bank: HLLBank, ez, zsum) -> jax.Array:
    m = bank.num_registers
    zl = jnp.log(ez + 1.0)
    beta = ez * _BETA14[0]
    acc = zl
    for c in _BETA14[1:]:
        beta = beta + c * acc
        acc = acc * zl
    alpha = 0.7213 / (1.0 + 1.079 / m)
    est = alpha * m * (m - ez) / (beta + zsum)
    # ez == m  <=>  every register is zero  <=>  empty set
    return jnp.where(ez < m, est, 0.0)


def reset(bank: HLLBank) -> HLLBank:
    return HLLBank(registers=jnp.zeros_like(bank.registers))
