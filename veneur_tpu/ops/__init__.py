"""Batched streaming-sketch kernels (the L0 of the framework).

Everything in this package is pure JAX on fixed-shape arrays: sketch *banks*
batched over a slot axis K (one slot = one distinct metric key), so that the
whole per-interval aggregation — the work done sample-by-sample inside
veneur's Worker goroutines (worker.go sym: Worker.ProcessMetric) — becomes a
handful of large XLA programs.
"""
