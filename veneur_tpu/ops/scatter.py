"""Shared scatter/segmented-batch utilities for the sketch banks.

A device batch is a fixed-shape set of parallel arrays (slots[N], values[N],
weights[N]) where slot == -1 marks padding. Every bank turns a batch into
vectorized scatters; the helpers here compute per-slot ranks (position of a
sample among the samples of the same slot within the batch), which is what
lets a scatter into per-slot ring buffers be expressed with static shapes.

The reference processes one sample at a time on the owning goroutine
(worker.go sym: Worker.ProcessMetric); here the same routing is a sort by
slot id plus rank arithmetic, done once per batch for the whole batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sort_by_slot(slots, *arrays, num_slots: int | None = None):
    """Stable-sort a batch by slot id. Padding (slot < 0) is mapped to a
    large id so it sorts to the end. Returns (sorted_slots, *sorted_arrays)
    with padding slots restored to -1.

    When the caller passes `num_slots` and (slot_bits + index_bits) fits
    an int32, the sort runs on ONE packed key (slot << idx_bits | idx)
    instead of a stable argsort: a single-array sort avoids XLA's
    multi-operand comparator path (4.5x faster on the CPU backend at a
    32k batch, measured) and the index in the low bits makes it
    inherently stable. Identical output either way."""
    n = slots.shape[0]
    if num_slots is not None and n > 0:
        idx_bits = max(1, (n - 1).bit_length())
        # pad sentinel is num_slots, so keys span [0, num_slots] slots
        slot_bits = (num_slots + 1).bit_length()
        if slot_bits + idx_bits <= 31:
            # clamp BOTH padding and out-of-range ids to the sentinel:
            # a stray id >= 2^(31-idx_bits) would otherwise overflow
            # the shift and wrap into a valid slot's key range. The
            # returned slots keep their original values, so downstream
            # mode="drop" scatters still discard OOB ids.
            key = jnp.where((slots < 0) | (slots > num_slots),
                            jnp.int32(num_slots),
                            slots).astype(jnp.int32)
            packed = (key << idx_bits) | jnp.arange(n, dtype=jnp.int32)
            order = jnp.sort(packed) & ((1 << idx_bits) - 1)
            return (slots[order],) + tuple(a[order] for a in arrays)
    key = jnp.where(slots < 0, jnp.iinfo(jnp.int32).max, slots)
    order = jnp.argsort(key, stable=True)
    out = tuple(a[order] for a in arrays)
    s = slots[order]
    return (s,) + out


def run_ranks(sorted_slots):
    """Given slot ids sorted ascending, return the 0-based rank of each
    element within its run of equal ids."""
    n = sorted_slots.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_slots[1:] != sorted_slots[:-1]]
    )
    run_start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    return idx - run_start


def run_lasts(sorted_slots):
    """Boolean mask marking the last element of each run of equal slot ids
    (used for last-write-wins gauge semantics)."""
    return jnp.concatenate(
        [sorted_slots[:-1] != sorted_slots[1:], jnp.ones((1,), jnp.bool_)]
    )


def segment_count(slots, mask, num_slots):
    """Count of True-mask samples per slot, dropping out-of-range ids."""
    idx = jnp.where(mask, slots, num_slots)  # OOB scatter index -> dropped
    return jnp.zeros((num_slots,), jnp.int32).at[idx].add(1, mode="drop")
