"""Batched merging t-digest bank — the TPU-native heart of the framework.

The reference keeps one `tdigest.MergingDigest` per distinct histogram/timer
key inside a Go map (tdigest/merging_digest.go sym: MergingDigest.Add /
.mergeAllTemps / .Merge / .Quantile; used by samplers/samplers.go sym:
Histo.Sample / Histo.Combine). Sample adds append to a temp buffer; when the
buffer fills, the centroids+buffer are sorted and greedily re-clustered under
the k1 scale function k(q) = delta * (asin(2q-1) + pi/2) / pi.

This module re-designs that as a *bank*: K digests live in fixed-shape device
arrays and every operation is batched over K, so "compress every digest" is
ONE sorted-run merge + scan over a [K, C+B] array — the shape XLA tiles well
on TPU — instead of 100k independent pointer-chasing loops.

Sort -> merge redesign (the compress hot path): a compress used to row-sort
the full [K, C+B] concatenation of centroids+buffer. But the centroid prefix
[K, :C] is ALREADY cluster-ordered — every _cluster_core output has its
positive-weight means non-decreasing with the zero-weight empties as a
suffix — so only the buffer [K, B] needs sorting (a stable packed-key radix
sort, _stable_sort_perm); the two sorted runs are then combined with an
exact, quantization-free rank-merge — a log-depth bitonic merge network
with lexicographic (canonical key, concatenation-order tag) exchanges
(_merge_sorted_runs) — reproducing the old full stable sort bit-for-bit,
including ±0.0 and duplicate values (lax.sort canonicalizes -0.0 to +0.0
before comparing; the canonical u32 key embeds the same order). This
mirrors the reference's mergeAllTemps, which likewise sorts only the temp
buffer against the already-ordered centroid list.

ORDERING INVARIANT (load-bearing): `mean`/`weight` rows must stay exactly
as _cluster_core emits them — positive-weight means non-decreasing, then
zero-weight empties. quantile() always relied on this to skip a defensive
re-sort; the merge-path compress now relies on it for CORRECTNESS, not just
speed. Only this module may write those fields (vlint SR02 enforces it);
writes elsewhere need a documented suppression proving the order survives.
The old full-row sort stays available for A/B (VENEUR_TPU_TDIGEST_FULL_SORT=1
or the full_sort= argument) until a TPU-live capture confirms the win.

State layout (per bank):
  mean, weight : f32[K, C]   merged centroids (weight 0 == empty slot)
  buf_value, buf_weight : f32[K, B]  unmerged sample buffer
  buf_n  : i32[K]            fill level of each buffer row
  vmin, vmax : f32[K]        exact extremes (+inf / -inf when empty)
  vsum, count, recip : f32[K]  sample-rate-weighted sum / count / sum(w/v)
                               (recip backs the `hmean` aggregate)
  vsum_lo, count_lo, recip_lo : f32[K]  2Sum compensation terms: a hot
                               timer at north-star rates pushes >2^24
                               samples through one slot per interval,
                               saturating plain f32; each batch folds its
                               dense delta into the (hi, lo) pair with an
                               error-free transformation, exactly like the
                               counter bank (scalar.py). Exact totals are
                               float64(hi) + float64(lo) on host.

Semantics parity notes:
  * Sample weight = 1/sample_rate, matching Histo.Sample's weight handling.
  * Compression (delta) defaults to 100 like veneur's config default; the
    centroid axis C is padded to >= delta+2 lanes.
  * Clustering uses the same k1 scale function as the reference; the greedy
    sequential merge is re-expressed as a lax.scan over the sorted axis
    (carrying cluster-start k-values per bank row), which reproduces the
    greedy boundaries exactly, followed by a parallel segment-reduce.
  * Quantile() interpolates between centroid-mean positions at
    (cum - w/2) / W, clamped by exact min/max — the standard merging-digest
    interpolation; parity with the Go implementation is asserted
    distributionally (±1%) in tests, mirroring tdigest/merging_digest_test.go.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import scatter
from .scalar import _two_sum

_INF = jnp.inf

# A/B escape hatch: force the pre-merge-path full-row comparator sort in
# every compress. Read at TRACE time (the environment is consulted when
# each program first compiles, not at import), so setting it any time
# before the first compile works; already-compiled programs keep the arm
# they were traced with. DEPRECATED (ISSUE 11): the merge path has been
# the serving default since ISSUE 3 with a pinned 1.97x win and bitwise
# A/B equivalence; the legacy arm is slated for removal once a TPU-live
# capture (capture_tpu_window.sh) confirms the win on hardware — setting
# the flag now warns loudly so deployments migrate off it first.
_warned_full_sort = False


def _full_sort_default() -> bool:
    on = os.environ.get("VENEUR_TPU_TDIGEST_FULL_SORT", "0") \
        not in ("", "0")
    global _warned_full_sort
    if on and not _warned_full_sort:
        _warned_full_sort = True
        import logging
        import warnings
        msg = ("VENEUR_TPU_TDIGEST_FULL_SORT=1 forces the DEPRECATED "
               "legacy full-row comparator sort in every t-digest "
               "compress (~2x the merge-path cost, bitwise-identical "
               "output). The flag and the legacy arm will be removed "
               "after a TPU-live capture confirms the merge-path win "
               "on hardware (ROADMAP flush item); unset it unless "
               "running the bench A/B.")
        warnings.warn(msg, DeprecationWarning, stacklevel=2)
        logging.getLogger(__name__).warning(msg)
    return on


class TDigestBank(NamedTuple):
    mean: jax.Array        # f32[K, C]
    weight: jax.Array      # f32[K, C]
    buf_value: jax.Array   # f32[K, B]
    buf_weight: jax.Array  # f32[K, B]
    buf_n: jax.Array       # i32[K]
    vmin: jax.Array        # f32[K]
    vmax: jax.Array        # f32[K]
    vsum: jax.Array        # f32[K]
    count: jax.Array       # f32[K]
    recip: jax.Array       # f32[K]
    vsum_lo: jax.Array     # f32[K] 2Sum compensation for vsum
    count_lo: jax.Array    # f32[K] 2Sum compensation for count
    recip_lo: jax.Array    # f32[K] 2Sum compensation for recip

    @property
    def num_slots(self):
        return self.mean.shape[0]

    @property
    def num_centroids(self):
        return self.mean.shape[1]

    @property
    def buf_size(self):
        return self.buf_value.shape[1]


def init(num_slots: int, compression: float = 100.0, buf_size: int = 256,
         dtype=jnp.float32) -> TDigestBank:
    """Fresh bank of `num_slots` empty digests.

    The greedy k1 merge can produce up to ~2*compression clusters in the
    worst case (pairs of adjacent clusters each span >= 1 k-unit of the
    total `compression` k-range), so C is padded to a multiple of 128 lanes
    >= 2*compression + 8 to map onto TPU vector lanes with headroom.
    """
    c = int(math.ceil((2.0 * compression + 8) / 128.0) * 128)
    k = num_slots
    return TDigestBank(
        mean=jnp.zeros((k, c), dtype),
        weight=jnp.zeros((k, c), dtype),
        buf_value=jnp.zeros((k, buf_size), dtype),
        buf_weight=jnp.zeros((k, buf_size), dtype),
        buf_n=jnp.zeros((k,), jnp.int32),
        vmin=jnp.full((k,), _INF, dtype),
        vmax=jnp.full((k,), -_INF, dtype),
        vsum=jnp.zeros((k,), dtype),
        count=jnp.zeros((k,), dtype),
        recip=jnp.zeros((k,), dtype),
        vsum_lo=jnp.zeros((k,), dtype),
        count_lo=jnp.zeros((k,), dtype),
        recip_lo=jnp.zeros((k,), dtype),
    )


def _k1(q, compression):
    """The k1 scale function used by the reference merging digest
    (tdigest/merging_digest.go sym: integratedLocation-equivalent)."""
    q = jnp.clip(q, 0.0, 1.0)
    return compression * (jnp.arcsin(2.0 * q - 1.0) + jnp.pi / 2.0) / jnp.pi


def _compress_impl(bank: TDigestBank, compression: float,
                   full_sort: bool | None = None) -> TDigestBank:
    """Merge every bank row's buffer into its centroid list.

    Equivalent of MergingDigest.mergeAllTemps, batched over K:
      1. concat centroids+buffer -> [K, M]; the centroid prefix is
         already cluster-ordered (the module invariant), so only the
         buffer half is row-sorted and the two runs are rank-merged —
         bit-identical to sorting the whole row at roughly half the
         comparator-sort work (empties sort to +inf with weight 0)
      2. greedy k1 clustering via lax.scan over the sorted axis: an element
         starts a new cluster when k1(q_right) - k1(q_cluster_start) > 1
      3. cluster ids are non-decreasing per row, so per-cluster weighted
         sums reduce to diffs of row cumsums at cluster boundaries
         (searchsorted per row) — no sequential per-digest loop remains.

    `full_sort` (or VENEUR_TPU_TDIGEST_FULL_SORT=1) forces the legacy
    full-row sort — the A/B arm bench.py measures against.
    """
    K, C = bank.mean.shape
    if full_sort is None:
        full_sort = _full_sort_default()

    vals = jnp.concatenate([bank.mean, bank.buf_value], axis=1)
    wts = jnp.concatenate([bank.weight, bank.buf_weight], axis=1)
    new_mean, w_c = _cluster_core(vals, wts, compression, C,
                                  sorted_prefix=0 if full_sort else C)

    return bank._replace(
        mean=new_mean,
        weight=w_c,
        buf_value=jnp.zeros_like(bank.buf_value),
        buf_weight=jnp.zeros_like(bank.buf_weight),
        buf_n=jnp.zeros_like(bank.buf_n),
    )


def _canonical_sort_key(x):
    """f32 -> u32 monotone key reproducing lax.sort's float comparator
    order EXACTLY: jax canonicalizes -0.0 -> +0.0 (and all NaNs to one
    standard NaN) before comparing with `lt`, so after the same zero
    canonicalization the usual sign-magnitude -> biased bit twiddle is
    a strict order-embedding of the comparator's equivalence classes.
    (NaN placement is outside the accuracy contract, as it always was
    for the full-row comparator sort.)"""
    x = jnp.where(x == 0.0, jnp.zeros((), x.dtype), x)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    neg = bits >= jnp.uint32(0x80000000)
    return jnp.where(neg, ~bits, bits | jnp.uint32(0x80000000))


def _stable_sort_perm(key):
    """Stable ascending row-sort of u32 keys, returning (sorted_key,
    perm) with perm the original lane of each sorted position — the
    exact permutation `lax.sort((key, lane))` would produce, computed
    ~3x cheaper on the CPU backend as two LSD-radix passes of
    SINGLE-operand u32 sorts over lossless packed (key-half ‖ 16-bit
    lane) words (multi-operand comparator sorts are the expensive form
    there). Pass 1 orders by the key's low half with the original lane
    as tiebreak; pass 2 by the high half with the pass-1 position as
    tiebreak — the classic stable-radix composition, so ties land in
    original-lane order. No quantization anywhere: the full 32-bit key
    is consumed across the two passes."""
    B = key.shape[1]
    if B > (1 << 16):
        raise ValueError(f"row width {B} exceeds the 16-bit lane pack")
    lane = jax.lax.broadcasted_iota(jnp.uint32, key.shape, 1)
    p1 = jax.lax.sort((key & jnp.uint32(0xFFFF)) << 16 | lane,
                      dimension=-1)
    i1 = (p1 & jnp.uint32(0xFFFF)).astype(jnp.int32)   # original lanes
    hi1 = jnp.take_along_axis(key >> 16, i1, axis=1)   # hi half, p1 order
    p2 = jax.lax.sort(hi1 << 16 | lane, dimension=-1)
    r2 = (p2 & jnp.uint32(0xFFFF)).astype(jnp.int32)   # pass-1 positions
    perm = jnp.take_along_axis(i1, r2, axis=1)
    sorted_key = (p2 & ~jnp.uint32(0xFFFF)) \
        | jnp.take_along_axis(p1 >> 16, r2, axis=1)
    return sorted_key, perm


def _merge_sorted_runs(akey, bkey, S: int, M: int):
    """Exact rank-merge of two row-sorted u32 key runs — akey [K, S]
    (the cluster-ordered centroid prefix) and bkey [K, M-S] (the
    freshly sorted buffer) — returning the merged CONCATENATION-ORDER
    TAGS [K, M]: tag t < S is prefix lane t, tag >= S is sorted-buffer
    position t-S. Gathering payloads through the tags is bit-for-bit
    the stable `lax.sort` of the whole row.

    Executed as a log-depth BITONIC MERGE network rather than the
    textbook searchsorted-both-ways + scatter: on the CPU backend the
    explicit form measured ~3.5s (per-element binary search is
    gather-bound) + ~13s (XLA scatter is a per-element loop) @100k x
    512, while the network is log2(M) stages of elementwise
    compare-exchanges — [prefix | pad(max) | reversed(buffer)] is
    bitonic, and merging carries only (key, tag), with the payloads
    gathered once afterwards. Each exchange compares lexicographic
    (canonical key, tag): the tag makes every element distinct, which
    (a) turns the network's fixed exchange pattern into a deterministic
    total order — comparison networks are not otherwise stable — and
    (b) encodes exactly the stable sort's tie-break: prefix lanes
    before buffer lanes at equal value, and within each run the
    original (stable) order."""
    K = akey.shape[0]
    P = 1 << (M - 1).bit_length()          # pad to a power of two
    pad = P - M
    # pads sit between the ascending and descending runs, keyed above
    # every real key (0xFFFFFFFF, the canonical-key maximum) and tagged
    # past every real tag, so the padded sequence stays bitonic and the
    # pads sink to the row tail; ties among pads are broken by tag.
    # Tags are u16 when P allows (halves the network's memory traffic);
    # strict < so the `+ tdt(M)` pad-tag base stays representable even
    # at the P == M == 65536 boundary, where pad is 0 but the constant
    # is still evaluated at trace time.
    tdt = jnp.uint16 if P < (1 << 16) else jnp.uint32
    padk = jnp.full((K, pad), jnp.uint32(0xFFFFFFFF))
    key = jnp.concatenate([akey, padk, bkey[:, ::-1]], axis=1)
    atag = jax.lax.broadcasted_iota(tdt, (K, S), 1)
    ptag = jax.lax.broadcasted_iota(tdt, (K, pad), 1) + tdt(M)
    btag = jax.lax.broadcasted_iota(tdt, (K, M - S), 1) + tdt(S)
    tag = jnp.concatenate([atag, ptag, btag[:, ::-1]], axis=1)

    stride = P // 2
    while stride >= 1:
        shape = (K, P // (2 * stride), 2, stride)
        k4 = key.reshape(shape)
        t4 = tag.reshape(shape)
        klo, khi = k4[:, :, 0, :], k4[:, :, 1, :]
        tlo, thi = t4[:, :, 0, :], t4[:, :, 1, :]
        swap = (klo > khi) | ((klo == khi) & (tlo > thi))
        key = jnp.stack([jnp.where(swap, khi, klo),
                         jnp.where(swap, klo, khi)], axis=2) \
            .reshape(K, P)
        tag = jnp.stack([jnp.where(swap, thi, tlo),
                         jnp.where(swap, tlo, thi)], axis=2) \
            .reshape(K, P)
        stride //= 2
    return tag[:, :M].astype(jnp.int32)


def _cluster_core(vals, wts, compression: float, C: int,
                  sorted_prefix: int = 0):
    """Greedy k1 clustering of arbitrary [K, M] (value, weight) rows into
    at most C centroids per row — the shared core of compress and the
    batched foreign-digest merge. Zero-weight entries are padding.

    `sorted_prefix=S` asserts vals[:, :S] is already cluster-ordered
    (positive-weight values non-decreasing, zero-weight entries last —
    the module's ordering invariant); then only vals[:, S:] is row-sorted
    and the runs are rank-merged, bit-identical to the full sort. Callers
    must only pass S > 0 for prefixes they can PROVE ordered — an
    unordered prefix silently mis-clusters."""
    K, M = vals.shape
    vals = jnp.where(wts > 0, vals, _INF)

    # Value order must be EXACT here: a quantized packed-key sort (float
    # monotonic bits | column index in an int32) was measured ~4x faster
    # on the CPU backend but shifts cluster membership by ±1 element at
    # quantization-step distances — a 9% p50 swing on bimodal gap data,
    # outside the pinned 1%-of-range accuracy contract. That rejection
    # is superseded by the sorted-run merge above: it removes most of
    # the comparator-sort work while keeping value order bit-exact.
    # (The ingest kernel's packed sort, scatter.sort_by_slot, is
    # different — its key is the integer slot id, packed losslessly.)
    if 0 < sorted_prefix < M:
        S = sorted_prefix
        akey = _canonical_sort_key(vals[:, :S])
        bkey, perm = _stable_sort_perm(
            _canonical_sort_key(vals[:, S:]))
        tags = _merge_sorted_runs(akey, bkey, S, M)
        # tag t: prefix lane t when t < S, else sorted-buffer position
        # t-S -> original buffer lane through stage 1's permutation
        src = jnp.where(
            tags < S, tags,
            S + jnp.take_along_axis(
                perm, jnp.clip(tags - S, 0, M - S - 1), axis=1))
        vals = jnp.take_along_axis(vals, src, axis=1)
        wts = jnp.take_along_axis(wts, src, axis=1)
    elif sorted_prefix >= M:
        pass  # the whole row is one ordered run — nothing to do
    else:
        vals, wts = jax.lax.sort((vals, wts), dimension=-1, num_keys=1)

    def boundaries(k_left, k_right, wts):
        # Greedy cluster boundaries, scanned over the sorted axis
        # (length M), carrying per-row k-value at current cluster
        # start. Initial carry is derived from data (k_left[:,0] - 2
        # <= any k minus 1, so the first weighted element always opens
        # a cluster) rather than a constant: inside shard_map a
        # constant carry would lack the varying mesh-axes type and
        # fail the scan type check.
        def step(k_start, xs):
            kl, kr, w = xs
            new = (kr - k_start > 1.0) & (w > 0)
            k_start = jnp.where(new, kl, k_start)
            return k_start, new

        _, is_new = jax.lax.scan(
            step,
            k_left[:, 0] - 2.0,
            (k_left.T, k_right.T, wts.T),
        )
        return is_new.T                                  # [K, M] bool

    return _cluster_tail(vals, wts, compression, C, boundaries)


def _cluster_tail(vals, wts, compression: float, C: int, boundary_fn):
    """The numeric tail of the greedy clustering, shared VERBATIM by
    the XLA compress (_cluster_core) and the fused Pallas kernel
    (kernels/compress.py): both arms' bit-identity contract rests on
    this being ONE definition — only the greedy boundary recurrence's
    loop FORM differs per arm (lax.scan for XLA/shard_map, a fori_loop
    for Mosaic; compare/select only, so any form is bit-equal), which
    is why it arrives as `boundary_fn(k_left, k_right, wts) ->
    is_new[K, M] bool`. Inputs are the SORTED (value, weight) rows
    (empties already +inf-keyed, weight 0)."""
    K, M = vals.shape
    total = jnp.sum(wts, axis=1, keepdims=True)          # [K, 1]
    safe_total = jnp.where(total > 0, total, 1.0)
    cum = jnp.cumsum(wts, axis=1)                        # [K, M] right edges

    k_right = _k1(cum / safe_total, compression)         # [K, M]
    k_left = _k1((cum - wts) / safe_total, compression)  # [K, M]
    is_new = boundary_fn(k_left, k_right, wts)           # [K, M] bool

    cluster = jnp.cumsum(is_new.astype(jnp.int32), axis=1) - 1  # [K, M]
    cluster = jnp.where(wts > 0, cluster, C - 1)  # empties -> last cluster id
    cluster = jnp.clip(cluster, 0, C - 1)  # pathological-overflow safety

    # Per-cluster sums = diff of cumsums at cluster end positions.
    # Empties carry value +inf for the SORT only; in the weighted sum
    # they must contribute 0, not 0*inf=NaN — a NaN here poisons the
    # cumsum for every element after the first empty whenever a row
    # holds a real +inf, and a NaN mean in the output prefix would make
    # the next compress's ordering comparator-undefined in both arms.
    cw = jnp.cumsum(wts, axis=1)
    cwv = jnp.cumsum(wts * jnp.where(wts > 0, vals, 0.0), axis=1)
    targets = jnp.arange(C, dtype=jnp.int32)

    ends = jax.vmap(lambda row: jnp.searchsorted(row, targets, side="right"))(
        cluster
    )                                                    # [K, C] in [0, M]

    def gather_at(c, idx):
        padded = jnp.concatenate([jnp.zeros((K, 1), c.dtype), c], axis=1)
        return jnp.take_along_axis(padded, idx, axis=1)

    w_upto = gather_at(cw, ends)
    wv_upto = gather_at(cwv, ends)
    w_c = jnp.diff(w_upto, axis=1, prepend=jnp.zeros((K, 1), cw.dtype))
    wv_c = jnp.diff(wv_upto, axis=1, prepend=jnp.zeros((K, 1), cw.dtype))

    # The empties parked on cluster C-1 contributed weight 0, so no mask
    # fixup is needed; real data can also land on C-1 legitimately.
    new_mean = jnp.where(w_c > 0, wv_c / jnp.where(w_c > 0, w_c, 1.0), 0.0)
    # Enforce the ordering invariant EXACTLY: consecutive clusters
    # partition a sorted row, so their exact means are non-decreasing —
    # but the f32 rounding of the cumsum-diff / division above can nudge
    # a mean a couple of ulp past its successor. The merge-path compress
    # consumes this output as an already-sorted run, so a rounding-level
    # inversion would silently reorder the next merge. A running max
    # over the positive-weight prefix pins the invariant at <= a few ulp
    # of adjustment (far inside the accuracy contract), identically in
    # both sort arms — A/B stays bitwise-equal.
    new_mean = jnp.where(
        w_c > 0,
        jax.lax.cummax(jnp.where(w_c > 0, new_mean, -_INF), axis=1),
        0.0)
    return new_mean, w_c


compress = partial(jax.jit, static_argnames=("compression", "full_sort"),
                   donate_argnames=("bank",))(_compress_impl)


@partial(jax.jit, static_argnames=("compression", "num_centroids",
                                   "sorted_prefix"))
def cluster_rows(values, weights, compression: float = 100.0,
                 num_centroids: int = 256, sorted_prefix: int = 0):
    """Cluster arbitrary padded centroid rows: f32[S, M] x2 ->
    (means f32[S, C], weights f32[S, C]).

    The batched foreign-digest merge for the global tier: a whole
    interval's forwarded digests, grouped per slot and padded into one
    matrix, collapse to <= C centroids per slot in ONE device program —
    instead of squeezing thousands of digests through the B-sized sample
    buffer with a compress pass per chunk (importsrv's Combine loop,
    worker.go sym: Worker.ImportMetricGRPC, turned into a batch op).

    Foreign rows arrive unordered, so the default is the full row sort.
    `sorted_prefix=S` is the fast arm for re-merge call sites that can
    PROVE values[:, :S] is cluster-ordered in every row (e.g. the
    importsrv re-chunk passes whose rows lead with a previous
    cluster_rows output) — never pass it for untrusted payloads."""
    return _cluster_core(values, weights, compression, num_centroids,
                         sorted_prefix=sorted_prefix)


def _add_batch_impl(bank: TDigestBank, slots, values, weights,
                    compression: float = 100.0,
                    full_sort: bool | None = None) -> TDigestBank:
    """Scatter a batch of (slot, value, weight) samples into the bank.

    Batched equivalent of Histo.Sample -> MergingDigest.Add. Samples append
    to per-slot buffers; rows that would overflow trigger a (batched)
    compress and the leftover samples are re-scattered, looping until the
    batch is fully absorbed (ceil(max_per_slot / B) iterations worst case).
    slot == -1 marks padding and is dropped via out-of-bounds scatter.
    `full_sort` reaches the overflow loop's compress (A/B arm selection).
    """
    K = bank.num_slots
    B = bank.buf_size

    s, v, w = scatter.sort_by_slot(slots, values, weights, num_slots=K)
    rank = scatter.run_ranks(s)
    valid = s >= 0
    sd = jnp.where(valid, s, K)  # OOB -> dropped by mode="drop"

    # Exact scalar statistics never need the buffer: pure segment reduces.
    # Sums fold through the 2Sum hi/lo pairs — the per-batch delta is a
    # dense f32 scatter-add (a batch holds at most `batch` samples per
    # slot, so the delta itself is near-exact), then the running totals
    # absorb it with an error-free transformation (scalar.py counters).
    dsum = jnp.zeros_like(bank.vsum).at[sd].add(w * v, mode="drop")
    dcount = jnp.zeros_like(bank.count).at[sd].add(w, mode="drop")
    drecip = jnp.zeros_like(bank.recip).at[sd].add(
        jnp.where(v != 0, w / jnp.where(v != 0, v, 1.0), 0.0), mode="drop")
    vsum, vsum_lo = _two_sum(bank.vsum, dsum + bank.vsum_lo)
    count, count_lo = _two_sum(bank.count, dcount + bank.count_lo)
    recip, recip_lo = _two_sum(bank.recip, drecip + bank.recip_lo)
    bank = bank._replace(
        vmin=bank.vmin.at[sd].min(jnp.where(valid, v, _INF), mode="drop"),
        vmax=bank.vmax.at[sd].max(jnp.where(valid, v, -_INF), mode="drop"),
        vsum=vsum, count=count, recip=recip,
        vsum_lo=vsum_lo, count_lo=count_lo, recip_lo=recip_lo,
    )

    def write_pass(bank, written):
        """One buffer-write pass: land every not-yet-written sample
        whose position fits its slot's buffer. Returns the updated
        bank and written mask."""
        # Rank among the not-yet-written samples of each slot: ranks are
        # consumed in order, so subtracting the per-slot written count
        # re-bases them.
        done_per_slot = scatter.segment_count(s, written & valid, K)
        pos = bank.buf_n[jnp.where(valid, s, 0)] + rank - done_per_slot[
            jnp.where(valid, s, 0)]
        can = valid & ~written & (pos < B)
        row = jnp.where(can, s, K)
        col = jnp.clip(pos, 0, B - 1)
        new_bv = bank.buf_value.at[row, col].set(v, mode="drop")
        new_bw = bank.buf_weight.at[row, col].set(w, mode="drop")
        wrote = scatter.segment_count(s, can, K)
        bank = bank._replace(buf_value=new_bv, buf_weight=new_bw,
                             buf_n=bank.buf_n + wrote)
        return bank, written | can

    def cond(state):
        _, written = state
        return jnp.any(valid & ~written)

    def body(state):
        bank, written = state
        bank, written = write_pass(bank, written)
        leftover = jnp.any(valid & ~written)
        bank = jax.lax.cond(
            leftover,
            lambda b: _compress_impl(b, compression, full_sort),
            lambda b: b,
            bank,
        )
        return bank, written

    def loop_path(bank):
        bank, _ = jax.lax.while_loop(
            cond, body, (bank, jnp.zeros_like(valid)))
        return bank

    def fast_path(bank):
        # the overflow predicate guarantees every valid sample fits, so
        # positions are direct (no done/wrote segment scatters needed —
        # the per-slot batch counts were already materialized for the
        # predicate itself)
        pos = bank.buf_n[jnp.where(valid, s, 0)] + rank
        row = jnp.where(valid, s, K)
        col = jnp.clip(pos, 0, B - 1)
        return bank._replace(
            buf_value=bank.buf_value.at[row, col].set(v, mode="drop"),
            buf_weight=bank.buf_weight.at[row, col].set(w, mode="drop"),
            buf_n=bank.buf_n + batch_per_slot)

    # The common case — no slot's buffer overflows — needs exactly one
    # write pass; the while_loop's carried-state machinery costs ~25%
    # of the dispatch on the CPU backend even when it runs one
    # iteration. Branch on the actual overflow condition (per-slot
    # batch count + current fill vs capacity) and keep the loop for
    # the hot-slot case only.
    batch_per_slot = scatter.segment_count(s, valid, K)
    overflows = jnp.any(bank.buf_n + batch_per_slot > B)
    return jax.lax.cond(overflows, loop_path, fast_path, bank)


add_batch = partial(jax.jit, static_argnames=("compression", "full_sort"),
                    donate_argnames=("bank",))(_add_batch_impl)


@partial(jax.jit, donate_argnames=("bank",))
def merge_centroids(bank: TDigestBank, slots, means, weights) -> TDigestBank:
    """Append foreign centroids (e.g. a forwarded digest's) into per-slot
    buffers, to be absorbed by the next compress.

    Batched equivalent of MergingDigest.Merge / Histo.Combine
    (samplers/samplers.go sym: Histo.Combine): merging a digest is just
    re-adding its centroids as weighted samples. Callers must compress
    first if buffers may overflow (the engine guarantees headroom).
    `slots`/`means`/`weights` are flat arrays, one entry per centroid,
    slot == -1 padding. Scalar stats (min/max/sum/count) are merged
    separately via `merge_scalars` since they are exact, not sketched.
    """
    K, B = bank.num_slots, bank.buf_size
    # Zero-weight padding centroids must not consume ranks (they'd shift
    # buffer positions and corrupt later writes), so mask them to slot -1
    # before the sort.
    slots = jnp.where(weights > 0, slots, -1)
    s, v, w = scatter.sort_by_slot(slots, means, weights, num_slots=K)
    rank = scatter.run_ranks(s)
    valid = (s >= 0) & (w > 0)
    pos = bank.buf_n[jnp.where(valid, s, 0)] + rank
    can = valid & (pos < B)
    row = jnp.where(can, s, K)
    col = jnp.clip(pos, 0, B - 1)
    return bank._replace(
        buf_value=bank.buf_value.at[row, col].set(v, mode="drop"),
        buf_weight=bank.buf_weight.at[row, col].set(w, mode="drop"),
        buf_n=bank.buf_n + scatter.segment_count(s, can, K),
    )


@partial(jax.jit, donate_argnames=("bank",))
def merge_scalars(bank: TDigestBank, slots, vmins, vmaxs, vsums, counts,
                  recips) -> TDigestBank:
    """Merge the exact per-digest scalar stats of forwarded digests."""
    K = bank.num_slots
    valid = slots >= 0
    sd = jnp.where(valid, slots, K)
    dsum = jnp.zeros_like(bank.vsum).at[sd].add(
        jnp.where(valid, vsums, 0.0), mode="drop")
    dcount = jnp.zeros_like(bank.count).at[sd].add(
        jnp.where(valid, counts, 0.0), mode="drop")
    drecip = jnp.zeros_like(bank.recip).at[sd].add(
        jnp.where(valid, recips, 0.0), mode="drop")
    vsum, vsum_lo = _two_sum(bank.vsum, dsum + bank.vsum_lo)
    count, count_lo = _two_sum(bank.count, dcount + bank.count_lo)
    recip, recip_lo = _two_sum(bank.recip, drecip + bank.recip_lo)
    return bank._replace(
        vmin=bank.vmin.at[sd].min(jnp.where(valid, vmins, _INF), mode="drop"),
        vmax=bank.vmax.at[sd].max(jnp.where(valid, vmaxs, -_INF), mode="drop"),
        vsum=vsum, count=count, recip=recip,
        vsum_lo=vsum_lo, count_lo=count_lo, recip_lo=recip_lo,
    )


def merge_banks(a: TDigestBank, b: TDigestBank,
                compression: float = 100.0) -> TDigestBank:
    """Slot-aligned union of two whole banks, BIT-COMMUTATIVE:
    merge_banks(a, b) == merge_banks(b, a) bit-for-bit (the sketch-
    engine property contract, tests/test_sketches.py). Both banks are
    compressed, their centroid rows concatenated and CANONICALLY
    sorted — lexicographic (canonical value key, weight bits, empties
    strictly last), so the sorted multiset is order-independent — then
    re-clustered through the ordinary k1 core. Scalar stats merge in
    f64 (each 2Sum pair's exact value is f64(hi)+f64(lo); f64 addition
    of the two exact values is commutative, unlike chained _two_sum
    folds). Host-level API (the import/oracle path), not a serving
    kernel."""
    a = _compress_impl(a, compression)
    b = _compress_impl(b, compression)
    C = a.num_centroids
    vals = jnp.concatenate([a.mean, b.mean], axis=1)
    wts = jnp.concatenate([a.weight, b.weight], axis=1)
    kv = _canonical_sort_key(jnp.where(wts > 0, vals, _INF))
    # weights are non-negative, so their raw bits are order-monotone;
    # empties key ABOVE any real weight so they sort strictly last even
    # against genuine +inf values
    kw = jnp.where(wts > 0,
                   jax.lax.bitcast_convert_type(wts, jnp.uint32),
                   jnp.uint32(0xFFFFFFFF))
    _kv, _kw, vals, wts = jax.lax.sort((kv, kw, vals, wts), dimension=-1,
                                       num_keys=2)
    mean, weight = _cluster_core(vals, wts, compression, C,
                                 sorted_prefix=vals.shape[1])
    # the bit-commutative f64 scalar merge is single-homed in
    # sketches/base.py (the engines' shared property contract);
    # imported at call time — module-level would cycle through the
    # sketches package's engine adapters back into this module
    from ..sketches.base import merge_scalar_banks_np
    scal = {k: jnp.asarray(v)
            for k, v in merge_scalar_banks_np(a, b).items()}
    return a._replace(mean=mean, weight=weight, **scal)


@jax.jit
def quantile(bank: TDigestBank, qs) -> jax.Array:
    """Batched MergingDigest.Quantile: [K] digests x [P] quantiles -> [K, P].

    Requires compressed, cluster-ordered state (empty buffers) — the
    output of _compress_impl/_cluster_core: per-row means non-decreasing
    over the positive-weight prefix, with zero-weight empties as a
    suffix (cluster ids are consecutive by construction, so an interior
    cluster always has weight > 0; the cummax clamp in _cluster_core
    makes the ordering exact, and vlint SR02 forbids outside writes).
    Every caller compresses first, which is why no defensive re-sort
    happens here: it would be a second full row sort per flush,
    measured at ~30% of the whole CPU flush @100k.

    Centroid i's mass is centered at quantile (cum_i - w_i/2) / W;
    linear interpolation between adjacent centroid means, clamped into
    [vmin, vmax], with the min/max themselves used below the first / above
    the last centroid midpoint (matching the reference's edge handling).
    """
    K, C = bank.mean.shape
    qs = jnp.asarray(qs, bank.mean.dtype)
    P = qs.shape[0]

    means, w = bank.mean, bank.weight

    total = jnp.sum(w, axis=1, keepdims=True)
    safe_total = jnp.where(total > 0, total, 1.0)
    cum = jnp.cumsum(w, axis=1)
    mid_q = (cum - w / 2.0) / safe_total                 # [K, C]
    # Empty clusters (sorted to the end) become duplicate q=1 knots with
    # value vmax, keeping knot_q ascending for jnp.interp.
    mid_q = jnp.where(w > 0, mid_q, 1.0)

    # Build interpolation knots: (0 -> vmin), (mid_q_i -> mean_i), (1 -> vmax)
    knot_q = jnp.concatenate(
        [jnp.zeros((K, 1), mid_q.dtype), mid_q,
         jnp.full((K, 1), 1.0, mid_q.dtype)], axis=1)
    vmin = jnp.where(jnp.isfinite(bank.vmin), bank.vmin, 0.0)[:, None]
    vmax = jnp.where(jnp.isfinite(bank.vmax), bank.vmax, 0.0)[:, None]
    knot_v = jnp.concatenate([vmin, jnp.where(w > 0, means, vmax), vmax],
                             axis=1)

    out = _interp_knots(knot_q, knot_v, qs)
    # Empty digests -> 0 (host layer skips unallocated slots anyway).
    return jnp.where(total > 0, out, 0.0)


def _interp_knots(knot_q, knot_v, qs):
    """Row-wise linear interpolation at qs over ascending knots —
    [K, M] x [P] -> [K, P] — with NO gathers.

    jnp.interp's searchsorted+gather lowers to a pathologically slow
    per-element path under the SPMD partitioner (shard_map), which made
    the mesh flush ~1000x slower than the single-chip program. Because
    knot_q is ascending per row, `knot_q < q` is a prefix mask, so the
    bracketing knots are the mask's last-True / first-False boundary
    positions, recoverable with masked reductions (elementwise ops only —
    partitioner-friendly on every path).
    """
    # Static unroll over the (small) P axis: keeping every intermediate
    # [K, M] leaves M in the lane dimension — a [K, M, P] broadcast would
    # put P (often 2-4) minor-most and waste 126/128 lanes per tile.
    if qs.shape[0] == 0:
        return jnp.zeros((knot_q.shape[0], 0), knot_q.dtype)
    zero = jnp.zeros((), knot_q.dtype)
    cols = []
    for p in range(qs.shape[0]):
        q = qs[p]
        mask = knot_q < q                              # [K, M] prefix
        nxt = jnp.concatenate(
            [mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1)
        lo_b = mask & ~nxt                             # last True
        prv = jnp.concatenate(
            [jnp.ones_like(mask[:, :1]), mask[:, :-1]], axis=1)
        hi_b = (~mask) & prv                           # first False
        q_lo = jnp.sum(jnp.where(lo_b, knot_q, zero), axis=1)   # [K]
        v_lo = jnp.sum(jnp.where(lo_b, knot_v, zero), axis=1)
        q_hi = jnp.sum(jnp.where(hi_b, knot_q, zero), axis=1)
        v_hi = jnp.sum(jnp.where(hi_b, knot_v, zero), axis=1)
        denom = q_hi - q_lo
        t = jnp.where(denom > 0,
                      (q - q_lo) / jnp.where(denom > 0, denom, 1.0), 0.0)
        out = v_lo + t * (v_hi - v_lo)
        # q at/below the first knot: prefix mask empty -> first value
        cols.append(jnp.where(jnp.any(mask, axis=1), out, knot_v[:, 0]))
    return jnp.stack(cols, axis=1)


@jax.jit
def aggregates(bank: TDigestBank):
    """The non-percentile flush aggregates of samplers.Histo
    (samplers/samplers.go sym: HistogramAggregates): max, min, sum, avg,
    count, hmean (median comes from quantile(0.5)).

    The single fold hi + lo here rounds once (relative error ~2^-24) —
    fine for on-device consumers; hosts needing exact counts past 2^24
    read the bank's (hi, lo) pairs directly and sum in float64."""
    cnt = bank.count + bank.count_lo
    vsum = bank.vsum + bank.vsum_lo
    recip = bank.recip + bank.recip_lo
    safe = jnp.where(cnt > 0, cnt, 1.0)
    return {
        "min": jnp.where(cnt > 0, bank.vmin, 0.0),
        "max": jnp.where(cnt > 0, bank.vmax, 0.0),
        "sum": vsum,
        "count": cnt,
        "avg": jnp.where(cnt > 0, vsum / safe, 0.0),
        "hmean": jnp.where(recip > 0, cnt / jnp.where(
            recip > 0, recip, 1.0), 0.0),
    }


def reset(bank: TDigestBank) -> TDigestBank:
    """Fresh interval state with the same shapes (the Worker.Flush map-swap
    equivalent, worker.go sym: Worker.Flush)."""
    k = bank.num_slots
    dt = bank.mean.dtype
    return TDigestBank(
        mean=jnp.zeros_like(bank.mean),
        weight=jnp.zeros_like(bank.weight),
        buf_value=jnp.zeros_like(bank.buf_value),
        buf_weight=jnp.zeros_like(bank.buf_weight),
        buf_n=jnp.zeros_like(bank.buf_n),
        vmin=jnp.full((k,), _INF, dt),
        vmax=jnp.full((k,), -_INF, dt),
        vsum=jnp.zeros((k,), dt),
        count=jnp.zeros((k,), dt),
        recip=jnp.zeros((k,), dt),
        vsum_lo=jnp.zeros((k,), dt),
        count_lo=jnp.zeros((k,), dt),
        recip_lo=jnp.zeros((k,), dt),
    )
