"""Batched merging t-digest bank — the TPU-native heart of the framework.

The reference keeps one `tdigest.MergingDigest` per distinct histogram/timer
key inside a Go map (tdigest/merging_digest.go sym: MergingDigest.Add /
.mergeAllTemps / .Merge / .Quantile; used by samplers/samplers.go sym:
Histo.Sample / Histo.Combine). Sample adds append to a temp buffer; when the
buffer fills, the centroids+buffer are sorted and greedily re-clustered under
the k1 scale function k(q) = delta * (asin(2q-1) + pi/2) / pi.

This module re-designs that as a *bank*: K digests live in fixed-shape device
arrays and every operation is batched over K, so "compress every digest" is
ONE sort + scan over a [K, C+B] array — the shape XLA tiles well on TPU —
instead of 100k independent pointer-chasing loops.

State layout (per bank):
  mean, weight : f32[K, C]   merged centroids (weight 0 == empty slot)
  buf_value, buf_weight : f32[K, B]  unmerged sample buffer
  buf_n  : i32[K]            fill level of each buffer row
  vmin, vmax : f32[K]        exact extremes (+inf / -inf when empty)
  vsum, count, recip : f32[K]  sample-rate-weighted sum / count / sum(w/v)
                               (recip backs the `hmean` aggregate)
  vsum_lo, count_lo, recip_lo : f32[K]  2Sum compensation terms: a hot
                               timer at north-star rates pushes >2^24
                               samples through one slot per interval,
                               saturating plain f32; each batch folds its
                               dense delta into the (hi, lo) pair with an
                               error-free transformation, exactly like the
                               counter bank (scalar.py). Exact totals are
                               float64(hi) + float64(lo) on host.

Semantics parity notes:
  * Sample weight = 1/sample_rate, matching Histo.Sample's weight handling.
  * Compression (delta) defaults to 100 like veneur's config default; the
    centroid axis C is padded to >= delta+2 lanes.
  * Clustering uses the same k1 scale function as the reference; the greedy
    sequential merge is re-expressed as a lax.scan over the sorted axis
    (carrying cluster-start k-values per bank row), which reproduces the
    greedy boundaries exactly, followed by a parallel segment-reduce.
  * Quantile() interpolates between centroid-mean positions at
    (cum - w/2) / W, clamped by exact min/max — the standard merging-digest
    interpolation; parity with the Go implementation is asserted
    distributionally (±1%) in tests, mirroring tdigest/merging_digest_test.go.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import scatter
from .scalar import _two_sum

_INF = jnp.inf


class TDigestBank(NamedTuple):
    mean: jax.Array        # f32[K, C]
    weight: jax.Array      # f32[K, C]
    buf_value: jax.Array   # f32[K, B]
    buf_weight: jax.Array  # f32[K, B]
    buf_n: jax.Array       # i32[K]
    vmin: jax.Array        # f32[K]
    vmax: jax.Array        # f32[K]
    vsum: jax.Array        # f32[K]
    count: jax.Array       # f32[K]
    recip: jax.Array       # f32[K]
    vsum_lo: jax.Array     # f32[K] 2Sum compensation for vsum
    count_lo: jax.Array    # f32[K] 2Sum compensation for count
    recip_lo: jax.Array    # f32[K] 2Sum compensation for recip

    @property
    def num_slots(self):
        return self.mean.shape[0]

    @property
    def num_centroids(self):
        return self.mean.shape[1]

    @property
    def buf_size(self):
        return self.buf_value.shape[1]


def init(num_slots: int, compression: float = 100.0, buf_size: int = 256,
         dtype=jnp.float32) -> TDigestBank:
    """Fresh bank of `num_slots` empty digests.

    The greedy k1 merge can produce up to ~2*compression clusters in the
    worst case (pairs of adjacent clusters each span >= 1 k-unit of the
    total `compression` k-range), so C is padded to a multiple of 128 lanes
    >= 2*compression + 8 to map onto TPU vector lanes with headroom.
    """
    c = int(math.ceil((2.0 * compression + 8) / 128.0) * 128)
    k = num_slots
    return TDigestBank(
        mean=jnp.zeros((k, c), dtype),
        weight=jnp.zeros((k, c), dtype),
        buf_value=jnp.zeros((k, buf_size), dtype),
        buf_weight=jnp.zeros((k, buf_size), dtype),
        buf_n=jnp.zeros((k,), jnp.int32),
        vmin=jnp.full((k,), _INF, dtype),
        vmax=jnp.full((k,), -_INF, dtype),
        vsum=jnp.zeros((k,), dtype),
        count=jnp.zeros((k,), dtype),
        recip=jnp.zeros((k,), dtype),
        vsum_lo=jnp.zeros((k,), dtype),
        count_lo=jnp.zeros((k,), dtype),
        recip_lo=jnp.zeros((k,), dtype),
    )


def _k1(q, compression):
    """The k1 scale function used by the reference merging digest
    (tdigest/merging_digest.go sym: integratedLocation-equivalent)."""
    q = jnp.clip(q, 0.0, 1.0)
    return compression * (jnp.arcsin(2.0 * q - 1.0) + jnp.pi / 2.0) / jnp.pi


def _compress_impl(bank: TDigestBank, compression: float) -> TDigestBank:
    """Merge every bank row's buffer into its centroid list.

    Equivalent of MergingDigest.mergeAllTemps, batched over K:
      1. concat centroids+buffer -> [K, M], sort rows by value
         (empties sort to +inf with weight 0)
      2. greedy k1 clustering via lax.scan over the sorted axis: an element
         starts a new cluster when k1(q_right) - k1(q_cluster_start) > 1
      3. cluster ids are non-decreasing per row, so per-cluster weighted
         sums reduce to diffs of row cumsums at cluster boundaries
         (searchsorted per row) — no sequential per-digest loop remains.
    """
    K, C = bank.mean.shape

    vals = jnp.concatenate([bank.mean, bank.buf_value], axis=1)
    wts = jnp.concatenate([bank.weight, bank.buf_weight], axis=1)
    new_mean, w_c = _cluster_core(vals, wts, compression, C)

    return bank._replace(
        mean=new_mean,
        weight=w_c,
        buf_value=jnp.zeros_like(bank.buf_value),
        buf_weight=jnp.zeros_like(bank.buf_weight),
        buf_n=jnp.zeros_like(bank.buf_n),
    )


def _cluster_core(vals, wts, compression: float, C: int):
    """Greedy k1 clustering of arbitrary [K, M] (value, weight) rows into
    at most C centroids per row — the shared core of compress and the
    batched foreign-digest merge. Zero-weight entries are padding."""
    K, M = vals.shape
    vals = jnp.where(wts > 0, vals, _INF)

    # Row sort: the exact multi-operand comparator sort, deliberately.
    # A quantized packed-key sort (float monotonic bits | column index
    # in an int32) is ~4x faster on the CPU backend, but reordering
    # values closer than the quantization step shifts cluster
    # membership by ±1 element — and at a bimodal gap the interpolated
    # median is knife-edge on exactly that membership (observed: 9% p50
    # swing on gap data, outside the pinned 1%-of-range accuracy
    # contract). Value order must be EXACT here; the ingest kernel's
    # packed sort (scatter.sort_by_slot) is different — its key is the
    # integer slot id, packed losslessly.
    vals, wts = jax.lax.sort((vals, wts), dimension=-1, num_keys=1)

    total = jnp.sum(wts, axis=1, keepdims=True)          # [K, 1]
    safe_total = jnp.where(total > 0, total, 1.0)
    cum = jnp.cumsum(wts, axis=1)                        # [K, M] right edges

    # Greedy cluster boundaries, scanned over the sorted axis (length M),
    # carrying per-row (k-value at current cluster start, cumulative weight).
    k_right = _k1(cum / safe_total, compression)         # [K, M]
    k_left = _k1((cum - wts) / safe_total, compression)  # [K, M]

    def step(k_start, xs):
        kl, kr, w = xs
        new = (kr - k_start > 1.0) & (w > 0)
        k_start = jnp.where(new, kl, k_start)
        return k_start, new

    # Initial carry is derived from data (k_left[:,0] - 2 <= any k minus 1,
    # so the first weighted element always opens a cluster) rather than a
    # constant: inside shard_map a constant carry would lack the varying
    # mesh-axes type and fail the scan type check.
    _, is_new = jax.lax.scan(
        step,
        k_left[:, 0] - 2.0,
        (k_left.T, k_right.T, wts.T),
    )
    is_new = is_new.T                                    # [K, M] bool

    cluster = jnp.cumsum(is_new.astype(jnp.int32), axis=1) - 1  # [K, M]
    cluster = jnp.where(wts > 0, cluster, C - 1)  # empties -> last cluster id
    cluster = jnp.clip(cluster, 0, C - 1)  # pathological-overflow safety

    # Per-cluster sums = diff of cumsums at cluster end positions.
    cw = jnp.cumsum(wts, axis=1)
    cwv = jnp.cumsum(wts * vals, axis=1)
    targets = jnp.arange(C, dtype=jnp.int32)

    ends = jax.vmap(lambda row: jnp.searchsorted(row, targets, side="right"))(
        cluster
    )                                                    # [K, C] in [0, M]

    def gather_at(c, idx):
        padded = jnp.concatenate([jnp.zeros((K, 1), c.dtype), c], axis=1)
        return jnp.take_along_axis(padded, idx, axis=1)

    w_upto = gather_at(cw, ends)
    wv_upto = gather_at(cwv, ends)
    w_c = jnp.diff(w_upto, axis=1, prepend=jnp.zeros((K, 1), cw.dtype))
    wv_c = jnp.diff(wv_upto, axis=1, prepend=jnp.zeros((K, 1), cw.dtype))

    # The empties parked on cluster C-1 contributed weight 0, so no mask
    # fixup is needed; real data can also land on C-1 legitimately.
    new_mean = jnp.where(w_c > 0, wv_c / jnp.where(w_c > 0, w_c, 1.0), 0.0)
    return new_mean, w_c


compress = partial(jax.jit, static_argnames=("compression",),
                   donate_argnames=("bank",))(_compress_impl)


@partial(jax.jit, static_argnames=("compression", "num_centroids"))
def cluster_rows(values, weights, compression: float = 100.0,
                 num_centroids: int = 256):
    """Cluster arbitrary padded centroid rows: f32[S, M] x2 ->
    (means f32[S, C], weights f32[S, C]).

    The batched foreign-digest merge for the global tier: a whole
    interval's forwarded digests, grouped per slot and padded into one
    matrix, collapse to <= C centroids per slot in ONE device program —
    instead of squeezing thousands of digests through the B-sized sample
    buffer with a compress pass per chunk (importsrv's Combine loop,
    worker.go sym: Worker.ImportMetricGRPC, turned into a batch op)."""
    return _cluster_core(values, weights, compression, num_centroids)


def _add_batch_impl(bank: TDigestBank, slots, values, weights,
                    compression: float = 100.0) -> TDigestBank:
    """Scatter a batch of (slot, value, weight) samples into the bank.

    Batched equivalent of Histo.Sample -> MergingDigest.Add. Samples append
    to per-slot buffers; rows that would overflow trigger a (batched)
    compress and the leftover samples are re-scattered, looping until the
    batch is fully absorbed (ceil(max_per_slot / B) iterations worst case).
    slot == -1 marks padding and is dropped via out-of-bounds scatter.
    """
    K = bank.num_slots
    B = bank.buf_size

    s, v, w = scatter.sort_by_slot(slots, values, weights, num_slots=K)
    rank = scatter.run_ranks(s)
    valid = s >= 0
    sd = jnp.where(valid, s, K)  # OOB -> dropped by mode="drop"

    # Exact scalar statistics never need the buffer: pure segment reduces.
    # Sums fold through the 2Sum hi/lo pairs — the per-batch delta is a
    # dense f32 scatter-add (a batch holds at most `batch` samples per
    # slot, so the delta itself is near-exact), then the running totals
    # absorb it with an error-free transformation (scalar.py counters).
    dsum = jnp.zeros_like(bank.vsum).at[sd].add(w * v, mode="drop")
    dcount = jnp.zeros_like(bank.count).at[sd].add(w, mode="drop")
    drecip = jnp.zeros_like(bank.recip).at[sd].add(
        jnp.where(v != 0, w / jnp.where(v != 0, v, 1.0), 0.0), mode="drop")
    vsum, vsum_lo = _two_sum(bank.vsum, dsum + bank.vsum_lo)
    count, count_lo = _two_sum(bank.count, dcount + bank.count_lo)
    recip, recip_lo = _two_sum(bank.recip, drecip + bank.recip_lo)
    bank = bank._replace(
        vmin=bank.vmin.at[sd].min(jnp.where(valid, v, _INF), mode="drop"),
        vmax=bank.vmax.at[sd].max(jnp.where(valid, v, -_INF), mode="drop"),
        vsum=vsum, count=count, recip=recip,
        vsum_lo=vsum_lo, count_lo=count_lo, recip_lo=recip_lo,
    )

    def write_pass(bank, written):
        """One buffer-write pass: land every not-yet-written sample
        whose position fits its slot's buffer. Returns the updated
        bank and written mask."""
        # Rank among the not-yet-written samples of each slot: ranks are
        # consumed in order, so subtracting the per-slot written count
        # re-bases them.
        done_per_slot = scatter.segment_count(s, written & valid, K)
        pos = bank.buf_n[jnp.where(valid, s, 0)] + rank - done_per_slot[
            jnp.where(valid, s, 0)]
        can = valid & ~written & (pos < B)
        row = jnp.where(can, s, K)
        col = jnp.clip(pos, 0, B - 1)
        new_bv = bank.buf_value.at[row, col].set(v, mode="drop")
        new_bw = bank.buf_weight.at[row, col].set(w, mode="drop")
        wrote = scatter.segment_count(s, can, K)
        bank = bank._replace(buf_value=new_bv, buf_weight=new_bw,
                             buf_n=bank.buf_n + wrote)
        return bank, written | can

    def cond(state):
        _, written = state
        return jnp.any(valid & ~written)

    def body(state):
        bank, written = state
        bank, written = write_pass(bank, written)
        leftover = jnp.any(valid & ~written)
        bank = jax.lax.cond(
            leftover,
            lambda b: _compress_impl(b, compression),
            lambda b: b,
            bank,
        )
        return bank, written

    def loop_path(bank):
        bank, _ = jax.lax.while_loop(
            cond, body, (bank, jnp.zeros_like(valid)))
        return bank

    def fast_path(bank):
        # the overflow predicate guarantees every valid sample fits, so
        # positions are direct (no done/wrote segment scatters needed —
        # the per-slot batch counts were already materialized for the
        # predicate itself)
        pos = bank.buf_n[jnp.where(valid, s, 0)] + rank
        row = jnp.where(valid, s, K)
        col = jnp.clip(pos, 0, B - 1)
        return bank._replace(
            buf_value=bank.buf_value.at[row, col].set(v, mode="drop"),
            buf_weight=bank.buf_weight.at[row, col].set(w, mode="drop"),
            buf_n=bank.buf_n + batch_per_slot)

    # The common case — no slot's buffer overflows — needs exactly one
    # write pass; the while_loop's carried-state machinery costs ~25%
    # of the dispatch on the CPU backend even when it runs one
    # iteration. Branch on the actual overflow condition (per-slot
    # batch count + current fill vs capacity) and keep the loop for
    # the hot-slot case only.
    batch_per_slot = scatter.segment_count(s, valid, K)
    overflows = jnp.any(bank.buf_n + batch_per_slot > B)
    return jax.lax.cond(overflows, loop_path, fast_path, bank)


add_batch = partial(jax.jit, static_argnames=("compression",),
                    donate_argnames=("bank",))(_add_batch_impl)


@partial(jax.jit, donate_argnames=("bank",))
def merge_centroids(bank: TDigestBank, slots, means, weights) -> TDigestBank:
    """Append foreign centroids (e.g. a forwarded digest's) into per-slot
    buffers, to be absorbed by the next compress.

    Batched equivalent of MergingDigest.Merge / Histo.Combine
    (samplers/samplers.go sym: Histo.Combine): merging a digest is just
    re-adding its centroids as weighted samples. Callers must compress
    first if buffers may overflow (the engine guarantees headroom).
    `slots`/`means`/`weights` are flat arrays, one entry per centroid,
    slot == -1 padding. Scalar stats (min/max/sum/count) are merged
    separately via `merge_scalars` since they are exact, not sketched.
    """
    K, B = bank.num_slots, bank.buf_size
    # Zero-weight padding centroids must not consume ranks (they'd shift
    # buffer positions and corrupt later writes), so mask them to slot -1
    # before the sort.
    slots = jnp.where(weights > 0, slots, -1)
    s, v, w = scatter.sort_by_slot(slots, means, weights, num_slots=K)
    rank = scatter.run_ranks(s)
    valid = (s >= 0) & (w > 0)
    pos = bank.buf_n[jnp.where(valid, s, 0)] + rank
    can = valid & (pos < B)
    row = jnp.where(can, s, K)
    col = jnp.clip(pos, 0, B - 1)
    return bank._replace(
        buf_value=bank.buf_value.at[row, col].set(v, mode="drop"),
        buf_weight=bank.buf_weight.at[row, col].set(w, mode="drop"),
        buf_n=bank.buf_n + scatter.segment_count(s, can, K),
    )


@partial(jax.jit, donate_argnames=("bank",))
def merge_scalars(bank: TDigestBank, slots, vmins, vmaxs, vsums, counts,
                  recips) -> TDigestBank:
    """Merge the exact per-digest scalar stats of forwarded digests."""
    K = bank.num_slots
    valid = slots >= 0
    sd = jnp.where(valid, slots, K)
    dsum = jnp.zeros_like(bank.vsum).at[sd].add(
        jnp.where(valid, vsums, 0.0), mode="drop")
    dcount = jnp.zeros_like(bank.count).at[sd].add(
        jnp.where(valid, counts, 0.0), mode="drop")
    drecip = jnp.zeros_like(bank.recip).at[sd].add(
        jnp.where(valid, recips, 0.0), mode="drop")
    vsum, vsum_lo = _two_sum(bank.vsum, dsum + bank.vsum_lo)
    count, count_lo = _two_sum(bank.count, dcount + bank.count_lo)
    recip, recip_lo = _two_sum(bank.recip, drecip + bank.recip_lo)
    return bank._replace(
        vmin=bank.vmin.at[sd].min(jnp.where(valid, vmins, _INF), mode="drop"),
        vmax=bank.vmax.at[sd].max(jnp.where(valid, vmaxs, -_INF), mode="drop"),
        vsum=vsum, count=count, recip=recip,
        vsum_lo=vsum_lo, count_lo=count_lo, recip_lo=recip_lo,
    )


@jax.jit
def quantile(bank: TDigestBank, qs) -> jax.Array:
    """Batched MergingDigest.Quantile: [K] digests x [P] quantiles -> [K, P].

    Requires compressed, cluster-ordered state (empty buffers) — the
    output of _compress_impl/_cluster_core: per-row means non-decreasing
    over the positive-weight prefix, with zero-weight empties as a
    suffix (cluster ids are consecutive by construction, so an interior
    cluster always has weight > 0). Every caller compresses first, which
    is why no defensive re-sort happens here: it would be a second full
    row sort per flush, measured at ~30% of the whole CPU flush @100k.

    Centroid i's mass is centered at quantile (cum_i - w_i/2) / W;
    linear interpolation between adjacent centroid means, clamped into
    [vmin, vmax], with the min/max themselves used below the first / above
    the last centroid midpoint (matching the reference's edge handling).
    """
    K, C = bank.mean.shape
    qs = jnp.asarray(qs, bank.mean.dtype)
    P = qs.shape[0]

    means, w = bank.mean, bank.weight

    total = jnp.sum(w, axis=1, keepdims=True)
    safe_total = jnp.where(total > 0, total, 1.0)
    cum = jnp.cumsum(w, axis=1)
    mid_q = (cum - w / 2.0) / safe_total                 # [K, C]
    # Empty clusters (sorted to the end) become duplicate q=1 knots with
    # value vmax, keeping knot_q ascending for jnp.interp.
    mid_q = jnp.where(w > 0, mid_q, 1.0)

    # Build interpolation knots: (0 -> vmin), (mid_q_i -> mean_i), (1 -> vmax)
    knot_q = jnp.concatenate(
        [jnp.zeros((K, 1), mid_q.dtype), mid_q,
         jnp.full((K, 1), 1.0, mid_q.dtype)], axis=1)
    vmin = jnp.where(jnp.isfinite(bank.vmin), bank.vmin, 0.0)[:, None]
    vmax = jnp.where(jnp.isfinite(bank.vmax), bank.vmax, 0.0)[:, None]
    knot_v = jnp.concatenate([vmin, jnp.where(w > 0, means, vmax), vmax],
                             axis=1)

    out = _interp_knots(knot_q, knot_v, qs)
    # Empty digests -> 0 (host layer skips unallocated slots anyway).
    return jnp.where(total > 0, out, 0.0)


def _interp_knots(knot_q, knot_v, qs):
    """Row-wise linear interpolation at qs over ascending knots —
    [K, M] x [P] -> [K, P] — with NO gathers.

    jnp.interp's searchsorted+gather lowers to a pathologically slow
    per-element path under the SPMD partitioner (shard_map), which made
    the mesh flush ~1000x slower than the single-chip program. Because
    knot_q is ascending per row, `knot_q < q` is a prefix mask, so the
    bracketing knots are the mask's last-True / first-False boundary
    positions, recoverable with masked reductions (elementwise ops only —
    partitioner-friendly on every path).
    """
    # Static unroll over the (small) P axis: keeping every intermediate
    # [K, M] leaves M in the lane dimension — a [K, M, P] broadcast would
    # put P (often 2-4) minor-most and waste 126/128 lanes per tile.
    if qs.shape[0] == 0:
        return jnp.zeros((knot_q.shape[0], 0), knot_q.dtype)
    zero = jnp.zeros((), knot_q.dtype)
    cols = []
    for p in range(qs.shape[0]):
        q = qs[p]
        mask = knot_q < q                              # [K, M] prefix
        nxt = jnp.concatenate(
            [mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1)
        lo_b = mask & ~nxt                             # last True
        prv = jnp.concatenate(
            [jnp.ones_like(mask[:, :1]), mask[:, :-1]], axis=1)
        hi_b = (~mask) & prv                           # first False
        q_lo = jnp.sum(jnp.where(lo_b, knot_q, zero), axis=1)   # [K]
        v_lo = jnp.sum(jnp.where(lo_b, knot_v, zero), axis=1)
        q_hi = jnp.sum(jnp.where(hi_b, knot_q, zero), axis=1)
        v_hi = jnp.sum(jnp.where(hi_b, knot_v, zero), axis=1)
        denom = q_hi - q_lo
        t = jnp.where(denom > 0,
                      (q - q_lo) / jnp.where(denom > 0, denom, 1.0), 0.0)
        out = v_lo + t * (v_hi - v_lo)
        # q at/below the first knot: prefix mask empty -> first value
        cols.append(jnp.where(jnp.any(mask, axis=1), out, knot_v[:, 0]))
    return jnp.stack(cols, axis=1)


@jax.jit
def aggregates(bank: TDigestBank):
    """The non-percentile flush aggregates of samplers.Histo
    (samplers/samplers.go sym: HistogramAggregates): max, min, sum, avg,
    count, hmean (median comes from quantile(0.5)).

    The single fold hi + lo here rounds once (relative error ~2^-24) —
    fine for on-device consumers; hosts needing exact counts past 2^24
    read the bank's (hi, lo) pairs directly and sum in float64."""
    cnt = bank.count + bank.count_lo
    vsum = bank.vsum + bank.vsum_lo
    recip = bank.recip + bank.recip_lo
    safe = jnp.where(cnt > 0, cnt, 1.0)
    return {
        "min": jnp.where(cnt > 0, bank.vmin, 0.0),
        "max": jnp.where(cnt > 0, bank.vmax, 0.0),
        "sum": vsum,
        "count": cnt,
        "avg": jnp.where(cnt > 0, vsum / safe, 0.0),
        "hmean": jnp.where(recip > 0, cnt / jnp.where(
            recip > 0, recip, 1.0), 0.0),
    }


def reset(bank: TDigestBank) -> TDigestBank:
    """Fresh interval state with the same shapes (the Worker.Flush map-swap
    equivalent, worker.go sym: Worker.Flush)."""
    k = bank.num_slots
    dt = bank.mean.dtype
    return TDigestBank(
        mean=jnp.zeros_like(bank.mean),
        weight=jnp.zeros_like(bank.weight),
        buf_value=jnp.zeros_like(bank.buf_value),
        buf_weight=jnp.zeros_like(bank.buf_weight),
        buf_n=jnp.zeros_like(bank.buf_n),
        vmin=jnp.full((k,), _INF, dt),
        vmax=jnp.full((k,), -_INF, dt),
        vsum=jnp.zeros((k,), dt),
        count=jnp.zeros((k,), dt),
        recip=jnp.zeros((k,), dt),
        vsum_lo=jnp.zeros((k,), dt),
        count_lo=jnp.zeros((k,), dt),
        recip_lo=jnp.zeros((k,), dt),
    )
