"""Counter and gauge banks — the scalar samplers as batched scatters.

Reference: samplers/samplers.go (sym: Counter.Sample — `value += v * 1/rate`,
flushed per interval; Gauge.Sample — last write wins; Counter.Combine /
Gauge.Combine for the forwarded global variants).

The reference accumulates counters in float64. JAX's default f32 loses
integer exactness past 2^24 (a single counter can see >10M samples per
interval), and enabling global x64 would poison every other kernel's
dtypes, so counters use a compensated (2Sum) f32 hi/lo pair: each batch is
segment-summed into a dense f32 delta (per-batch sums are small and exact
enough), then folded into the pair with an error-free transformation —
f64-grade totals with pure f32 ops.

Gauges keep f32 plus an i32 sequence number so last-write-wins holds across
batches and across forwarded merges.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import scatter


class CounterBank(NamedTuple):
    hi: jax.Array  # f32[K]
    lo: jax.Array  # f32[K] compensation term

    @property
    def num_slots(self):
        return self.hi.shape[0]


class GaugeBank(NamedTuple):
    value: jax.Array  # f32[K]
    seq: jax.Array    # i32[K], last writer's sequence, -1 == never written

    @property
    def num_slots(self):
        return self.value.shape[0]


def init_counters(num_slots: int) -> CounterBank:
    # hi and lo must be distinct buffers: the bank is donated to the
    # scatter kernels, and XLA rejects donating one buffer twice.
    return CounterBank(hi=jnp.zeros((num_slots,), jnp.float32),
                       lo=jnp.zeros((num_slots,), jnp.float32))


def init_gauges(num_slots: int) -> GaugeBank:
    return GaugeBank(value=jnp.zeros((num_slots,), jnp.float32),
                     seq=jnp.full((num_slots,), -1, jnp.int32))


def _two_sum(a, b):
    """Error-free transformation: a + b = s + err exactly (Knuth 2Sum)."""
    s = a + b
    a2 = s - b
    b2 = s - a2
    err = (a - a2) + (b - b2)
    return s, err


def _fold(bank: CounterBank, delta) -> CounterBank:
    s, err = _two_sum(bank.hi, delta + bank.lo)
    return CounterBank(hi=s, lo=err)


@partial(jax.jit, donate_argnames=("bank",))
def counter_add(bank: CounterBank, slots, values, weights) -> CounterBank:
    """Batched Counter.Sample: value[slot] += v * weight (weight = 1/rate)."""
    K = bank.num_slots
    row = jnp.where(slots >= 0, slots, K)
    delta = jnp.zeros((K,), jnp.float32).at[row].add(
        (values * weights).astype(jnp.float32), mode="drop")
    return _fold(bank, delta)


@partial(jax.jit, donate_argnames=("bank",))
def counter_merge(bank: CounterBank, slots, values) -> CounterBank:
    """Combine forwarded counter values (global counters: the cross-chip
    union is a psum of the same delta layout)."""
    K = bank.num_slots
    row = jnp.where(slots >= 0, slots, K)
    delta = jnp.zeros((K,), jnp.float32).at[row].add(
        values.astype(jnp.float32), mode="drop")
    return _fold(bank, delta)


def counter_totals(bank: CounterBank):
    """Read totals with the compensation folded back in (host side does
    float64(hi) + float64(lo) for full precision)."""
    return bank.hi, bank.lo


@partial(jax.jit, donate_argnames=("bank",))
def gauge_set(bank: GaugeBank, slots, values, seqs) -> GaugeBank:
    """Batched Gauge.Sample with last-write-wins.

    `seqs` is a monotonically increasing host-assigned sequence per sample;
    within a batch the per-slot max-seq sample wins, and across batches /
    forwarded merges the stored seq arbitrates.
    """
    K = bank.num_slots
    s, v, q = scatter.sort_by_slot(slots, values, seqs, num_slots=K)
    last = scatter.run_lasts(s) & (s >= 0)  # stable sort => last == max seq
    row = jnp.where(last, s, K)
    new_seq = bank.seq.at[row].max(q, mode="drop")
    won = last & (q >= new_seq[jnp.clip(s, 0, K - 1)])
    row_w = jnp.where(won, s, K)
    return GaugeBank(value=bank.value.at[row_w].set(v, mode="drop"),
                     seq=new_seq)


def reset_counters(bank: CounterBank) -> CounterBank:
    return init_counters(bank.num_slots)


def reset_gauges(bank: GaugeBank) -> GaugeBank:
    """Gauges are last-write-wins *within* an interval; a gauge is
    re-reported only when sampled again, so interval reset clears the seq."""
    return init_gauges(bank.num_slots)
