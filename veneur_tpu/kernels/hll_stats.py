"""Pallas TPU kernel for the HLL estimate reduction.

The pure-JAX estimator in ops/hll.py materializes `registers.astype(f32)`
and `exp2(-regs)` intermediates of shape [K, m] — 8x the HBM traffic of
the u8 register file itself when XLA doesn't fuse the whole chain. This
kernel streams each u8 block through VMEM exactly once, accumulating the
two row statistics the LogLog-Beta estimator needs:

    ez   = #(register == 0)          (per row)
    zsum = sum(2^-register)          (per row)

Grid: one program per BK-row block; each program walks the m register
columns in 512-lane chunks with a fori_loop, accumulating [BK, 512]
partials that are lane-reduced at the end. The final (tiny, [K]-shaped)
beta-polynomial arithmetic stays in plain jnp outside the kernel.

Use `hll_stats(registers, interpret=True)` on CPU for tests; on TPU the
real kernel runs. ops/hll.py picks this path automatically on TPU.
(Moved here from ops/pallas_hll.py — vlint PK01 single-homes every
pl.* primitive under veneur_tpu/kernels/.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import count_fallback

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_ERR = None
except Exception as _e:             # noqa: BLE001 — probed at entry
    pl = pltpu = None
    _PALLAS_ERR = _e

# u8 min tile is (32, 128); BK=32 rows keeps every block aligned.
_BK = 32
_LANES = 512


def _stats_kernel(regs_ref, ez_ref, zsum_ref):
    m = regs_ref.shape[1]
    steps = m // _LANES

    def body(i, carry):
        ez_acc, zsum_acc = carry
        # mosaic has no direct u8->f32 cast; widen through i32
        chunk = regs_ref[:, pl.ds(i * _LANES, _LANES)].astype(jnp.int32)
        f = chunk.astype(jnp.float32)
        ez_acc = ez_acc + jnp.where(chunk == 0, 1.0, 0.0)
        zsum_acc = zsum_acc + jnp.exp2(-f)
        return ez_acc, zsum_acc

    ez_acc, zsum_acc = jax.lax.fori_loop(
        0, steps, body,
        (jnp.zeros((_BK, _LANES), jnp.float32),
         jnp.zeros((_BK, _LANES), jnp.float32)))
    ez_ref[:] = jnp.sum(ez_acc, axis=1, keepdims=True)
    zsum_ref[:] = jnp.sum(zsum_acc, axis=1, keepdims=True)


def _stats_jnp(registers):
    """The plain-jnp twin (the fallback arm): identical statistics
    without the streaming pass — what ops/hll._estimate_jnp reduces."""
    ez = jnp.sum(registers == 0, axis=1).astype(jnp.float32)
    zsum = jnp.sum(jnp.exp2(-registers.astype(jnp.float32)), axis=1)
    return ez, zsum


@functools.partial(jax.jit, static_argnames=("interpret",))
def hll_stats(registers, interpret: bool = False):
    """(ez[K], zsum[K]) for a u8[K, m] register bank via one streaming
    pass. K is padded up to the 32-row block internally.

    Counted fallback branch (vlint PK01): a register width off the
    512-lane chunk grid (no real precision >= 9 hits this) or an
    unavailable pallas degrades to the jnp reduction — same
    statistics, no streaming claim."""
    K, m = registers.shape
    if m % _LANES != 0:
        count_fallback(f"hll_stats: m={m} not a multiple of {_LANES}")
        return _stats_jnp(registers)
    if pl is None:
        count_fallback(f"hll_stats: pallas unavailable ({_PALLAS_ERR})")
        return _stats_jnp(registers)
    Kp = (K + _BK - 1) // _BK * _BK
    if Kp != K:
        registers = jnp.pad(registers, ((0, Kp - K), (0, 0)))
    ez, zsum = pl.pallas_call(
        _stats_kernel,
        grid=(Kp // _BK,),
        in_specs=[pl.BlockSpec((_BK, m), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((_BK, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BK, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Kp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Kp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(registers)
    return ez[:K, 0], zsum[:K, 0]
