"""Pallas TPU kernels — the fused-kernel layer of the flush hot path.

ISSUE 15: the flight recorder attributes ~94% of the 100k tick to
`device.exec`, and with the incremental flush (ISSUE 11) bounding the
work set to the dirty [D, C] slice, the one structural lever left is
killing the HBM round-trips BETWEEN the compress stages: XLA
materializes the sort keys, the merged runs, and the cumsum/cluster
intermediates as [D, M] HBM arrays between fused subcomputations. The
kernels here fuse each hot path into ONE `pallas_call` whose
intermediates live in VMEM:

  compress.py   packed-key sort of the sample buffer + log-depth
                bitonic rank-merge against the cluster-ordered centroid
                prefix + greedy k1 cluster/cummax-clamp — the whole
                t-digest compress, one kernel invocation per bucket.
  ull_insert.py scatter-join insert for UltraLogLog register banks —
                sequential lattice-join RMW replacing the XLA-CPU
                sort + segmented-scan + gather path (~87us/member,
                BENCH_SUITE_r11 c17).
  hll_stats.py  the streaming HLL estimate reduction (moved from
                ops/pallas_hll.py — every pl.* primitive in the tree
                now lives under this package, machine-checked by
                vlint PK01).

ARM MODEL (the `tpu_fused_kernels` knob): every kernel-routed
executable is built under exactly one arm —

  "fused"      the Mosaic-compiled kernel on a real TPU backend;
  "interpret"  the same kernel under `interpret=True` — the CPU
               testing arm that proves BIT-IDENTITY against the XLA
               program without hardware (tier-1's correctness bar);
  "xla"        the existing XLA program, untouched.

`resolve_arm` maps the knob (auto|on|off) + the backend platform to an
arm through runtime probes; any refusal (Pallas missing, Mosaic
rejecting a primitive, the probe kernel failing) degrades LOUDLY to
"xla": a warning is logged and `veneur.kernels.fallback_total` counts
it — vlint PK01 additionally requires every kernel entry point in this
package to carry such a counted fallback branch, so a refused backend
can never silently serve a half-fused program.

Bit-identity contract (tests/test_pallas.py): under the "interpret"
arm every kernel reproduces its XLA twin EXACTLY — including ±0.0
canonicalization in the sort keys, duplicate-key stability, NaN
payload bits riding the payload lanes, and the SR02 cummax ordering
invariant — because the sort/merge networks are order-isomorphic to
the XLA path's (distinct lexicographic (key, tag) pairs have ONE
ascending order) and the numeric stages run the identical jnp ops on
identical inputs.
"""

from __future__ import annotations

import functools
import logging

logger = logging.getLogger(__name__)

ARMS = ("fused", "interpret", "xla")
MODES = ("auto", "on", "off")


def count_fallback(reason: str):
    """Count + log one kernel->XLA degradation. Every kernel entry
    point's fallback branch routes through here (vlint PK01): the
    counter is `veneur.kernels.fallback_total` on the process registry,
    surfaced at /debug/flush next to the per-engine arm stamps."""
    from ..observe.registry import DEFAULT_REGISTRY, SERVER_SCOPE
    DEFAULT_REGISTRY.incr(SERVER_SCOPE, "kernels.fallback")
    logger.warning("fused-kernel fallback to the XLA program: %s",
                   reason)


def fallback_total() -> int:
    """Cumulative kernel->XLA degradations this process (/debug)."""
    from ..observe.registry import DEFAULT_REGISTRY, SERVER_SCOPE
    return DEFAULT_REGISTRY.total(SERVER_SCOPE, "kernels.fallback")


@functools.lru_cache(maxsize=None)
# vlint: disable=PK01 reason=availability probe, not a serving entry
# point — resolve_arm owns the counted fallback when this is False
def probe_interpret() -> bool:
    """Can this jax run a trivial `pallas_call(interpret=True)`? The
    EXACT capability the interpret arm (and its tier-1 tests) consume;
    tests/envprobes.py gates on this probe."""
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def k(x_ref, o_ref):
            o_ref[:] = x_ref[:] + 1.0

        out = pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True)(jnp.zeros((8, 128), jnp.float32))
        return bool(out[0, 0] == 1.0)
    except Exception as e:          # noqa: BLE001 — any failure = absent
        logger.info("pallas interpret probe failed: %s", e)
        return False


@functools.lru_cache(maxsize=None)
def probe_compiled() -> bool:
    """Can the REAL fused compress kernel compile on this backend?
    Probes with a tiny instance of the actual kernel (not a toy): a
    Mosaic refusal of any primitive the kernel uses must surface HERE,
    at arm-resolution time, so serving degrades to XLA before the
    first flush — never mid-tick. False on non-TPU platforms (the
    compiled arm only exists on tpu/axon; CPU uses interpret)."""
    try:
        import jax
        if jax.devices()[0].platform not in ("tpu", "axon"):
            return False
        import jax.numpy as jnp

        from . import compress
        mean = jnp.zeros((8, 128), jnp.float32)
        weight = jnp.zeros((8, 128), jnp.float32)
        bv = jnp.zeros((8, 8), jnp.float32)
        bw = jnp.zeros((8, 8), jnp.float32)
        jax.jit(lambda *a: compress.fused_compress(
            *a, compression=40.0, interpret=False)).lower(
            mean, weight, bv, bw).compile()
        return True
    except Exception as e:          # noqa: BLE001 — refusal = fallback
        logger.info("pallas compiled probe failed: %s", e)
        return False


def verify_engine_kernels(heng, seng, arms: dict, set_slots: int,
                          batch_size: int) -> dict:
    """Shape-accurate second-stage probe for the COMPILED arm.

    `probe_compiled()` proves Mosaic accepts the compress kernel at a
    toy shape; Mosaic refusals can also be SHAPE-dependent (VMEM
    overflow at a deep buffer, tile misalignment, a register file too
    wide for one block), and the ULL insert kernel is a different
    program entirely. So before an engine serves the "fused" arm,
    AOT-compile each fused kernel it will actually dispatch at the
    ENGINE'S serving shapes — the compress at its real centroid/buffer
    widths over the fixed row block, the insert at the real
    [set_slots, m] register file and batch width — and demote that
    engine's arm to the counted XLA fallback on refusal, at
    CONSTRUCTION time, never mid-tick. interpret/xla arms pass through
    untouched (no Mosaic involved)."""
    out = dict(arms)

    def _compiles(build, what: str) -> bool:
        try:
            build()
            return True
        except Exception as e:      # noqa: BLE001 — refusal = fallback
            logger.info("%s refused at serving shape: %s", what, e)
            return False

    if out.get("histogram") == "fused" \
            and hasattr(heng, "compress_fused_impl"):
        import jax
        import jax.numpy as jnp

        from . import compress as _compress
        proto = heng.init(1)
        C, B = int(proto.num_centroids), int(proto.buf_size)
        R = _compress._BLOCK_ROWS
        comp = float(getattr(heng, "compression", 100.0))
        f32 = jnp.float32

        def build_compress():
            jax.jit(lambda m, w, bv, bw: _compress.fused_compress(
                m, w, bv, bw, compression=comp, interpret=False)
            ).lower(
                jax.ShapeDtypeStruct((R, C), f32),
                jax.ShapeDtypeStruct((R, C), f32),
                jax.ShapeDtypeStruct((R, B), f32),
                jax.ShapeDtypeStruct((R, B), f32),
            ).compile()

        if not _compiles(build_compress, "fused compress"):
            count_fallback(
                f"fused compress refused at serving shape C={C} "
                f"B={B} (block {R}) — this engine keeps the XLA "
                "compress")
            out["histogram"] = "xla"
    if out.get("set") == "fused" and hasattr(seng, "insert_fused_impl"):
        import jax
        import jax.numpy as jnp

        from . import ull_insert as _ull_insert
        bank_aval = jax.eval_shape(lambda: seng.init(set_slots))

        def build_insert():
            jax.jit(lambda b, s, i, v: _ull_insert.fused_insert(
                b, s, i, v, interpret=False)
            ).lower(
                bank_aval,
                jax.ShapeDtypeStruct((batch_size,), jnp.int32),
                jax.ShapeDtypeStruct((batch_size,), jnp.int32),
                jax.ShapeDtypeStruct((batch_size,), jnp.uint8),
            ).compile()

        if not _compiles(build_insert, "fused ULL insert"):
            count_fallback(
                f"fused ULL insert refused at serving shape "
                f"[{set_slots}, {getattr(seng, 'num_registers', '?')}] "
                f"x batch {batch_size} — this engine keeps the XLA "
                "insert")
            out["set"] = "xla"
    return out


def resolve_arm(mode: str, platform: str | None = None) -> str:
    """Map the `tpu_fused_kernels` knob to the arm every kernel-routed
    executable is built under.

      off   -> "xla" always.
      auto  -> "fused" on a TPU backend whose probe passes (counted
               fallback to "xla" when Mosaic refuses); "xla" on CPU —
               the interpret arm is a CORRECTNESS harness, not a
               serving default (it simulates the kernel).
      on    -> like auto on TPU; on CPU the interpret arm serves (the
               testing stance: the oracle/chaos suites run the actual
               kernel math through the whole pipeline), with a counted
               fallback when even interpret is unavailable.
    """
    if mode not in MODES:
        raise ValueError(
            f"tpu_fused_kernels must be one of {'/'.join(MODES)}, "
            f"got {mode!r}")
    if mode == "off":
        return "xla"
    if platform is None:
        try:
            import jax
            platform = jax.devices()[0].platform
        except Exception:           # noqa: BLE001 — no backend = no kernel
            count_fallback("no jax backend available")
            return "xla"
    if platform in ("tpu", "axon"):
        if probe_compiled():
            return "fused"
        count_fallback(
            f"tpu_fused_kernels={mode} on {platform} but the compress "
            "kernel did not compile (Mosaic refusal — see the probe "
            "log line)")
        return "xla"
    if mode == "on":
        if probe_interpret():
            return "interpret"
        count_fallback(
            "tpu_fused_kernels=on without a TPU backend and "
            "pallas interpret mode unavailable")
        return "xla"
    return "xla"
