"""Fused t-digest compress — one Pallas kernel per bucket.

The XLA merge-path compress (ops/tdigest.py `_cluster_core` with
`sorted_prefix=C`) is three logical dispatches XLA fuses only loosely:
the packed-key radix sort of the sample buffer, the log-depth bitonic
rank-merge against the cluster-ordered centroid prefix, and the greedy
k1 cluster + cummax-clamp. Each stage's [D, M] intermediates (canonical
keys, tags, permutations, merged payload rows, cumsums) round-trip
through HBM between fusion islands. This kernel runs the WHOLE pipeline
per row-block inside one `pallas_call`, so every intermediate lives in
VMEM and the bucket pays exactly one kernel invocation — the structural
HBM win tests assert (one pallas_call in the flush program's jaxpr).

TWO IN-KERNEL SORT ARMS, one numeric pipeline:

  * `network=False` (the interpret/CPU serving arm): the kernel body
    runs `_cluster_core`'s EXACT op sequence — the packed-key radix
    sort, the bitonic rank-merge, the tag gather, and the numeric
    stages, verbatim. Same ops on same inputs in the same order =
    same bits AND same speed as the XLA program (the "no slower than
    XLA on CPU-interpret" gate), with the whole compress living in
    one pallas_call.
  * `network=True` (the Mosaic/TPU arm, also what `probe_compiled`
    compiles): `lax.sort` has no Mosaic lowering, so the sort/merge
    stages are explicit compare-exchange NETWORKS — a bitonic full
    sort of the buffer run carrying the payload lanes, then
    `_merge_sorted_runs`' exchange network replicated literally (same
    pad placement, same reversed run, same lexicographic predicate).
    Every (canonical key, tag) pair is DISTINCT, so the stable-by-key
    order is the unique ascending one and any correct comparison sort
    produces the identical sequence; exchanges MOVE payload bits,
    never compute on them, so ±0.0 canonicalization lives only in the
    keys and NaN payloads ride untouched. The numeric stages are the
    identical jnp/lax ops as `_cluster_core` (cumsum, arcsin-k1, the
    greedy boundary recurrence, searchsorted + take_along_axis,
    cumsum-diff segment sums, the SR02 cummax clamp) — re-derivations
    are exactly where a few-ulp (or NaN-vs-zero) divergence would
    creep in, so there are none.

BIT-IDENTITY (the acceptance bar, tests/test_pallas.py): BOTH arms
reproduce `_compress_impl` bit-for-bit under `interpret=True` on CPU —
±0.0/NaN key canonicalization, duplicate-key stability, NaN payload
bits, cluster-id overflow clipping, and the cummax clamp included.
The network arm is additionally fuzzed as plain jnp against
`_stable_sort_perm`/`_merge_sorted_runs` directly, so the TPU-compiled
arm's order math carries a CPU proof even before the TPU capture.

The row axis is embarrassingly parallel, so the grid blocks rows:
`_BLOCK_ROWS` per program when compiled (VMEM-bounded),
`_BLOCK_ROWS_INTERPRET` under interpret (bounds the simulator's live
temporaries on big banks).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import count_fallback
# kernels/ is a blessed sketch-math module (sk01_allow): this kernel
# IS the t-digest compress (fused arm) and shares the canonical key,
# k1, and cluster-core definitions instead of duplicating them
from ..ops import tdigest as _td

_INF = jnp.inf
_BLOCK_ROWS = 256        # compiled-arm row block: ~5 MB VMEM at M=512
# interpret-arm row block: the simulator holds every intermediate of a
# block live at once, so an unbounded block over a 100k bank would
# peak at GBs of [K, M] temporaries; 4096 rows bounds it at the
# incremental path's largest bucket while keeping the grid loop short
_BLOCK_ROWS_INTERPRET = 4096


# --------------------------------------------------------------- networks

def _cmp_swap(asc, key, tag, vals, wts, stride):
    """One compare-exchange pass at `stride`: element i pairs with
    i XOR stride; `asc` is the per-pair-block direction (True =
    ascending), broadcast over [R, P/(2*stride), 1]. The predicate is
    strict lexicographic (key, tag) — every pair distinct, so the
    network's fixed pattern yields a deterministic total order and the
    payload lanes (vals, wts) are MOVED bit-for-bit, never computed."""
    R, P = key.shape
    shape = (R, P // (2 * stride), 2, stride)

    def split(x):
        x4 = x.reshape(shape)
        return x4[:, :, 0, :], x4[:, :, 1, :]

    klo, khi = split(key)
    tlo, thi = split(tag)
    vlo, vhi = split(vals)
    wlo, whi = split(wts)
    gt = (klo > khi) | ((klo == khi) & (tlo > thi))
    swap = gt ^ (~asc)

    def join(lo, hi):
        return jnp.stack([jnp.where(swap, hi, lo),
                          jnp.where(swap, lo, hi)], axis=2).reshape(R, P)

    return join(klo, khi), join(tlo, thi), join(vlo, vhi), join(wlo, whi)


def _bitonic_sort(key, tag, vals, wts):
    """Full ascending bitonic sort of [R, P] rows (P a power of two)
    by lexicographic (key, tag), payloads carried. With distinct
    (key, tag) pairs this produces THE stable-by-key order — the same
    sequence as ops/tdigest._stable_sort_perm's packed radix passes."""
    P = key.shape[1]
    k = 2
    while k <= P:
        nblk_dir = jax.lax.broadcasted_iota(
            jnp.int32, (1, P // k, 1), 1)
        j = k // 2
        while j >= 1:
            # direction constant over each 2j-block: ascending iff the
            # block's k-bit is clear ((i & k) == 0; i = block_start)
            blocks = P // (2 * j)
            rep = blocks // (P // k)          # 2j-blocks per k-block
            asc = jnp.repeat(nblk_dir % 2 == 0, rep, axis=1) \
                if rep > 1 else (nblk_dir % 2 == 0)
            key, tag, vals, wts = _cmp_swap(asc, key, tag, vals, wts, j)
            j //= 2
        k *= 2
    return key, tag, vals, wts


def _bitonic_merge(key, tag, vals, wts):
    """`_merge_sorted_runs`' exchange network verbatim (stride P/2
    down to 1, always-ascending lexicographic exchanges), with payload
    lanes riding along instead of the XLA path's tag-gather epilogue —
    the same tags select the same elements either way."""
    P = key.shape[1]
    asc = jnp.ones((1, 1, 1), bool)
    stride = P // 2
    while stride >= 1:
        key, tag, vals, wts = _cmp_swap(asc, key, tag, vals, wts,
                                        stride)
        stride //= 2
    return key, tag, vals, wts


# ----------------------------------------------------------- kernel body

def _fused_cluster_network(vals, wts, compression: float, C: int,
                           S: int):
    """The network-arm twin of ops/tdigest._cluster_core(
    sorted_prefix=S) for 0 < S < M: bitonic-sort the suffix run,
    rank-merge against the prefix through _merge_sorted_runs' exchange
    network, then the identical numeric pipeline.
    [R, M] x2 -> [R, C] x2."""
    R, M = vals.shape
    vals = jnp.where(wts > 0, vals, _INF)
    key = _td._canonical_sort_key(vals)

    # -- phase A: stable sort of the buffer run (lanes S..M-1) --------
    nb = M - S
    Pb = 1 << (nb - 1).bit_length()
    bk, bv, bw = key[:, S:], vals[:, S:], wts[:, S:]
    btag = jax.lax.broadcasted_iota(jnp.int32, (R, nb), 1)
    if Pb != nb:
        # pads: canonical-key maximum with tags past every real lane —
        # strictly largest (key, tag), so they sink to the tail even
        # against real 0xFFFFFFFF keys (all-ones-payload NaNs)
        pk = jnp.full((R, Pb - nb), jnp.uint32(0xFFFFFFFF))
        pt = jax.lax.broadcasted_iota(
            jnp.int32, (R, Pb - nb), 1) + nb
        pz = jnp.zeros((R, Pb - nb), vals.dtype)
        bk = jnp.concatenate([bk, pk], axis=1)
        btag = jnp.concatenate([btag, pt], axis=1)
        bv = jnp.concatenate([bv, pz], axis=1)
        bw = jnp.concatenate([bw, pz], axis=1)
    bk, _bt, bv, bw = _bitonic_sort(bk, btag, bv, bw)
    bk, bv, bw = bk[:, :nb], bv[:, :nb], bw[:, :nb]

    # -- phase B: rank-merge against the prefix (network of
    #    _merge_sorted_runs: [prefix | pads | reversed buffer]) -------
    P = 1 << (M - 1).bit_length()
    pad = P - M
    atag = jax.lax.broadcasted_iota(jnp.int32, (R, S), 1)
    ptag = jax.lax.broadcasted_iota(jnp.int32, (R, pad), 1) + M
    sbt = jax.lax.broadcasted_iota(jnp.int32, (R, nb), 1) + S
    mk = jnp.concatenate(
        [key[:, :S], jnp.full((R, pad), jnp.uint32(0xFFFFFFFF)),
         bk[:, ::-1]], axis=1)
    mt = jnp.concatenate([atag, ptag, sbt[:, ::-1]], axis=1)
    zp = jnp.zeros((R, pad), vals.dtype)
    mv = jnp.concatenate([vals[:, :S], zp, bv[:, ::-1]], axis=1)
    mw = jnp.concatenate([wts[:, :S], zp, bw[:, ::-1]], axis=1)
    _mk, _mt, mv, mw = _bitonic_merge(mk, mt, mv, mw)
    vals, wts = mv[:, :M], mw[:, :M]

    # -- numeric pipeline: the ONE shared tail (_cluster_tail) with
    #    the greedy boundary recurrence as a Mosaic-friendly fori_loop
    #    (compare/select only, so any loop form is bit-equal to the
    #    XLA arm's lax.scan)
    def boundaries(k_left, k_right, w_all):
        def step(i, carry):
            k_start, is_new = carry
            kl = jax.lax.dynamic_slice_in_dim(k_left, i, 1, axis=1)
            kr = jax.lax.dynamic_slice_in_dim(k_right, i, 1, axis=1)
            w = jax.lax.dynamic_slice_in_dim(w_all, i, 1, axis=1)
            new = (kr - k_start > 1.0) & (w > 0)
            k_start = jnp.where(new, kl, k_start)
            is_new = jax.lax.dynamic_update_slice_in_dim(
                is_new, new, i, axis=1)
            return k_start, is_new

        k0 = jax.lax.dynamic_slice_in_dim(k_left, 0, 1, axis=1) - 2.0
        _, is_new = jax.lax.fori_loop(
            0, M, step, (k0, jnp.zeros((R, M), bool)))
        return is_new

    return _td._cluster_tail(vals, wts, compression, C, boundaries)


def _compress_kernel(compression, C, network, mean_ref, weight_ref,
                     bv_ref, bw_ref, out_mean_ref, out_weight_ref):
    vals = jnp.concatenate([mean_ref[:], bv_ref[:]], axis=1)
    wts = jnp.concatenate([weight_ref[:], bw_ref[:]], axis=1)
    if network:
        nm, nw = _fused_cluster_network(vals, wts, compression, C, S=C)
    else:
        nm, nw = _td._cluster_core(vals, wts, compression, C,
                                   sorted_prefix=C)
    out_mean_ref[:] = nm
    out_weight_ref[:] = nw


# ---------------------------------------------------------- entry point

def fused_compress(mean, weight, buf_value, buf_weight,
                   compression: float, interpret: bool,
                   network: bool | None = None):
    """One fused compress dispatch over a [K, C] centroid block + its
    [K, B] buffers -> (new_mean, new_weight) [K, C]. jit-composable
    (callers embed it in the flush program; `interpret` is a
    trace-time constant from the resolved arm).

    `network` picks the in-kernel sort arm (see the module
    docstring); the default — compare-exchange networks when
    compiling for a real backend, `_cluster_core`'s lax.sort form
    under interpret — serves both gates (Mosaic compilability there,
    XLA speed parity here). Tests override it to prove the network
    arm's bit-identity on CPU.

    Counted fallback branch (vlint PK01): shapes the networks cannot
    serve (a buffer wider than the 16-bit lane pack, mirroring
    _stable_sort_perm's own bound, or a degenerate axis) degrade to
    the XLA `_cluster_core` — loud, counted, bit-identical."""
    if network is None:
        network = not interpret
    K, C = mean.shape
    B = buf_value.shape[1]
    if B > (1 << 16) or K == 0 or C < 2 or B < 1:
        count_fallback(
            f"fused_compress: unsupported shape K={K} C={C} B={B}")
        vals = jnp.concatenate([mean, buf_value], axis=1)
        wts = jnp.concatenate([weight, buf_weight], axis=1)
        return _td._cluster_core(vals, wts, compression, C,
                                 sorted_prefix=C)
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
    except Exception as e:          # noqa: BLE001 — pallas absent
        count_fallback(f"fused_compress: pallas unavailable ({e})")
        vals = jnp.concatenate([mean, buf_value], axis=1)
        wts = jnp.concatenate([weight, buf_weight], axis=1)
        return _td._cluster_core(vals, wts, compression, C,
                                 sorted_prefix=C)

    import functools

    br = min(_BLOCK_ROWS_INTERPRET if interpret else _BLOCK_ROWS, K)
    Kp = -(-K // br) * br
    if Kp != K:
        pad = ((0, Kp - K), (0, 0))
        mean = jnp.pad(mean, pad)
        weight = jnp.pad(weight, pad)
        buf_value = jnp.pad(buf_value, pad)
        buf_weight = jnp.pad(buf_weight, pad)

    kern = functools.partial(_compress_kernel, float(compression), C,
                             bool(network))
    vmem = pltpu.VMEM
    nm, nw = pl.pallas_call(
        kern,
        grid=(Kp // br,),
        in_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((br, B), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((br, B), lambda i: (i, 0), memory_space=vmem),
        ],
        out_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Kp, C), mean.dtype),
            jax.ShapeDtypeStruct((Kp, C), mean.dtype),
        ],
        interpret=interpret,
    )(mean, weight, buf_value, buf_weight)
    return nm[:K], nw[:K]


def fused_compress_bank(bank, compression: float, interpret: bool,
                        network: bool | None = None):
    """Whole-bank fused compress: the engine-facing twin of
    ops/tdigest._compress_impl (scalar leaves untouched, buffers
    zeroed). Writes bank.mean/weight with the kernel's output — the
    kernel enforces the SR02 cummax clamp exactly as _cluster_core
    does (tests pin bitwise equality), and this module is on the SR02
    allow list as a second invariant-preserving writer."""
    nm, nw = fused_compress(bank.mean, bank.weight, bank.buf_value,
                            bank.buf_weight, compression, interpret,
                            network)
    return bank._replace(
        mean=nm, weight=nw,
        buf_value=jnp.zeros_like(bank.buf_value),
        buf_weight=jnp.zeros_like(bank.buf_weight),
        buf_n=jnp.zeros_like(bank.buf_n))
