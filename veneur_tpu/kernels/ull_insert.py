"""Pallas scatter-join insert for UltraLogLog register banks.

The XLA insert (sketches/ull.py `_insert_impl`) cannot ride a
scatter-max — the ULL register state is only PARTIALLY ordered, so it
sorts the batch by flat register address, collapses duplicates with a
segmented associative scan of the lattice join, and lands the unique
survivors with a gather-join-scatter. On XLA-CPU that scan is the
single slowest sketch op in the tree (~87us/member, BENCH_SUITE_r11
c17, vs ~1us for HLL's scatter-max).

This kernel is the scatter-join the lattice actually wants: ONE pass
over the batch doing an in-place read-join-write per update against
the aliased register buffer. No sort, no scan, no dedup — the join is
associative, commutative, and idempotent, so ANY application order
(including duplicate (slot, idx) targets hitting the same register
repeatedly) folds to the identical final register value the
sort+scan+dedup path computes. Registers are u8 integers, so
"identical" here is exact equality, not an up-to-rounding claim —
tests/test_pallas.py fuzzes byte equality against `_insert_impl`.

`input_output_aliases={0: 0}` makes the register buffer update
in-place (the enclosing ingest executable donates the bank), so the
kernel's HBM traffic is the touched registers, not a bank copy."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import count_fallback
# kernels/ is a blessed sketch-math module (sk01_allow): this kernel
# IS the ULL insert (fused arm) and shares the one lattice-join
# definition instead of duplicating it
from ..sketches import ull as _ull


def _insert_kernel(regs_ref, slots_ref, idx_ref, vals_ref, out_ref):
    n = slots_ref.shape[0]

    def body(i, carry):
        s = slots_ref[i]

        def land(c):
            col = idx_ref[i]
            cur = out_ref[s, col].astype(jnp.int32)
            val = vals_ref[i].astype(jnp.int32)
            out_ref[s, col] = _ull._join_i32(cur, val).astype(jnp.uint8)
            return c

        return jax.lax.cond(s >= 0, land, lambda c: c, carry)

    jax.lax.fori_loop(0, n, body, 0)


def fused_insert(bank, slots, reg_idx, vals, interpret: bool):
    """Batched ULL insert through the scatter-join kernel — the fused
    twin of sketches/ull._insert_impl (same signature minus the
    trace-time `interpret` arm constant; jit-composable, caller
    donates the bank).

    Counted fallback branch (vlint PK01): an unavailable pallas (or a
    degenerate batch shape) degrades to the XLA sort+scan path —
    loud, counted, value-identical."""
    K, m = bank.registers.shape
    n = int(slots.shape[0])
    if n == 0 or K == 0:
        count_fallback(f"ull fused_insert: degenerate shape n={n} K={K}")
        return _ull._insert_impl(bank, slots, reg_idx, vals)
    try:
        from jax.experimental import pallas as pl
    except Exception as e:          # noqa: BLE001 — pallas absent
        count_fallback(f"ull fused_insert: pallas unavailable ({e})")
        return _ull._insert_impl(bank, slots, reg_idx, vals)

    regs = pl.pallas_call(
        _insert_kernel,
        out_shape=jax.ShapeDtypeStruct((K, m), jnp.uint8),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(bank.registers, slots.astype(jnp.int32),
      reg_idx.astype(jnp.int32), vals.astype(jnp.uint8))
    return _ull.ULLBank(registers=regs)
