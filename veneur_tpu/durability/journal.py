"""The storage layer: append-only CRC32C-framed record journal.

On-disk layout (all integers little-endian):

    journal file:   MAGIC ("VTPUJRN1", 8 bytes) | u64 generation
                    | frames
    snapshot file:  SNAP_MAGIC ("VTPUSNP1", 8 bytes) | u64 generation
                    | frames
    frame:          u32 length | u32 crc32c | u8 rec_type | payload
                    where length = 1 + len(payload) (the type byte is
                    part of the framed body) and the CRC covers
                    type byte + payload.

The generation is what makes snapshot+compaction atomic END TO END: a
snapshot is written with generation G+1, renamed into place, and only
then is the journal truncated and restamped to G+1. A crash between
the rename and the truncate leaves a G+1 snapshot next to a G journal
whose records are ALREADY folded into the snapshot — recovery sees
journal_generation < snapshot_generation and drops the stale journal
records instead of double-applying them (counted, logged).

Torn-write tolerance: a crash can leave a partial frame at the tail
(power loss mid-write) or, in the worst case, a bit flip anywhere in
the unsynced tail. Recovery reads frames until the first one whose
header is incomplete, whose length is implausible, or whose CRC
mismatches — everything from that offset on is discarded and the file
is truncated back to the last good frame when reopened for append
(`truncated_frames_total` counts the events). Recovery therefore NEVER
raises on a corrupt journal and never invents records: a frame is
either returned bit-exact or dropped with everything after it.

Fsync policy (`always` / `interval` / `never`): every append pushes
bytes to the OS (so a process kill loses nothing that was appended —
only power loss can), and `always` additionally fsyncs per append,
`interval` at most once per `fsync_interval_s` (plus at every `sync()`
— the server calls it on the flush boundary), `never` leaves syncing
to the kernel.

Snapshot + compaction is atomic: the full state is written to a temp
file, fsynced, `os.replace`d over the snapshot, the directory entry
fsynced, and only THEN is the journal truncated — a crash at any point
leaves either the old (snapshot, journal) pair or the new one, never a
mix with holes.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time

log = logging.getLogger("veneur_tpu.durability")

MAGIC = b"VTPUJRN1"
SNAP_MAGIC = b"VTPUSNP1"
_GEN = struct.Struct("<Q")              # file generation, after the magic
HEADER_BYTES = len(MAGIC) + _GEN.size   # magic + generation
_HEADER = struct.Struct("<II")          # frame length, crc32c
# a frame longer than this is treated as corruption, not a record —
# bounds what a flipped length field can make recovery try to read
MAX_FRAME_BYTES = 1 << 30

FSYNC_POLICIES = ("always", "interval", "never")


def _make_crc32c_table():
    poly = 0x82F63B78                   # Castagnoli, reflected
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_CRC32C_TABLE = _make_crc32c_table()


def _crc32c_scalar(data: bytes, crc: int = 0) -> int:
    """Reference byte-at-a-time implementation (and the fast path for
    short inputs, where the vector setup would dominate)."""
    table = _CRC32C_TABLE
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ table[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


# --- vectorized CRC-32C -----------------------------------------------
#
# The flush tick CRCs the whole serialized interval (hundreds of KB);
# the byte loop above runs ~4 MB/s in CPython, which would make the
# checksum THE cost of durability (bench_suite config 12). CRC is
# linear over GF(2), which buys a numpy formulation:
#
#   * split the message into L 64-byte lanes and run the byte loop over
#     the LANE axis — 64 numpy iterations, each processing one byte
#     column of every lane at once;
#   * fold the L per-lane registers together in log2(L) rounds, where
#     "advance register x across m zero bytes" is a linear map applied
#     via four 256-entry uint32 tables (one per register byte);
#   * the advance tables for m = 2^j bytes are built once by composing
#     the 1-byte map with itself, and cached process-wide.
#
# Front-padding with zero bytes is free (a zero register stays zero
# through zero bytes), so the message is padded to a power-of-two lane
# count and the fold needs no odd-lane special case.

_LANE = 64          # bytes per lane in the columnwise pass
_ADV_LEVELS: list = []       # _ADV_LEVELS[j]: uint32[4,256], advance 2^j bytes
_ADV_LOCK = threading.Lock() # appends to _ADV_LEVELS must be ordered


def _apply_adv(tables, vals):
    """Apply a 4-table advance map to uint32 values (scalar or array)."""
    import numpy as _np
    v = _np.asarray(vals, _np.uint32)
    return (tables[0][v & 0xFF]
            ^ tables[1][(v >> 8) & 0xFF]
            ^ tables[2][(v >> 16) & 0xFF]
            ^ tables[3][(v >> 24) & 0xFF])


def _adv_level(j: int):
    """Advance-by-2^j-bytes tables, built lazily and cached (under a
    lock: a racing pair of builders appending out of order would
    assign the wrong span to a level)."""
    import numpy as _np
    if j < len(_ADV_LEVELS):             # fast path, append-only list
        return _ADV_LEVELS[j]
    with _ADV_LOCK:
        if not _ADV_LEVELS:
            # level 0: advance one byte. For the low register byte b
            # the next state is TABLE[b]; a byte at position p>0 just
            # shifts down 8 bits (its low byte is 0 and TABLE[0] == 0).
            b = _np.arange(256, dtype=_np.uint32)
            t0 = _np.array(_CRC32C_TABLE, _np.uint32)
            _ADV_LEVELS.append(_np.stack([t0, b, b << 8, b << 16]))
        while len(_ADV_LEVELS) <= j:
            cur = _ADV_LEVELS[-1]
            _ADV_LEVELS.append(_np.stack(
                [_apply_adv(cur, cur[p]) for p in range(4)]))
        return _ADV_LEVELS[j]


def _advance(crc: int, n_bytes: int) -> int:
    """Advance a raw register across n zero bytes (binary decompose)."""
    j = 0
    while n_bytes:
        if n_bytes & 1:
            crc = int(_apply_adv(_adv_level(j), crc))
        n_bytes >>= 1
        j += 1
    return crc


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) — the checksum the storage world uses for
    record framing (iSCSI, ext4, leveldb); stdlib zlib only ships the
    IEEE polynomial. Short inputs take the table loop; long ones the
    vectorized lane fold (bit-identical: tests pin both against the
    published check value and each other)."""
    n = len(data)
    if n < 4 * _LANE:
        return _crc32c_scalar(data, crc)
    import numpy as np
    lanes = 1 << max(0, (n - 1).bit_length() - 6)   # pow2 >= n/64
    total = lanes * _LANE
    buf = np.zeros(total, np.uint8)
    # vlint: disable=DR02 reason=CRC lane fold reads the frame bytes as
    # u8 lanes for checksumming — not an engine-state codec
    buf[total - n:] = np.frombuffer(data, np.uint8)  # front zero-pad
    cols = buf.reshape(lanes, _LANE)
    t0 = np.array(_CRC32C_TABLE, np.uint32)
    reg = np.zeros(lanes, np.uint32)
    for jcol in range(_LANE):
        reg = (reg >> 8) ^ t0[(reg ^ cols[:, jcol]) & 0xFF]
    # log-depth fold: advance the left lane across the right lane's span
    level = 6                                        # 2^6 = _LANE bytes
    while len(reg) > 1:
        tables = _adv_level(level)
        reg = _apply_adv(tables, reg[0::2]) ^ reg[1::2]
        level += 1
    raw = int(reg[0])
    init = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    return (raw ^ _advance(init, n)) ^ 0xFFFFFFFF


def encode_frame(rec_type: int, payload: bytes) -> bytes:
    body = bytes([rec_type]) + payload
    return _HEADER.pack(len(body), crc32c(body)) + body


def decode_frames(data: bytes, offset: int = 0):
    """Parse frames from `data[offset:]`. Returns
    (records, good_end_offset, truncated) where records is a list of
    (rec_type, payload) and truncated is True when a bad/partial frame
    stopped the scan before the end of the buffer."""
    records = []
    n = len(data)
    while True:
        if offset + _HEADER.size > n:
            return records, offset, offset != n
        length, crc = _HEADER.unpack_from(data, offset)
        start = offset + _HEADER.size
        if length < 1 or length > MAX_FRAME_BYTES or start + length > n:
            return records, offset, True
        body = data[start:start + length]
        if crc32c(body) != crc:
            return records, offset, True
        records.append((body[0], body[1:]))
        offset = start + length


def read_framed_file(path: str, magic: bytes):
    """Read one framed file (journal/snapshot/history segment/manifest):
    (records, generation, good_end_offset, truncated). A missing,
    short, or wrong-magic file reads as empty with generation -1
    (unknown); a torn tail stops the scan at the last good frame.
    Never raises on corruption — the shared recovery contract."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], -1, 0, False
    header = len(magic) + _GEN.size
    if len(data) < header or data[:len(magic)] != magic:
        # not ours / torn before the header finished: treat as empty,
        # flag it if there were bytes to lose
        return [], -1, 0, bool(data)
    (gen,) = _GEN.unpack_from(data, len(magic))
    records, end, truncated = decode_frames(data, header)
    return records, gen, end, truncated


def write_framed_file(path: str, magic: bytes, generation: int,
                      records, fsync: bool = True) -> int:
    """Atomically (re)write one framed file: write-temp, fsync,
    os.replace, fsync the directory — the same publish discipline as
    Journal.snapshot, shared by the history tier's segment and
    manifest writes (durability/history.py), which is why the raw file
    I/O lives HERE (vlint DR01: journal.py owns the framing/fsync/
    atomic-rename contract). A crash at any point leaves either the
    old file or the new one, never a torn mix. Returns bytes written."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(magic + _GEN.pack(int(generation)))
        for rec_type, payload in records:
            f.write(encode_frame(rec_type, payload))
        f.flush()
        if fsync:
            os.fsync(f.fileno())
        nbytes = f.tell()
    os.replace(tmp, path)
    if fsync:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    return nbytes


class Journal:
    """One named journal + snapshot pair inside a durability directory.

    Lifecycle: construct, `load()` once to recover state (returns the
    snapshot's records and the journal's records, in write order), then
    `append()`/`sync()` during serving and `snapshot()` at compaction
    points. `load()` also truncates any torn tail so the append cursor
    starts at the last good frame. Thread-safe: appends from gRPC
    handler threads and the flusher interleave under one lock."""

    def __init__(self, directory: str, name: str,
                 fsync: str = "interval", fsync_interval_s: float = 1.0,
                 clock=time.monotonic, registry=None,
                 destination: str = "durability"):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy must be one of {FSYNC_POLICIES}, "
                f"got {fsync!r}")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.journal_path = os.path.join(directory, name + ".journal")
        self.snapshot_path = os.path.join(directory, name + ".snapshot")
        # exclusivity: two processes appending to one journal corrupt
        # each other silently (interleaved frames fail CRC and recovery
        # truncates them away as "torn") — so each journal holds an
        # advisory flock for its lifetime and a second opener fails
        # LOUDLY. A real SIGKILL releases the lock with the fd; the
        # in-process kill simulations release it via release_lock().
        self._lock_f = None
        try:
            import fcntl
        except ImportError:          # pragma: no cover - non-unix
            fcntl = None
        if fcntl is not None:
            self._lock_f = open(os.path.join(directory, name + ".lock"),
                                "a+b")
            try:
                fcntl.flock(self._lock_f.fileno(),
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                self._lock_f.close()
                self._lock_f = None
                raise RuntimeError(
                    f"durability journal {name!r} in {directory} is "
                    "already locked by a live process — two appenders "
                    "on one journal corrupt each other; point each "
                    "server at its own durability_dir") from None
        self.fsync_policy = fsync
        self.fsync_interval_s = fsync_interval_s
        self._clock = clock
        if registry is None:
            # the unified telemetry spine (observe/registry.py) — the
            # same process-default instance resilience re-exports
            from ..observe.registry import DEFAULT_REGISTRY
            registry = DEFAULT_REGISTRY
        self._registry = registry
        self._destination = destination
        self._lock = threading.RLock()
        self._f = None
        self._generation = 0
        self._last_fsync = clock()
        self.last_snapshot_ns = 0       # duration of the last snapshot

    # ------------------------------------------------------------ load

    def _read_validated(self, path: str, magic: bytes):
        """(records, generation, good_length, truncated) for one framed
        file; a missing/short/wrong-magic file reads as empty with
        generation -1 (unknown)."""
        return read_framed_file(path, magic)

    def load(self):
        """Recover: returns (snapshot_records or None, journal_records).
        Truncates the journal's torn tail on disk and opens it for
        append, so every later `append()` lands after the last good
        frame. A journal whose generation predates the snapshot's was
        already folded into it (the crash hit between the snapshot
        rename and the journal truncate) — its records are dropped
        whole, not double-applied. Never raises on corruption — bad
        frames and everything after them are dropped, counted in
        truncated_frames_total."""
        with self._lock:
            snap_records, snap_gen, _end, snap_torn = \
                self._read_validated(self.snapshot_path, SNAP_MAGIC)
            jrn_records, jrn_gen, good_end, jrn_torn = \
                self._read_validated(self.journal_path, MAGIC)
            for torn, path in ((snap_torn, self.snapshot_path),
                               (jrn_torn, self.journal_path)):
                if torn:
                    self._registry.incr(self._destination,
                                        "durability.truncated_frames")
                    log.warning(
                        "durability: torn/corrupt frame in %s; "
                        "truncating to last good record", path)
            snapshot = snap_records if snap_gen >= 0 and snap_records \
                else None
            if snapshot is not None and jrn_gen < snap_gen:
                # stale journal: its ops are already inside the
                # snapshot; replaying both would double-apply
                self._registry.incr(self._destination,
                                    "durability.stale_journal_dropped")
                log.warning(
                    "durability: journal %s generation %d predates "
                    "snapshot generation %d (crash between snapshot "
                    "rename and truncate); dropping %d already-"
                    "compacted record(s)", self.journal_path, jrn_gen,
                    snap_gen, len(jrn_records))
                jrn_records = []
                good_end = 0       # restamp the journal below
            self._generation = max(snap_gen, jrn_gen, 0)
            # open for append at the last good offset (creates + writes
            # the header when the file is new, torn inside the header,
            # or stale)
            if good_end < HEADER_BYTES:
                self._f = open(self.journal_path, "wb")
                self._f.write(MAGIC + _GEN.pack(self._generation))
            else:
                self._f = open(self.journal_path, "r+b")
                self._f.truncate(good_end)
                self._f.seek(good_end)
            self._f.flush()
            if self.fsync_policy != "never":
                os.fsync(self._f.fileno())
            return snapshot, jrn_records

    # ---------------------------------------------------------- append

    def _ensure_open(self):
        if self._f is None:
            raise RuntimeError(
                "Journal.load() must run before append() — recovery "
                "truncates the torn tail the append cursor depends on")

    def append(self, rec_type: int, payload: bytes):
        with self._lock:
            self._ensure_open()
            frame = encode_frame(rec_type, payload)
            self._f.write(frame)
            # always push to the OS: a process kill then loses nothing
            # that was appended; fsync policy only governs power loss
            self._f.flush()
            if self.fsync_policy == "always":
                os.fsync(self._f.fileno())
                self._last_fsync = self._clock()
            elif self.fsync_policy == "interval":
                now = self._clock()
                if now - self._last_fsync >= self.fsync_interval_s:
                    os.fsync(self._f.fileno())
                    self._last_fsync = now
            self._registry.incr(self._destination,
                                "durability.journal_appends")
        return len(frame)

    def sync(self):
        """Flush + fsync now (flush-boundary / shutdown hook); a no-op
        for policy `never` beyond pushing buffered bytes to the OS."""
        with self._lock:
            if self._f is None:
                return
            self._f.flush()
            if self.fsync_policy != "never":
                os.fsync(self._f.fileno())
                self._last_fsync = self._clock()

    def size_bytes(self) -> int:
        with self._lock:
            if self._f is None:
                try:
                    return os.path.getsize(self.journal_path)
                except OSError:
                    return 0
            return self._f.tell()

    # -------------------------------------------------------- snapshot

    def snapshot(self, records) -> int:
        """Atomically replace the snapshot with `records` (a list of
        (rec_type, payload)) and truncate the journal: write-temp,
        fsync, rename, fsync the directory, THEN truncate + restamp.
        The snapshot carries generation G+1 while the journal still
        says G until the truncate lands, so a crash anywhere inside
        this sequence recovers to either (old snapshot + full journal)
        or (new snapshot + empty-or-dropped journal) — never a
        double-application. Returns the snapshot duration in ns (the
        veneur.durability.snapshot_duration gauge)."""
        with self._lock:
            self._ensure_open()
            t0 = time.monotonic_ns()
            new_gen = self._generation + 1
            tmp = self.snapshot_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(SNAP_MAGIC + _GEN.pack(new_gen))
                for rec_type, payload in records:
                    f.write(encode_frame(rec_type, payload))
                f.flush()
                if self.fsync_policy != "never":
                    os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
            if self.fsync_policy != "never":
                # the rename must be durable BEFORE the journal shrinks,
                # or a crash between the two leaves neither
                dfd = os.open(self.directory, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            self._f.seek(0)
            self._f.truncate(0)
            self._f.write(MAGIC + _GEN.pack(new_gen))
            self._f.flush()
            self._generation = new_gen
            if self.fsync_policy != "never":
                os.fsync(self._f.fileno())
                self._last_fsync = self._clock()
            self.last_snapshot_ns = time.monotonic_ns() - t0
            self._registry.incr(self._destination,
                                "durability.snapshots")
            return self.last_snapshot_ns

    def release_lock(self):
        """Drop the advisory process lock WITHOUT flushing or closing
        the journal — what a real SIGKILL does to the fd. Exists for
        the kill-restart simulations (utils.faults.kill_journal_lock);
        production code never calls it."""
        with self._lock:
            if self._lock_f is not None:
                self._lock_f.close()
                self._lock_f = None

    def close(self):
        with self._lock:
            if self._f is not None:
                self.sync()
                self._f.close()
                self._f = None
            self.release_lock()
