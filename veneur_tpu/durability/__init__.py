"""Durable state: a crash-safe journal for the forward replay ledger,
the merged spill tier, and the receiver's dedupe watermarks.

The reference design was crash-only because its state was one interval
deep — a restart lost at most the interval in flight. The exactly-once
forward machinery changed that: the sender now holds a bounded
multi-interval replay ladder plus a merged spill tier, and the receiver
holds per-sender dedupe watermarks; all of it evaporated on restart,
silently reopening the under-/over-count windows the idempotency
envelopes exist to close. This package persists exactly that state:

  * `journal` — the storage layer: an append-only, CRC32C-framed,
    length-prefixed record log with torn-write tolerance (recovery
    truncates at the first bad frame, counted), a configurable fsync
    policy (`always` / `interval` / `never`), and atomic
    snapshot+compaction (write-temp, fsync, rename) at flush
    boundaries.
  * `records` — the typed layer: serializes parked `ForwardEnvelope`
    intervals (reusing `cluster/wire.py`'s sketch codecs — centroids,
    HLL registers, counters, gauges), spill-tier contents with gauge
    ages, and receiver-side per-sender watermarks.
  * `state` — the integration façades: `ForwardJournal` (the sender's
    op log, consumed by `resilience.ResilientForwarder`),
    `WatermarkJournal` (the receiver's per-flush watermark log,
    consumed by `Server` + `cluster.importsrv.DedupeLedger`), and
    `EngineJournal` (the global tier's engine-state log: write-ahead
    import ops + per-engine delta checkpoints at flush boundaries, so
    the fleet's admitted-and-merged interval state survives a crash
    and a restarted global flushes BIT-IDENTICAL state).

Mergeable-sketch semantics are what make the recovered state safe: a
parked interval's t-digest centroids / HLL registers / counter sums
re-merge losslessly after a crash, and replaying them under their
ORIGINAL envelopes lets the receiver's dedupe ledger drop anything it
already Combined before the crash.

All on-disk writes in this package go through the `Journal` append /
snapshot API — vlint DR01 machine-checks that no other module under
`durability/` opens files for writing.

The `history` module (ISSUE 14) grows a READ tier on top: a retained
window of committed checkpoint generations (one per closed flush
interval, manifest-indexed by interval-close wall time) and the
time-travel query engine behind `GET /query` — historical percentiles,
counts, and cardinalities reconstructed through the same recovery-
restore path, into scratch engines, never the live banks.
"""

from .history import HistoryStore, QueryError, QueryTier
from .journal import Journal, crc32c
from .state import EngineJournal, ForwardJournal, WatermarkJournal

__all__ = ["Journal", "crc32c", "EngineJournal", "ForwardJournal",
           "WatermarkJournal", "HistoryStore", "QueryTier",
           "QueryError"]
