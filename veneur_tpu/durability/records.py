"""The typed record layer over the journal framing.

Sketch payloads reuse the cluster wire codecs (`cluster/wire.py`): a
parked interval's `ForwardExport` serializes as a `forwardrpc.
MetricList` — the exact bytes the forwarder would put on the wire —
plus a side channel of exact f64 counter values (the wire rounds
counters to int64; the journal must hand back exactly what was parked
so a recovered replay is bit-identical to the send the crash
interrupted). t-digest centroids, HLL registers, gauges, and the
min/max/sum/count/reciprocal_sum scalars are all lossless in the
MetricList itself.

Record kinds (sender-side "forward" journal — an op log whose replay
reconstructs the `ResilientForwarder` ladder + spill tier exactly):

    META         sender_id + next interval_seq (identity; a recovered
                 sender MUST resume under its original sender_id or the
                 receiver's dedupe ledger cannot see its replays)
    BEGIN        a send attempt entered the ladder: seq, chunk
                 progress, age, and the full export payload (write-
                 ahead: appended BEFORE any wire traffic, so a crash
                 mid-ladder cannot lose the interval)
    DONE         seq delivered — the entry leaves the ladder
    UPDATE       partial delivery / spill re-merge changed an entry's
                 export or chunk progress
    AGE          one failed-flush aging pass over the ladder
    DEMOTE       the oldest entry overflowed into the merged spill tier
    SPILL_MERGE  the spill tier drained into the current interval
    SPILL_STATE  full spill-tier contents (snapshot compaction only)

Receiver-side "dedupe" journal:

    WATERMARKS   the per-sender max admitted interval_seq at a flush
                 boundary — a restarted global restores these so an
                 ancient replay (already flushed downstream before the
                 crash) is dropped, not re-admitted
"""

from __future__ import annotations

import struct

from ..models.pipeline import ForwardExport

REC_META = 1
REC_BEGIN = 2
REC_DONE = 3
REC_UPDATE = 4
REC_AGE = 5
REC_DEMOTE = 6
REC_SPILL_MERGE = 7
REC_SPILL_STATE = 8
REC_WATERMARKS = 9

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")
_BEGIN_HEAD = struct.Struct("<QIII")    # seq, chunk_offset, chunk_count, age
_UPDATE_HEAD = struct.Struct("<QII")    # seq, chunk_offset, chunk_count


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return _U32.pack(len(b)) + b


def _unpack_str(data: bytes, off: int):
    (n,) = _U32.unpack_from(data, off)
    off += _U32.size
    return data[off:off + n].decode("utf-8"), off + n


# ------------------------------------------------------------- exports

def encode_export(export: ForwardExport) -> bytes:
    """ForwardExport -> MetricList bytes + exact f64 counter values."""
    from ..cluster import wire
    from ..cluster.protos import forward_pb2
    blob = forward_pb2.MetricList(
        metrics=wire.export_to_metrics(export)).SerializeToString()
    exact = b"".join(_F64.pack(float(v)) for _k, v in export.counters)
    return _U32.pack(len(blob)) + blob + exact


def decode_export(data: bytes, off: int = 0):
    """-> (ForwardExport, next_offset). Inverse of encode_export; the
    exact counter side channel overwrites the wire's int64 rounding."""
    from ..cluster import wire
    from ..cluster.protos import forward_pb2
    (n,) = _U32.unpack_from(data, off)
    off += _U32.size
    ml = forward_pb2.MetricList.FromString(data[off:off + n])
    off += n
    export = wire.export_from_metrics(ml.metrics)
    for i in range(len(export.counters)):
        key, _v = export.counters[i]
        (v,) = _F64.unpack_from(data, off)
        off += _F64.size
        export.counters[i] = (key, v)
    return export, off


# ------------------------------------------------- sender (forward) ops

def encode_meta(sender_id: str, next_seq: int) -> bytes:
    return _pack_str(sender_id) + _U64.pack(next_seq)


def decode_meta(data: bytes):
    sender_id, off = _unpack_str(data, 0)
    (next_seq,) = _U64.unpack_from(data, off)
    return sender_id, next_seq


def encode_begin(seq: int, chunk_offset: int, chunk_count: int,
                 age: int, export: ForwardExport) -> bytes:
    return _BEGIN_HEAD.pack(seq, chunk_offset, chunk_count, age) \
        + encode_export(export)


def decode_begin(data: bytes):
    seq, chunk_offset, chunk_count, age = _BEGIN_HEAD.unpack_from(data, 0)
    export, _ = decode_export(data, _BEGIN_HEAD.size)
    return seq, chunk_offset, chunk_count, age, export


def encode_done(seq: int) -> bytes:
    return _U64.pack(seq)


def decode_done(data: bytes) -> int:
    return _U64.unpack_from(data, 0)[0]


def encode_update(seq: int, chunk_offset: int, chunk_count: int,
                  export: ForwardExport) -> bytes:
    return _UPDATE_HEAD.pack(seq, chunk_offset, chunk_count) \
        + encode_export(export)


def decode_update(data: bytes):
    seq, chunk_offset, chunk_count = _UPDATE_HEAD.unpack_from(data, 0)
    export, _ = decode_export(data, _UPDATE_HEAD.size)
    return seq, chunk_offset, chunk_count, export


# ------------------------------------------------------ spill snapshot

def encode_spill_state(spill) -> bytes:
    """Full spill-tier contents for snapshot compaction: the sketch
    dicts ride as one export payload (same wire codecs), gauge ages and
    the remembered merge ages ride as side lists keyed by position/key.
    """
    export = ForwardExport()
    export.histograms.extend(
        (key, h[0], h[1], h[2], h[3], h[4], h[5], h[6])
        for key, h in spill._histos.items())
    export.sets.extend(spill._sets.items())
    export.counters.extend(spill._counters.items())
    export.gauges.extend((key, v) for key, (v, _a)
                         in spill._gauges.items())
    out = [encode_export(export)]
    ages = [a for _v, a in spill._gauges.values()]
    out.append(_U32.pack(len(ages)))
    out.extend(_U32.pack(a) for a in ages)
    out.append(_U32.pack(len(spill._merged_gauge_ages)))
    for key, age in spill._merged_gauge_ages.items():
        out.append(_pack_str(key.name) + _pack_str(key.type)
                   + _pack_str(key.joined_tags) + _U32.pack(age))
    return b"".join(out)


def decode_spill_state(data: bytes, spill) -> None:
    """Restore `spill` (a fresh SpillBuffer) from encode_spill_state
    bytes."""
    import numpy as np

    from ..ingest.parser import MetricKey
    export, off = decode_export(data, 0)
    for key, means, weights, vmin, vmax, vsum, cnt, recip in (
            export.histograms):
        spill._histos[key] = [np.asarray(means, np.float32),
                              np.asarray(weights, np.float32),
                              float(vmin), float(vmax), float(vsum),
                              float(cnt), float(recip)]
    for key, regs in export.sets:
        spill._sets[key] = np.asarray(regs, np.uint8)
    for key, value in export.counters:
        spill._counters[key] = float(value)
    (n_ages,) = _U32.unpack_from(data, off)
    off += _U32.size
    ages = []
    for _ in range(n_ages):
        (a,) = _U32.unpack_from(data, off)
        off += _U32.size
        ages.append(a)
    for (key, value), age in zip(export.gauges, ages):
        spill._gauges[key] = [float(value), age]
    (n_merged,) = _U32.unpack_from(data, off)
    off += _U32.size
    for _ in range(n_merged):
        name, off = _unpack_str(data, off)
        mtype, off = _unpack_str(data, off)
        tags, off = _unpack_str(data, off)
        (age,) = _U32.unpack_from(data, off)
        off += _U32.size
        spill._merged_gauge_ages[MetricKey(name, mtype, tags)] = age


# --------------------------------------------- receiver (dedupe) marks

def encode_watermarks(marks: dict) -> bytes:
    out = [_U32.pack(len(marks))]
    for sender_id, seq in marks.items():
        out.append(_pack_str(sender_id) + _U64.pack(int(seq)))
    return b"".join(out)


def decode_watermarks(data: bytes) -> dict:
    (n,) = _U32.unpack_from(data, 0)
    off = _U32.size
    marks = {}
    for _ in range(n):
        sender_id, off = _unpack_str(data, off)
        (seq,) = _U64.unpack_from(data, off)
        off += _U64.size
        marks[sender_id] = seq
    return marks
