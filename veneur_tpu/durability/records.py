"""The typed record layer over the journal framing.

Sketch payloads reuse the cluster wire codecs (`cluster/wire.py`): a
parked interval's `ForwardExport` serializes as a `forwardrpc.
MetricList` — the exact bytes the forwarder would put on the wire —
plus a side channel of exact f64 counter values (the wire rounds
counters to int64; the journal must hand back exactly what was parked
so a recovered replay is bit-identical to the send the crash
interrupted). t-digest centroids, HLL registers, gauges, and the
min/max/sum/count/reciprocal_sum scalars are all lossless in the
MetricList itself.

Record kinds (sender-side "forward" journal — an op log whose replay
reconstructs the `ResilientForwarder` ladder + spill tier exactly):

    META         sender_id + next interval_seq (identity; a recovered
                 sender MUST resume under its original sender_id or the
                 receiver's dedupe ledger cannot see its replays)
    BEGIN        a send attempt entered the ladder: seq, chunk
                 progress, age, and the full export payload (write-
                 ahead: appended BEFORE any wire traffic, so a crash
                 mid-ladder cannot lose the interval)
    DONE         seq delivered — the entry leaves the ladder
    UPDATE       partial delivery / spill re-merge changed an entry's
                 export or chunk progress
    AGE          one failed-flush aging pass over the ladder
    DEMOTE       the oldest entry overflowed into the merged spill tier
    SPILL_MERGE  the spill tier drained into the current interval
    SPILL_STATE  full spill-tier contents (snapshot compaction only)

Receiver-side "dedupe" journal:

    WATERMARKS   the per-sender max admitted interval_seq at a flush
                 boundary — a restarted global restores these so an
                 ancient replay (already flushed downstream before the
                 crash) is dropped, not re-admitted

Receiver-side "engine" journal (ISSUE 9 — global-tier checkpoint):

    ENGINE_IMPORT  write-ahead op log: one admitted import request's
                   metrics as forwardrpc.MetricList bytes (the wire
                   codec, reused verbatim) under a monotone op id,
                   appended BEFORE the worker queues see the metrics
                   and before the sender's ack — an admitted-and-acked
                   interval can no longer die with the process
    ENGINE_META    one engine's checkpoint header at a flush boundary:
                   shape fingerprint (a restore against a differently-
                   configured engine must refuse loudly, not scatter
                   rows into the wrong slots), the applied-op
                   watermark (ops <= it are inside the checkpoint; ops
                   above it replay on top), and the gauge sequence
    ENGINE_KEYS    one bank's full interner table (slot -> key/scope/
                   last-interval) + the interner's interval counter
    ENGINE_BANK    one bank's DIRTY rows: banks are interval-scoped
                   (the flush swap re-zeroes every row), so "fresh
                   init + the rows touched since the swap" IS the full
                   bank state — a checkpoint is self-contained and a
                   steady-state tick serializes only touched piles.
                   Leaves ride as raw little-endian numpy bytes:
                   recovery must hand back BIT-EXACT f32/u8 rows (the
                   wire's centroid list drops zero-weight entries and
                   re-orders — fine for forwarding, fatal for a
                   restore that must flush bit-identically). This
                   module is the ONLY home of bank-leaf tobytes()/
                   frombuffer (vlint DR02).
    ENGINE_STAGED  one engine's staged-but-unlanded import
                   accumulators (centroid piles, HLL rows, exact-f64
                   counter sums, last-write-wins gauges) — applied ops
                   whose data has not reached the device yet live
                   here, so the watermark stays honest
    ENGINE_COMMIT  group-completeness marker, LAST record of each
                   engine's checkpoint group. The group's records are
                   separate journal frames, so a crash mid-append can
                   leave META on disk without its KEYS/BANK/STAGED —
                   recovery only accepts a group whose COMMIT arrived,
                   falling back to the engine's previous complete
                   group otherwise (a torn META whose watermark still
                   suppressed op replay would be silent data loss)
"""

from __future__ import annotations

import struct

import numpy as np

from ..models.pipeline import ForwardExport

REC_META = 1
REC_BEGIN = 2
REC_DONE = 3
REC_UPDATE = 4
REC_AGE = 5
REC_DEMOTE = 6
REC_SPILL_MERGE = 7
REC_SPILL_STATE = 8
REC_WATERMARKS = 9
REC_ENGINE_IMPORT = 10
REC_ENGINE_META = 11
REC_ENGINE_KEYS = 12
REC_ENGINE_BANK = 13
REC_ENGINE_STAGED = 14
REC_ENGINE_COMMIT = 15
# time-travel history tier (ISSUE 14, durability/history.py):
#   HISTORY_META   first record of a history SEGMENT file — one closed
#                  flush interval's identity: generation id, the
#                  interval-close wall time, the previous boundary's
#                  close time (the interval's open edge), the
#                  per-engine RETIRE watermarks (the op ids the flush
#                  swap actually carried — the exact per-engine upper
#                  replay cut for this interval), and the op-id range
#                  the segment retains. The rest of the segment is the
#                  previous boundary's checkpoint groups (REC_ENGINE_*
#                  records, reused verbatim — the interval's baseline)
#                  followed by the interval's write-ahead import ops.
#   HISTORY_INDEX  one manifest row per COMMITTED generation: id,
#                  close/open times, segment byte size. The manifest
#                  is rewritten atomically; a generation absent from
#                  it is not committed, whatever files exist.
REC_HISTORY_META = 16
REC_HISTORY_INDEX = 17

# engine bank kinds (the order pipeline.AggregationEngine owns them in)
BANK_HISTO = 0
BANK_COUNTER = 1
BANK_GAUGE = 2
BANK_SET = 3

# leaf order per bank kind — load-bearing: encode and decode walk the
# same tuple, and a new leaf added to a bank NamedTuple must be added
# here (the fingerprint's shape fields catch width drift, this catches
# leaf drift)
HISTO_LEAVES = ("mean", "weight", "buf_value", "buf_weight", "buf_n",
                "vmin", "vmax", "vsum", "count", "recip", "vsum_lo",
                "count_lo", "recip_lo")
COUNTER_LEAVES = ("hi", "lo")
GAUGE_LEAVES = ("value", "seq")
SET_LEAVES = ("registers",)
BANK_LEAVES = {BANK_HISTO: HISTO_LEAVES, BANK_COUNTER: COUNTER_LEAVES,
               BANK_GAUGE: GAUGE_LEAVES, BANK_SET: SET_LEAVES}

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")
_BEGIN_HEAD = struct.Struct("<QIII")    # seq, chunk_offset, chunk_count, age
_UPDATE_HEAD = struct.Struct("<QII")    # seq, chunk_offset, chunk_count


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return _U32.pack(len(b)) + b


def _unpack_str(data: bytes, off: int):
    (n,) = _U32.unpack_from(data, off)
    off += _U32.size
    return data[off:off + n].decode("utf-8"), off + n


# ------------------------------------------------------------- exports

def encode_export(export: ForwardExport) -> bytes:
    """ForwardExport -> MetricList bytes + exact f64 counter values."""
    from ..cluster import wire
    from ..cluster.protos import forward_pb2
    blob = forward_pb2.MetricList(
        metrics=wire.export_to_metrics(export)).SerializeToString()
    exact = b"".join(_F64.pack(float(v)) for _k, v in export.counters)
    return _U32.pack(len(blob)) + blob + exact


def decode_export(data: bytes, off: int = 0):
    """-> (ForwardExport, next_offset). Inverse of encode_export; the
    exact counter side channel overwrites the wire's int64 rounding."""
    from ..cluster import wire
    from ..cluster.protos import forward_pb2
    (n,) = _U32.unpack_from(data, off)
    off += _U32.size
    ml = forward_pb2.MetricList.FromString(data[off:off + n])
    off += n
    export = wire.export_from_metrics(ml.metrics)
    for i in range(len(export.counters)):
        key, _v = export.counters[i]
        (v,) = _F64.unpack_from(data, off)
        off += _F64.size
        export.counters[i] = (key, v)
    return export, off


# ------------------------------------------------- sender (forward) ops

def encode_meta(sender_id: str, next_seq: int) -> bytes:
    return _pack_str(sender_id) + _U64.pack(next_seq)


def decode_meta(data: bytes):
    sender_id, off = _unpack_str(data, 0)
    (next_seq,) = _U64.unpack_from(data, off)
    return sender_id, next_seq


def encode_begin(seq: int, chunk_offset: int, chunk_count: int,
                 age: int, export: ForwardExport,
                 kind: str = "full") -> bytes:
    """The kind byte (0 = full, 1 = delta) trails the export payload:
    a parked interval replays under its ORIGINAL full/delta marker
    after a crash (a recovered delta re-stamped full would silently
    reset the receiver's gap baseline). Trailing keeps pre-ISSUE-13
    journals decodable — an absent byte reads as "full", which every
    pre-delta interval was."""
    return _BEGIN_HEAD.pack(seq, chunk_offset, chunk_count, age) \
        + encode_export(export) \
        + (b"\x01" if kind == "delta" else b"\x00")


def decode_begin(data: bytes):
    seq, chunk_offset, chunk_count, age = _BEGIN_HEAD.unpack_from(data, 0)
    export, off = decode_export(data, _BEGIN_HEAD.size)
    kind = "delta" if (off < len(data) and data[off] == 1) else "full"
    return seq, chunk_offset, chunk_count, age, export, kind


def encode_done(seq: int) -> bytes:
    return _U64.pack(seq)


def decode_done(data: bytes) -> int:
    return _U64.unpack_from(data, 0)[0]


def encode_update(seq: int, chunk_offset: int, chunk_count: int,
                  export: ForwardExport) -> bytes:
    return _UPDATE_HEAD.pack(seq, chunk_offset, chunk_count) \
        + encode_export(export)


def decode_update(data: bytes):
    seq, chunk_offset, chunk_count = _UPDATE_HEAD.unpack_from(data, 0)
    export, _ = decode_export(data, _UPDATE_HEAD.size)
    return seq, chunk_offset, chunk_count, export


# ------------------------------------------------------ spill snapshot

def encode_spill_state(spill) -> bytes:
    """Full spill-tier contents for snapshot compaction: the sketch
    dicts ride as one export payload (same wire codecs), gauge ages and
    the remembered merge ages ride as side lists keyed by position/key.
    """
    export = ForwardExport(
        # the engine tag rides the wire row (byte 0): without it a ULL
        # server's spilled registers would journal under the HLL code
        # and silently max-join after a cross-engine restore
        set_engine=getattr(spill, "set_engine", "hll"))
    export.histograms.extend(
        (key, h[0], h[1], h[2], h[3], h[4], h[5], h[6])
        for key, h in spill._histos.items())
    export.sets.extend(spill._sets.items())
    export.counters.extend(spill._counters.items())
    export.gauges.extend((key, v) for key, (v, _a)
                         in spill._gauges.items())
    out = [encode_export(export)]
    ages = [a for _v, a in spill._gauges.values()]
    out.append(_U32.pack(len(ages)))
    out.extend(_U32.pack(a) for a in ages)
    out.append(_U32.pack(len(spill._merged_gauge_ages)))
    for key, age in spill._merged_gauge_ages.items():
        out.append(_pack_str(key.name) + _pack_str(key.type)
                   + _pack_str(key.joined_tags) + _U32.pack(age))
    return b"".join(out)


def decode_spill_state(data: bytes, spill) -> None:
    """Restore `spill` (a fresh SpillBuffer) from encode_spill_state
    bytes."""
    import numpy as np

    from ..ingest.parser import MetricKey
    export, off = decode_export(data, 0)
    # restore the engine the registers were tagged with, so later
    # same-key spills join under the right semantics and re-forwards
    # carry the original code (a backend-switched restart then fails
    # LOUDLY at the receiver's belt check, never silently merges)
    spill.set_engine = export.set_engine
    for key, means, weights, vmin, vmax, vsum, cnt, recip in (
            export.histograms):
        spill._histos[key] = [np.asarray(means, np.float32),
                              np.asarray(weights, np.float32),
                              float(vmin), float(vmax), float(vsum),
                              float(cnt), float(recip)]
    for key, regs in export.sets:
        spill._sets[key] = np.asarray(regs, np.uint8)
    for key, value in export.counters:
        spill._counters[key] = float(value)
    (n_ages,) = _U32.unpack_from(data, off)
    off += _U32.size
    ages = []
    for _ in range(n_ages):
        (a,) = _U32.unpack_from(data, off)
        off += _U32.size
        ages.append(a)
    for (key, value), age in zip(export.gauges, ages):
        spill._gauges[key] = [float(value), age]
    (n_merged,) = _U32.unpack_from(data, off)
    off += _U32.size
    for _ in range(n_merged):
        name, off = _unpack_str(data, off)
        mtype, off = _unpack_str(data, off)
        tags, off = _unpack_str(data, off)
        (age,) = _U32.unpack_from(data, off)
        off += _U32.size
        spill._merged_gauge_ages[MetricKey(name, mtype, tags)] = age


# --------------------------------------------- receiver (dedupe) marks

def encode_watermarks(marks: dict) -> bytes:
    out = [_U32.pack(len(marks))]
    for sender_id, seq in marks.items():
        out.append(_pack_str(sender_id) + _U64.pack(int(seq)))
    return b"".join(out)


def decode_watermarks(data: bytes) -> dict:
    (n,) = _U32.unpack_from(data, 0)
    off = _U32.size
    marks = {}
    for _ in range(n):
        sender_id, off = _unpack_str(data, off)
        (seq,) = _U64.unpack_from(data, off)
        off += _U64.size
        marks[sender_id] = seq
    return marks


# ---------------------------------------- engine checkpoint (global tier)
#
# The serialization home for engine state (vlint DR02): bank leaves
# cross into and out of the journal ONLY here, as raw little-endian
# numpy buffers — bit-exact by construction, no float formatting, no
# zero-weight dropping, no re-ordering.

_ENG_META = struct.Struct("<IIQQ")      # engine_idx, n_engines, watermark,
                                        # gauge_seq
_ENG_FPR = struct.Struct("<IIIIIIId")   # histo K, C, B, counter K, gauge K,
                                        # set K, hll m, compression
_ENG_KEYS_HEAD = struct.Struct("<IBII")  # engine_idx, bank_kind, interval, n
_ENG_KEY_ENTRY = struct.Struct("<IiI")   # slot, scope, last_interval
_ENG_BANK_HEAD = struct.Struct("<IBI")   # engine_idx, bank_kind, n_rows
_ENG_LEAF_HEAD = struct.Struct("<BI")    # dtype code, row width (0 = 1-D)

_DTYPE_CODES = {0: np.float32, 1: np.int32, 2: np.uint8, 3: np.int64}
_CODE_OF_DTYPE = {np.dtype(v): k for k, v in _DTYPE_CODES.items()}


def _engine_descs(cfg) -> tuple | None:
    """Engine-identity strings appended to the fingerprint when the
    config selects NON-DEFAULT sketch backends (ISSUE 10): a restore
    into a different backend — or the same backend at different
    accuracy params — must refuse loudly, not scatter one engine's
    leaf bytes into another's banks. None for the default pair, so
    default-engine fingerprints (and every pre-registry journal) keep
    their original 8-tuple shape byte-for-byte."""
    hb = getattr(cfg, "histogram_backend", "tdigest")
    sb = getattr(cfg, "set_backend", "hll")
    if hb == "tdigest" and sb == "hll":
        return None
    if hb == "tdigest":
        hd = "tdigest/1"
    else:
        hd = (f"req/1:levels={int(getattr(cfg, 'req_levels', 2))},"
              f"capacity={int(getattr(cfg, 'req_capacity', 256))}")
    if sb == "hll":
        sd = "hll/1"
    else:
        sd = f"ull/1:p={int(getattr(cfg, 'ull_precision', 13))}"
    return hd, sd


def engine_fingerprint(cfg, num_centroids: int) -> tuple:
    """The shape identity a checkpoint was taken under. A restore into
    an engine with a different fingerprint must refuse whole (rows would
    scatter into the wrong slots / wrong widths). With non-default
    sketch backends the tuple grows two engine-identity strings (see
    _engine_descs) — a checkpoint written under `ull`+`req` can never
    silently restore into a default-engine server or vice versa."""
    sb = getattr(cfg, "set_backend", "hll")
    m = (1 << int(getattr(cfg, "ull_precision", 13))) if sb == "ull" \
        else (1 << int(cfg.hll_precision))
    base = (int(cfg.histogram_slots), int(num_centroids),
            int(cfg.buffer_depth), int(cfg.counter_slots),
            int(cfg.gauge_slots), int(cfg.set_slots),
            m, float(cfg.compression))
    descs = _engine_descs(cfg)
    return base if descs is None else base + descs


def encode_engine_import(op_id: int, metrics, envelope=None) -> bytes:
    """One admitted import request: its metricpb.Metric list as
    forwardrpc.MetricList bytes (the forward wire codec, reused) under
    a monotone op id, plus the request's idempotency envelope
    (sender_id, interval_seq, chunk_index, chunk_count) when it
    carried one. The envelope is LOAD-BEARING for recovery: restoring
    admitted-and-merged state without also restoring the dedupe
    ledger's memory of its envelope would let the sender's ambiguous-
    failure replay of that same interval re-admit and DOUBLE-COUNT —
    the exact bug the one-tick-behind watermark journal was allowed to
    tolerate only while admitted state died with the process.
    Protobuf roundtrips its f32/f64 fields exactly, so replaying the
    decoded metrics is bit-identical to applying the originals."""
    from ..cluster.protos import forward_pb2
    blob = forward_pb2.MetricList(metrics=list(metrics)) \
        .SerializeToString()
    head = _U64.pack(op_id)
    if envelope is None:
        return head + b"\x00" + blob
    sender_id, seq, chunk_index, chunk_count = envelope
    return (head + b"\x01" + _pack_str(str(sender_id))
            + _U64.pack(int(seq))
            + _U32.pack(int(chunk_index)) + _U32.pack(int(chunk_count))
            + blob)


def decode_engine_import(data: bytes):
    """-> (op_id, [metricpb.Metric], envelope tuple or None)."""
    from ..cluster.protos import forward_pb2
    (op_id,) = _U64.unpack_from(data, 0)
    off = _U64.size
    has_env = data[off]
    off += 1
    envelope = None
    if has_env:
        sender_id, off = _unpack_str(data, off)
        (seq,) = _U64.unpack_from(data, off)
        off += _U64.size
        chunk_index, chunk_count = struct.unpack_from("<II", data, off)
        off += 8
        envelope = (sender_id, seq, chunk_index, chunk_count)
    ml = forward_pb2.MetricList.FromString(data[off:])
    return op_id, list(ml.metrics), envelope


def encode_engine_meta(engine_idx: int, n_engines: int, watermark: int,
                       gauge_seq: int, fingerprint: tuple) -> bytes:
    out = _ENG_META.pack(engine_idx, n_engines, watermark,
                         int(gauge_seq)) \
        + _ENG_FPR.pack(*fingerprint[:8])
    # non-default backends append their engine-identity strings; the
    # default pair stays byte-identical to the pre-registry record
    for desc in fingerprint[8:]:
        out += _pack_str(str(desc))
    return out


def decode_engine_meta(data: bytes):
    engine_idx, n_engines, watermark, gauge_seq = \
        _ENG_META.unpack_from(data, 0)
    fpr = list(_ENG_FPR.unpack_from(data, _ENG_META.size))
    off = _ENG_META.size + _ENG_FPR.size
    while off < len(data):
        desc, off = _unpack_str(data, off)
        fpr.append(desc)
    return engine_idx, n_engines, watermark, gauge_seq, tuple(fpr)


def encode_engine_keys(engine_idx: int, bank_kind: int, interval: int,
                       entries) -> bytes:
    """One bank's interner table: [(slot, scope, last_interval, name,
    type, joined_tags)] + the interner's interval counter."""
    entries = list(entries)
    out = [_ENG_KEYS_HEAD.pack(engine_idx, bank_kind, interval,
                               len(entries))]
    for slot, scope, last_interval, name, mtype, tags in entries:
        out.append(_ENG_KEY_ENTRY.pack(slot, scope, last_interval))
        out.append(_pack_str(name))
        out.append(_pack_str(mtype))
        out.append(_pack_str(tags))
    return b"".join(out)


def decode_engine_keys(data: bytes):
    engine_idx, bank_kind, interval, n = _ENG_KEYS_HEAD.unpack_from(data, 0)
    off = _ENG_KEYS_HEAD.size
    entries = []
    for _ in range(n):
        slot, scope, last_interval = _ENG_KEY_ENTRY.unpack_from(data, off)
        off += _ENG_KEY_ENTRY.size
        name, off = _unpack_str(data, off)
        mtype, off = _unpack_str(data, off)
        tags, off = _unpack_str(data, off)
        entries.append((slot, scope, last_interval, name, mtype, tags))
    return engine_idx, bank_kind, interval, entries


def _encode_leaf(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    width = arr.shape[1] if arr.ndim == 2 else 0
    return _ENG_LEAF_HEAD.pack(_CODE_OF_DTYPE[arr.dtype], width) \
        + arr.tobytes()


def _decode_leaf(data: bytes, off: int, n_rows: int):
    code, width = _ENG_LEAF_HEAD.unpack_from(data, off)
    off += _ENG_LEAF_HEAD.size
    dtype = np.dtype(_DTYPE_CODES[code])
    count = n_rows * (width or 1)
    nbytes = count * dtype.itemsize
    arr = np.frombuffer(data, dtype, count, off).copy()
    if width:
        arr = arr.reshape(n_rows, width)
    return arr, off + nbytes


def encode_engine_bank(engine_idx: int, bank_kind: int,
                       slot_ids: np.ndarray, leaves: dict,
                       leaf_names: tuple | None = None) -> bytes:
    """One bank's dirty rows: slot ids + every leaf's rows at those
    ids, in a fixed leaf order, as raw little-endian bytes.
    `leaf_names` defaults to the default engines' BANK_LEAVES order;
    non-default sketch backends pass their own (the fingerprint's
    engine descs guarantee encode and decode agree on it)."""
    slot_ids = np.ascontiguousarray(slot_ids, np.int32)
    out = [_ENG_BANK_HEAD.pack(engine_idx, bank_kind, len(slot_ids)),
           slot_ids.tobytes()]
    for name in (leaf_names or BANK_LEAVES[bank_kind]):
        out.append(_encode_leaf(leaves[name]))
    return b"".join(out)


def decode_engine_bank(data: bytes, leaf_names_of=None):
    """`leaf_names_of(bank_kind)` (optional) supplies the decode-side
    leaf order for non-default engines; default is BANK_LEAVES."""
    engine_idx, bank_kind, n = _ENG_BANK_HEAD.unpack_from(data, 0)
    off = _ENG_BANK_HEAD.size
    slot_ids = np.frombuffer(data, np.int32, n, off).copy()
    off += n * 4
    names = (leaf_names_of(bank_kind) if leaf_names_of is not None
             else BANK_LEAVES[bank_kind])
    leaves = {}
    for name in names:
        leaves[name], off = _decode_leaf(data, off, n)
    return engine_idx, bank_kind, slot_ids, leaves


def encode_engine_staged(engine_idx: int, staged: dict) -> bytes:
    """Staged-but-unlanded import accumulators, order-preserving (the
    landing order feeds the k1 clustering and the gauge sequence, both
    order-sensitive):
      centroids   [(slot, means f32[w], weights f32[w], min, max, sum,
                    count, recip)]
      sets        [(slot, registers u8[m])]
      counters    [(slot, exact f64 sum)]   (dict insertion order)
      gauges      [(slot, f64 value)]       (dict insertion order)
    """
    out = [_U32.pack(engine_idx)]
    cents = staged.get("centroids", [])
    out.append(_U32.pack(len(cents)))
    for slot, means, weights, vmin, vmax, vsum, cnt, recip in cents:
        means = np.ascontiguousarray(means, np.float32)
        weights = np.ascontiguousarray(weights, np.float32)
        out.append(_U32.pack(int(slot)) + _U32.pack(len(means)))
        out.append(means.tobytes())
        out.append(weights.tobytes())
        out.append(struct.pack("<5d", vmin, vmax, vsum, cnt, recip))
    sets = staged.get("sets", [])
    out.append(_U32.pack(len(sets)))
    for slot, regs in sets:
        regs = np.ascontiguousarray(regs, np.uint8)
        out.append(_U32.pack(int(slot)) + _U32.pack(len(regs)))
        out.append(regs.tobytes())
    for field in ("counters", "gauges"):
        items = staged.get(field, [])
        out.append(_U32.pack(len(items)))
        for slot, value in items:
            out.append(_U32.pack(int(slot)) + _F64.pack(float(value)))
    return b"".join(out)


def decode_engine_staged(data: bytes):
    (engine_idx,) = _U32.unpack_from(data, 0)
    off = _U32.size
    staged = {"centroids": [], "sets": [], "counters": [], "gauges": []}
    (n,) = _U32.unpack_from(data, off)
    off += _U32.size
    for _ in range(n):
        slot, w = struct.unpack_from("<II", data, off)
        off += 8
        means = np.frombuffer(data, np.float32, w, off).copy()
        off += 4 * w
        weights = np.frombuffer(data, np.float32, w, off).copy()
        off += 4 * w
        scalars = struct.unpack_from("<5d", data, off)
        off += 40
        staged["centroids"].append((slot, means, weights) + scalars)
    (n,) = _U32.unpack_from(data, off)
    off += _U32.size
    for _ in range(n):
        slot, m = struct.unpack_from("<II", data, off)
        off += 8
        regs = np.frombuffer(data, np.uint8, m, off).copy()
        off += m
        staged["sets"].append((slot, regs))
    for field in ("counters", "gauges"):
        (n,) = _U32.unpack_from(data, off)
        off += _U32.size
        for _ in range(n):
            (slot,) = _U32.unpack_from(data, off)
            off += _U32.size
            (value,) = _F64.unpack_from(data, off)
            off += _F64.size
            staged[field].append((slot, value))
    return engine_idx, staged


# ------------------------------------------- history tier (ISSUE 14)

_HIST_META = struct.Struct("<QQQI")   # gen, close_ns, prev_close_ns,
#                                       n_engines
_HIST_IDX = struct.Struct("<QQQQ")    # gen, close_ns, prev_close_ns,
#                                       segment bytes


def encode_history_meta(gen: int, close_ns: int, prev_close_ns: int,
                        retire_wms, op_lo: int, op_hi: int) -> bytes:
    """One history segment's identity record (see the kind table)."""
    retire_wms = [int(w) for w in retire_wms]
    out = [_HIST_META.pack(int(gen), int(close_ns), int(prev_close_ns),
                           len(retire_wms))]
    out.extend(_U64.pack(w) for w in retire_wms)
    out.append(_U64.pack(int(op_lo)))
    out.append(_U64.pack(int(op_hi)))
    return b"".join(out)


def decode_history_meta(data: bytes):
    """-> (gen, close_ns, prev_close_ns, [retire_wm per engine],
    op_lo, op_hi)."""
    gen, close_ns, prev_close_ns, n = _HIST_META.unpack_from(data, 0)
    off = _HIST_META.size
    wms = []
    for _ in range(n):
        (w,) = _U64.unpack_from(data, off)
        off += _U64.size
        wms.append(w)
    (op_lo,) = _U64.unpack_from(data, off)
    off += _U64.size
    (op_hi,) = _U64.unpack_from(data, off)
    return gen, close_ns, prev_close_ns, wms, op_lo, op_hi


def encode_history_index(gen: int, close_ns: int, prev_close_ns: int,
                         nbytes: int) -> bytes:
    return _HIST_IDX.pack(int(gen), int(close_ns), int(prev_close_ns),
                          int(nbytes))


def decode_history_index(data: bytes):
    """-> (gen, close_ns, prev_close_ns, nbytes)."""
    return _HIST_IDX.unpack_from(data, 0)


def encode_engine_checkpoint(engine_idx: int, n_engines: int,
                             snap: dict) -> list:
    """One engine's flush-boundary checkpoint as a typed-record list
    (the unit the server appends per tick and hands to snapshot
    compaction — self-contained: fresh banks + these records IS the
    engine's state at the boundary)."""
    recs = [(REC_ENGINE_META, encode_engine_meta(
        engine_idx, n_engines, snap["last_import_op"],
        snap["gauge_seq"], snap["fingerprint"]))]
    for kind, (interval, entries) in snap["interner"].items():
        recs.append((REC_ENGINE_KEYS, encode_engine_keys(
            engine_idx, kind, interval, entries)))
    leaf_names = snap.get("leaf_names", {})
    for kind, (slot_ids, leaves) in snap["banks"].items():
        if len(slot_ids) == 0:
            continue              # fresh rows need no record
        recs.append((REC_ENGINE_BANK, encode_engine_bank(
            engine_idx, kind, slot_ids, leaves,
            leaf_names=leaf_names.get(kind))))
    staged = snap["staged"]
    if any(staged.get(f) for f in ("centroids", "sets", "counters",
                                   "gauges")):
        recs.append((REC_ENGINE_STAGED,
                     encode_engine_staged(engine_idx, staged)))
    # completeness marker LAST: recovery only trusts committed groups
    recs.append((REC_ENGINE_COMMIT, _U32.pack(engine_idx)))
    return recs


def decode_engine_commit(data: bytes) -> int:
    return _U32.unpack_from(data, 0)[0]
