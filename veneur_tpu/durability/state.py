"""Integration façades: the sender's forward op log and the receiver's
watermark log.

`ForwardJournal` is consumed by `resilience.ResilientForwarder`: each
ladder mutation (write-ahead BEGIN, DONE, partial-tail UPDATE, AGE,
DEMOTE, SPILL_MERGE) appends one typed record, and recovery replays the
ops in order to reconstruct the ladder + spill tier bit-exactly (the
application logic lives with the semantics, in `resilience.py`; this
module only stores and parses). Compaction snapshots the full state
(META + SPILL_STATE + one BEGIN per parked entry) and truncates.

`WatermarkJournal` is consumed by the Server on behalf of the dedupe
ledger: once per flush it appends the per-sender max admitted
interval_seq (skipped when unchanged), and recovery merges every
record by max so a restarted global restores the highest watermark it
ever flushed under. The merged map is bounded (`max_senders`,
oldest-recorded dropped first) so a parade of one-shot sender ids
cannot grow the snapshot without bound.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from . import records
from .journal import Journal


class ForwardJournal:
    """The sender-side op log. One instance per ResilientForwarder;
    appends happen only on the flusher thread (like the forwarder
    itself), `sync()`/`maybe_compact()` on the flush boundary."""

    def __init__(self, directory: str, fsync: str = "interval",
                 fsync_interval_s: float = 1.0,
                 snapshot_journal_bytes: int = 1 << 22,
                 clock=time.monotonic, registry=None,
                 destination: str = "durability"):
        self.journal = Journal(directory, "forward", fsync=fsync,
                               fsync_interval_s=fsync_interval_s,
                               clock=clock, registry=registry,
                               destination=destination)
        self.snapshot_journal_bytes = snapshot_journal_bytes

    def load_ops(self) -> list:
        """All recoverable ops in write order: the snapshot's records
        (themselves valid ops: META, SPILL_STATE, BEGINs) followed by
        the journal's. Truncates any torn tail; never raises."""
        snapshot, journal = self.journal.load()
        return list(snapshot or []) + list(journal)

    # -- append helpers (one per ladder op) --

    def meta(self, sender_id: str, next_seq: int):
        self.journal.append(records.REC_META,
                            records.encode_meta(sender_id, next_seq))

    def begin(self, seq: int, chunk_offset: int, chunk_count: int,
              age: int, export, kind: str = "full"):
        self.journal.append(
            records.REC_BEGIN,
            records.encode_begin(seq, chunk_offset, chunk_count, age,
                                 export, kind))

    def done(self, seq: int):
        self.journal.append(records.REC_DONE, records.encode_done(seq))

    def update(self, seq: int, chunk_offset: int, chunk_count: int,
               export):
        self.journal.append(
            records.REC_UPDATE,
            records.encode_update(seq, chunk_offset, chunk_count,
                                  export))

    def age(self):
        self.journal.append(records.REC_AGE, b"")

    def demote(self):
        self.journal.append(records.REC_DEMOTE, b"")

    def spill_merge(self):
        self.journal.append(records.REC_SPILL_MERGE, b"")

    # -- flush-boundary hooks --

    def sync(self):
        self.journal.sync()

    def maybe_compact(self, snapshot_records_fn) -> bool:
        """Snapshot + truncate when the journal outgrew its budget.
        `snapshot_records_fn()` returns the full-state record list
        (ResilientForwarder.durable_snapshot_records)."""
        if self.journal.size_bytes() < self.snapshot_journal_bytes:
            return False
        self.journal.snapshot(snapshot_records_fn())
        return True

    def size_bytes(self) -> int:
        return self.journal.size_bytes()

    def close(self):
        self.journal.close()


class EngineJournal:
    """The global tier's engine-state log (ISSUE 9).

    Two record families share one journal+snapshot pair named
    "engine": ENGINE_IMPORT write-ahead ops appended by the import
    handler threads (BEFORE the sender's ack — an admitted interval
    survives a crash), and per-engine checkpoint groups (META + KEYS +
    BANK + STAGED) appended by the flusher at each flush boundary.
    A checkpoint group is SELF-CONTAINED (banks are interval-scoped:
    fresh init + dirty rows is the whole state), so compaction writes
    the latest groups plus the ops the per-engine watermarks haven't
    covered yet, and truncates everything older.

    Storage only — the application semantics (which ops replay, how
    banks rebuild) live with the engine in models/pipeline.py and the
    Server's recovery pass."""

    def __init__(self, directory: str, fsync: str = "interval",
                 fsync_interval_s: float = 1.0,
                 snapshot_journal_bytes: int = 1 << 22,
                 clock=time.monotonic, registry=None,
                 destination: str = "durability"):
        self.journal = Journal(directory, "engine", fsync=fsync,
                               fsync_interval_s=fsync_interval_s,
                               clock=clock, registry=registry,
                               destination=destination)
        self.snapshot_journal_bytes = snapshot_journal_bytes
        self.last_checkpoint_bytes = 0

    def load_records(self) -> list:
        """All recoverable records in write order (snapshot groups
        first, then the journal's). Truncates any torn tail; never
        raises."""
        snapshot, journal = self.journal.load()
        return list(snapshot or []) + list(journal)

    def append_import(self, payload: bytes):
        """Write-ahead one admitted import op (already encoded by
        records.encode_engine_import). Called from handler threads;
        the journal's lock serializes against checkpoint appends."""
        self.journal.append(records.REC_ENGINE_IMPORT, payload)

    def append_checkpoint(self, recs) -> int:
        """Append one flush boundary's checkpoint record groups;
        returns the bytes written (the engine_snapshot_bytes gauge)."""
        n = 0
        for rec_type, payload in recs:
            n += self.journal.append(rec_type, payload)
        self.last_checkpoint_bytes = n
        return n

    def maybe_compact(self, snapshot_records) -> bool:
        """Snapshot + truncate when the journal outgrew its budget.
        `snapshot_records` is the full-state record list (latest
        checkpoint groups + retained uncovered ops)."""
        if self.journal.size_bytes() < self.snapshot_journal_bytes:
            return False
        self.journal.snapshot(snapshot_records)
        return True

    def sync(self):
        self.journal.sync()

    def size_bytes(self) -> int:
        return self.journal.size_bytes()

    def generation(self) -> int:
        return self.journal._generation

    def close(self):
        self.journal.close()


class WatermarkJournal:
    """The receiver-side watermark log. Appends happen on the flusher
    thread (flush boundary); recovery runs in Server.__init__, before
    any listener exists."""

    def __init__(self, directory: str, fsync: str = "interval",
                 fsync_interval_s: float = 1.0,
                 snapshot_journal_bytes: int = 1 << 20,
                 max_senders: int = 4096,
                 clock=time.monotonic, registry=None,
                 destination: str = "durability"):
        self.journal = Journal(directory, "dedupe", fsync=fsync,
                               fsync_interval_s=fsync_interval_s,
                               clock=clock, registry=registry,
                               destination=destination)
        self.snapshot_journal_bytes = snapshot_journal_bytes
        self.max_senders = max_senders
        # merged view of everything recorded so far (recency-ordered:
        # most recently recorded last; the eviction order)
        self._marks: OrderedDict[str, int] = OrderedDict()

    def load(self) -> dict:
        """Recover the merged per-sender watermark map (max across all
        records, snapshot first). Never raises."""
        snapshot, journal = self.journal.load()
        for rec_type, payload in list(snapshot or []) + list(journal):
            if rec_type != records.REC_WATERMARKS:
                continue
            try:
                marks = records.decode_watermarks(payload)
            except Exception:
                continue   # a foreign record kind must not kill recovery
            self._absorb(marks)
        return dict(self._marks)

    def _absorb(self, marks: dict):
        for sender_id, seq in marks.items():
            cur = self._marks.get(sender_id, 0)
            self._marks[sender_id] = max(cur, int(seq))
            self._marks.move_to_end(sender_id)
        while len(self._marks) > self.max_senders:
            self._marks.popitem(last=False)

    def record(self, marks: dict):
        """Append this flush's per-sender max admitted seqs; skipped
        when nothing changed since the last record (idle globals must
        not grow the journal)."""
        changed = {s: q for s, q in marks.items()
                   if int(q) > self._marks.get(s, 0)}
        if not changed:
            return
        self._absorb(changed)
        self.journal.append(records.REC_WATERMARKS,
                            records.encode_watermarks(changed))
        if self.journal.size_bytes() >= self.snapshot_journal_bytes:
            self.journal.snapshot([(
                records.REC_WATERMARKS,
                records.encode_watermarks(dict(self._marks)))])

    def sync(self):
        self.journal.sync()

    def size_bytes(self) -> int:
        return self.journal.size_bytes()

    def close(self):
        self.journal.close()
