"""Time-travel query tier (ISSUE 14): serve historical percentiles and
cardinalities from the durability journal.

The durability subsystem already persists everything a "what was p99 of
`api.latency` at 14:03 yesterday" answer needs: flush-boundary engine
delta checkpoints (ISSUE 9) and write-ahead import ops, over sketch
engines whose merge is bit-commutative (ISSUE 10) — t-digest/REQ
mergeability is exactly what makes cross-interval quantile composition
sound (arxiv 1902.04023 / 2511.17396). This module turns that
crash-safety byte stream into an operator-facing read tier with zero
new write-path information: every byte a query reads was already being
written.

Two halves:

`HistoryStore` — RETENTION. One closed flush interval = one GENERATION:
a self-contained segment file holding the interval's identity record
(REC_HISTORY_META: close wall time, open edge, per-engine RETIRE
watermarks — the op ids the flush swap actually carried), the PREVIOUS
boundary's checkpoint groups (the interval's baseline: banks are
interval-scoped, so `baseline + the interval's ops` IS the interval's
journal-visible flushed state), and the interval's write-ahead import
ops. Segments publish atomically (write-temp/fsync/rename via
journal.write_framed_file — the raw I/O stays single-homed in
journal.py per vlint DR01) and COMMIT by appearing in the manifest,
itself rewritten atomically — a crash at any point leaves a consistent
committed prefix, orphan files are swept at open. Pruning (by
generation count and by age against the NEWEST close stamp, so
scripted clocks stay scripted) rewrites the manifest first and only
then unlinks; a generation a running query holds a LEASE on is
deferred, never yanked mid-read.

`QueryTier` — the READ PATH. `GET /query?metric=&q=&t0=&t1=` resolves
the covering generations, reconstructs each one into a SCRATCH
AggregationEngine — a fresh engine from the factory, private interner,
restored through the same `restore_checkpoint` + `import_list` surface
crash recovery uses (per-engine replay cut: baseline watermark < op_id
<= retire watermark), never the live pipeline's banks — then merges
the matched rows across intervals through the engine contract (the
import-landing path routes into merge_centroids / the compactor's
direct re-insert / the set lattice join) inside a single-use MERGE
engine whose configured percentiles are the requested quantiles, and
reads the answers off its flush frame. Counters bypass the wire's
int64 rounding and merge as exact f64 on host. Queries run on a
dedicated executor with a bounded result cache keyed on
(metric, window, generation-range); the query path acquires no live
engine lock (machine-checked by vlint QT01) and surfaces as
flight-recorder phases `query>query.{resolve,restore,merge,estimate}`
plus `veneur.query.*` self-metrics.

Documented gaps (README "Time-travel queries"): UDP samples that
landed between checkpoints are not journaled and therefore not
reconstructable (import-path data is exact); LOCAL_ONLY-scoped keys
never export; gauges (last-write-wins) are not served; mesh/native
engines are excluded from durability entirely.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass

from . import journal, records

log = logging.getLogger("veneur_tpu.durability.history")

SEG_MAGIC = b"VTPUHSG1"
MAN_MAGIC = b"VTPUHMN1"

# result tokens a q= spec may name besides numeric quantiles
SCALAR_TOKENS = ("count", "sum", "min", "max", "avg")
HIST_TYPES = ("histogram", "timer")


class HistoryCorrupt(Exception):
    """A committed generation's segment failed validation at read time
    (bit flip under the manifest's feet). Queries touching it fail
    LOUDLY — the tier never silently invents or omits an interval."""


class QueryError(Exception):
    def __init__(self, status: int, detail: str):
        super().__init__(detail)
        self.status = status
        self.detail = detail


@dataclass(frozen=True)
class GenerationEntry:
    """One committed generation as the manifest lists it."""
    gen: int
    close_ns: int
    prev_close_ns: int
    nbytes: int
    path: str


def collect_checkpoint_groups(recs):
    """Walk REC_ENGINE_* records into committed checkpoint groups —
    the ONE home of the COMMIT discipline, shared by crash recovery
    (Server._recover_engine_state) and the query tier's generation
    reconstruction, so the two can never drift: a group's META/KEYS/
    BANK/STAGED frames only count once its COMMIT arrived (a torn
    group restored anyway would be silent data loss). BANK payloads
    stay ENCODED here (their leaf order is engine-aware; each caller
    decodes against its own engines). Returns (groups: {engine_idx:
    group}, ops: [encoded ENGINE_IMPORT payloads], torn: uncommitted
    group count, errors: undecodable record count)."""
    latest: dict[int, dict] = {}
    pending: dict[int, dict] = {}
    ops: list = []
    errors = 0
    for rec_type, payload in recs:
        try:
            if rec_type == records.REC_ENGINE_IMPORT:
                ops.append(payload)
            elif rec_type == records.REC_ENGINE_META:
                idx, n_eng, wm, gseq, fpr = \
                    records.decode_engine_meta(payload)
                pending[idx] = {"meta": (n_eng, wm, gseq, fpr),
                                "keys": {}, "banks": [], "staged": {}}
            elif rec_type == records.REC_ENGINE_KEYS:
                idx, kind, interval, entries = \
                    records.decode_engine_keys(payload)
                if idx in pending:
                    pending[idx]["keys"][kind] = (interval, entries)
            elif rec_type == records.REC_ENGINE_BANK:
                idx, kind, _n = records._ENG_BANK_HEAD.unpack_from(
                    payload, 0)
                if idx in pending:
                    pending[idx]["banks"].append(payload)
            elif rec_type == records.REC_ENGINE_STAGED:
                idx, staged = records.decode_engine_staged(payload)
                if idx in pending:
                    pending[idx]["staged"] = staged
            elif rec_type == records.REC_ENGINE_COMMIT:
                idx = records.decode_engine_commit(payload)
                if idx in pending:
                    latest[idx] = pending.pop(idx)
        except Exception:
            errors += 1
    return latest, ops, len(pending), errors


class HistoryStore:
    """The retention half: committed checkpoint generations, indexed by
    interval-close wall time in a small on-disk manifest, pruned
    atomically, leased while queries read them. Appends happen on the
    flusher thread; resolve/load/release on query executor threads —
    one lock covers the in-memory index and lease table (file reads
    run outside it)."""

    def __init__(self, directory: str,
                 retention_generations: int = 64,
                 retention_seconds: float = 0.0,
                 fsync: bool = True, registry=None,
                 scope: str = "_server",
                 name: str = "engine.history"):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.name = name
        self.retention_generations = max(1, int(retention_generations))
        self.retention_seconds = float(retention_seconds)
        self._fsync = fsync
        if registry is None:
            from ..observe.registry import DEFAULT_REGISTRY
            registry = DEFAULT_REGISTRY
        self._registry = registry
        self._scope = scope
        self._lock = threading.Lock()
        self._entries: list[GenerationEntry] = []
        self._leases: dict[int, int] = {}
        self._deferred: dict[int, str] = {}   # pruned-but-leased gens
        self._next_gen = 1
        self._load()

    # ------------------------------------------------------------ files

    def _seg_path(self, gen: int) -> str:
        return os.path.join(self.directory,
                            f"{self.name}.{gen:016d}.seg")

    def _man_path(self) -> str:
        return os.path.join(self.directory, self.name + ".manifest")

    def _count(self, counter: str, n: int = 1):
        self._registry.incr(self._scope, counter, n)

    def _load(self):
        """Recover the committed set: manifest rows whose segment files
        validate end to end (header magic, every frame CRC-good, the
        identity record first). Torn manifests truncate to the last
        good row; rows whose segment is missing/torn are dropped
        (counted, loud) — the committed prefix survives bit-exact.
        Orphan segments/temps (crash between segment publish and
        manifest commit, or a deferred unlink that never ran) are
        swept. Never raises."""
        recs, man_gen, _end, torn = journal.read_framed_file(
            self._man_path(), MAN_MAGIC)
        if torn:
            self._count("durability.history_truncated")
            log.warning("history: torn manifest %s; recovering the "
                        "committed prefix", self._man_path())
        entries = []
        for rec_type, payload in recs:
            if rec_type != records.REC_HISTORY_INDEX:
                continue
            try:
                gen, close_ns, prev_close_ns, nbytes = \
                    records.decode_history_index(payload)
            except Exception:
                self._count("durability.history_truncated")
                continue
            if nbytes == 0:
                # empty generation (coalesced idle window): a manifest
                # row is its whole existence — nothing to validate
                entries.append(GenerationEntry(gen, close_ns,
                                               prev_close_ns, 0, ""))
                continue
            path = self._seg_path(gen)
            if not self._segment_valid(path, gen):
                self._count("durability.history_dropped_generations")
                log.warning(
                    "history: generation %d segment %s missing or "
                    "corrupt; dropping it from the committed set",
                    gen, path)
                continue
            entries.append(GenerationEntry(gen, close_ns, prev_close_ns,
                                           nbytes, path))
        entries.sort(key=lambda e: e.gen)
        self._entries = entries
        self._next_gen = max([man_gen] + [e.gen for e in entries]
                             + [0]) + 1
        known = {os.path.basename(e.path) for e in entries}
        prefix = self.name + "."
        for fn in os.listdir(self.directory):
            if not fn.startswith(prefix):
                continue
            if fn.endswith(".tmp") or (fn.endswith(".seg")
                                       and fn not in known):
                try:
                    os.unlink(os.path.join(self.directory, fn))
                except OSError:
                    pass

    def _segment_valid(self, path: str, gen: int) -> bool:
        """Full read validation (every frame CRC-checked): the open-
        time gate behind 'queries answer only from committed
        generations'."""
        recs, g, _end, torn = journal.read_framed_file(path, SEG_MAGIC)
        return (g == gen and not torn and bool(recs)
                and recs[0][0] == records.REC_HISTORY_META)

    # ---------------------------------------------------------- writes

    def append(self, close_ns: int, prev_close_ns: int, retire_wms,
               baseline_recs, op_recs) -> int:
        """Seal one closed interval as a generation: publish the
        segment atomically, then commit it (and any prune) with one
        atomic manifest rewrite. `baseline_recs` is the PREVIOUS
        boundary's checkpoint record group list; `op_recs` is
        [(op_id, encoded ENGINE_IMPORT payload)] for the interval.
        Called on the flusher thread only (single appender) — the
        lock guards just the in-memory index/lease state shared with
        query threads, so every write+fsync runs OUTSIDE it and a
        slow disk never stalls acquire/release/debug reads."""
        with self._lock:
            gen = self._next_gen
            self._next_gen += 1
        op_ids = [i for i, _p in op_recs]
        meta = records.encode_history_meta(
            gen, close_ns, prev_close_ns, retire_wms,
            min(op_ids) if op_ids else 0,
            max(op_ids) if op_ids else 0)
        recs = [(records.REC_HISTORY_META, meta)]
        recs.extend(baseline_recs)
        recs.extend((records.REC_ENGINE_IMPORT, p)
                    for _i, p in op_recs)
        path = self._seg_path(gen)
        nbytes = journal.write_framed_file(
            path, SEG_MAGIC, gen, recs, fsync=self._fsync)
        with self._lock:
            self._entries.append(GenerationEntry(
                gen, int(close_ns), int(prev_close_ns), nbytes, path))
            dropped = self._prune_locked()
            rows, man_gen = self._manifest_rows_locked()
        self._write_manifest(rows, man_gen)
        # manifest committed: only now do pruned files go away (the
        # lease check runs under the lock; the unlinks themselves are
        # cheap and crash-safe — an orphan is swept at next open)
        with self._lock:
            self._unlink_locked(dropped)
        return gen

    def append_empty(self, close_ns: int, prev_close_ns: int) -> int:
        """Seal a provably-EMPTY interval (fresh baseline, no ops) as
        a zero-cost generation: a manifest row only, no segment file —
        and CONSECUTIVE empty intervals coalesce into one row whose
        close stamp extends (empty + empty = empty, so widening an
        empty generation's window is sound; widening a DATA
        generation's would claim its data for time it doesn't cover).
        An idle import tier therefore pays one small manifest rewrite
        per tick instead of a segment + manifest + ~5 fsyncs, and a
        long idle stretch consumes ONE retention slot instead of
        evicting the generations that hold data. Queries over the
        window still resolve (and answer empty) rather than 404."""
        with self._lock:
            if self._entries and self._entries[-1].nbytes == 0:
                last = self._entries[-1]
                self._entries[-1] = GenerationEntry(
                    last.gen, int(close_ns), last.prev_close_ns, 0, "")
                gen = last.gen
            else:
                gen = self._next_gen
                self._next_gen += 1
                self._entries.append(GenerationEntry(
                    gen, int(close_ns), int(prev_close_ns), 0, ""))
            # prune on BOTH branches: the widened close stamp advances
            # the age floor, so an idle stretch must keep retiring the
            # data generations that age out under it
            dropped = self._prune_locked()
            rows, man_gen = self._manifest_rows_locked()
        self._write_manifest(rows, man_gen)
        with self._lock:
            self._unlink_locked(dropped)
        return gen

    def _prune_locked(self) -> list:
        """Apply both retention bounds; returns the dropped entries
        whose files may be unlinked AFTER the manifest commit. Age is
        measured against the NEWEST generation's close stamp (flush
        timestamps), so scripted clocks prune deterministically."""
        keep = self._entries
        if self.retention_seconds > 0 and keep:
            floor = keep[-1].close_ns \
                - int(self.retention_seconds * 1e9)
            aged = [e for e in keep if e.close_ns < floor]
            keep = [e for e in keep if e.close_ns >= floor]
        else:
            aged = []
        over = len(keep) - self.retention_generations
        dropped = aged + keep[:max(0, over)]
        self._entries = keep[max(0, over):]
        if dropped:
            self._count("durability.history_pruned", len(dropped))
        return dropped

    def _unlink_locked(self, dropped):
        for e in dropped:
            if not e.path:
                continue        # empty generation: no file to remove
            if self._leases.get(e.gen):
                # a running query holds this generation: defer the
                # unlink to its release — pruning never yanks a leased
                # segment mid-read
                self._deferred[e.gen] = e.path
                continue
            try:
                os.unlink(e.path)
            except OSError:
                pass

    def _manifest_rows_locked(self):
        """Snapshot (manifest rows, manifest generation) under the
        lock; the atomic write happens outside it."""
        rows = [(records.REC_HISTORY_INDEX,
                 records.encode_history_index(
                     e.gen, e.close_ns, e.prev_close_ns, e.nbytes))
                for e in self._entries]
        return rows, self._next_gen - 1

    def _write_manifest(self, rows, man_gen):
        journal.write_framed_file(self._man_path(), MAN_MAGIC,
                                  man_gen, rows, fsync=self._fsync)

    # ----------------------------------------------------------- reads

    def acquire(self, t0_ns: int, t1_ns: int) -> list:
        """Generations whose interval (prev_close, close] overlaps
        [t0, t1], lease-held until release() — prune defers their
        unlink while the lease lives."""
        with self._lock:
            out = [e for e in self._entries
                   if e.close_ns >= t0_ns and e.prev_close_ns < t1_ns]
            for e in out:
                self._leases[e.gen] = self._leases.get(e.gen, 0) + 1
            return out

    def release(self, entries):
        with self._lock:
            for e in entries:
                n = self._leases.get(e.gen, 0) - 1
                if n > 0:
                    self._leases[e.gen] = n
                    continue
                self._leases.pop(e.gen, None)
                path = self._deferred.pop(e.gen, None)
                if path is not None:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass

    def load(self, entry: GenerationEntry):
        """Read + parse one generation's segment: (meta tuple, groups
        {engine_idx: group with ENCODED bank payloads}, ops
        [ENGINE_IMPORT payloads]). Raises HistoryCorrupt on any
        validation failure — a bit-flipped generation fails the query
        loudly, it never silently drops an interval from the answer."""
        if entry.nbytes == 0:
            # empty generation: its window is claimed, its content is
            # nothing (fresh baseline + zero ops)
            return ((entry.gen, entry.close_ns, entry.prev_close_ns,
                     [], 0, 0), {}, [])
        recs, gen, _end, torn = journal.read_framed_file(entry.path,
                                                         SEG_MAGIC)
        if torn or gen != entry.gen or not recs \
                or recs[0][0] != records.REC_HISTORY_META:
            self._count("durability.history_dropped_generations")
            raise HistoryCorrupt(
                f"generation {entry.gen} segment failed validation")
        meta = records.decode_history_meta(recs[0][1])
        groups, ops, torn_groups, errors = \
            collect_checkpoint_groups(recs[1:])
        if torn_groups or errors:
            self._count("durability.history_dropped_generations")
            raise HistoryCorrupt(
                f"generation {entry.gen}: {torn_groups} torn baseline "
                f"group(s), {errors} undecodable record(s)")
        return meta, groups, ops

    # ----------------------------------------------------------- intro

    def entries(self) -> list:
        with self._lock:
            return list(self._entries)

    def debug_state(self) -> dict:
        with self._lock:
            es = self._entries
            return {
                "generations": len(es),
                "bytes": sum(e.nbytes for e in es),
                "oldest_close_ns": es[0].close_ns if es else None,
                "newest_close_ns": es[-1].close_ns if es else None,
                "first_gen": es[0].gen if es else None,
                "last_gen": es[-1].gen if es else None,
                "leases": sum(self._leases.values()),
                "deferred_unlinks": len(self._deferred),
                "retention_generations": self.retention_generations,
                "retention_seconds": self.retention_seconds,
            }


# --------------------------------------------------------------- query


def _pct_label(q: float) -> str:
    """The flush frame's percentile suffix for q — MUST mirror the
    pipeline's `f".{p * 100:g}percentile"` presentation."""
    return f"{q * 100:g}"


def parse_qspec(raw: str):
    """q= spec -> (sorted unique quantiles, scalar tokens, want_card,
    want_counter). Tokens: floats in (0,1) are quantiles; count/sum/
    min/max/avg are histogram scalars; `cardinality` the set estimate;
    `value` the counter total. Raises QueryError(400) on junk."""
    quantiles: list[float] = []
    scalars: list[str] = []
    want_card = want_counter = False
    for tok in (t.strip() for t in raw.split(",")):
        if not tok:
            continue
        if tok == "cardinality":
            want_card = True
            continue
        if tok == "value":
            want_counter = True
            continue
        if tok in SCALAR_TOKENS:
            scalars.append(tok)
            continue
        try:
            q = float(tok)
        except ValueError:
            raise QueryError(
                400, f"unknown q token {tok!r} (want a quantile in "
                     "(0,1), count/sum/min/max/avg, cardinality, or "
                     "value)") from None
        if not (0.0 < q < 1.0) or not math.isfinite(q):
            raise QueryError(400, f"quantile {tok!r} out of (0, 1)")
        quantiles.append(q)
    if not (quantiles or scalars or want_card or want_counter):
        raise QueryError(400, "q= names nothing to compute")
    return tuple(sorted(set(quantiles))), tuple(scalars), \
        want_card, want_counter


class QueryTier:
    """The read half: scratch-engine reconstruction + cross-interval
    merge + estimate, on a dedicated executor, behind a bounded result
    cache. Holds NO reference to the live pipeline — engines come from
    `engine_factory(percentiles=, aggregates=, merge=)`, each a fresh
    AggregationEngine with a private interner (vlint QT01 machine-
    checks that this module never touches an engine lock or bank)."""

    def __init__(self, store: HistoryStore, engine_factory,
                 n_engines: int, *, flight=None, registry=None,
                 scope: str = "_server", engines_describe=None,
                 max_concurrent: int = 1, cache_entries: int = 64,
                 timeout_s: float = 30.0, clock=time.time):
        self._store = store
        self._factory = engine_factory
        self._n = max(1, int(n_engines))
        self._flight = flight
        if registry is None:
            from ..observe.registry import DEFAULT_REGISTRY
            registry = DEFAULT_REGISTRY
        self._registry = registry
        self._scope = scope
        self._describe = engines_describe or {}
        self._timeout_s = float(timeout_s)
        self._clock = clock
        self._exec = ThreadPoolExecutor(
            max_workers=max(1, int(max_concurrent)),
            thread_name_prefix="query")
        self._cache_entries = max(0, int(cache_entries))
        self._cache: OrderedDict = OrderedDict()
        self._cache_lock = threading.Lock()
        # per-generation reconstruction cache (metric-independent
        # extractions, keyed by immutable generation id) — what makes
        # a dashboard's second metric over the same window cheap.
        # Sized to the retention window so a full-window scan actually
        # fits (each entry is bounded by one interval's export size)
        self._gen_cache: OrderedDict = OrderedDict()
        self._GEN_CACHE = max(16, store.retention_generations)

    def close(self):
        self._exec.shutdown(wait=False)

    def _count(self, counter: str, n: int = 1):
        self._registry.incr(self._scope, counter, n)

    # ------------------------------------------------------ public API

    def query(self, params: dict) -> dict:
        """Validate + run one query on the executor (the read path is
        isolated from HTTP handler threads and from each other), under
        the tier's wall timeout."""
        self._count("query.requests")
        try:
            spec = self._validate(params)
        except QueryError:
            self._count("query.errors")
            raise
        fut = self._exec.submit(self._run, spec)
        try:
            return fut.result(timeout=self._timeout_s)
        except QueryError:
            self._count("query.errors")
            raise
        except FutureTimeout:
            self._count("query.errors")
            # a RUNNING query cannot be killed (it finishes, releases
            # its leases, and seeds the cache for a retry), but cancel
            # frees any queued-not-started successors so one
            # pathological query doesn't make every waiter behind it
            # burn its own timeout before even starting
            fut.cancel()
            raise QueryError(
                503, f"query timed out after {self._timeout_s:g}s "
                     "(still running on the query executor)") from None
        except HistoryCorrupt as e:
            self._count("query.errors")
            raise QueryError(500, str(e)) from None
        except Exception as e:
            self._count("query.errors")
            log.exception("query failed")
            raise QueryError(500, f"query failed: {e}") from None

    def _validate(self, params: dict) -> dict:
        name = (params.get("metric") or "").strip()
        if not name:
            raise QueryError(400, "metric= is required")
        try:
            t0 = float(params["t0"])
            t1 = float(params["t1"])
        except (KeyError, TypeError, ValueError):
            raise QueryError(
                400, "t0= and t1= are required (epoch seconds)") \
                from None
        if not (t1 > t0):
            raise QueryError(400, "t1 must be > t0")
        quantiles, scalars, want_card, want_counter = \
            parse_qspec(params.get("q") or "")
        mtype = params.get("type")
        if mtype is not None and mtype not in (
                "histogram", "timer", "counter", "set"):
            raise QueryError(
                400, f"type {mtype!r} not queryable (histogram/timer/"
                     "counter/set; gauges are last-write-wins and not "
                     "served from history)")
        tags = params.get("tags")
        if tags:
            # canonicalize to the engine's joined form (sorted,
            # comma-joined — wire.metric_key_of / the parser): a
            # caller's unsorted spelling must match the stored key,
            # not silently return matched_keys=0
            tags = ",".join(sorted(t for t in tags.split(",") if t))
        return {
            "name": name, "t0": t0, "t1": t1,
            "tags": tags, "type": mtype,
            "quantiles": quantiles, "scalars": scalars,
            "want_card": want_card, "want_counter": want_counter,
        }

    # ------------------------------------------------------- execution

    def _run(self, spec: dict) -> dict:
        tick = root = None
        if self._flight is not None:
            tick = self._flight.open_tick(int(self._clock()))
            root = tick.start("query")
        entries = []
        try:
            ph = -1 if tick is None else tick.start("query.resolve",
                                                    root)
            entries = self._store.acquire(int(spec["t0"] * 1e9),
                                          int(spec["t1"] * 1e9))
            if tick is not None:
                tick.finish(ph, generations=len(entries))
            if not entries:
                raise QueryError(
                    404, "no retained generations cover "
                         f"[{spec['t0']:g}, {spec['t1']:g}] — the "
                         "window predates the retention horizon or "
                         "postdates the newest flush")
            self._count("query.generations_scanned", len(entries))
            key = (spec["name"], spec["tags"], spec["type"],
                   spec["quantiles"], spec["scalars"],
                   spec["want_card"], spec["want_counter"],
                   entries[0].gen, entries[-1].gen)
            cached = self._cache_get(key)
            if cached is not None:
                self._count("query.cache_hits")
                out = dict(cached)
                # request-specific metadata is NOT part of the cache
                # key (two windows resolving to the same generation
                # range share one entry) — echo THIS request's, not
                # the first one's
                out["t0"], out["t1"] = spec["t0"], spec["t1"]
                out["cache"] = "hit"
                return out
            out = self._execute(spec, entries, tick, root)
            self._cache_put(key, out)
            out = dict(out)
            out["cache"] = "miss"
            return out
        finally:
            if entries:
                self._store.release(entries)
            if tick is not None:
                tick.finish(root)
                self._flight.end_tick(tick)
                self._flight.adopt(tick)

    def _cache_get(self, key):
        with self._cache_lock:
            v = self._cache.get(key)
            if v is not None:
                self._cache.move_to_end(key)
            return v

    def _cache_put(self, key, value):
        if not self._cache_entries:
            return
        with self._cache_lock:
            self._cache[key] = value
            self._cache.move_to_end(key)
            while len(self._cache) > self._cache_entries:
                self._cache.popitem(last=False)

    def _match(self, spec, key, kinds) -> bool:
        if key.name != spec["name"] or key.type not in kinds:
            return False
        if spec["type"] is not None and key.type != spec["type"]:
            return False
        tags = spec["tags"]
        return tags is None or key.joined_tags == tags

    def _engine_indices(self, spec) -> list:
        """The engine groups a query must restore: one, when the exact
        key (type + tags) pins the digest route; else all of them."""
        if spec["type"] is not None and spec["tags"] is not None:
            from ..utils.hashing import metric_digest
            return [metric_digest(spec["name"], spec["type"],
                                  spec["tags"]) % self._n]
        return list(range(self._n))

    def _execute(self, spec, entries, tick, root) -> dict:
        from ..cluster import wire
        from ..ingest.parser import MetricKey

        engine_idxs = self._engine_indices(spec)
        # RESTORE: one journal-visible flushed state per generation,
        # via the recovery-restore path into scratch engines
        ph = -1 if tick is None else tick.start("query.restore", root)
        exts = [self._reconstruct(e, engine_idxs) for e in entries]
        if tick is not None:
            tick.finish(ph, generations=len(entries),
                        engines=len(engine_idxs))

        # MERGE: matched rows from every interval land in ONE merge
        # engine through the import contract (merge_centroids / direct
        # compactor re-insert / set lattice join); tag variants
        # canonicalize onto one untagged key so the answer aggregates
        # across them; counters merge as exact f64 on host (the wire
        # row rounds them to int64)
        ph = -1 if tick is None else tick.start("query.merge", root)
        quantiles = spec["quantiles"] or (0.5,)
        merge_eng = self._factory(percentiles=quantiles,
                                  aggregates=("min", "max", "sum",
                                              "count", "avg"),
                                  merge=True)
        matched: set = set()
        counter_total = 0.0
        hkey = MetricKey(spec["name"], "histogram", "")
        skey = MetricKey(spec["name"], "set", "")
        from ..models.pipeline import ForwardExport
        for i, ext in enumerate(exts):
            sub = ForwardExport(set_engine=ext["set_engine"])
            for row in ext["histograms"]:
                if self._match(spec, row[0], HIST_TYPES):
                    matched.add(row[0])
                    sub.histograms.append((hkey,) + tuple(row[1:]))
            for key, regs in ext["sets"]:
                if self._match(spec, key, ("set",)):
                    matched.add(key)
                    sub.sets.append((skey, regs))
            if sub.histograms or sub.sets:
                merge_eng.import_list(i + 1,
                                      wire.export_to_metrics(sub))
            for key, value in ext["counters"].items():
                if self._match(spec, key, ("counter",)):
                    matched.add(key)
                    counter_total += value
        if tick is not None:
            tick.finish(ph, matched=len(matched))

        # ESTIMATE: the merge engine's own flush answers — its
        # configured percentiles ARE the requested quantiles, its
        # aggregates the scalar legs, its set row the cardinality
        ph = -1 if tick is None else tick.start("query.estimate", root)
        fr = merge_eng.flush(timestamp=int(spec["t1"]))
        rows: dict = {}
        from ..metrics import MetricType
        for m in fr.frame:
            if m.tags:
                continue
            rows[(m.name, m.type)] = float(m.value)
        name = spec["name"]
        results: dict = {}
        if spec["quantiles"]:
            results["quantiles"] = {
                _pct_label(q): rows.get(
                    (f"{name}.{_pct_label(q)}percentile",
                     MetricType.GAUGE))
                for q in spec["quantiles"]}
        for agg in spec["scalars"]:
            mt = MetricType.COUNTER if agg == "count" \
                else MetricType.GAUGE
            results[agg] = rows.get((f"{name}.{agg}", mt))
        if spec["want_card"]:
            results["cardinality"] = rows.get((name, MetricType.GAUGE))
        if spec["want_counter"]:
            results["value"] = counter_total if any(
                k.type == "counter" for k in matched) else None
        if tick is not None:
            tick.finish(ph)

        return {
            "metric": name, "t0": spec["t0"], "t1": spec["t1"],
            "tags": spec["tags"], "type": spec["type"],
            "generations": {
                "count": len(entries),
                "first": entries[0].gen, "last": entries[-1].gen,
                "window_ns": [entries[0].prev_close_ns,
                              entries[-1].close_ns],
            },
            "engines": self._describe,
            "matched_keys": len(matched),
            "results": results,
            "gaps": ["udp-between-checkpoints", "local-only-keys",
                     "gauges", "mesh/native-excluded"],
        }

    # -------------------------------------------- per-generation state

    def _reconstruct(self, entry, engine_idxs) -> dict:
        """One generation's journal-visible flushed state, extracted
        metric-independently (so the small per-generation cache serves
        any later query): restore the baseline checkpoint group into a
        fresh scratch engine, replay the interval's ops through the
        per-engine cut (baseline watermark < op_id <= retire
        watermark, the same monotone-per-queue filter recovery uses),
        flush the scratch, and keep the export rows + the frame's
        non-exported counter values."""
        from ..cluster import wire
        from ..ingest.parser import MetricKey
        from ..metrics import MetricType
        from ..utils.hashing import metric_digest

        full = len(engine_idxs) == self._n
        cache_key = entry.gen
        if full:
            with self._cache_lock:
                hit = self._gen_cache.get(cache_key)
                if hit is not None:
                    self._gen_cache.move_to_end(cache_key)
                    return hit
        meta, groups, op_payloads = self._store.load(entry)
        _gen, close_ns, _prev, retire_wms, _lo, _hi = meta
        # the digest modulus is part of a generation's identity: ops
        # route by `digest % n`, watermarks are per-engine — history
        # sealed under a DIFFERENT engine count cannot be re-routed
        # exactly (ops would replay against the wrong baselines,
        # double-counting some and dropping others). Refuse LOUDLY,
        # the same stance crash recovery takes on a count mismatch —
        # never a confidently-wrong answer.
        for g in groups.values():
            if g["meta"][0] != self._n:
                raise HistoryCorrupt(
                    f"generation {entry.gen} was sealed under "
                    f"{g['meta'][0]} engine(s); this server runs "
                    f"{self._n} — re-sharded history cannot answer "
                    "exactly (prune it or restore the original "
                    "num_workers)")
        if op_payloads and len(retire_wms) != self._n:
            raise HistoryCorrupt(
                f"generation {entry.gen} carries {len(retire_wms)} "
                f"retire watermark(s) for a {self._n}-engine server "
                "— engine count changed under retained history")
        ops = [records.decode_engine_import(p) for p in op_payloads]
        # ONE key-extraction/hashing pass routes each op's metrics by
        # the live tier's digest modulus; the per-engine loop below
        # just consumes its bucket (re-walking the ops per engine
        # would pay the protobuf key walk + hash n times over)
        shares_by_engine: dict[int, list] = {i: [] for i in engine_idxs}
        want = set(engine_idxs)
        for op_id, pbs, _env in ops:
            buckets: dict[int, list] = {}
            for pb in pbs:
                try:
                    k = wire.metric_key_of(pb)
                except Exception:
                    continue
                e = metric_digest(k.name, k.type,
                                 k.joined_tags) % self._n
                if e in want:
                    buckets.setdefault(e, []).append(pb)
            for e, share in buckets.items():
                shares_by_engine[e].append((op_id, share))
        ext = {"histograms": [], "sets": [], "set_engine": "hll",
               "counters": {}}
        for idx in engine_idxs:
            g = groups.get(idx)
            retire = retire_wms[idx] if idx < len(retire_wms) else 0
            shares = shares_by_engine[idx]
            if g is None and not shares:
                continue
            if g is not None and not shares \
                    and self._group_is_empty(g):
                continue   # provably empty interval share: no engine
            scratch = self._factory(merge=False)
            wm = 0
            if g is not None:
                _n_eng, wm, gseq, fpr = g["meta"]
                banks = {}
                for payload in g["banks"]:
                    _idx, kind, ids, leaves = \
                        records.decode_engine_bank(
                            payload,
                            leaf_names_of=scratch.bank_leaf_names)
                    banks[kind] = (ids, leaves)
                scratch.restore_checkpoint(fpr, gseq, wm, g["keys"],
                                           banks, g["staged"])
            for op_id, share in shares:
                if op_id <= wm or op_id > retire:
                    continue
                scratch.import_list(op_id, share)
            res = scratch.flush(
                timestamp=max(1, int(close_ns) // 1_000_000_000))
            ext["histograms"].extend(res.export.histograms)
            ext["sets"].extend(res.export.sets)
            ext["set_engine"] = res.export.set_engine
            for key, value in res.export.counters:
                ext["counters"][key] = \
                    ext["counters"].get(key, 0.0) + float(value)
            # counters whose scope kept them out of the export (MIXED/
            # local keys that landed via checkpointed bank rows) flush
            # into the frame instead — fold those in by exact key
            exported = {k for k, _v in res.export.counters}
            for m in res.frame:
                if m.type != MetricType.COUNTER:
                    continue
                key = MetricKey(m.name, "counter", ",".join(m.tags))
                if key in exported:
                    continue
                ext["counters"][key] = \
                    ext["counters"].get(key, 0.0) + float(m.value)
        if full:
            with self._cache_lock:
                self._gen_cache[cache_key] = ext
                self._gen_cache.move_to_end(cache_key)
                while len(self._gen_cache) > self._GEN_CACHE:
                    self._gen_cache.popitem(last=False)
        return ext

    @staticmethod
    def _group_is_empty(g) -> bool:
        """True when a baseline group provably reconstructs to an empty
        interval share: no bank rows, nothing staged, no interned keys
        — restoring it would flush nothing."""
        if g["banks"]:
            return False
        staged = g["staged"]
        if any(staged.get(f) for f in ("centroids", "sets", "counters",
                                       "gauges")):
            return False
        return not any(entries for _iv, entries in g["keys"].values())

    def debug_state(self) -> dict:
        with self._cache_lock:
            return {
                "cache_entries": len(self._cache),
                "cache_capacity": self._cache_entries,
                "generation_cache_entries": len(self._gen_cache),
                "timeout_s": self._timeout_s,
                "requests": self._registry.total(self._scope,
                                                 "query.requests"),
                "errors": self._registry.total(self._scope,
                                               "query.errors"),
                "cache_hits": self._registry.total(self._scope,
                                                   "query.cache_hits"),
            }
