"""veneur-emit: compose one metric (or span) from the command line.

Parity: cmd/veneur-emit/main.go (sym: main + its flag set): -hostport,
-count/-gauge/-timing/-set with -name, -tag, -ssf to ship SSF instead of
statsd, and -command to time a subprocess and emit its duration (plus
exit status), exiting with the child's code.
"""

from __future__ import annotations

import argparse
import shlex
import socket
import subprocess
import sys
import time
from urllib.parse import urlparse


def build_statsd_lines(args) -> list[bytes]:
    tags = f"|#{args.tag}" if args.tag else ""
    lines = []
    if args.count is not None:
        lines.append(f"{args.name}:{args.count}|c{tags}")
    if args.gauge is not None:
        lines.append(f"{args.name}:{args.gauge}|g{tags}")
    if args.timing is not None:
        lines.append(f"{args.name}:{args.timing}|ms{tags}")
    if args.set is not None:
        lines.append(f"{args.name}:{args.set}|s{tags}")
    return [ln.encode() for ln in lines]


def build_ssf_span(args):
    from .. import ssf
    from ..ssf.protos import ssf_pb2

    tags = dict(t.split(":", 1) if ":" in t else (t, "")
                for t in (args.tag.split(",") if args.tag else []))
    span = ssf_pb2.SSFSpan(version=0, service=args.service or "veneur-emit")
    if args.count is not None:
        span.metrics.append(ssf.count(args.name, float(args.count), tags))
    if args.gauge is not None:
        span.metrics.append(ssf.gauge(args.name, float(args.gauge), tags))
    if args.timing is not None:
        span.metrics.append(ssf.timing(args.name, float(args.timing) / 1e3,
                                       ssf.MILLISECOND, tags))
    if args.set is not None:
        span.metrics.append(ssf.set_sample(args.name, str(args.set), tags))
    return span


def send_payload(hostport: str, payload: bytes):
    u = urlparse(hostport if "://" in hostport else f"udp://{hostport}")
    if u.scheme in ("udp", ""):
        host = u.hostname or "127.0.0.1"
        family = socket.AF_INET6 if ":" in host else socket.AF_INET
        sock = socket.socket(family, socket.SOCK_DGRAM)
        sock.sendto(payload, (host, u.port or 8125))
        sock.close()
    elif u.scheme == "unix":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        sock.sendto(payload, u.path)
        sock.close()
    elif u.scheme == "tcp":
        with socket.create_connection(
                (u.hostname or "127.0.0.1", u.port or 8125),
                timeout=5) as sock:
            sock.sendall(payload)
    else:
        raise ValueError(f"unsupported scheme {u.scheme!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="veneur-emit")
    ap.add_argument("-hostport", default="udp://127.0.0.1:8125",
                    help="destination, e.g. udp://127.0.0.1:8125")
    ap.add_argument("-name", help="metric name")
    ap.add_argument("-count", type=float, default=None)
    ap.add_argument("-gauge", type=float, default=None)
    ap.add_argument("-timing", type=float, default=None,
                    help="timer value (ms)")
    ap.add_argument("-set", default=None, help="set member")
    ap.add_argument("-tag", default="", help="comma-separated k:v tags")
    ap.add_argument("-ssf", action="store_true",
                    help="send as an SSF span instead of statsd")
    ap.add_argument("-service", default="", help="SSF service name")
    ap.add_argument("-command", default="",
                    help="run this command, time it, emit the duration")
    args = ap.parse_args(argv)

    rc = 0
    if args.command:
        if not args.name:
            args.name = "veneur_emit.command"
        t0 = time.perf_counter()
        rc = subprocess.call(shlex.split(args.command))
        args.timing = (time.perf_counter() - t0) * 1000.0
        args.tag = (args.tag + "," if args.tag else "") + f"exit_status:{rc}"
    elif not args.name:
        ap.error("-name is required unless -command is given")

    if args.ssf:
        span = build_ssf_span(args)
        send_payload(args.hostport, span.SerializeToString())
    else:
        for line in build_statsd_lines(args):
            send_payload(args.hostport, line)
    return rc


if __name__ == "__main__":
    sys.exit(main())
