"""The main daemon CLI — `python -m veneur_tpu.cli.veneur -f config.yaml`.

Parity: cmd/veneur/main.go (sym: main): read config, build server, run
until signalled.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu")
    ap.add_argument("-f", dest="config", required=True,
                    help="path to YAML config")
    ap.add_argument("--validate-config", action="store_true",
                    help="parse config and exit")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="enable debug logging")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from ..config import read_config
    cfg = read_config(args.config)
    if args.validate_config:
        print("config ok")
        return 0

    if cfg.aggregation_backend == "cpu":
        from ..utils.platform import pin_cpu
        pin_cpu()

    from ..server import Server
    srv = Server(cfg)
    srv.start()
    logging.getLogger("veneur").info(
        "veneur-tpu serving: statsd=%s interval=%ss workers=%d",
        cfg.statsd_listen_addresses, cfg.interval_seconds, cfg.num_workers)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            # not the main thread (embedded/test use): rely on the
            # caller to stop us instead of signals
            break
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
