"""veneur-proxy: consistent-hash metrics across the global tier.

Parity: cmd/veneur-proxy/main.go (sym: main) + proxy.go (sym:
NewProxyFromConfig). Reads a YAML config (the reference's proxy config
keys), builds a Discoverer (consul or static `forward_destinations`),
and serves the forwardrpc contract, re-routing each metric by its key
digest onto the owning global veneur.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

import yaml


def proxy_from_config(cfg: dict):
    """Build and start a ProxyServer from proxy YAML keys. Returns the
    running proxy, or raises ValueError on an unusable config."""
    from ..cluster.discovery import ConsulDiscoverer, StaticDiscoverer
    from ..cluster.proxy import ProxyServer
    from ..config import _parse_interval

    service = cfg.get("consul_forward_service_name", "")
    if service:
        disc = ConsulDiscoverer(
            cfg.get("consul_url", "http://127.0.0.1:8500"))
    else:
        static = cfg.get("forward_destinations", [])
        if not static:
            raise ValueError(
                "proxy config needs consul_forward_service_name or "
                "forward_destinations")
        disc = StaticDiscoverer(static)

    refresh = _parse_interval(cfg.get("consul_refresh_interval", "30s"))
    proxy = ProxyServer(disc, service_name=service,
                        refresh_interval_s=refresh)
    addr = cfg.get("grpc_address", "0.0.0.0:8128")
    proxy.start(addr)
    # legacy HTTP face (proxy.go sym: Proxy.Handler): POST /import
    http_addr = cfg.get("http_address", "")
    if http_addr:
        from ..cluster.proxy import HttpProxyFront
        proxy.http_front = HttpProxyFront(proxy)
        proxy.http_front.start(http_addr)
    logging.getLogger("veneur-proxy").info(
        "proxying on %s -> %d destinations", addr, len(proxy.ring))
    return proxy


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="veneur-proxy")
    ap.add_argument("-f", dest="config", required=True,
                    help="path to proxy YAML config")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    with open(args.config) as f:
        cfg = yaml.safe_load(f) or {}

    try:
        proxy = proxy_from_config(cfg)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            # not the main thread (embedded/test use): rely on the
            # caller to stop us instead of signals
            break
    stop.wait()
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
