"""veneur-prometheus: poll a Prometheus /metrics endpoint and relay the
families to a veneur as DogStatsD.

Parity: cmd/veneur-prometheus/main.go (sym: main): on an interval, GET
the exposition text, translate each family — counters as deltas since
the previous poll (first poll primes the cache), gauges as absolute
values, histogram/summary components as their counter/gauge parts — and
emit statsd lines with the Prometheus labels as tags.

The relay's own telemetry (polls, poll errors, series relayed, send
errors) flows through the unified registry (observe/registry.py) like
every other veneur.* self-metric, and `--self-metrics-address` serves
it as one Prometheus scrape surface — previously the only view was
partial (log lines), invisible to scrapers.
"""

from __future__ import annotations

import argparse
import logging
import re
import socket
import sys
import time
import urllib.request

log = logging.getLogger("veneur-prometheus")

# the label body must be matched as a sequence of quoted values, not
# [^}]* — an unescaped '}' is legal inside a quoted label value
_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<labels>(?:\s*[a-zA-Z_][a-zA-Z0-9_]*\s*=\s*'
    r'"(?:[^"\\]|\\.)*"\s*,?)*)\})?\s+'
    r"(?P<value>[^\s]+)(?:\s+\d+)?$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """Prometheus text format → [(name, labels dict, value, type)].
    TYPE comments drive counter/gauge classification; untyped series
    default to gauge."""
    types: dict[str, str] = {}
    out = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        name = m.group("name")
        labels = dict(_LABEL.findall(m.group("labels") or ""))
        base = name
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                base = name[:-len(suffix)]
                break
        ftype = types.get(base, types.get(name, "gauge"))
        out.append((name, labels, value, ftype))
    return out


def to_statsd_lines(samples, prev: dict, prefix: str = "",
                    ignored_labels=()):
    """Translate one poll; `prev` carries last cumulative values for
    delta-ing counters (mutated in place)."""
    lines = []
    for name, labels, value, ftype in samples:
        labels = {k: v for k, v in labels.items()
                  if k not in ignored_labels}
        tagstr = ",".join(f"{k}:{v}" for k, v in sorted(labels.items()))
        key = (name, tagstr)
        mname = prefix + name
        if ftype in ("counter", "histogram", "summary") and (
                name.endswith(("_total", "_count", "_bucket", "_sum"))
                or ftype == "counter"):
            last = prev.get(key)
            prev[key] = value
            if last is None or value < last:   # first poll / reset
                continue
            delta = value - last
            if delta == 0:
                continue
            line = f"{mname}:{delta}|c"
        else:
            line = f"{mname}:{value}|g"
        if tagstr:
            line += f"|#{tagstr}"
        lines.append(line.encode())
    return lines


def poll_once(url: str, prev: dict, prefix: str = "",
              timeout_s: float = 10.0):
    # vlint: disable=RS01 reason=scrape ingest in a one-shot CLI, not
    # server egress: the poll loop already tolerates a failed scrape
    # (skips the interval) and retrying inside would skew counter deltas
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        text = resp.read().decode("utf-8", "replace")
    return to_statsd_lines(parse_exposition(text), prev, prefix)


def start_self_metrics_server(address: str, registry):
    """Expose the relay's own unified-registry telemetry as a
    Prometheus scrape surface: the exposition server IS the sink's
    (one implementation of address parsing / routing / content type),
    just with no flush body — only the live registry snapshot.
    Returns the started sink (.port is the bound port, .stop() tears
    it down)."""
    from ..sinks.prometheus import PrometheusMetricSink

    sink = PrometheusMetricSink(listen_address=address,
                                registries=(registry,))
    sink.start()
    return sink


def main(argv=None) -> int:
    from ..observe import SERVER_SCOPE, TelemetryRegistry

    ap = argparse.ArgumentParser(prog="veneur-prometheus")
    ap.add_argument("-p", "--prometheus-host",
                    default="http://localhost:9090/metrics",
                    help="metrics endpoint to poll")
    ap.add_argument("-s", "--statsd-host", default="127.0.0.1:8126",
                    help="veneur statsd address")
    ap.add_argument("-i", "--interval", type=float, default=10.0)
    ap.add_argument("--prefix", default="", help="metric name prefix")
    ap.add_argument("--once", action="store_true",
                    help="poll twice back-to-back and exit (testing)")
    ap.add_argument("--self-metrics-address", default="",
                    help="serve the relay's own veneur.prometheus.* "
                         "telemetry (unified registry) for scraping, "
                         "e.g. 127.0.0.1:9126")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    host, _, port = args.statsd_host.rpartition(":")
    host = host.strip("[]")
    if not port.isdigit():
        print(f"-s must be host:port, got {args.statsd_host!r}",
              file=sys.stderr)
        return 1
    dest = (host or "127.0.0.1", int(port))
    family = socket.AF_INET6 if ":" in dest[0] else socket.AF_INET
    sock = socket.socket(family, socket.SOCK_DGRAM)

    registry = TelemetryRegistry()
    if args.self_metrics_address:
        start_self_metrics_server(args.self_metrics_address, registry)

    prev: dict = {}
    n_polls = 0
    while True:
        try:
            lines = poll_once(args.prometheus_host, prev, args.prefix)
            sent = 0
            for ln in lines:
                try:
                    sock.sendto(ln, dest)
                    sent += 1
                except OSError:
                    registry.incr(SERVER_SCOPE, "prometheus.send_errors")
            registry.incr(SERVER_SCOPE, "prometheus.polls")
            registry.incr(SERVER_SCOPE, "prometheus.series_relayed",
                          sent)
            log.info("relayed %d series", sent)
        except Exception as e:
            registry.incr(SERVER_SCOPE, "prometheus.poll_errors")
            log.error("poll failed: %s", e)
        n_polls += 1
        if args.once and n_polls >= 2:
            return 0
        time.sleep(args.interval if not args.once else 0.05)


if __name__ == "__main__":
    sys.exit(main())
