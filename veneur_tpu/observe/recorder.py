"""Flight recorder: a bounded ring of per-flush-tick phase trees.

Every flush tick records where its milliseconds went — engine drain,
XLA dispatch, device exec (bounded by block_until_ready), MetricFrame
materialize, per-sink fan-out (including skips and still-in-flight
threads), the forward ladder (per-attempt retry/backoff, breaker
rejections, replay entries, journal ops) — into one `TickRecord`.
The last `capacity` ticks live in a preallocated ring; `/debug/flush`
serves them as JSON and `emit_spans` replays each tick as an SSF span
tree through the server's own trace client (flusher.go self-tracing
parity).

Hot-path cost model: one `time.monotonic_ns()` call and one index bump
per phase edge, under a lock held for the bump only; phase slots are
preallocated (`_Phase` objects recycled with their tick slot), so the
steady state allocates nothing per phase. Overflow past `max_phases`
drops the phase (counted on the tick), never grows the slot array.

Recorder state is strictly process-local: no journal interaction, no
persistence — a SimulatedKill/SIGKILL loses the ring and nothing else
(the chaos suite pins that a kill can't corrupt what remains).

Cross-thread attribution: the flusher thread owns the tick and parks
it in a contextvar (`set_current_tick`) so code it calls synchronously
— the forward ladder, egress retries, journal ops — can attribute
phases without plumbing. Threads the flusher *spawns* (engine flushes,
sink fan-out) do not inherit the contextvar; the server hands them
explicit (tick, parent) handles, and egress calls made from non-flush
threads (span sinks, background pollers) see no current tick and
record nothing — which is the correct attribution.
"""

from __future__ import annotations

import contextvars
import threading
import time

from . import registry as _registry

class _Scope:
    """What the contextvar carries: the tick plus the phase index new
    child phases should parent under (the server moves the parent as it
    enters its top-level phases, so the forward ladder's attempt/replay
    phases nest under `forward`, not beside it)."""

    __slots__ = ("tick", "parent")

    def __init__(self, tick: "TickRecord", parent: int = -1):
        self.tick = tick
        self.parent = parent


_current_scope: contextvars.ContextVar["_Scope | None"] = \
    contextvars.ContextVar("veneur_flight_scope", default=None)


def current_scope() -> "_Scope | None":
    """The (tick, parent) scope in progress on THIS thread's context
    (None off the flusher thread)."""
    return _current_scope.get()


def current_tick() -> "TickRecord | None":
    sc = _current_scope.get()
    return None if sc is None else sc.tick


def set_current_tick(tick: "TickRecord | None", parent: int = -1):
    return _current_scope.set(
        None if tick is None else _Scope(tick, parent))


def reset_current_tick(token):
    _current_scope.reset(token)


class _Phase:
    """One preallocated phase slot. `t1 == 0` means still in flight."""

    __slots__ = ("name", "parent", "t0", "t1", "meta")

    def __init__(self):
        self.name = ""
        self.parent = -1
        self.t0 = 0
        self.t1 = 0
        self.meta = None


class _PhaseCtx:
    """Context-manager handle from TickRecord.phase()."""

    __slots__ = ("_tick", "idx")

    def __init__(self, tick, idx):
        self._tick = tick
        self.idx = idx

    def __enter__(self):
        return self.idx

    def __exit__(self, exc_type, exc, tb):
        self._tick.finish(self.idx)
        return False


class TickRecord:
    """One flush tick's phase tree (preallocated, reused by the ring).

    Each tick carries its own TRACE IDENTITY (`trace_id`, `span_id`),
    pinned at begin_tick — not minted at span-emission time — so the
    forward path can stamp the identity onto wire envelopes WHILE the
    tick runs (cross-tier span propagation) and `emit_spans` later
    replays the exact same tree the remote tier parented under.
    `close_ns` is the interval-close wall time the tick represents
    (the server stamps it; scripted timestamps stay scripted), riding
    the same envelopes to feed the global's e2e latency accounting."""

    __slots__ = ("tick_id", "ts", "wall_start_ns", "mono_start", "mono_end",
                 "n", "dropped", "_slots", "_lock",
                 "trace_id", "span_id", "close_ns")

    def __init__(self, max_phases: int):
        self._slots = [_Phase() for _ in range(max_phases)]
        self._lock = threading.Lock()
        self.tick_id = -1
        self.ts = 0
        self.wall_start_ns = 0
        self.mono_start = 0
        self.mono_end = 0
        self.n = 0
        self.dropped = 0
        self.trace_id = 0
        self.span_id = 0
        self.close_ns = 0

    def _reset(self, tick_id: int, ts: int):
        from ..trace import _span_id   # shared int63 id space
        self.tick_id = tick_id
        self.ts = ts
        self.wall_start_ns = time.time_ns()
        self.mono_start = time.monotonic_ns()
        self.mono_end = 0
        self.n = 0
        self.dropped = 0
        self.trace_id = _span_id()
        self.span_id = _span_id()
        self.close_ns = self.wall_start_ns

    # ---- hot path ----

    def start(self, name: str, parent: int = -1) -> int:
        """Open a phase; returns its index (-1 = slot budget exhausted,
        safe to pass to finish). Thread-safe: the slot's fields are
        initialized BEFORE the index publish (`self.n = i + 1`), all
        under the lock — a reader (snapshot / emit_spans on another
        thread) that observes the new n must never see the recycled
        slot's previous-tick contents (a stale nonzero t1 would read
        as a completed phase with absurd timestamps)."""
        t0 = time.monotonic_ns()
        with self._lock:
            i = self.n
            if i >= len(self._slots):
                self.dropped += 1
                return -1
            s = self._slots[i]
            s.name = name
            s.parent = parent
            s.t0 = t0
            s.t1 = 0
            s.meta = None
            self.n = i + 1
        return i

    def finish(self, idx: int, **meta):
        """Close a phase (single writer per slot — no lock needed)."""
        if idx < 0:
            return
        s = self._slots[idx]
        s.t1 = time.monotonic_ns()
        if meta:
            s.meta = meta

    def phase(self, name: str, parent: int = -1) -> _PhaseCtx:
        """`with tick.phase("drain") as idx:` convenience wrapper."""
        return _PhaseCtx(self, self.start(name, parent))

    def add(self, name: str, t0_ns: int, t1_ns: int, parent: int = -1,
            **meta) -> int:
        """Record a phase whose edges were stamped elsewhere (engine
        flush threads return their stamps in FlushResult.stats).
        Fields-before-publish, like start()."""
        with self._lock:
            i = self.n
            if i >= len(self._slots):
                self.dropped += 1
                return -1
            s = self._slots[i]
            s.name = name
            s.parent = parent
            s.t0 = t0_ns
            s.t1 = t1_ns
            s.meta = meta or None
            self.n = i + 1
        return i

    def annotate(self, idx: int, **meta):
        if idx < 0:
            return
        s = self._slots[idx]
        s.meta = {**(s.meta or {}), **meta}

    # ---- read side ----

    def duration_ns(self) -> int:
        end = self.mono_end or time.monotonic_ns()
        return end - self.mono_start

    def phases(self):
        """[(name, t0_ns, t1_ns, parent)] — t1 of an in-flight phase
        reads 0."""
        return [(s.name, s.t0, s.t1, s.parent)
                for s in self._slots[:self.n]]

    def attributed_ns(self) -> int:
        """Nanoseconds accounted to completed TOP-LEVEL phases —
        the numerator of the >=95% coverage acceptance gate (children
        nest inside their parents, so only roots sum)."""
        return sum(s.t1 - s.t0 for s in self._slots[:self.n]
                   if s.parent == -1 and s.t1 > s.t0)

    def to_dict(self) -> dict:
        """JSON-ready timeline: offsets are ns from tick start so a
        reader can lay phases on one axis without epoch math."""
        base = self.mono_start
        phases = []
        for s in self._slots[:self.n]:
            d = {"name": s.name, "parent": s.parent,
                 "start_ns": s.t0 - base,
                 "end_ns": (s.t1 - base) if s.t1 else None,
                 "in_flight": s.t1 == 0}
            if s.meta:
                d["meta"] = s.meta
            phases.append(d)
        dur = (self.mono_end - base) if self.mono_end else None
        return {"tick_id": self.tick_id, "timestamp": self.ts,
                "wall_start_ns": self.wall_start_ns,
                "duration_ns": dur, "phases": phases,
                "dropped_phases": self.dropped}


class FlightRecorder:
    """The bounded ring. Ticks are serialized (one flusher thread);
    the ring hands out its oldest slot for reuse, so a sink thread
    finishing a phase from `capacity` ticks ago writes into a slot
    about to be recycled — stale but never unsafe (slot objects are
    never freed, and the snapshot tolerates in-flight phases)."""

    def __init__(self, capacity: int = 32, max_phases: int = 192):
        self.capacity = max(1, capacity)
        self.max_phases = max(8, max_phases)
        self._ring = [TickRecord(self.max_phases)
                      for _ in range(self.capacity)]
        self._next = 0          # flusher-thread-only
        self._tick_count = 0
        self._lock = threading.Lock()   # snapshot vs begin_tick

    def begin_tick(self, ts: int) -> TickRecord:
        with self._lock:
            tick = self._ring[self._next]
            self._next = (self._next + 1) % self.capacity
            self._tick_count += 1
            tick._reset(self._tick_count, ts)
        return tick

    def end_tick(self, tick: TickRecord):
        tick.mono_end = time.monotonic_ns()

    def open_tick(self, ts: int) -> TickRecord:
        """A PRIVATE TickRecord outside the ring, for CONCURRENT
        recorders (the import observer's handler threads): record into
        it freely, then publish the finished record with adopt().
        begin_tick would hand concurrent callers recycled ring slots —
        with more in-flight requests than ring capacity, _reset wipes
        a slot out from under the request still writing to it."""
        tick = TickRecord(self.max_phases)
        tick._reset(0, ts)      # tick_id assigned at adopt()
        return tick

    def adopt(self, tick: TickRecord):
        """Publish a COMPLETED open_tick record into the ring (takes
        the next slot; the recycled slot object is dropped). The tick
        must be finished — end_tick first — since ring readers treat
        membership as 'this tick happened'."""
        with self._lock:
            self._tick_count += 1
            tick.tick_id = self._tick_count
            self._ring[self._next] = tick
            self._next = (self._next + 1) % self.capacity

    @property
    def tick_count(self) -> int:
        return self._tick_count

    def last_tick(self) -> TickRecord | None:
        with self._lock:
            if self._tick_count == 0:
                return None
            return self._ring[(self._next - 1) % self.capacity]

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """The ring as JSON-ready dicts, newest tick first."""
        with self._lock:
            n = min(self._tick_count, self.capacity)
            ticks = [self._ring[(self._next - 1 - i) % self.capacity]
                     for i in range(n)]
        out = [t.to_dict() for t in ticks]
        if limit is not None:
            out = out[:max(0, limit)]
        return out

    def emit_spans(self, tick: TickRecord, client, *,
                   trace_id: int | None = None, parent_id: int = 0,
                   namer=None) -> int:
        """Replay one tick as an SSF span tree through the trace
        client (the server's own ingest path — flusher.go parity).
        Returns the number of spans enqueued.

        The root span uses the tick's OWN pinned identity (`trace_id`
        defaults to tick.trace_id, root id is tick.span_id) — the same
        identity the forward path stamped onto wire envelopes, so a
        remote tier's import spans parent correctly. A receiver passes
        `trace_id`/`parent_id` from the decoded envelope to graft its
        import tick under the REMOTE sender's flush span, and `namer`
        to name the tree (defaults to the flush span names)."""
        if client is None:
            return 0
        from ..ssf.protos import ssf_pb2
        from ..trace import _span_id

        if namer is None:
            namer = _registry.flush_span_name
        wall0 = tick.wall_start_ns
        mono0 = tick.mono_start
        trace_id = trace_id or tick.trace_id or _span_id()
        root_id = tick.span_id or _span_id()
        end = tick.mono_end or time.monotonic_ns()
        root = ssf_pb2.SSFSpan(
            version=0, trace_id=trace_id, id=root_id,
            parent_id=parent_id,
            name=namer(), service="veneur",
            start_timestamp=wall0,
            end_timestamp=wall0 + (end - mono0))
        root.tags["tick_id"] = str(tick.tick_id)
        sent = 1 if client.record(root) else 0
        ids = {}
        for i, (name, t0, t1, parent) in enumerate(tick.phases()):
            if t1 == 0:
                continue   # in-flight at emission; /debug/flush has it
            sid = _span_id()
            ids[i] = sid
            span = ssf_pb2.SSFSpan(
                version=0, trace_id=trace_id, id=sid,
                parent_id=ids.get(parent, root_id),
                name=namer(name), service="veneur",
                start_timestamp=wall0 + (t0 - mono0),
                end_timestamp=wall0 + (t1 - mono0))
            sent += 1 if client.record(span) else 0
        return sent

    def debug_state(self, limit: int | None = None) -> dict:
        return {"tick_count": self._tick_count,
                "capacity": self.capacity,
                "max_phases_per_tick": self.max_phases,
                "ticks": self.snapshot(limit)}
