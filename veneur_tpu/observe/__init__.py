"""Self-observation: the unified telemetry spine + the flight recorder.

One data model, three faces (flusher.go's self-instrumentation, grown
into a subsystem):

  * `registry.TelemetryRegistry` — the ONE registry every self-metric
    counter/gauge in the process flows through (the egress resilience
    counters, the durability journal counters, the server's ingest/
    flush/sink accounting). The registry is also the only module
    allowed to *name* `veneur.*` self-metrics (vlint TL01).
  * `recorder.FlightRecorder` — a bounded ring of per-flush-tick phase
    trees (drain / device dispatch / device exec / materialize / sink
    fan-out / forward ladder / journal ops), lock-cheap monotonic
    stamping, preallocated slots.
  * introspection — the recorder's `snapshot()` feeds the http_api's
    `/debug/flush` endpoint, `emit_spans()` feeds the SSF self-tracing
    client, and `registry.phase_timer_samples()` feeds phase durations
    back into the server's own engine as `veneur.flush.phase.*` timers.
"""

from .fleet import FleetView, ImportObserver
from .recorder import (FlightRecorder, TickRecord, current_scope,
                       current_tick, reset_current_tick,
                       set_current_tick)
from .registry import (DEFAULT_REGISTRY, SERVER_SCOPE, TelemetryRegistry,
                       e2e_timer_samples, fanout_timer_sample,
                       phase_timer_samples)

__all__ = [
    "DEFAULT_REGISTRY", "SERVER_SCOPE", "TelemetryRegistry",
    "phase_timer_samples", "e2e_timer_samples", "fanout_timer_sample",
    "FlightRecorder", "TickRecord", "FleetView", "ImportObserver",
    "current_tick", "current_scope", "set_current_tick",
    "reset_current_tick",
]
