"""Fleet view: per-sender e2e latency, freshness, and import tracing.

The receiver-side half of cross-tier tracing. Every forwarded chunk
arrives with an idempotency envelope and (from tracing-enabled
senders) a trace context — the sender's flush-tick trace/span ids plus
the interval-close wall time (wire codecs: cluster/wire.py). Two
consumers live here:

  * `FleetView` — bounded per-sender bookkeeping. Each ADMITTED
    interval's close time parks in a pending set; at the global's next
    flush tick `on_flush(now_ns)` turns the set into close->merged
    latency samples (the `veneur.e2e.*` timers), advances the
    per-sender freshness watermark (newest close time merged), and
    feeds a rolling window serving p50/p99 to `GET /debug/fleet`.
    Close times are COMPARED, never merged: e2e accounting can change
    no flushed tenant state (the exactly-once chaos oracles pin it).

  * `ImportObserver` — per-request import observation. Each import
    request (gRPC SendMetrics/V2, HTTP /import) records its
    dedupe/apply phases as a TickRecord in a bounded ring (the same
    flight-recorder machinery as flush ticks, served under
    /debug/fleet) and — when the sender propagated a trace context —
    replays them as SSF spans PARENTED ON THE REMOTE SENDER'S FLUSH
    SPAN, yielding one span tree per interval across both processes.

Thread model: handler threads call both concurrently; FleetView takes
one lock per call, the import ring reuses the recorder's locking. The
clock is injectable for the fault harness.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from . import registry as _registry
from .recorder import FlightRecorder, TickRecord

# a storm of admissions between two global flushes must not grow the
# pending set unboundedly; overflow drops the OLDEST pending sample
# (observability loss only, counted in debug_state)
MAX_PENDING_INTERVALS = 8192


class _SenderView:
    __slots__ = ("last_seen_ns", "newest_close_ns", "intervals_merged",
                 "window", "sketch_engines", "engine_rejects")

    def __init__(self, window: int):
        self.last_seen_ns = 0
        self.newest_close_ns = 0      # freshness watermark
        self.intervals_merged = 0
        self.window = deque(maxlen=window)   # e2e ms samples
        # sketch-engine/wire stamp the sender last declared (None until
        # a request carried a verdict) + rejected-request count — the
        # mixed-fleet signature an operator reads BEFORE it degrades
        self.sketch_engines = None
        self.engine_rejects = 0


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over a small sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


class FleetView:
    """Bounded per-sender e2e/freshness bookkeeping (receiver side)."""

    def __init__(self, max_senders: int = 1024, window: int = 256,
                 clock=time.time_ns):
        self.max_senders = max(1, max_senders)
        self.window = max(8, window)
        self._clock = clock
        self._lock = threading.Lock()
        self._senders: OrderedDict[str, _SenderView] = OrderedDict()
        # (sender_id, seq) -> close_ns, admitted since the last flush
        self._pending: OrderedDict = OrderedDict()
        self.pending_dropped = 0

    def _touch(self, sender_id: str, now_ns: int) -> _SenderView:
        sv = self._senders.get(sender_id)
        if sv is None:
            while len(self._senders) >= self.max_senders:
                self._senders.popitem(last=False)
            sv = self._senders[sender_id] = _SenderView(self.window)
        else:
            self._senders.move_to_end(sender_id)
        sv.last_seen_ns = now_ns
        return sv

    def observe_interval(self, sender_id: str, seq: int,
                         close_ns: int = 0) -> None:
        """One ADMITTED chunk arrived. Bumps last-seen; with a close
        time, parks the interval for e2e accounting at the next flush
        (chunks of one interval collapse onto one pending sample)."""
        now = self._clock()
        with self._lock:
            self._touch(sender_id, now)
            if close_ns:
                self._pending[(sender_id, int(seq))] = int(close_ns)
                while len(self._pending) > MAX_PENDING_INTERVALS:
                    self._pending.popitem(last=False)
                    self.pending_dropped += 1

    def note_stamp(self, sender_id: str, stamp: str | None,
                   ok: bool) -> None:
        """Record one request's sketch-engine stamp verdict (ISSUE 10):
        the sender's declared engines (or "(legacy)" for unstamped
        peers) and, on mismatch, the reject count — so /debug/fleet
        shows a MIXED fleet per sender, not just an aggregate counter.

        Liveness discipline: an ACCEPTED stamp only ANNOTATES a row the
        normal admission path created (a request whose body then fails
        decode must not look alive — the rejected-import rule); a
        MISMATCH creates/touches the row — the sender IS alive and
        misconfigured, which is exactly what the page must show."""
        with self._lock:
            if ok:
                sv = self._senders.get(sender_id)
                if sv is None:
                    return
            else:
                sv = self._touch(sender_id, self._clock())
                sv.engine_rejects += 1
            sv.sketch_engines = stamp if stamp is not None else "(legacy)"

    def on_flush(self, now_ns: int) -> dict:
        """Flush boundary: everything admitted since the previous tick
        is now merged into flushed state. Returns {sender_id: [ms]}
        close->merged latency samples (for the e2e timer dogfood) and
        advances each sender's freshness watermark."""
        out: dict = {}
        with self._lock:
            pending, self._pending = self._pending, OrderedDict()
            for (sender_id, _seq), close_ns in pending.items():
                sv = self._senders.get(sender_id)
                if sv is None:
                    sv = self._touch(sender_id, now_ns)
                ms = max(0.0, (now_ns - close_ns) / 1e6)
                sv.window.append(ms)
                sv.intervals_merged += 1
                sv.newest_close_ns = max(sv.newest_close_ns, close_ns)
                out.setdefault(sender_id, []).append(ms)
        return out

    def freshness(self, now_ns: int | None = None) -> dict:
        """{sender_id: age_ns of the newest MERGED interval} — the
        per-sender staleness gauge (senders with no merged close time
        yet are omitted)."""
        now = self._clock() if now_ns is None else now_ns
        with self._lock:
            return {sid: max(0, now - sv.newest_close_ns)
                    for sid, sv in self._senders.items()
                    if sv.newest_close_ns}

    def sender_count(self) -> int:
        with self._lock:
            return len(self._senders)

    def debug_state(self, now_ns: int | None = None) -> dict:
        """JSON-ready per-sender rows for GET /debug/fleet."""
        now = self._clock() if now_ns is None else now_ns
        with self._lock:
            pending_by_sender: dict = {}
            for (sid, _seq) in self._pending:
                pending_by_sender[sid] = pending_by_sender.get(sid, 0) + 1
            senders = {}
            for sid, sv in self._senders.items():
                vals = sorted(sv.window)
                senders[sid] = {
                    "last_seen_age_s": max(0.0,
                                           (now - sv.last_seen_ns) / 1e9),
                    "newest_close_ns": sv.newest_close_ns,
                    "freshness_age_ms": (
                        max(0.0, (now - sv.newest_close_ns) / 1e6)
                        if sv.newest_close_ns else None),
                    "intervals_merged": sv.intervals_merged,
                    "sketch_engines": sv.sketch_engines,
                    "engine_mismatch_rejects": sv.engine_rejects,
                    "pending": pending_by_sender.get(sid, 0),
                    "e2e_ms": {
                        "count": len(vals),
                        "p50": round(_percentile(vals, 0.50), 3),
                        "p99": round(_percentile(vals, 0.99), 3),
                    },
                }
            return {"senders": senders,
                    "pending_intervals": len(self._pending),
                    "pending_dropped": self.pending_dropped}


_NO_STAMP = object()   # "this request carried no stamp verdict"


class _ImportScope:
    """Context for one import request: phases into the import ring,
    spans parented on the remote sender's flush span, fleet feed."""

    __slots__ = ("_obs", "tick", "env", "trace", "admitted", "n_metrics",
                 "kind", "rejected", "stamp")

    def __init__(self, obs: "ImportObserver", env, trace, kind: str,
                 stamp=_NO_STAMP):
        self._obs = obs
        self.env = env
        self.trace = trace
        self.admitted = False
        self.n_metrics = 0
        self.kind = kind
        self.rejected = False       # 4xx'd before a dedupe verdict
        self.stamp = stamp          # accepted engine stamp (None=legacy)
        self.tick = None
        if obs.flight is not None:
            # a PRIVATE record, published at __exit__: handler threads
            # run concurrently, and a ring slot handed out here could
            # be recycled mid-request once in-flight requests exceed
            # ring capacity (one slow client + a burst of fast ones)
            self.tick = obs.flight.open_tick(int(time.time()))

    def start(self, name: str, parent: int = -1) -> int:
        return -1 if self.tick is None else self.tick.start(name, parent)

    def finish(self, idx: int, **meta):
        if self.tick is not None:
            self.tick.finish(idx, **meta)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        obs = self._obs
        if self.tick is not None:
            # zero-length summary phase: the request identity/verdict,
            # readable from the ring and the emitted span tree alike
            self.tick.finish(
                self.tick.start("request"), kind=self.kind,
                sender=(self.env[0] if self.env else None),
                seq=(self.env[1] if self.env else None),
                admitted=self.admitted, n_metrics=self.n_metrics)
            obs.flight.end_tick(self.tick)
            obs.flight.adopt(self.tick)
            if self.trace is not None:
                client = obs.client()
                if client is not None:
                    obs.flight.emit_spans(
                        self.tick, client,
                        trace_id=self.trace[0],
                        parent_id=self.trace[1],
                        namer=_registry.import_span_name)
        if obs.fleet is not None and self.env is not None \
                and exc_type is None and not self.rejected:
            if self.admitted:
                close_ns = self.trace[2] if self.trace is not None else 0
                obs.fleet.observe_interval(self.env[0], self.env[1],
                                           close_ns)
            else:
                # a deduped chunk still proves the sender alive; a
                # REJECTED request (4xx before a dedupe verdict) must
                # not — bumping last-seen for a sender whose every
                # body fails decode would mask it on the very page an
                # operator consults to find it
                obs.fleet.observe_interval(self.env[0], self.env[1], 0)
            if self.stamp is not _NO_STAMP:
                # annotate the row the feed above just created with
                # the ACCEPTED engine stamp (mismatches never get
                # here — the handler rejected before opening a scope)
                obs.fleet.note_stamp(self.env[0], self.stamp, True)
        return False


class ImportObserver:
    """Bundles what the import handlers need to observe one request:
    the bounded import ring (flight-recorder TickRecords), the fleet
    view, and the server's trace client (late-bound — the client only
    exists once an SSF listener is up)."""

    def __init__(self, fleet: FleetView | None = None,
                 flight: FlightRecorder | None = None,
                 client=None):
        self.fleet = fleet
        self.flight = flight
        self._client = client            # callable -> trace client|None

    def client(self):
        c = self._client
        return c() if callable(c) else c

    def request(self, env, trace, kind: str,
                stamp=_NO_STAMP) -> _ImportScope:
        """Open the observation scope for one import request. `env` is
        the decoded envelope tuple (or None), `trace` the decoded
        trace-context tuple (or None), `kind` "grpc"/"http"; `stamp`
        (when the handler checked one) is the ACCEPTED sketch-engine
        stamp, annotated onto the sender's fleet row at scope exit."""
        return _ImportScope(self, env, trace, kind, stamp)

    def debug_state(self, limit: int | None = 16) -> dict | None:
        if self.flight is None:
            return None
        return self.flight.debug_state(limit)
