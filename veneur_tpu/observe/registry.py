"""The unified telemetry registry — the one spine for veneur.* self-metrics.

Before this module the process had three disjoint self-telemetry
surfaces: the egress layer's ResilienceRegistry, the durability
package's `veneur.durability.*` counter drain, and the Server's ad-hoc
attribute counters under `_stats_lock`. They are now all instances (or
scopes) of ONE `TelemetryRegistry`, and the name/tag mapping from
registry keys to wire metrics lives in exactly one place —
`TelemetryRegistry.drain` below. vlint TL01 enforces the monopoly:
naming a `veneur.*` self-metric anywhere else in the tree is flagged.

Key model: every counter/gauge is addressed by `(scope, name)`.

  scope `_server`         the owning Server's process-wide accounting;
                          drains with NO tags
                          (`veneur.packet.received_total`, ...)
  scope `"kind:instance"` a per-component stat (kind one of sink /
                          plugin / spansink, e.g. `sink:datadog`);
                          drains tagged with the scope itself
  scope anything else     a per-destination egress stat; drains tagged
                          `destination:<scope>` (destinations are
                          often URLs, so a bare `:` cannot be the
                          component-kind discriminator)

Name model (unchanged from the pre-unification drains, so every
existing dashboard keeps working):

  dotted name             `veneur.<name>` (+ `_total` for counters):
                          `flush.error` -> `veneur.flush.error_total`
  plain name              the egress layer's short counters land under
                          `veneur.resilience.<name>_total`

Counters are interval-delta (drained-and-reset each flush, like the
reference's internal statsd client) with a cumulative shadow for
scrape surfaces (`snapshot`, Prometheus semantics). Gauges are
last-write-wins and cleared on drain (a component that didn't report
this interval emits nothing). Levels are monotonic process-lifetime
counts that never drain (e.g. `flush.count`).
"""

from __future__ import annotations

import threading

from ..metrics import InterMetric, MetricType

# The one scope that drains untagged: the Server's own accounting.
SERVER_SCOPE = "_server"

_PREFIX = "veneur."
_RESILIENCE_PREFIX = "veneur.resilience."

# Self-timer names for the flight recorder's dogfood loop (the only
# other veneur.* names this module mints): each flush tick's top-level
# phase durations are re-ingested as LOCAL-ONLY timers, so the server's
# own t-digest engine serves percentiles of its own flush phases.
PHASE_TIMER_PREFIX = "veneur.flush.phase."


def metric_name(name: str, counter: bool) -> str:
    """Registry key name -> wire metric name (the one mapping)."""
    full = (_PREFIX + name) if "." in name else (_RESILIENCE_PREFIX
                                                + name)
    return full + ("_total" if counter else "")


# component kinds whose scopes tag as themselves ("sink:datadog" ->
# tag sink:datadog, "sender:<id>" -> the fleet view's per-sender
# freshness/e2e gauges); anything else is a destination
_COMPONENT_KINDS = ("sink:", "plugin:", "spansink:", "sender:")


def scope_tags(scope: str) -> list:
    if scope == SERVER_SCOPE:
        return []
    if scope.startswith(_COMPONENT_KINDS):
        return [scope]
    return [f"destination:{scope}"]


class TelemetryRegistry:
    """Thread-safe (scope, name)-keyed counters/gauges/levels, drained
    once per flush by the server into veneur.* self-metrics. This class
    IS the former ResilienceRegistry (resilience.py re-exports it under
    that name); `incr`/`take`/`peek` keep their exact contracts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, str], int] = {}
        self._cumulative: dict[tuple[str, str], int] = {}
        self._gauges: dict[tuple[str, str], float] = {}
        self._levels: dict[tuple[str, str], int] = {}

    # ---- counters (interval-delta + cumulative shadow) ----

    def incr(self, destination: str, counter: str, n: int = 1):
        if n == 0:
            return
        self.mark(destination, counter, n)

    def mark(self, scope: str, name: str, n: int = 1):
        """Like incr, but records the key even when n == 0 — for
        per-interval stats whose ZERO is a signal (a sink that flushed
        0 metrics or hit 0 errors still reports, as the pre-unification
        sink-stat drain did)."""
        with self._lock:
            key = (scope, name)
            self._counters[key] = self._counters.get(key, 0) + n
            self._cumulative[key] = self._cumulative.get(key, 0) + n

    def take(self) -> dict[tuple[str, str], int]:
        """Drain: return-and-reset (interval-delta semantics, like the
        server's other self-telemetry counters)."""
        with self._lock:
            out, self._counters = self._counters, {}
        return out

    def peek(self, destination: str, counter: str) -> int:
        with self._lock:
            return self._counters.get((destination, counter), 0)

    def total(self, scope: str, name: str) -> int:
        """Cumulative count since process start (survives drains)."""
        with self._lock:
            return self._cumulative.get((scope, name), 0)

    def totals_by_name_prefix(self, prefix: str) -> dict:
        """{(scope, name): cumulative} for every counter whose name
        starts with `prefix` — scrape surfaces (the /debug/fleet
        forward-bytes block) read destination-scoped families without
        knowing the destination strings up front."""
        with self._lock:
            return {(s, n): v for (s, n), v in self._cumulative.items()
                    if n.startswith(prefix)}

    # ---- gauges (last-write-wins, cleared on drain) ----

    def set_gauge(self, scope: str, name: str, value: float):
        with self._lock:
            self._gauges[(scope, name)] = float(value)

    def take_gauges(self) -> dict[tuple[str, str], float]:
        with self._lock:
            out, self._gauges = self._gauges, {}
        return out

    # ---- levels (monotonic, never drained) ----

    def incr_level(self, scope: str, name: str, n: int = 1):
        with self._lock:
            key = (scope, name)
            self._levels[key] = self._levels.get(key, 0) + n

    def level(self, scope: str, name: str) -> int:
        with self._lock:
            return self._levels.get((scope, name), 0)

    # ---- drains ----

    def drain(self, ts: int, hostname: str = "") -> list[InterMetric]:
        """One interval's self-metrics: every counter (reset) and gauge
        (cleared), named and tagged by the module-level mapping. The
        ONE place registry keys become veneur.* wire names."""
        out = []
        for (scope, cname), v in sorted(self.take().items()):
            out.append(InterMetric(
                name=metric_name(cname, counter=True), timestamp=ts,
                value=v, tags=scope_tags(scope),
                type=MetricType.COUNTER, hostname=hostname))
        for (scope, gname), v in sorted(self.take_gauges().items()):
            out.append(InterMetric(
                name=metric_name(gname, counter=False), timestamp=ts,
                value=v, tags=scope_tags(scope),
                type=MetricType.GAUGE, hostname=hostname))
        return out

    def snapshot(self, ts: int, hostname: str = "") -> list[InterMetric]:
        """Non-destructive view for scrape surfaces: cumulative
        counters (Prometheus counter semantics), current gauges, and
        levels (as gauges). Nothing is reset."""
        with self._lock:
            counters = dict(self._cumulative)
            gauges = dict(self._gauges)
            levels = dict(self._levels)
        out = []
        for (scope, cname), v in sorted(counters.items()):
            out.append(InterMetric(
                name=metric_name(cname, counter=True), timestamp=ts,
                value=v, tags=scope_tags(scope),
                type=MetricType.COUNTER, hostname=hostname))
        for (scope, gname), v in sorted(gauges.items()):
            out.append(InterMetric(
                name=metric_name(gname, counter=False), timestamp=ts,
                value=v, tags=scope_tags(scope),
                type=MetricType.GAUGE, hostname=hostname))
        for (scope, lname), v in sorted(levels.items()):
            out.append(InterMetric(
                name=metric_name(lname, counter=False), timestamp=ts,
                value=v, tags=scope_tags(scope),
                type=MetricType.GAUGE, hostname=hostname))
        return out

    def debug_state(self) -> dict:
        """JSON-ready registry contents for /debug/flush."""
        with self._lock:
            return {
                "counters": {f"{s}|{n}": v for (s, n), v
                             in sorted(self._cumulative.items())},
                "gauges": {f"{s}|{n}": v for (s, n), v
                           in sorted(self._gauges.items())},
                "levels": {f"{s}|{n}": v for (s, n), v
                           in sorted(self._levels.items())},
            }


# The process-default registry: egress objects constructed without an
# explicit registry (config-built sinks, forwarders, journals) count
# here, and Server._self_metrics drains it. Per-Server accounting uses
# a per-instance registry so two servers in one process (the chaos
# harness topology) never cross-count.
DEFAULT_REGISTRY = TelemetryRegistry()


def phase_timer_samples(tick) -> list:
    """The dogfood loop: one flush tick's TOP-LEVEL phase durations as
    parsed timer samples, ready for Server._route_metric. LOCAL-ONLY
    scope is load-bearing: these samples must never ride a forward
    envelope (the exactly-once chaos suite proves forwarded state
    bit-identical to an oracle, and phase durations are timing noise).
    Emitted here because the registry module owns veneur.* naming."""
    from ..ingest.parser import LOCAL_ONLY, MetricKey, UDPMetric
    from ..utils.hashing import metric_digest

    out = []
    for name, t0, t1, parent in tick.phases():
        if parent != -1 or t1 <= t0:
            continue   # only completed top-level phases
        mname = PHASE_TIMER_PREFIX + name
        key = MetricKey(mname, "timer", "")
        out.append(UDPMetric(
            key=key, digest=metric_digest(mname, "timer", ""),
            value=(t1 - t0) / 1e6, scope=LOCAL_ONLY))
    mname = PHASE_TIMER_PREFIX + "total"
    key = MetricKey(mname, "timer", "")
    out.append(UDPMetric(
        key=key, digest=metric_digest(mname, "timer", ""),
        value=tick.duration_ns() / 1e6, scope=LOCAL_ONLY))
    return out


def flush_span_name(phase_name: str | None = None) -> str:
    """SSF span names for the recorder's self-tracing emission (the
    flusher.go `veneur.flush` span parity) — minted here, with the
    other self-metric names."""
    return "veneur.flush" if phase_name is None \
        else "veneur.flush." + phase_name


def import_span_name(phase_name: str | None = None) -> str:
    """SSF span names for the receiver's import tree (the cross-tier
    half of one interval's span tree: these spans parent on the REMOTE
    sender's flush span via the propagated trace context)."""
    return "veneur.import" if phase_name is None \
        else "veneur.import." + phase_name


def fanout_timer_sample(sink_name: str, duration_ms: float):
    """One sink's fan-out duration as a LOCAL-ONLY timer sample
    (`veneur.flush.phase.fanout.<sink>`): the per-sink child of the
    dogfood phase timers, emitted by the sink's OWN flush thread when
    it finishes (the tick-end sampler would race sinks still in
    flight). Local-only for the same reason as the phase timers: a
    slow vendor's timing noise must never ride a forward envelope."""
    from ..ingest.parser import LOCAL_ONLY, MetricKey, UDPMetric
    from ..utils.hashing import metric_digest

    mname = PHASE_TIMER_PREFIX + "fanout." + sink_name
    key = MetricKey(mname, "timer", "")
    return UDPMetric(
        key=key, digest=metric_digest(mname, "timer", ""),
        value=float(duration_ms), scope=LOCAL_ONLY)


# End-to-end interval latency (close -> merged-into-flush at the
# global), per sender. Timer samples dogfood through the engine like
# the phase timers; the per-sender freshness watermark rides the
# registry as a sender:-scoped gauge. Names minted here (TL01).
E2E_TIMER_NAME = "veneur.e2e.interval_latency_ms"


def e2e_timer_samples(per_sender_ms: dict) -> list:
    """{sender_id: [latency_ms, ...]} -> LOCAL-ONLY timer samples
    tagged sender:<id>, ready for Server._route_metric. LOCAL_ONLY is
    load-bearing exactly as for phase timers: e2e bookkeeping must
    never change forwarded state (the chaos oracles pin it)."""
    from ..ingest.parser import LOCAL_ONLY, MetricKey, UDPMetric
    from ..utils.hashing import metric_digest

    out = []
    for sender_id, samples in per_sender_ms.items():
        tags = f"sender:{sender_id}"
        key = MetricKey(E2E_TIMER_NAME, "timer", tags)
        digest = metric_digest(E2E_TIMER_NAME, "timer", tags)
        for ms in samples:
            out.append(UDPMetric(key=key, digest=digest,
                                 value=float(ms), scope=LOCAL_ONLY))
    return out
