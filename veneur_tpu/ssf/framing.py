"""SSF stream framing: length-prefixed protobuf frames over TCP/UNIX.

Parity: protocol/wire.go (sym: protocol.ReadSSF, protocol.WriteSSF,
protocol.Message, protocol.ValidateTrace). PROVENANCE: frame layout from
prior knowledge of the reference (empty mount — see SURVEY.md): one
version byte, then a little-endian uint32 payload length, then the
SSFSpan protobuf; re-verify the byte layout before claiming wire interop
with an existing deployment.

Robustness contract (mirrors the reference's): a frame that is
oversized, truncated, or fails protobuf decoding raises a framing error
the caller can distinguish from connection EOF, so one bad client cannot
wedge a listener.
"""

from __future__ import annotations

import struct

from .protos import ssf_pb2

VERSION_BYTE = 0x00
_LEN = struct.Struct("<I")

# Defensive bound mirroring the reference's refusal to allocate
# attacker-controlled buffer sizes.
MAX_FRAME_LENGTH = 16 * 1024 * 1024

# Parity boundary shared with native/vtpu_ingest.cpp (kPbSkipMaxDepth,
# enforced by vlint NA02): the native parser skips unknown-field groups
# only to this nesting depth — anything deeper falls back to THIS
# module's decoder (the google.protobuf runtime, whose own recursion
# limit is far larger), so the two paths accept the same datagrams.
PB_SKIP_MAX_DEPTH = 16


class FramingError(ValueError):
    """Bad frame (version, length, or protobuf decode)."""


def write_ssf(span: ssf_pb2.SSFSpan) -> bytes:
    """Encode one span as a stream frame (protocol.WriteSSF)."""
    payload = span.SerializeToString()
    return bytes([VERSION_BYTE]) + _LEN.pack(len(payload)) + payload


def _read_exact(read, n: int) -> bytes:
    """Read exactly n bytes from `read` (a socket-style or file-style
    callable is normalised by read_ssf); b'' mid-message = truncation."""
    chunks = []
    got = 0
    while got < n:
        c = read(n - got)
        if not c:
            raise EOFError(f"stream closed mid-frame ({got}/{n} bytes)")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def read_ssf_frame(stream) -> bytes | None:
    """Read one frame's raw protobuf payload (for consumers with their
    own decoder, e.g. the native span fast path). Returns None on clean
    EOF; raises FramingError on a corrupt frame and EOFError on
    truncation mid-frame."""
    read = stream.recv if hasattr(stream, "recv") else stream.read
    first = read(1)
    if not first:
        return None
    if first[0] != VERSION_BYTE:
        raise FramingError(f"unknown SSF frame version {first[0]:#x}")
    (length,) = _LEN.unpack(_read_exact(read, 4))
    if length > MAX_FRAME_LENGTH:
        raise FramingError(f"frame length {length} exceeds max "
                           f"{MAX_FRAME_LENGTH}")
    return _read_exact(read, length)


def read_ssf(stream) -> ssf_pb2.SSFSpan | None:
    """Read one framed span (protocol.ReadSSF). Returns None on clean
    EOF (closed between frames); raises FramingError on a corrupt frame
    and EOFError on truncation mid-frame."""
    payload = read_ssf_frame(stream)
    if payload is None:
        return None
    try:
        return ssf_pb2.SSFSpan.FromString(payload)
    except Exception as e:
        raise FramingError(f"bad SSF protobuf payload: {e}") from e


def parse_ssf_datagram(data: bytes) -> ssf_pb2.SSFSpan:
    """UDP SSF: the datagram is a bare SSFSpan protobuf, no framing
    (Server.ReadSSFPacketSocket)."""
    try:
        return ssf_pb2.SSFSpan.FromString(data)
    except Exception as e:
        raise FramingError(f"bad SSF datagram: {e}") from e


def validate_trace(span: ssf_pb2.SSFSpan) -> bool:
    """Is this span a *trace* span (id + start/end present), as opposed
    to a bare metrics carrier (protocol.ValidateTrace)?"""
    return bool(span.id and span.start_timestamp and span.end_timestamp
                and span.name)
