"""SSF — Sensor Sample Format: veneur's native span/sample wire format.

Parity: the hand-written helpers of the reference's ssf package —
ssf/*.go (sym: ssf.Count, ssf.Gauge, ssf.Histogram, ssf.Timing, ssf.Set,
ssf.Status, ssf.RandomlySample, ssf.Samples) — around the protobuf types
in protos/ssf.proto (sym: ssf.SSFSpan, ssf.SSFSample).

Samples are fire-and-forget metric points that ride inside spans; the
ssfmetrics sink extracts them into the aggregation engines on the server
side, so an application emitting spans gets metrics "for free".
"""

from __future__ import annotations

import random
import time

from .protos import ssf_pb2

SSFSpan = ssf_pb2.SSFSpan
SSFSample = ssf_pb2.SSFSample

# unit strings the reference attaches to timings
NANOSECOND = "ns"
MICROSECOND = "µs"
MILLISECOND = "ms"
SECOND = "s"

# unit string → seconds; "us" is an accepted ASCII alias for µs
TIME_UNITS = {
    NANOSECOND: 1e-9,
    MICROSECOND: 1e-6,
    "us": 1e-6,
    MILLISECOND: 1e-3,
    SECOND: 1.0,
}
_TIME_UNITS = TIME_UNITS


def _mk(metric, name: str, value: float, tags=None, unit: str = "",
        sample_rate: float = 1.0, timestamp: int | None = None,
        **kw) -> ssf_pb2.SSFSample:
    s = ssf_pb2.SSFSample(
        metric=metric, name=name, value=float(value),
        sample_rate=float(sample_rate), unit=unit,
        timestamp=int(timestamp) if timestamp is not None
        else time.time_ns(), **kw)
    for k, v in (tags or {}).items():
        s.tags[k] = v
    return s


def count(name: str, value: float, tags=None, **kw) -> ssf_pb2.SSFSample:
    """A counter increment (ssf.Count)."""
    return _mk(ssf_pb2.SSFSample.COUNTER, name, value, tags, **kw)


def gauge(name: str, value: float, tags=None, **kw) -> ssf_pb2.SSFSample:
    """A gauge observation (ssf.Gauge)."""
    return _mk(ssf_pb2.SSFSample.GAUGE, name, value, tags, **kw)


def histogram(name: str, value: float, tags=None,
              **kw) -> ssf_pb2.SSFSample:
    """A histogram observation (ssf.Histogram)."""
    return _mk(ssf_pb2.SSFSample.HISTOGRAM, name, value, tags, **kw)


def timing(name: str, duration_s: float, unit: str = MILLISECOND,
           tags=None, **kw) -> ssf_pb2.SSFSample:
    """A timer observation; duration is seconds, converted to `unit`
    (ssf.Timing takes a time.Duration + resolution the same way)."""
    scale = _TIME_UNITS.get(unit, 1.0)
    return _mk(ssf_pb2.SSFSample.HISTOGRAM, name, duration_s / scale,
               tags, unit=unit, **kw)


def set_sample(name: str, member: str, tags=None,
               **kw) -> ssf_pb2.SSFSample:
    """A set-membership observation (ssf.Set); the member string travels
    in `message`."""
    return _mk(ssf_pb2.SSFSample.SET, name, 0.0, tags, message=member,
               **kw)


def status(name: str, state: int, tags=None, message: str = "",
           **kw) -> ssf_pb2.SSFSample:
    """A service-check observation (ssf.Status)."""
    return _mk(ssf_pb2.SSFSample.STATUS, name, float(state), tags,
               status=state, message=message, **kw)


def randomly_sample(rate: float, *samples, rng=random):
    """Keep the batch with probability `rate`, stamping the sample-rate on
    the survivors so aggregation re-weights them (ssf.RandomlySample)."""
    if rate >= 1.0 or rng.random() < rate:
        for s in samples:
            s.sample_rate = float(rate)
        return list(samples)
    return []


class Samples:
    """Batch of samples with a single .add() and one report hand-off
    (ssf.Samples)."""

    def __init__(self):
        self.batch: list[ssf_pb2.SSFSample] = []

    def add(self, *samples: ssf_pb2.SSFSample):
        self.batch.extend(samples)

    def attach(self, span: ssf_pb2.SSFSpan):
        span.metrics.extend(self.batch)
