"""Generated protobuf module for SSF (protoc --python_out)."""

import os
import sys

_here = os.path.dirname(__file__)
if _here not in sys.path:
    sys.path.insert(0, _here)

import ssf_pb2  # noqa: E402

__all__ = ["ssf_pb2"]
