"""YAML configuration, keeping veneur's flat key names.

Parity: config.go (sym: Config), config_parse.go (sym: ReadConfig —
YAML file + env-var overrides), example.yaml. A veneur operator's YAML
should drop in: the keys below are the reference's names; unknown keys
warn rather than error (veneur ignores them), and `VENEUR_`-prefixed
environment variables override file values like envconfig does.

New keys for the TPU engine (the north star's `aggregation_backend: tpu`)
are grouped at the bottom of the dataclass.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field, fields

import yaml

from .ingest import parser

log = logging.getLogger("veneur_tpu.config")


def _parse_interval(v) -> float:
    """veneur durations are Go-style strings ("10s", "500ms") or numbers
    of seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    for suffix in ("ms", "s", "m", "h"):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * units[suffix]
    return float(s)


@dataclass
class Config:
    # --- core (config.go names) ---
    interval: str = "10s"
    hostname: str = ""
    omit_empty_hostname: bool = False
    tags: list = field(default_factory=list)
    tags_exclude: list = field(default_factory=list)
    percentiles: list = field(default_factory=lambda: [0.5, 0.75, 0.99])
    aggregates: list = field(default_factory=lambda: ["min", "max", "count"])
    num_workers: int = 1          # engine shards (device axis on TPU)
    num_readers: int = 1          # UDP reader sockets (SO_REUSEPORT)
    metric_max_length: int = 4096
    read_buffer_size_bytes: int = 1 << 21  # SO_RCVBUF per UDP socket
    trace_max_length_bytes: int = 16384
    flush_max_per_body: int = 25000
    synchronize_with_interval: bool = False
    statsd_listen_addresses: list = field(default_factory=list)
    ssf_listen_addresses: list = field(default_factory=list)
    grpc_listen_addresses: list = field(default_factory=list)
    http_address: str = ""
    debug: bool = False
    enable_profiling: bool = False
    profile_port: int = 9943           # JAX profiler (xprof) server port
    mutex_profile_fraction: int = 0    # accepted for YAML compat;
    block_profile_rate: int = 0        # Go-runtime-only, warned at start
    sentry_dsn: str = ""
    stats_address: str = ""

    # --- forwarding / cluster ---
    forward_address: str = ""
    forward_use_grpc: bool = True
    consul_forward_service_name: str = ""
    consul_refresh_interval: str = "30s"
    # --- wire compression (ISSUE 13; README "Wire compression") ---
    # Delta forwarding: each interval ships only the sketches the
    # dirty-slot bitmap saw touched (idle counter zeros / empty set
    # register banks stay home), with a periodic full resync and a
    # receiver-side gap check — a delta above a missed seq is refused
    # loudly (HTTP 409 / FAILED_PRECONDITION "delta-over-gap") and the
    # sender falls back to a full resync, so exactly-once still holds.
    # On by default: deltas are lossless for touched keys; the only
    # trade is that IDLE keys refresh the global's series liveness
    # once per resync instead of every interval.
    forward_delta: bool = True
    # every Nth forwarded interval is a full resync (re-ships every
    # active key, idle ones included); demotions/gap refusals force
    # one sooner. >= 1.
    forward_full_resync_intervals: int = 60
    # Centroid wire row: "lossless" (default — repeated f64 centroid
    # pairs, bit-exact) | "q16" (u16 affine-scaled means + varint
    # 1/8-fixed-point weights, ~4-5x smaller at bounded quantization
    # error; exact count/sum/min/max unaffected). Folded into the
    # engine/wire stamp ("h=tdigest/1q") so BOTH ends of a forwarding
    # pair must agree — a mixed fleet rejects loudly before decode.
    forward_centroid_codec: str = "lossless"

    # --- egress resilience (veneur_tpu/resilience.py) ---
    # Per-attempt socket timeout for every network egress (sinks +
    # forwarders); the reference hardcoded 10s per client.
    flush_timeout: str = "10s"
    retry_max_attempts: int = 3
    retry_backoff_base: str = "200ms"   # full-jitter: U(0, base*2^k)
    retry_backoff_cap: str = "5s"
    # per-flush, per-destination wall budget covering attempts, socket
    # timeouts AND backoff sleeps — one wedged vendor can't eat the tick
    retry_deadline: str = "8s"
    # consecutive failed DELIVERIES (each a full retry ladder) -> open;
    # the breaker records a call's final outcome, not per-attempt errors
    breaker_failure_threshold: int = 5
    breaker_open_duration: str = "30s"   # open -> half-open cooldown
    breaker_half_open_successes: int = 1  # probes to close again
    # re-merge spill buffer: failed forwards' sketches held for the next
    # interval (lossless for counters/histos/sets; gauges age out)
    spill_max_sketches: int = 65536
    spill_gauge_max_age_intervals: int = 4
    # failed intervals kept as distinct replay-ledger entries (each
    # replayed under its ORIGINAL idempotency envelope — exactly-once);
    # older entries fold into the merged spill tier above (at-least-once)
    spill_max_intervals: int = 8

    # --- exactly-once forward (idempotency envelope + dedupe ledger) ---
    # Sender identity stamped on every forwarded chunk. Default "" =
    # a fresh <hostname>-<pid>-<rand> per process start, so a restart
    # can never collide with its predecessor's receiver-side ledger.
    forward_sender_id: str = ""
    # Receiver side: the global tier's per-sender dedupe ledger.
    forward_dedupe_enabled: bool = True
    forward_dedupe_max_seqs_per_sender: int = 512
    forward_dedupe_max_senders: int = 1024
    forward_dedupe_ttl: str = "1h"   # idle senders forgotten after this

    # --- durable state (veneur_tpu/durability/) ---
    # Off by default: with durability disabled the flush path does zero
    # journal work and behavior is identical to the pre-durability tree
    # (regression-tested). When on, the sender's replay ladder + spill
    # tier and the receiver's dedupe watermarks survive a hard kill:
    # recovery runs before any listener binds, parked intervals replay
    # under their ORIGINAL envelopes, and a restarted global refuses
    # ancient replays it already flushed downstream.
    durability_enabled: bool = False
    durability_dir: str = "veneur-durability"
    # fsync policy: "always" (fsync per append — power-loss-proof,
    # slowest), "interval" (fsync at most once per
    # durability_fsync_interval plus every flush boundary — the
    # default; a process kill still loses nothing, only power loss can
    # cost up to one interval of records), "never" (leave syncing to
    # the kernel).
    durability_fsync: str = "interval"
    durability_fsync_interval: str = "1s"
    # snapshot+compact a journal once it outgrows this many bytes
    # (checked at flush boundaries; atomic write-temp/fsync/rename)
    durability_snapshot_journal_bytes: int = 1 << 22
    # Global-tier engine checkpointing (ISSUE 9): with durability on,
    # an IMPORT-tier server (a gRPC import listener, or is_global —
    # NOT http_address alone, which is also just the ops listener on
    # sending tiers; an HTTP-only global sets is_global: true)
    # additionally write-aheads every admitted import op and delta-
    # checkpoints its engines' merged sketch state (dirty piles only,
    # plus the interner key tables and staged imports) at each flush
    # boundary — a hard-killed global restarts with the fleet's
    # admitted-and-merged interval state, bit-identical at the next
    # flush. No effect on sending-only servers, with mesh engines
    # (sharded banks), or under native_ingest (the bridge owns the
    # interner). Requires durability_enabled.
    durability_engine_snapshot: bool = True
    # dirty fraction above which a checkpoint fetches whole bank
    # leaves and slices on host instead of a device-side row gather
    # (a near-full gather costs more than the contiguous fetch);
    # only the dirty rows are serialized either way. (0, 1].
    durability_engine_delta_threshold: float = 0.5

    # --- time-travel query tier (durability/history.py, ISSUE 14) ---
    # Retain a window of committed checkpoint generations (one per
    # closed flush interval: the boundary's checkpoint groups + the
    # interval's write-ahead import ops, sealed atomically and indexed
    # by interval-close wall time) and serve GET /query?metric=&q=&
    # t0=&t1= from them: historical percentiles, counts, and
    # cardinalities reconstructed into SCRATCH engines and merged
    # across intervals through the engine contract. 0 (the default)
    # = off: no history files, no query endpoint, zero write-path
    # cost. Requires durability_enabled + an engine-checkpointing
    # import tier (the same arming rule as durability_engine_snapshot;
    # mesh/native excluded). README "Time-travel queries".
    history_retention_generations: int = 0
    # additionally drop generations older than this relative to the
    # NEWEST retained close stamp ("0s" = count bound only)
    history_retention_seconds: str = "0s"
    # queries run on a dedicated executor (never the ingest/flush
    # path): its width, the bounded result cache (keyed on metric +
    # window + generation range), and the per-query wall timeout
    query_max_concurrent: int = 1
    query_cache_entries: int = 64
    query_timeout: str = "30s"

    # --- overload defense (veneur_tpu/ingest/admission.py) ---
    # Off by default: with the defense disabled the ingest path does
    # zero admission work and behavior is identical to the pre-defense
    # tree (regression-pinned). When on, per-prefix metric-key budgets
    # bound bank growth under a cardinality storm (over-budget keys
    # fold into the prefix's `__other__` sketch), and an adaptive
    # packet-shed governor engages when the flush tick overruns the
    # interval or worker queues saturate. Every degradation decision
    # is counted (`veneur.overload.*`); vlint OV01 machine-checks it.
    overload_defense_enabled: bool = False
    # live interned keys a prefix (the name up to the first separator)
    # may mint before new keys fold into `<prefix>.__other__`
    overload_max_keys_per_prefix: int = 65536
    # tracked prefixes; beyond this, new prefixes share one global
    # `__other__` key (bounds the controller's own memory)
    overload_max_prefixes: int = 4096
    overload_prefix_separator: str = "."
    overload_other_suffix: str = "__other__"
    # sampling applied to samples folding into a hot `__other__` key
    # (1.0 = fold everything); survivors are rate-corrected, so folded
    # counter totals / histogram weights stay unbiased
    overload_fold_sample_rate: float = 1.0
    # the governor's floor: adaptive packet admission never drops below
    # this rate, no matter how overloaded the tick signal reads
    overload_min_sample_rate: float = 0.05
    # a tick whose wall time exceeds this fraction of the flush
    # interval reads as overloaded (multiplicative shed-rate decrease)
    overload_tick_overrun_ratio: float = 0.8
    # worker-queue fill fraction that reads as overloaded
    overload_queue_high_watermark: float = 0.75
    # reset the per-prefix cardinality estimators every N flush ticks
    # (0 = never); the estimate is a per-window distinct-key count
    overload_estimator_window_intervals: int = 64
    # Huffman-Bucket estimator registers per prefix (power of two
    # >= 16; 256 gives ~6.5% relative error at 256 bytes/prefix)
    overload_sketch_buckets: int = 256
    # --- parser hardening (counted rejection, not unbounded keys) ---
    # metric names / individual tags longer than these are parse
    # errors (veneur.packet.error_total), never interned keys.
    # Defaults come from the parser so config-less library callers
    # (parse_metric/parse_packet directly) enforce the same bounds.
    metric_max_name_length: int = parser.MAX_NAME_LENGTH
    metric_max_tag_length: int = parser.MAX_TAG_LENGTH

    # --- observability (veneur_tpu/observe/) ---
    # Flight recorder: every flush tick records its phase tree (drain /
    # device dispatch / device exec / materialize / sink fan-out /
    # forward ladder / journal ops) into a bounded ring served by
    # GET /debug/flush and replayed as SSF spans through the server's
    # own trace client. Overhead is one monotonic_ns stamp + index bump
    # per phase edge (bench_suite c13 pins it under 1% of the tick).
    flight_recorder: bool = True
    flight_recorder_ticks: int = 32        # ring: last N ticks kept
    flight_recorder_max_phases: int = 192  # per-tick phase slot budget
    # Dogfood loop: re-ingest each tick's top-level phase durations as
    # LOCAL-ONLY `veneur.flush.phase.*` timers, so the engine serves
    # percentiles of its own flush phases like any tenant metric.
    flush_phase_timers: bool = True
    # On-demand jax.profiler capture around flush ticks, triggered via
    # GET /debug/flush/profile?ticks=N. Off by default: a profiler
    # capture is a debug action an operator must opt into.
    debug_flush_profile: bool = False
    debug_flush_profile_dir: str = "veneur-profile"
    # Fleet-scope tracing, receiver half (observe/fleet.py): the
    # per-sender e2e/freshness view behind GET /debug/fleet. Bounds:
    # distinct sender ids tracked (LRU past the bound) and the rolling
    # e2e sample window per sender serving the endpoint's p50/p99.
    # Sender-side trace stamping needs no knob — it derives from the
    # flight recorder's tick identity and encodes to nothing when the
    # recorder is off.
    fleet_max_senders: int = 1024
    fleet_e2e_window: int = 256

    # --- TLS (statsd/SSF stream listeners) ---
    tls_key: str = ""
    tls_certificate: str = ""
    tls_authority_certificate: str = ""

    # --- watchdog / lifecycle ---
    flush_watchdog_missed_flushes: int = 0

    # --- SSF / tracing ---
    indicator_span_timer_name: str = ""
    ssf_buffer_size: int = 16384   # span worker queue depth

    # --- sinks ---
    datadog_api_key: str = ""
    datadog_api_hostname: str = "https://app.datadoghq.com"
    datadog_flush_max_per_body: int = 25000
    datadog_trace_api_address: str = ""   # local APM agent, e.g.
    #                                       http://127.0.0.1:8126
    signalfx_api_key: str = ""
    signalfx_endpoint_base: str = "https://ingest.signalfx.com"
    signalfx_vary_key_by: str = ""
    kafka_broker: str = ""
    kafka_topic: str = ""
    kafka_metric_topic: str = ""
    kafka_span_topic: str = ""
    splunk_hec_address: str = ""
    splunk_hec_token: str = ""
    newrelic_account_id: int = 0
    newrelic_insert_key: str = ""
    lightstep_access_token: str = ""
    lightstep_collector_host: str = "https://collector.lightstep.com"
    xray_address: str = ""
    falconer_address: str = ""
    prometheus_repeater_address: str = ""
    flush_file: str = ""          # localfile plugin target
    aws_s3_bucket: str = ""
    aws_region: str = ""
    aws_access_key_id: str = ""
    aws_secret_access_key: str = ""

    # --- TPU engine (new; the north star's aggregation_backend key) ---
    aggregation_backend: str = "tpu"   # "tpu" | "cpu" (forces jax cpu)
    # Sketch-engine selection (veneur_tpu/sketches/, ISSUE 10).
    # histogram_backend: "tdigest" (default; absolute-rank k1 digest,
    # tight mid-range percentiles) | "req" (relative-error adaptive
    # compactors: ~1% p99.9 value error on heavy-tailed streams where
    # t-digest clusters blur the tail; mid-range is distribution-
    # dependent — see README "Sketch engines").
    # set_backend: "hll" (default; LogLog-Beta, 2^p u8 registers) |
    # "ull" (UltraLogLog, arxiv 2308.16862: ~half the register bytes
    # at equal nominal error via an ML estimator).
    # BOTH ends of a forwarding pair must run the SAME engines: every
    # forward request carries an engine/wire stamp and a mismatched
    # receiver rejects loudly (veneur.import.engine_mismatch_total,
    # per-sender at /debug/fleet) instead of merging incompatible
    # sketches. Not supported with native_ingest or tpu_num_devices>1
    # (those own their banks).
    histogram_backend: str = "tdigest"
    set_backend: str = "hll"
    tpu_ull_precision: int = 13        # ULL registers = 2^p bytes/slot
    tpu_req_levels: int = 2            # REQ compactor levels
    tpu_req_capacity: int = 256        # items per level per slot
    tpu_histogram_slots: int = 1 << 15
    tpu_counter_slots: int = 1 << 14
    tpu_gauge_slots: int = 1 << 14
    tpu_set_slots: int = 1 << 12
    tpu_batch_size: int = 8192
    tpu_buffer_depth: int = 256
    tpu_compression: float = 100.0
    tpu_hll_precision: int = 14
    tpu_slot_idle_ttl_intervals: int = 16
    tpu_num_devices: int = 0           # 0 = all visible devices
    # Flush-result fetch strategy: "sync" | "staged" | "host" | "async".
    # Non-sync modes work around relayed backends where a synchronous
    # device_get invalidates the serving executable (TPU_EVIDENCE_r04.md).
    tpu_flush_fetch: str = "sync"
    # Compact wire mode: quantile/min/max columns fetched as f16 with
    # sentinel-gated full-precision fallback; count/sum stay exact.
    # Halves the flush fetch on transport-constrained rigs. Not
    # supported with multi-device engines.
    tpu_flush_fetch_f16: bool = False
    # Incremental dirty-slot flush (ISSUE 11): the flush program
    # consumes the delta-checkpoint dirty bitmap and compresses/
    # materializes ONLY the piles touched this interval — cold piles
    # keep their fresh-init state and baseline rows verbatim,
    # bit-identical to the full program. Above the threshold dirty
    # fraction (histogram bank) the full program runs instead. Ignored
    # (always full) with tpu_num_devices > 1 — the mesh engine owns
    # sharded banks with no per-slot bitmaps.
    tpu_flush_incremental: bool = True
    tpu_flush_incremental_threshold: float = 0.75
    # Double-buffered flush (ISSUE 11): the tick boundary only retires
    # the interval under the ingest lock (one rebind into shadow
    # banks); draining, import landing, and the flush program run
    # outside it, so admit/ingest never stalls behind the flush
    # executable or materialize. Off = legacy drain-under-lock
    # ordering (the mesh engine always uses legacy).
    tpu_flush_double_buffer: bool = True
    # Fused Pallas kernels (ISSUE 15): one-kernel-per-bucket compress
    # (t-digest sort+rank-merge+cluster with VMEM intermediates) and
    # the ULL scatter-join insert. "auto" = compiled kernels on real
    # TPU backends with a loud, counted fallback to the XLA programs
    # (veneur.kernels.fallback_total) when Mosaic refuses; XLA on CPU.
    # "on" additionally serves interpret-mode kernels on CPU (testing
    # stance; bit-identical to XLA by contract). "off" = XLA only.
    # /debug/flush sketch_engines.kernels reports the built arms.
    tpu_fused_kernels: str = "auto"

    # --- native C++ ingest bridge (native/vtpu_ingest.cpp) ---
    # When on, UDP DogStatsD ingest (readers + parse + key interning +
    # batch assembly) runs in the C++ bridge and Python only pumps
    # device-ready batches; one engine owns the full slot space.
    native_ingest: bool = False
    native_ring_capacity: int = 1 << 20
    # Pump dispatch width (decoupled from tpu_batch_size, which sizes the
    # per-sample staging path). Wider batches amortize per-dispatch cost
    # (moderately on CPU — the t-digest scatter program is ~30ms/dispatch
    # nearly flat in width; substantially on TPU, where dispatch+transfer
    # overhead dominates the sub-ms kernel). 32k balances that against
    # drain latency at flush time. See BENCH_SUITE c8_s5* and the
    # buffer-aliasing note in NativePump._pump_bank.
    native_pump_batch: int = 1 << 15

    # populated by the loader, not a YAML key:
    is_global: bool = False

    @property
    def interval_seconds(self) -> float:
        return _parse_interval(self.interval)

    @property
    def consul_refresh_seconds(self) -> float:
        return _parse_interval(self.consul_refresh_interval)

    @property
    def flush_timeout_seconds(self) -> float:
        return _parse_interval(self.flush_timeout)


_FIELDS = {f.name: f for f in fields(Config)}


def read_config(path: str | None = None, text: str | None = None,
                env: dict | None = None) -> Config:
    """ReadConfig: YAML file -> Config, with VENEUR_<UPPER_KEY> env
    overrides (the envconfig behavior)."""
    raw = {}
    if text is not None:
        raw = yaml.safe_load(text) or {}
    elif path is not None:
        with open(path) as f:
            raw = yaml.safe_load(f) or {}

    cfg = Config()
    for k, v in raw.items():
        if k in _FIELDS:
            setattr(cfg, k, _coerce(k, v))
        else:
            log.warning("unknown config key %r ignored", k)

    env = os.environ if env is None else env
    for name in _FIELDS:
        ev = env.get("VENEUR_" + name.upper())
        if ev is not None:
            setattr(cfg, name, _coerce(name, ev))
    _validate(cfg)
    return cfg


_KNOWN_AGGREGATES = {"min", "max", "sum", "avg", "count", "median",
                     "hmean"}


def _validate(cfg: Config) -> None:
    """Reject configs that would fail obscurely later (bad percentiles
    clip silently in the quantile kernel; zero intervals spin the flush
    loop). Unknown aggregates warn, like veneur's lenient parsing."""
    for p in cfg.percentiles:
        if not (0.0 < float(p) < 1.0):
            raise ValueError(
                f"percentile {p} out of range (0, 1) exclusive")
    if len(cfg.percentiles) > 8:
        # the flush program's quantile interpolation unrolls over the
        # percentile list (a deliberate lane-efficiency trade at the
        # default 3-4): each extra percentile re-reads the full knot
        # matrix, so very long lists scale the flush cost linearly
        log.warning(
            "%d percentiles configured: flush cost grows linearly with "
            "the percentile count (the quantile program unrolls over "
            "it); typical deployments use 3-4", len(cfg.percentiles))
    if cfg.interval_seconds <= 0:
        raise ValueError(f"interval must be positive: {cfg.interval!r}")
    if cfg.durability_fsync not in ("always", "interval", "never"):
        raise ValueError(
            "durability_fsync must be one of always/interval/never, "
            f"got {cfg.durability_fsync!r}")
    if cfg.durability_enabled and not cfg.durability_dir:
        raise ValueError(
            "durability_enabled requires a durability_dir")
    if cfg.durability_snapshot_journal_bytes < 4096:
        raise ValueError(
            "durability_snapshot_journal_bytes must be >= 4096 "
            "(a snapshot cycle per append would thrash the disk)")
    if not (0.0 < cfg.durability_engine_delta_threshold <= 1.0):
        raise ValueError(
            "durability_engine_delta_threshold must be in (0, 1]: it "
            "is the dirty fraction above which a checkpoint switches "
            "from row gather to whole-leaf fetch, got "
            f"{cfg.durability_engine_delta_threshold!r}")
    if cfg.history_retention_generations < 0:
        raise ValueError(
            "history_retention_generations must be >= 0 (0 = "
            "time-travel tier off)")
    if cfg.history_retention_generations > 0 and \
            not cfg.durability_enabled:
        raise ValueError(
            "history_retention_generations requires "
            "durability_enabled (the time-travel tier reads the "
            "engine checkpoint journal)")
    if _parse_interval(cfg.history_retention_seconds) < 0:
        raise ValueError(
            "history_retention_seconds must be >= 0 (0 = no age "
            "bound)")
    if cfg.query_max_concurrent < 1:
        raise ValueError("query_max_concurrent must be >= 1")
    if cfg.query_cache_entries < 0:
        raise ValueError("query_cache_entries must be >= 0")
    if _parse_interval(cfg.query_timeout) <= 0:
        raise ValueError("query_timeout must be a positive duration")
    for key in ("flush_timeout", "retry_backoff_base",
                "retry_backoff_cap", "retry_deadline",
                "breaker_open_duration", "forward_dedupe_ttl",
                "durability_fsync_interval"):
        if _parse_interval(getattr(cfg, key)) <= 0:
            raise ValueError(
                f"{key} must be a positive duration: "
                f"{getattr(cfg, key)!r}")
    for key in ("retry_max_attempts", "breaker_failure_threshold",
                "breaker_half_open_successes", "spill_max_intervals",
                "forward_dedupe_max_seqs_per_sender",
                "forward_dedupe_max_senders",
                "forward_full_resync_intervals"):
        if getattr(cfg, key) < 1:
            raise ValueError(f"{key} must be >= 1")
    if cfg.forward_centroid_codec not in ("lossless", "q16"):
        raise ValueError(
            "forward_centroid_codec must be lossless or q16, got "
            f"{cfg.forward_centroid_codec!r} (both ends of a "
            "forwarding pair must run the same codec — it is part of "
            "the engine/wire stamp)")
    if cfg.flight_recorder_ticks < 1 or \
            cfg.flight_recorder_max_phases < 8:
        raise ValueError(
            "flight_recorder_ticks must be >= 1 and "
            "flight_recorder_max_phases >= 8 (a tick's fixed phases "
            "alone need that many slots)")
    if cfg.fleet_max_senders < 1 or cfg.fleet_e2e_window < 8:
        raise ValueError(
            "fleet_max_senders must be >= 1 and fleet_e2e_window >= 8 "
            "(a p99 over fewer samples is noise)")
    for key in ("overload_max_keys_per_prefix", "overload_max_prefixes"):
        if getattr(cfg, key) < 1:
            raise ValueError(f"{key} must be >= 1")
    for key in ("overload_fold_sample_rate", "overload_min_sample_rate"):
        v = getattr(cfg, key)
        if not (0.0 < v <= 1.0):
            raise ValueError(f"{key} must be in (0, 1], got {v!r}")
    if cfg.overload_tick_overrun_ratio <= 0:
        raise ValueError("overload_tick_overrun_ratio must be positive")
    if not (0.0 < cfg.overload_queue_high_watermark <= 1.0):
        raise ValueError(
            "overload_queue_high_watermark must be in (0, 1]")
    if cfg.overload_estimator_window_intervals < 0:
        raise ValueError(
            "overload_estimator_window_intervals must be >= 0 "
            "(0 = never reset)")
    b = cfg.overload_sketch_buckets
    if b < 16 or (b & (b - 1)):
        raise ValueError(
            "overload_sketch_buckets must be a power of two >= 16, "
            f"got {b}")
    if cfg.overload_defense_enabled and not cfg.overload_prefix_separator:
        raise ValueError(
            "overload_defense_enabled requires a non-empty "
            "overload_prefix_separator")
    for key in ("metric_max_name_length", "metric_max_tag_length"):
        if getattr(cfg, key) < 16:
            raise ValueError(
                f"{key} must be >= 16 (shorter limits would reject "
                "ordinary metric traffic)")
    if cfg.debug_flush_profile and not cfg.debug_flush_profile_dir:
        raise ValueError(
            "debug_flush_profile requires a debug_flush_profile_dir")
    if cfg.spill_max_sketches < 0 or \
            cfg.spill_gauge_max_age_intervals < 0:
        raise ValueError(
            "spill_max_sketches / spill_gauge_max_age_intervals "
            "must be >= 0")
    unknown = [a for a in cfg.aggregates
               if a not in _KNOWN_AGGREGATES]
    if unknown:
        log.warning("unknown aggregates %r ignored (known: %s)",
                    unknown, sorted(_KNOWN_AGGREGATES))
    for key in ("tpu_histogram_slots", "tpu_counter_slots",
                "tpu_gauge_slots", "tpu_set_slots", "tpu_batch_size",
                "native_pump_batch"):
        if getattr(cfg, key) <= 0:
            raise ValueError(f"{key} must be positive")
    if cfg.tpu_buffer_depth < 8:
        raise ValueError("tpu_buffer_depth must be >= 8")
    if not (4 <= cfg.tpu_hll_precision <= 16):
        raise ValueError("tpu_hll_precision must be in [4, 16]")
    if cfg.histogram_backend not in ("tdigest", "req"):
        raise ValueError(
            f"histogram_backend must be tdigest or req, got "
            f"{cfg.histogram_backend!r}")
    if cfg.set_backend not in ("hll", "ull"):
        raise ValueError(
            f"set_backend must be hll or ull, got {cfg.set_backend!r}")
    if not (4 <= cfg.tpu_ull_precision <= 16):
        raise ValueError("tpu_ull_precision must be in [4, 16]")
    if cfg.tpu_req_levels < 1 or cfg.tpu_req_capacity < 32 \
            or cfg.tpu_req_capacity % 8:
        raise ValueError(
            "tpu_req_levels must be >= 1 and tpu_req_capacity a "
            "multiple of 8 >= 32 (the compactor's protect/trigger "
            "sections need the room)")
    if (cfg.histogram_backend != "tdigest"
            or cfg.set_backend != "hll"):
        if cfg.native_ingest:
            raise ValueError(
                "non-default sketch backends are not supported with "
                "native_ingest (the C++ bridge computes HLL updates)")
        if cfg.tpu_num_devices > 1:
            raise ValueError(
                "non-default sketch backends are not supported with "
                "tpu_num_devices > 1 (the mesh engine owns its banks)")
    if cfg.tpu_flush_fetch_f16 and cfg.tpu_num_devices > 1:
        raise ValueError(
            "tpu_flush_fetch_f16 is not supported with tpu_num_devices > 1 "
            "(the mesh flush program has its own wire layout)")
    if cfg.tpu_flush_fetch not in ("sync", "staged", "host", "async"):
        raise ValueError(
            "tpu_flush_fetch must be one of sync/staged/host/async")
    if not (0.0 < cfg.tpu_flush_incremental_threshold <= 1.0):
        raise ValueError(
            "tpu_flush_incremental_threshold must be in (0, 1]: the "
            "dirty fraction above which the full flush program runs")
    if cfg.tpu_fused_kernels not in ("auto", "on", "off"):
        raise ValueError(
            "tpu_fused_kernels must be one of auto/on/off")
    if cfg.tpu_fused_kernels != "off" and cfg.tpu_num_devices > 1:
        # the mesh engine builds its own sharded flush program and
        # never consults the kernel arm — not an error (auto is the
        # default everywhere), but "on" deserves a loud note
        if cfg.tpu_fused_kernels == "on":
            log.warning(
                "tpu_fused_kernels=on is ignored with "
                "tpu_num_devices > 1: the mesh engine serves its own "
                "sharded flush program (XLA arm)")
    # t-digest centroid capacity is ~2*compression (fixed 100), padded to
    # 128 lanes. A buffer shallower than that makes the global import
    # path pay ceil(C/B) compress dispatches per landing round —
    # quadratic-ish for tiny buffers. Legal, but worth a loud warning.
    if cfg.tpu_buffer_depth < 256:
        log.warning(
            "tpu_buffer_depth=%d is below the t-digest centroid "
            "capacity (256): forwarded-digest imports will pay %d "
            "compress dispatches per landing round instead of 1",
            cfg.tpu_buffer_depth, -(-256 // cfg.tpu_buffer_depth))
    if cfg.stats_address:
        host, sep, port = cfg.stats_address.rpartition(":")
        if (not sep or not port.isdigit()
                or not (0 < int(port) < 65536)
                or (":" in host
                    and not (host.startswith("[")
                             and host.endswith("]")))):
            raise ValueError(
                f"stats_address must be host:port (IPv6 hosts "
                f"bracketed), got {cfg.stats_address!r}")


def _coerce(name: str, v):
    f = _FIELDS[name]
    t = f.type
    if t == "bool" or isinstance(f.default, bool):
        if isinstance(v, str):
            return v.strip().lower() in ("1", "true", "yes", "on")
        return bool(v)
    if isinstance(f.default, int) and not isinstance(f.default, bool):
        return int(v)
    if isinstance(f.default, float):
        return float(v)
    if t == "list" or "list" in str(t):
        if isinstance(v, str):
            v = [s.strip() for s in v.split(",") if s.strip()]
        v = list(v)
        if name == "percentiles":  # float-element list keys
            v = [float(x) for x in v]
        return v
    return v
