"""DogStatsD wire-format parser.

Parity target: samplers/parser.go (sym: ParseMetric, ParseEvent,
ParseServiceCheck; types UDPMetric, MetricKey; scope consts MixedScope /
LocalOnly / GlobalOnly). The grammar:

  metric:        <name>:<value>|<type>[|@<rate>][|#<tag1:v1,tag2>]
  event:         _e{<title_len>,<text_len>}:<title>|<text>[|d:ts|h:host|
                 k:aggkey|p:prio|s:source|t:alerttype|#tags]
  service check: _sc|<name>|<status>[|d:ts|h:host|#tags|m:message]

Types: c (counter), g (gauge), ms (timer), h (histogram), s (set),
d (distribution — treated as histogram with global scope, matching how
veneur maps DogStatsD distributions onto its global aggregation).

Veneur extensions honored here exactly like the reference:
  * a `veneurlocalonly` tag forces LocalOnly scope, `veneurglobalonly`
    forces GlobalOnly; both are *stripped* from the stored tag set.
  * tags are sorted and joined with "," into MetricKey.JoinedTags.
  * the 32-bit FNV-1a digest over name+type+joined tags shards the key
    space (server.go: `Workers[Digest % len(Workers)]`).

This pure-Python parser is the conformance reference; the C++ batch parser
(native/) must match it bit-for-bit on the same corpus (tests share the
table).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..utils.hashing import metric_digest

MIXED_SCOPE = 0
LOCAL_ONLY = 1
GLOBAL_ONLY = 2

_TYPE_MAP = {
    b"c": "counter",
    b"g": "gauge",
    b"ms": "timer",
    b"h": "histogram",
    b"s": "set",
    b"d": "histogram",  # DogStatsD distribution -> globally-merged histogram
}


class ParseError(ValueError):
    pass


# Hardening defaults (config keys metric_max_name_length /
# metric_max_tag_length): a metric name or single tag past these is a
# COUNTED parse error, never an interned key — an adversarial packet
# minting multi-KB names must not grow the bank or the interner map
# (the overload-defense stance: degradation is counted, not silent,
# and never unbounded). Callers with a Config pass its values through.
MAX_NAME_LENGTH = 1024
MAX_TAG_LENGTH = 512


@dataclass(frozen=True)
class MetricKey:
    name: str
    type: str
    joined_tags: str


@dataclass
class UDPMetric:
    """One parsed sample (samplers.UDPMetric equivalent)."""
    key: MetricKey
    digest: int
    value: float | str     # str for sets
    sample_rate: float = 1.0
    scope: int = MIXED_SCOPE
    tags: list[str] = field(default_factory=list)


@dataclass
class Event:
    title: str
    text: str
    timestamp: Optional[int] = None
    hostname: str = ""
    aggregation_key: str = ""
    priority: str = ""
    source_type: str = ""
    alert_type: str = ""
    tags: list[str] = field(default_factory=list)


@dataclass
class ServiceCheck:
    name: str
    status: int
    timestamp: Optional[int] = None
    hostname: str = ""
    message: str = ""
    tags: list[str] = field(default_factory=list)


def parse_metric(packet: bytes,
                 exclude_tags: frozenset | None = None,
                 max_name_len: int = MAX_NAME_LENGTH,
                 max_tag_len: int = MAX_TAG_LENGTH) -> UDPMetric:
    """Parse one DogStatsD metric line (no trailing newline).

    `exclude_tags` (config.go sym: Config.TagsExclude) drops tags whose
    NAME (the part before ":", or the whole tag) matches — before key
    construction, so metrics differing only in an excluded tag aggregate
    together, exactly like the reference. `max_name_len`/`max_tag_len`
    reject oversized names/tags as parse errors BEFORE any key exists
    (parser hardening — see MAX_NAME_LENGTH above)."""
    if not packet:
        raise ParseError("empty packet")

    colon = packet.find(b":")
    if colon <= 0:
        raise ParseError(f"missing name/value separator: {packet!r}")
    if colon > max_name_len:
        raise ParseError(
            f"metric name over {max_name_len} bytes "
            f"(got {colon}): {packet[:64]!r}...")
    name = packet[:colon]
    rest = packet[colon + 1:]

    parts = rest.split(b"|")
    if len(parts) < 2:
        raise ParseError(f"missing type: {packet!r}")
    valstr, typestr = parts[0], parts[1]

    mtype = _TYPE_MAP.get(typestr)
    if mtype is None:
        raise ParseError(f"invalid type {typestr!r} in {packet!r}")

    if mtype == "set":
        value: float | str = valstr.decode("utf-8", "replace")
    else:
        if not valstr:
            raise ParseError(f"empty value: {packet!r}")
        try:
            value = float(valstr)
        except ValueError:
            raise ParseError(f"invalid value {valstr!r} in {packet!r}")
        if value != value or value in (float("inf"), float("-inf")):
            raise ParseError(f"non-finite value in {packet!r}")

    sample_rate = 1.0
    tags: list[str] = []
    scope = GLOBAL_ONLY if typestr == b"d" else MIXED_SCOPE
    seen_rate = False
    seen_tags = False

    for section in parts[2:]:
        if not section:
            raise ParseError(f"empty section in {packet!r}")
        lead = section[:1]
        if lead == b"@":
            if seen_rate:
                raise ParseError(f"duplicate sample rate in {packet!r}")
            seen_rate = True
            try:
                sample_rate = float(section[1:])
            except ValueError:
                raise ParseError(f"invalid sample rate in {packet!r}")
            if not (0.0 < sample_rate <= 1.0):
                raise ParseError(f"sample rate out of range in {packet!r}")
            if mtype in ("gauge", "set") and sample_rate != 1.0:
                # matches the reference: rates only make sense for
                # counters/timers/histograms
                raise ParseError(
                    f"sample rate invalid for {mtype}: {packet!r}")
        elif lead == b"#":
            if seen_tags:
                raise ParseError(f"duplicate tag section in {packet!r}")
            seen_tags = True
            for t in section[1:].split(b","):
                if len(t) > max_tag_len:
                    raise ParseError(
                        f"tag over {max_tag_len} bytes "
                        f"(got {len(t)}): {t[:64]!r}...")
                ts = t.decode("utf-8", "replace")
                if ts == "veneurlocalonly":
                    scope = LOCAL_ONLY
                elif ts == "veneurglobalonly":
                    scope = GLOBAL_ONLY
                elif ts:
                    tags.append(ts)
            if exclude_tags:
                tags = [t for t in tags
                        if t.partition(":")[0] not in exclude_tags]
            tags.sort()
        else:
            raise ParseError(f"unknown section {section!r} in {packet!r}")

    if not name:
        raise ParseError(f"empty metric name: {packet!r}")
    name_s = name.decode("utf-8", "replace")
    joined = ",".join(tags)
    key = MetricKey(name=name_s, type=mtype, joined_tags=joined)
    return UDPMetric(
        key=key,
        digest=metric_digest(name_s, mtype, joined),
        value=value,
        sample_rate=sample_rate,
        scope=scope,
        tags=tags,
    )


def parse_event(packet: bytes) -> Event:
    """Parse a DogStatsD event: _e{tl,xl}:title|text|..."""
    if not packet.startswith(b"_e{"):
        raise ParseError(f"not an event: {packet!r}")
    close = packet.find(b"}")
    if close < 0:
        raise ParseError(f"unterminated length header: {packet!r}")
    lens = packet[3:close].split(b",")
    if len(lens) != 2:
        raise ParseError(f"bad length header: {packet!r}")
    try:
        tl, xl = int(lens[0]), int(lens[1])
    except ValueError:
        raise ParseError(f"bad length header: {packet!r}")
    if tl < 0 or xl < 0:
        raise ParseError(f"negative length in header: {packet!r}")
    if packet[close + 1: close + 2] != b":":
        raise ParseError(f"missing ':' after header: {packet!r}")
    body = packet[close + 2:]
    if len(body) < tl + 1 + xl:
        raise ParseError(f"truncated event body: {packet!r}")
    title = body[:tl]
    if body[tl: tl + 1] != b"|":
        raise ParseError(f"bad title length: {packet!r}")
    text = body[tl + 1: tl + 1 + xl]
    ev = Event(title=title.decode("utf-8", "replace"),
               text=text.decode("utf-8", "replace").replace("\\n", "\n"))
    for section in body[tl + 1 + xl:].split(b"|"):
        if not section:
            continue
        if section.startswith(b"d:"):
            try:
                ev.timestamp = int(section[2:])
            except ValueError:
                raise ParseError(f"bad event timestamp: {packet!r}")
        elif section.startswith(b"h:"):
            ev.hostname = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"k:"):
            ev.aggregation_key = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"p:"):
            ev.priority = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"s:"):
            ev.source_type = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"t:"):
            ev.alert_type = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"#"):
            ev.tags = sorted(
                t.decode("utf-8", "replace")
                for t in section[1:].split(b",") if t)
        else:
            raise ParseError(f"unknown event section {section!r}")
    return ev


def parse_service_check(packet: bytes) -> ServiceCheck:
    """Parse a DogStatsD service check: _sc|name|status|..."""
    if not packet.startswith(b"_sc|"):
        raise ParseError(f"not a service check: {packet!r}")
    parts = packet.split(b"|")
    if len(parts) < 3:
        raise ParseError(f"truncated service check: {packet!r}")
    name = parts[1].decode("utf-8", "replace")
    try:
        status = int(parts[2])
    except ValueError:
        raise ParseError(f"bad status: {packet!r}")
    if status not in (0, 1, 2, 3):
        raise ParseError(f"status out of range: {packet!r}")
    sc = ServiceCheck(name=name, status=status)
    for section in parts[3:]:
        if section.startswith(b"d:"):
            try:
                sc.timestamp = int(section[2:])
            except ValueError:
                raise ParseError(f"bad timestamp: {packet!r}")
        elif section.startswith(b"h:"):
            sc.hostname = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"m:"):
            sc.message = section[2:].decode("utf-8", "replace")
        elif section.startswith(b"#"):
            sc.tags = sorted(
                t.decode("utf-8", "replace")
                for t in section[1:].split(b",") if t)
        else:
            raise ParseError(f"unknown sc section {section!r}")
    return sc


def parse_packet(packet: bytes, exclude_tags: frozenset | None = None,
                 max_name_len: int = MAX_NAME_LENGTH,
                 max_tag_len: int = MAX_TAG_LENGTH):
    """Dispatch one datagram line to the right parser, like
    Server.HandleMetricPacket (server.go)."""
    if packet.startswith(b"_e{"):
        return parse_event(packet)
    if packet.startswith(b"_sc|"):
        return parse_service_check(packet)
    return parse_metric(packet, exclude_tags, max_name_len, max_tag_len)
