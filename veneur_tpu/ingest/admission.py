"""Overload defense: cardinality budgets, fold-to-other, backpressure.

Production ingest at millions of users throws traffic shapes the
reference never defended against: a bad deploy minting a unique tag
per request (unbounded bank growth), one hot key absorbing half a
bank, and sustained ingest above flush capacity (silent queue drops).
This module is the admission-control layer between the parser and the
workers:

  * **Per-prefix metric-key budgets.** Every metric key belongs to a
    prefix/tenant (the name up to the first `.`). A prefix may mint at
    most `max_keys_per_prefix` live bank slots; keys beyond the budget
    are FOLDED into that prefix's per-type `__other__` key — itself an
    ordinary mergeable sketch (t-digest / HLL / counter), so degraded
    keys still aggregate correctly fleet-wide when forwarded (the
    UltraLogLog-mergeability stance of arxiv 2308.16862: degrade into
    something that still merges, never into a lossy scalar). The
    number of tracked prefixes is itself budgeted
    (`max_prefixes`); beyond it, new prefixes fold into one global
    `__other__` key.
  * **Huffman-Bucket cardinality estimator** (arxiv 2603.10930): a
    per-prefix m-bucket register array — one O(1) hash+max per
    distinct key, one O(m) pass per estimate, mergeable by
    elementwise max — so a tag-cardinality explosion is *detected*
    (and reported via `/debug/flush` and
    `veneur.overload.keys_over_budget_total`) at a fixed m bytes per
    prefix, no matter how many keys the storm mints.
  * **Backpressure / adaptive sampling.** When the flush tick overruns
    the interval (the PR 6 flight-recorder tick duration is the
    signal) or worker queues saturate, the governor multiplicatively
    drops its packet admission rate; the server sheds whole packets
    pre-parse at that rate (cheapest possible shed — no parse, no
    queue) and rate-corrects the surviving counter/timer/histogram
    samples (`sample_rate *= rate`, so flushed totals stay unbiased).
    Healthy ticks recover the rate multiplicatively back to 1.0.

Every degradation decision is *counted* through the owning Server's
TelemetryRegistry (`veneur.overload.*`): never silent drops, never
OOM. vlint OV01 machine-checks the contract: a drop verdict
(`return None`) in any admit*/fold*/shed* decision function here must
increment a registry counter in the same branch.

Placement note: budget enforcement hooks the KeyInterner's slot
*allocation* path (models/worker.py) rather than the per-sample parse
path — a key already holding a slot pays literally zero admission
cost (the interner map hit it already pays), which is what keeps
steady-state overhead under 2% of packet-parse cost
(tests/test_perf_regression.py pins it). The packet-level governor
check is per-datagram, amortized across its lines.

Not available with `native_ingest` (the C++ bridge owns interning
there) — the server logs and disables the defense.
"""

from __future__ import annotations

import math
import random
import threading

from ..observe.registry import SERVER_SCOPE
from ..utils.hashing import fmix64, metric_digest
from .parser import GLOBAL_ONLY, LOCAL_ONLY, MetricKey, UDPMetric

_M64 = 0xFFFFFFFFFFFFFFFF

# sample kinds whose weights are rate-correctable (weight = 1/rate):
# thinning is statistically lossless for these. Gauges (last-write-
# wins) and sets (distinct counts) cannot be corrected and are never
# fold-sampled; under packet-level shed they are lost WITH the packet,
# counted in shed_packets.
RATE_CORRECTED_TYPES = frozenset(("counter", "timer", "histogram"))


def estimate_registers(regs) -> float:
    """Cardinality estimate from a register array (or an immutable
    bytes snapshot of one — debug_state estimates outside the
    controller lock). Linear counting only in its small-range regime
    (estimate <= 2.5m): past that, a single surviving zero register
    would cap the result at m*ln(m) however large the true count."""
    m = len(regs)
    zeros = 0
    inv_sum = 0.0
    for r in regs:
        if r == 0:
            zeros += 1
        inv_sum += 2.0 ** -r
    if zeros:
        lc = m * math.log(m / zeros)
        if lc <= 2.5 * m:
            return lc
    alpha = 0.7213 / (1.0 + 1.079 / m)
    return alpha * m * m / inv_sum


class HuffmanBucketSketch:
    """Bucketed max-rank cardinality estimator (arxiv 2603.10930's
    bucket-array shape): m u8 registers, update = hash -> bucket gets
    max(register, leading-zero rank), estimate = one O(m) harmonic-mean
    pass with a linear-counting small-range correction, merge =
    elementwise max. The point is the cost profile — O(1) update, m
    bytes of state, O(m) estimate — not tail precision: at m=256 the
    relative error is ~6.5%, plenty to tell "10x over budget" from
    steady state."""

    __slots__ = ("m", "_shift", "regs")

    def __init__(self, m: int = 256):
        if m & (m - 1) or m < 16:
            raise ValueError("sketch buckets must be a power of two >= 16")
        self.m = m
        self._shift = 64 - (m.bit_length() - 1)
        self.regs = bytearray(m)

    def update(self, h64: int) -> bool:
        """Fold one 64-bit hash in; True iff a register grew (the cheap
        proxy for 'a key pattern this window has not seen')."""
        b = (h64 >> self._shift) & (self.m - 1)
        rest = (h64 << (64 - self._shift)) & _M64 | (1 << (64 - self._shift)) - 1
        rho = 65 - rest.bit_length()
        if rho > self.regs[b]:
            self.regs[b] = rho
            return True
        return False

    def estimate(self) -> float:
        return estimate_registers(self.regs)

    def merge(self, other: "HuffmanBucketSketch"):
        for i, r in enumerate(other.regs):
            if r > self.regs[i]:
                self.regs[i] = r

    def reset(self):
        self.regs = bytearray(self.m)


class _PrefixState:
    __slots__ = ("admitted", "sketch", "fold_name")

    def __init__(self, fold_name: str, sketch_buckets: int):
        self.admitted = 0                 # live interned keys (budget use)
        self.sketch = HuffmanBucketSketch(sketch_buckets)
        self.fold_name = fold_name        # this prefix's fold target


class AdmissionController:
    """One per Server, shared by every engine's KeyInterners. Hot-path
    contract: an interner map HIT never reaches this object; only slot
    allocation (admit_key / release_key) and over-budget samples
    (fold_metric) do, plus one per-datagram governor check
    (shed_rate / admit_packet) on the server's ingest path."""

    def __init__(self, *, registry,
                 max_keys_per_prefix: int = 65536,
                 max_prefixes: int = 4096,
                 prefix_separator: str = ".",
                 other_suffix: str = "__other__",
                 fold_sample_rate: float = 1.0,
                 min_sample_rate: float = 0.05,
                 tick_overrun_ratio: float = 0.8,
                 queue_high_watermark: float = 0.75,
                 estimator_window_intervals: int = 64,
                 sketch_buckets: int = 256,
                 rng: random.Random | None = None):
        self._tel = registry
        self.max_keys_per_prefix = int(max_keys_per_prefix)
        self.max_prefixes = int(max_prefixes)
        self._sep = prefix_separator
        self._suffix = other_suffix
        self.fold_sample_rate = float(fold_sample_rate)
        self.min_sample_rate = float(min_sample_rate)
        self.tick_overrun_ratio = float(tick_overrun_ratio)
        self.queue_high_watermark = float(queue_high_watermark)
        self.estimator_window = int(estimator_window_intervals)
        self._sketch_m = int(sketch_buckets)
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._prefixes: dict[str, _PrefixState] = {}
        # the global fold target for keys of over-budget PREFIXES
        self._overflow = _PrefixState(other_suffix, sketch_buckets)
        # fold keys this controller minted: admitted without consuming
        # budget and skipped by release_key. Bounded by
        # (max_prefixes + 1) x metric types. An adversary NAMING a
        # metric "<p>.__other__" is not here, so it just spends its
        # prefix's budget like any other key (and, over budget, merges
        # into the genuine fold key — harmless by construction).
        self._minted: set[MetricKey] = set()
        self._fold_cache: dict[tuple[str, str], tuple[MetricKey, int]] = {}
        # backpressure governor state. shed_rate is read lock-free on
        # the packet hot path (a torn read of a float is impossible in
        # CPython; staleness of one packet is harmless).
        self.shed_rate = 1.0
        self._ticks = 0
        self._last = {"folded": 0, "sampled_out": 0, "shed": 0,
                      "over_budget": 0}

    # ------------- engaged? -------------

    @property
    def engaged(self) -> bool:
        return self.shed_rate < 1.0

    # ------------- packet-level backpressure (server ingest path) ----

    def admit_packet(self):
        """One datagram's shed decision under the adaptive rate. True =
        process it; None = shed (counted). Only called when
        shed_rate < 1.0 (the caller's one-branch fast gate)."""
        if self._rng.random() < self.shed_rate:
            return True
        self._tel.incr(SERVER_SCOPE, "overload.shed_packets")
        return None

    # ------------- key-level budgets (interner allocation path) ------

    def _prefix_of(self, name: str) -> str:
        return name.partition(self._sep)[0]

    def admit_key(self, key: MetricKey):
        """Budget verdict for a key about to mint a bank slot: True =
        admit (budget consumed — the interner calls release_key if the
        allocation then fails), None = fold into the prefix's
        `__other__` key instead. One call per key per interner
        lifetime for in-budget keys; per sample for over-budget keys
        (their samples keep missing the interner map)."""
        with self._lock:
            if key in self._minted:
                return True               # our own fold keys ride free
            prefix = self._prefix_of(key.name)
            st = self._prefixes.get(prefix)
            if st is None:
                if len(self._prefixes) >= self.max_prefixes:
                    st = self._overflow
                else:
                    st = _PrefixState(
                        prefix + self._sep + self._suffix, self._sketch_m)
                    self._prefixes[prefix] = st
            changed = st.sketch.update(fmix64(hash(key) & _M64))
            if st.admitted >= self.max_keys_per_prefix:
                if changed:
                    # estimator-gated: counts (approximately) DISTINCT
                    # over-budget keys, not their per-sample traffic —
                    # folded_samples carries the volume
                    self._tel.incr(SERVER_SCOPE,
                                   "overload.keys_over_budget")
                return None
            st.admitted += 1
            return True

    def release_key(self, key: MetricKey):
        """A previously admitted key left its interner (idle eviction,
        or the allocation it was admitted for failed): return its
        budget slot."""
        with self._lock:
            if key in self._minted:
                return
            st = self._prefixes.get(self._prefix_of(key.name))
            if st is None:
                st = self._overflow
            if st.admitted > 0:
                st.admitted -= 1

    def _fold_key(self, key: MetricKey,
                  local: bool = False) -> tuple[MetricKey, int]:
        """The (cached) fold target for an over-budget key: the
        per-(prefix, type) `__other__` key, tagless so every shard and
        every sender in the fleet folds into the SAME mergeable key.
        `local` selects the `.local` twin — the fold target for
        veneurlocalonly samples, which must never leave the host (it
        needs no fleet mergeability precisely because it never
        forwards) and must not share a slot with forwarded folds (a
        slot's scope is per-key, not per-sample).

        The fast path is LOCK-FREE: both dicts only ever gain entries,
        their values are immutable tuples, and CPython dict reads are
        atomic under the GIL — so the per-sample cost of a sustained
        fold storm is one dict hit, not a controller-lock acquisition
        per worker thread. A racing miss just takes the locked path."""
        st = self._prefixes.get(self._prefix_of(key.name))
        fold_name = st.fold_name if st is not None \
            else self._overflow.fold_name
        if local:
            fold_name = fold_name + self._sep + "local"
        ck = (fold_name, key.type)
        cached = self._fold_cache.get(ck)
        if cached is not None:
            return cached
        with self._lock:
            cached = self._fold_cache.get(ck)
            if cached is None:
                fk = MetricKey(fold_name, key.type, "")
                cached = (fk, metric_digest(fold_name, key.type, ""))
                self._fold_cache[ck] = cached
                self._minted.add(fk)
            return cached

    def fold_metric(self, m: UDPMetric, fwd_out: bool):
        """Rewrite one over-budget sample onto its fold key. The fold
        is SAMPLED for rate-correctable kinds (fold_sample_rate):
        survivors carry `sample_rate *= fold_sample_rate`, so the
        folded counter totals / histogram weights stay unbiased while
        the hot `__other__` slot's ingest cost is bounded. Returns the
        folded UDPMetric, or None when this sample was sampled out
        (counted). The folded_samples counter is NOT incremented here:
        the engine calls count_folded() once the rewrite actually
        leaves for its fold slot (landed locally or re-routed to its
        home engine) — a rewrite the bank then refuses must count as
        the bank drop it is, not as a fold.

        Scope policy: on a forwarding server (`fwd_out`) folds ride to
        the global tier as GLOBAL_ONLY so the fleet's `__other__` rows
        merge there — EXCEPT veneurlocalonly samples, whose values
        must never leave the host: those keep LOCAL_ONLY and fold into
        the prefix's `.local` twin key, so a forwarded fold slot never
        carries (or retroactively rescopes to) local-only data."""
        rate = self.fold_sample_rate
        sample_rate = m.sample_rate
        if rate < 1.0 and m.key.type in RATE_CORRECTED_TYPES:
            if self._rng.random() >= rate:
                self._tel.incr(SERVER_SCOPE, "overload.fold_sampled_out")
                return None
            sample_rate = max(sample_rate * rate, 1e-9)
        local = m.scope == LOCAL_ONLY
        scope = m.scope if (local or not fwd_out) else GLOBAL_ONLY
        fk, digest = self._fold_key(m.key, local)
        return UDPMetric(key=fk, digest=digest, value=m.value,
                         sample_rate=sample_rate, scope=scope, tags=[])

    def fold_key(self, key: MetricKey) -> tuple[MetricKey, int]:
        """(fold target, routing digest) for an over-budget IMPORTED
        metric (the global tier's Combine path — no sampling: a
        forwarded digest is an interval aggregate, not a sample). The
        caller counts via count_folded() once the fold actually goes
        somewhere."""
        return self._fold_key(key)

    def count_folded(self, n: int = 1):
        """One sample (or imported aggregate) was redirected onto its
        fold key — landed in a local slot or re-routed to the fold
        key's home engine. Redirects that later drop (full queue, full
        bank) are counted by the normal worker.dropped /
        dropped_no_slot accounting, like any routed sample."""
        self._tel.incr(SERVER_SCOPE, "overload.folded_samples", n)

    # ------------- governor (flush-tick boundary) -------------

    def on_tick(self, elapsed_s: float, interval_s: float,
                queue_fill: float) -> dict:
        """Adapt the shed rate from this tick's overload signals: the
        tick's wall duration (the flight recorder's tick span) against
        the flush interval, and the worst worker-queue fill fraction.
        Multiplicative decrease under overload, multiplicative
        recovery toward 1.0 when healthy. Also rolls the estimator
        window. Returns this interval's degradation deltas for the
        tick's phase record."""
        overloaded = (
            interval_s > 0
            and elapsed_s > self.tick_overrun_ratio * interval_s
        ) or queue_fill >= self.queue_high_watermark
        if overloaded:
            self.shed_rate = max(self.min_sample_rate,
                                 self.shed_rate * 0.5)
        elif self.shed_rate < 1.0:
            self.shed_rate = min(1.0, self.shed_rate * 1.6)
        with self._lock:
            self._ticks += 1
            if self.estimator_window > 0 and \
                    self._ticks % self.estimator_window == 0:
                for st in self._prefixes.values():
                    st.sketch.reset()
                self._overflow.sketch.reset()
        tel = self._tel
        cum = {
            "folded": tel.total(SERVER_SCOPE, "overload.folded_samples"),
            "sampled_out": tel.total(SERVER_SCOPE,
                                     "overload.fold_sampled_out"),
            "shed": tel.total(SERVER_SCOPE, "overload.shed_packets"),
            "over_budget": tel.total(SERVER_SCOPE,
                                     "overload.keys_over_budget"),
        }
        delta = {k: cum[k] - self._last[k] for k in cum}
        self._last = cum
        delta["rate"] = self.shed_rate
        delta["overloaded"] = overloaded
        return delta

    # ------------- introspection -------------

    def export_sketches(self) -> list:
        """[(prefix, bytes registers)] snapshots of the per-prefix
        cardinality sketches — the forward-wire rows the global tier
        merges by max (fleet-wide cardinality, ISSUE 10 satellite).
        Cheap: one bytes() copy per prefix under the lock."""
        with self._lock:
            out = [(p, bytes(st.sketch.regs))
                   for p, st in self._prefixes.items()]
            if any(self._overflow.sketch.regs):
                out.append((self._suffix, bytes(self._overflow.sketch.regs)))
            return out

    def prefix_count(self) -> int:
        with self._lock:
            return len(self._prefixes)

    def debug_state(self, top: int = 20) -> dict:
        """JSON-ready admission state for GET /debug/flush: budgets,
        the governor, and the top prefixes by estimated cardinality
        (admitted vs estimate is the explosion signature)."""
        tel = self._tel
        # Snapshot (prefix, admitted, regs-bytes) under the lock; the
        # O(m)-per-prefix estimates run AFTER release — admit_key/
        # release_key on the storm's hot path must never wait out a
        # /debug/flush scrape of thousands of prefixes.
        with self._lock:
            snap = [(p, st.admitted, bytes(st.sketch.regs))
                    for p, st in self._prefixes.items()]
            overflow_adm = self._overflow.admitted
            overflow_regs = bytes(self._overflow.sketch.regs)
            nprefix = len(snap)
        rows = [
            {"prefix": p, "admitted": admitted,
             "estimated_keys": round(estimate_registers(regs), 1),
             "over_budget": admitted >= self.max_keys_per_prefix}
            for p, admitted, regs in snap
        ]
        overflow_est = round(estimate_registers(overflow_regs), 1)
        rows.sort(key=lambda r: -r["estimated_keys"])
        return {
            "enabled": True,
            "adaptive_sample_rate": self.shed_rate,
            "engaged": self.engaged,
            "fold_sample_rate": self.fold_sample_rate,
            "max_keys_per_prefix": self.max_keys_per_prefix,
            "max_prefixes": self.max_prefixes,
            "prefix_count": nprefix,
            "prefixes": rows[:top],
            "overflow": {"admitted": overflow_adm,
                         "estimated_keys": overflow_est},
            "counters": {
                "folded_samples": tel.total(
                    SERVER_SCOPE, "overload.folded_samples"),
                "fold_sampled_out": tel.total(
                    SERVER_SCOPE, "overload.fold_sampled_out"),
                "keys_over_budget": tel.total(
                    SERVER_SCOPE, "overload.keys_over_budget"),
                "shed_packets": tel.total(
                    SERVER_SCOPE, "overload.shed_packets"),
            },
        }


def from_config(cfg, registry) -> AdmissionController:
    """Build the Server's controller from the overload_* config keys."""
    return AdmissionController(
        registry=registry,
        max_keys_per_prefix=cfg.overload_max_keys_per_prefix,
        max_prefixes=cfg.overload_max_prefixes,
        prefix_separator=cfg.overload_prefix_separator,
        other_suffix=cfg.overload_other_suffix,
        fold_sample_rate=cfg.overload_fold_sample_rate,
        min_sample_rate=cfg.overload_min_sample_rate,
        tick_overrun_ratio=cfg.overload_tick_overrun_ratio,
        queue_high_watermark=cfg.overload_queue_high_watermark,
        estimator_window_intervals=cfg.overload_estimator_window_intervals,
        sketch_buckets=cfg.overload_sketch_buckets,
    )
