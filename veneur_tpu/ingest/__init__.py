"""Ingest layer: DogStatsD parsing, UDP/TCP/UNIX listeners, SSF framing.

Host-side analogue of networking.go (sym: Server.ReadMetricSocket),
samplers/parser.go, and protocol/ in the reference.
"""
