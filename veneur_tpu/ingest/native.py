"""ctypes bindings + pump for the C++ ingest bridge (native/vtpu_ingest.cpp).

The bridge is the TPU build's native analogue of veneur's ingest front half
(server.go sym: Server.ReadMetricSocket ×num_readers on SO_REUSEPORT
sockets; samplers/parser.go sym: ParseMetric; the digest-sharded dispatch of
worker.go): C++ reader threads parse DogStatsD lines, intern MetricKeys to
device-bank slots, and stage (slot, value, weight) samples in per-bank
rings. Python's job shrinks to polling device-ready batches.

Pieces here:
  * build()/load(): compile (once) and dlopen the shared library.
  * NativeBridge: the raw C API, numpy-typed.
  * BridgeKeyView: presents a bridge bank through the KeyInterner interface
    (active_items / scope_of / key_of / advance_interval / dropped_no_slot)
    so AggregationEngine.flush works unchanged on top of C++ interning.
  * NativePump: the polling thread — drains sample rings into the engine's
    batch-ingest kernels, keeps the slot→key mirrors fresh, and routes
    slow-path lines (events, service checks, CPython-float oddities,
    invalid UTF-8) through the Python parser.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

import numpy as np

from ..ingest.parser import MetricKey
from ..models.worker import SlotInfo

_BANKS = {"histo": 0, "counter": 1, "gauge": 2, "set": 3}
_MTYPE_NAMES = ["counter", "gauge", "timer", "histogram", "set"]

P_METRIC, P_ERROR, P_OTHER = 0, 1, 2

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libvtpu_ingest.so")

_lib = None
_lib_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def build(force: bool = False) -> str:
    """Compile the shared library if missing. Returns its path."""
    src = os.path.join(_NATIVE_DIR, "vtpu_ingest.cpp")
    if not os.path.exists(src):
        raise NativeUnavailable(f"source missing: {src}")
    if force or not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
        proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeUnavailable(
                f"native build failed:\n{proc.stdout}\n{proc.stderr}")
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # Env override: point the whole process at an alternate build of
        # the bridge — how CI runs the native tests under TSAN
        # (`make -C native tsan`, then VENEUR_TPU_NATIVE_LIB=
        # native/build/libvtpu_ingest_tsan.so with libtsan LD_PRELOADed).
        path = os.environ.get("VENEUR_TPU_NATIVE_LIB") or build()
        lib = ctypes.CDLL(path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        f32p = ctypes.POINTER(ctypes.c_float)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.vtpu_create.restype = ctypes.c_void_p
        lib.vtpu_create.argtypes = [ctypes.c_int32] * 8
        lib.vtpu_destroy.argtypes = [ctypes.c_void_p]
        lib.vtpu_handle_packet.argtypes = [ctypes.c_void_p, u8p,
                                           ctypes.c_int32]
        # c_char_p: ctypes passes the bytes object's buffer directly
        # (read-only, zero-copy) — this call is per-datagram on the SSF
        # hot path, where a bytearray+frombuffer wrap costs ~10us/call
        lib.vtpu_handle_ssf.restype = ctypes.c_int32
        lib.vtpu_handle_ssf.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int32]
        lib.vtpu_set_indicator_timer.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_char_p]
        lib.vtpu_start_udp.restype = ctypes.c_int32
        lib.vtpu_start_udp.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int32, ctypes.c_int32,
                                       ctypes.c_int32]
        lib.vtpu_start_ssf_udp.restype = ctypes.c_int32
        lib.vtpu_start_ssf_udp.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p,
                                           ctypes.c_int32, ctypes.c_int32,
                                           ctypes.c_int32, ctypes.c_int32]
        lib.vtpu_drain_ssf_other.restype = ctypes.c_int32
        lib.vtpu_drain_ssf_other.argtypes = [ctypes.c_void_p, u8p,
                                             ctypes.c_int32]
        lib.vtpu_ssf_bound_port.restype = ctypes.c_int32
        lib.vtpu_ssf_bound_port.argtypes = [ctypes.c_void_p]
        lib.vtpu_stop.argtypes = [ctypes.c_void_p]
        lib.vtpu_poll.restype = ctypes.c_int32
        lib.vtpu_poll.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                  ctypes.c_int32, i32p, f32p, f32p, i32p]
        lib.vtpu_drain_new_keys.restype = ctypes.c_int32
        lib.vtpu_drain_new_keys.argtypes = [ctypes.c_void_p, u8p,
                                            ctypes.c_int32]
        lib.vtpu_drain_other.restype = ctypes.c_int32
        lib.vtpu_drain_other.argtypes = [ctypes.c_void_p, u8p,
                                         ctypes.c_int32]
        lib.vtpu_slot_scopes.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                         u8p, ctypes.c_int32]
        lib.vtpu_advance_interval.restype = ctypes.c_int32
        lib.vtpu_advance_interval.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int32]
        lib.vtpu_key_count.restype = ctypes.c_int64
        lib.vtpu_key_count.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.vtpu_intern.restype = ctypes.c_int32
        lib.vtpu_intern.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                    ctypes.c_int32, u8p, ctypes.c_int32,
                                    u8p, ctypes.c_int32]
        lib.vtpu_stats.argtypes = [ctypes.c_void_p, u64p]
        lib.vtpu_set_tags_exclude.argtypes = [ctypes.c_void_p, u8p,
                                              ctypes.c_int32]
        lib.vtpu_parse_one.restype = ctypes.c_int32
        lib.vtpu_parse_one.argtypes = [u8p, ctypes.c_int32, u8p,
                                       ctypes.c_int32, i32p]
        lib.vtpu_bench_parse.restype = ctypes.c_double
        lib.vtpu_bench_parse.argtypes = [u8p, ctypes.c_int32,
                                         ctypes.c_int32]
        lib.vtpu_bound_port.restype = ctypes.c_int32
        lib.vtpu_bound_port.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def parse_one(line: bytes):
    """Stateless conformance parse via the C++ parser.

    Returns (verdict, fields|None) where fields mirror
    parser.parse_metric's result: dict(name, type, joined_tags, digest,
    value, sample_rate, scope)."""
    lib = load()
    buf = np.zeros(4 + len(line) * 2 + 256, np.uint8)
    out_len = ctypes.c_int32(0)
    arr = np.frombuffer(bytearray(line), np.uint8) if line else \
        np.zeros(1, np.uint8)
    v = lib.vtpu_parse_one(_u8(arr), len(line), _u8(buf), len(buf),
                           ctypes.byref(out_len))
    if v != P_METRIC:
        return v, None
    b = buf.tobytes()[:out_len.value]
    mtype, scope = b[0], b[1]
    rate, value = struct.unpack_from("<dd", b, 2)
    (digest,) = struct.unpack_from("<I", b, 18)
    off = 22
    (nl,) = struct.unpack_from("<H", b, off)
    off += 2
    name = b[off:off + nl].decode()
    off += nl
    (tl,) = struct.unpack_from("<H", b, off)
    off += 2
    tags = b[off:off + tl].decode()
    off += tl
    (ml,) = struct.unpack_from("<H", b, off)
    off += 2
    member = b[off:off + ml].decode()
    return v, {
        "name": name, "type": _MTYPE_NAMES[mtype], "joined_tags": tags,
        "digest": digest, "value": member if _MTYPE_NAMES[mtype] == "set"
        else value, "sample_rate": rate, "scope": scope,
    }


class NativeBridge:
    """Owning wrapper over one C++ bridge instance."""

    def __init__(self, histo_slots: int, counter_slots: int,
                 gauge_slots: int, set_slots: int, hll_precision: int = 14,
                 idle_ttl: int = 16, ring_capacity: int = 1 << 20,
                 max_packet: int = 8192):
        self._lib = load()
        self._h = self._lib.vtpu_create(
            histo_slots, counter_slots, gauge_slots, set_slots,
            hll_precision, idle_ttl, ring_capacity, max_packet)
        self.capacities = {"histo": histo_slots, "counter": counter_slots,
                           "gauge": gauge_slots, "set": set_slots}
        self._key_buf = np.zeros(1 << 20, np.uint8)
        self._other_buf = np.zeros(1 << 20, np.uint8)
        self._closed = False

    def close(self):
        if not self._closed:
            self._closed = True
            self._lib.vtpu_destroy(self._h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -------- ingest --------

    def handle_packet(self, data: bytes):
        arr = np.frombuffer(bytearray(data), np.uint8) if data else \
            np.zeros(1, np.uint8)
        self._lib.vtpu_handle_packet(self._h, _u8(arr), len(data))

    def handle_ssf(self, data: bytes) -> int:
        """Decode one SSF span datagram and stage its embedded samples
        natively (sinks/ssfmetrics.py's C++ twin). Returns 1 = handled,
        0 = caller must run the Python span path for this datagram
        (STATUS samples present), -1 = malformed protobuf."""
        return int(self._lib.vtpu_handle_ssf(self._h, data, len(data)))

    def set_indicator_timer(self, name: str) -> None:
        """Enable the indicator-span duration timer
        (indicator_span_timer_name). Call before readers start."""
        self._lib.vtpu_set_indicator_timer(self._h, name.encode())

    def set_tags_exclude(self, names) -> None:
        """Install tags_exclude (config.go sym: Config.TagsExclude) in
        the C++ parser. Must be called BEFORE start_udp — the list is
        read lock-free by the reader threads."""
        packed = "\n".join(names).encode()
        arr = np.frombuffer(bytearray(packed), np.uint8) if packed else \
            np.zeros(1, np.uint8)
        self._lib.vtpu_set_tags_exclude(self._h, _u8(arr), len(packed))

    def start_udp(self, host: str, port: int, n_readers: int,
                  rcvbuf: int = 0) -> int:
        rc = self._lib.vtpu_start_udp(
            self._h, host.encode(), port, n_readers, rcvbuf)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return rc

    def start_ssf_udp(self, host: str, port: int, n_readers: int,
                      rcvbuf: int = 0, max_dgram: int = 16384) -> int:
        """Start native SSF span readers (one datagram = one SSFSpan):
        recvmmsg + decode + ring staging in C++; fallback datagrams
        queue for drain_ssf_other. Returns the bound port."""
        rc = self._lib.vtpu_start_ssf_udp(
            self._h, host.encode(), port, n_readers, rcvbuf, max_dgram)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return rc

    def drain_ssf_other(self) -> list:
        """Fallback SSF datagrams (STATUS-carrying spans) for the
        Python span pipeline, as raw protobuf bytes."""
        out = []
        while True:
            n = self._lib.vtpu_drain_ssf_other(
                self._h, _u8(self._other_buf), len(self._other_buf))
            if n <= 0:
                break
            b = self._other_buf[:n].tobytes()
            off = 0
            while off < n:
                (ln,) = struct.unpack_from("<I", b, off)
                off += 4
                out.append(b[off:off + ln])
                off += ln
        return out

    def stop(self):
        self._lib.vtpu_stop(self._h)

    def bound_port(self) -> int:
        return self._lib.vtpu_bound_port(self._h)

    # -------- draining --------

    def poll(self, bank: str, out_slots, out_a, out_b, out_c) -> int:
        return self._lib.vtpu_poll(
            self._h, _BANKS[bank], len(out_slots), _i32(out_slots),
            _f32(out_a), _f32(out_b), _i32(out_c))

    def drain_new_keys(self):
        """Yield (bank, mtype, scope, slot, name, joined_tags)."""
        out = []
        while True:
            n = self._lib.vtpu_drain_new_keys(
                self._h, _u8(self._key_buf), len(self._key_buf))
            if n <= 0:
                break
            b = self._key_buf.tobytes()[:n]
            off = 0
            while off < n:
                bank, mtype, scope = b[off], b[off + 1], b[off + 2]
                (slot,) = struct.unpack_from("<i", b, off + 3)
                off += 7
                (nl,) = struct.unpack_from("<H", b, off)
                off += 2
                name = b[off:off + nl].decode()
                off += nl
                (tl,) = struct.unpack_from("<H", b, off)
                off += 2
                tags = b[off:off + tl].decode()
                off += tl
                out.append((bank, mtype, scope, slot, name, tags))
            if n < len(self._key_buf) // 2:
                break
        return out

    def drain_other(self):
        """Yield raw slow-path lines (bytes)."""
        out = []
        while True:
            n = self._lib.vtpu_drain_other(
                self._h, _u8(self._other_buf), len(self._other_buf))
            if n <= 0:
                break
            b = self._other_buf.tobytes()[:n]
            off = 0
            while off < n:
                (sl,) = struct.unpack_from("<H", b, off)
                off += 2
                out.append(b[off:off + sl])
                off += sl
            if n < len(self._other_buf) // 2:
                break
        return out

    def slot_scopes(self, bank: str) -> np.ndarray:
        out = np.zeros(self.capacities[bank], np.uint8)
        self._lib.vtpu_slot_scopes(self._h, _BANKS[bank], _u8(out),
                                   len(out))
        return out

    def advance_interval(self, bank: str) -> int:
        return self._lib.vtpu_advance_interval(self._h, _BANKS[bank])

    def key_count(self, bank: str) -> int:
        return self._lib.vtpu_key_count(self._h, _BANKS[bank])

    def intern(self, mtype: str, scope: int, name: str,
               joined_tags: str) -> int:
        """Intern one key through the C++ table (slow path, ssfmetrics
        bridge, global-tier Combine). Returns slot or -1."""
        nb = name.encode()
        tb = joined_tags.encode()
        na = np.frombuffer(bytearray(nb), np.uint8) if nb else \
            np.zeros(1, np.uint8)
        ta = np.frombuffer(bytearray(tb), np.uint8) if tb else \
            np.zeros(1, np.uint8)
        return self._lib.vtpu_intern(
            self._h, _MTYPE_NAMES.index(mtype), scope, _u8(na), len(nb),
            _u8(ta), len(tb))

    def stats(self) -> dict:
        out = np.zeros(14, np.uint64)
        self._lib.vtpu_stats(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        keys = ("packets", "lines", "samples", "parse_errors",
                "slow_routed", "drops_no_slot", "ring_drops",
                "other_drops", "pending_other", "ssf_spans",
                "ssf_fallbacks", "ssf_errors", "ssf_other_drops",
                "pending_ssf_other")
        return dict(zip(keys, out.tolist()))


class BridgeKeyView:
    """KeyInterner-shaped facade over one bridge bank.

    AggregationEngine.flush consumes active_items()/scope_of()/key_of()/
    advance_interval()/len()/dropped_no_slot; here those are backed by the
    C++ interner plus a host mirror:
      * slot→MetricKey mirror, updated from drain_new_keys()
      * touched mask, updated by the pump from each polled batch (exact
        w.r.t. bank contents — no interval race with the readers)
      * scope snapshot, refreshed at flush time.
    """

    def __init__(self, bridge: NativeBridge, bank: str):
        self.bridge = bridge
        self.bank = bank
        self.capacity = bridge.capacities[bank]
        self.mirror: dict[int, MetricKey] = {}
        self.touched = np.zeros(self.capacity, bool)
        self._scopes = np.zeros(self.capacity, np.uint8)
        # Per-slot SlotInfo holders carrying the engine's flush
        # presentation cache; replaced whenever the C++ interner
        # reassigns a slot to a new key (register()).
        self._holders: dict[int, SlotInfo] = {}
        self.dropped_no_slot = 0

    def __len__(self):
        return self.bridge.key_count(self.bank)

    def lookup(self, key: MetricKey, scope: int) -> int:
        """KeyInterner.lookup parity for the engine's Python entry points
        (engine.process on slow-path lines, import_* Combine staging):
        interns through the C++ table, mirrors, and marks touched.
        Caller holds the engine lock, so mark+dispatch is atomic w.r.t.
        flush."""
        slot = self.bridge.intern(key.type, scope, key.name,
                                  key.joined_tags)
        if slot < 0:
            self.dropped_no_slot += 1
            return -1
        if self.mirror.get(slot) != key:
            self._holders[slot] = SlotInfo(slot, 0, scope)
        self.mirror[slot] = key
        self.touched[slot] = True
        return slot

    def register(self, slot: int, key: MetricKey):
        if self.mirror.get(slot) != key:
            self._holders[slot] = SlotInfo(slot, 0, 0)
        self.mirror[slot] = key

    def mark(self, slots: np.ndarray):
        self.touched[slots] = True

    def refresh_scopes(self):
        self._scopes = self.bridge.slot_scopes(self.bank)

    def key_of(self, slot: int):
        return self.mirror.get(slot)

    def scope_of(self, slot: int) -> int:
        return int(self._scopes[slot])

    def active_items(self):
        self.refresh_scopes()
        out = []
        scopes = self._scopes
        for slot in np.nonzero(self.touched)[0].tolist():
            key = self.mirror.get(slot)
            if key is not None:
                holder = self._holders.get(slot)
                if holder is None:
                    holder = self._holders[slot] = SlotInfo(slot, 0, 0)
                out.append((key, slot, int(scopes[slot]), holder))
        return out

    def advance_interval(self):
        self.touched[:] = False
        self.bridge.advance_interval(self.bank)


class NativePump:
    """Polls the bridge and feeds the engine's batch-ingest kernels.

    One pump thread replaces the per-packet Python parse path: it moves
    staged samples bank-by-bank into the XLA scatter programs in
    `batch`-sized chunks (fixed shapes — no recompiles), mirrors new key
    registrations, and hands slow-path lines to `slow_path` (the Python
    parser + engine.process round trip).
    """

    def __init__(self, bridge: NativeBridge, engine, views: dict,
                 slow_path, batch: int = 8192, idle_sleep: float = 0.002,
                 ssf_slow_path=None):
        self.bridge = bridge
        self.engine = engine
        self.views = views
        self.slow_path = slow_path
        # raw SSF datagrams the native listener could not express
        # (STATUS samples); routed to the Python span pipeline
        self.ssf_slow_path = ssf_slow_path
        self.batch = batch
        self.idle_sleep = idle_sleep
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # pump_once may be called by both the pump thread and
        # Server.drain(); they share the poll buffers, so cycles are
        # serialized
        self._pump_lock = threading.Lock()
        self._bufs = {
            b: (np.zeros(batch, np.int32), np.zeros(batch, np.float32),
                np.zeros(batch, np.float32), np.zeros(batch, np.int32))
            for b in _BANKS
        }

    def start(self):
        self._thread = threading.Thread(target=self._run, name="native-pump",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self):
        import logging
        import time
        while not self._stop.is_set():
            try:
                moved = self.pump_once()
            except Exception:
                # a dead pump silently halts ALL aggregation (rings
                # fill, every sample drops); degrade loudly instead
                logging.getLogger(__name__).exception(
                    "pump cycle failed; retrying")
                time.sleep(0.1)
                continue
            if moved == 0:
                time.sleep(self.idle_sleep)

    def pump_once(self) -> int:
        """One poll cycle across all banks; returns items moved."""
        with self._pump_lock:
            moved = 0
            for bank in _BANKS:
                moved += self._pump_bank(bank)
            for line in self.bridge.drain_other():
                self.slow_path(line)
                moved += 1
            if self.ssf_slow_path is not None:
                for payload in self.bridge.drain_ssf_other():
                    self.ssf_slow_path(payload)
                    moved += 1
            return moved

    def drain(self, timeout: float = 10.0) -> bool:
        """Pump until the bridge is empty (deterministic test settling:
        the analogue of Server.drain's queue accounting)."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            moved = self.pump_once()
            st = self.bridge.stats()
            if moved == 0 and st["pending_other"] == 0 \
                    and st["pending_ssf_other"] == 0:
                return True
        return False

    def _sync_keys(self):
        for bank_i, mtype, scope, slot, name, tags in \
                self.bridge.drain_new_keys():
            bank = ("histo", "counter", "gauge", "set")[bank_i]
            key = MetricKey(name=name, type=_MTYPE_NAMES[mtype],
                            joined_tags=tags)
            self.views[bank].register(slot, key)
            del scope

    def _pump_bank(self, bank: str) -> int:
        slots, a, b, c = self._bufs[bank]
        total = 0
        while True:
            n = self.bridge.poll(bank, slots, a, b, c)
            if n <= 0:
                break
            if n < self.batch:
                slots[n:] = -1  # pad rows are dropped by the kernels
            # Sync key records BEFORE marking/dispatching this batch: the
            # bridge enqueues a new-key record before the first sample for
            # that key reaches a ring, so every slot in this batch has its
            # mirror entry drainable now — a flush interleaving after
            # dispatch can always resolve slot→key.
            self._sync_keys()
            # COPY before dispatch — the kernels must never see the
            # pump's reused poll buffers. jax's CPU client ZERO-COPIES
            # page-aligned numpy arrays into executable arguments, so an
            # async dispatch still holds the buffer when the next poll
            # overwrites it (observed as both over- and under-counted
            # banks at batch>=32768, where numpy's allocation becomes
            # mmap'd/page-aligned; 8192-wide buffers happened to be
            # heap-allocated, which the runtime copies). The Python
            # staging path has the same contract — _Stage.drain()
            # copies. A fresh copy is ~30us at 32k width vs the ~30ms
            # scatter program it feeds.
            sl = slots.copy()
            view = self.views[bank]
            mark = lambda s_: view.mark(s_)  # runs under the engine lock
            eng = self.engine
            if bank == "histo":
                eng.ingest_histo_batch(sl, a.copy(), b.copy(), count=n,
                                       mark=mark)
            elif bank == "counter":
                eng.ingest_counter_batch(sl, a.copy(), b.copy(), count=n,
                                         mark=mark)
            elif bank == "gauge":
                eng.ingest_gauge_batch(sl, a.copy(), count=n, mark=mark)
            else:
                # astype allocates fresh storage, which satisfies the
                # aliasing contract for the rho column by itself
                eng.ingest_set_batch(sl, c.copy(), a.astype(np.uint8),
                                     count=n, mark=mark)
            total += n
            if n < self.batch:
                break
        return total
