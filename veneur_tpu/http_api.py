"""Debug/ops HTTP server: healthchecks, version, the legacy JSON
import path, and the flush introspection surface.

Parity: handlers.go (sym: Server.Serve / HTTPServe — /healthcheck,
/healthcheck/tcp, /version, /builddate) and handlers_global.go (sym:
Server.handleImport — POST /import with a []JSONMetric body; the Go gob
digest blobs are JSON centroid arrays here, matching what
cluster.forward.HttpJsonForwarder emits). The reference also exposes
net/http/pprof; the Python analogues are GET /debug/threads (a stack
dump of every thread) and GET /debug/flush — the flight recorder's
ring of phase-attributed flush ticks plus breaker/ladder/journal/
dedupe-ledger state (schema in README "Observability"), with
GET /debug/flush/profile?ticks=N triggering an on-demand jax.profiler
capture when the server was configured with debug_flush_profile.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import __version__
from .cluster import wire
from .cluster.protos import metric_pb2
from .ingest.parser import MetricKey
from .utils.hashing import metric_digest

log = logging.getLogger("veneur_tpu.http")

BUILD_DATE = "dev"

_TYPE_TO_PB = {
    "counter": metric_pb2.Counter,
    "gauge": metric_pb2.Gauge,
    "histogram": metric_pb2.Histogram,
    "timer": metric_pb2.Timer,
    "set": metric_pb2.Set,
}


def json_metric_to_pb(d: dict) -> metric_pb2.Metric:
    """One JSONMetric dict → metricpb.Metric, so the HTTP import path
    reuses the gRPC path's merge machinery (handleImport →
    Worker.ImportMetric equivalence)."""
    mtype = d.get("type", "")
    if mtype not in _TYPE_TO_PB:
        raise ValueError(f"unknown metric type {mtype!r}")
    m = metric_pb2.Metric(name=d["name"], type=_TYPE_TO_PB[mtype],
                          tags=list(d.get("tags", [])))
    if mtype in ("histogram", "timer"):
        h = d["histogram"]
        td = m.histogram.t_digest
        # both centroid carriers decode through wire.py (WC01): the
        # lossless [[mean, weight]] list or the q16 packed row
        means, weights = wire.histogram_centroids_from_json(h)
        for mean, w in zip(means, weights):
            if float(w) > 0:
                td.centroids.add(mean=float(mean), weight=float(w))
        td.min = float(h.get("min", 0.0))
        td.max = float(h.get("max", 0.0))
        td.sum = float(h.get("sum", 0.0))
        td.count = float(h.get("count", 0.0))
        td.reciprocal_sum = float(h.get("reciprocal_sum", 0.0))
    elif mtype == "set":
        m.set.hyper_log_log = bytes.fromhex(d["set"])
    elif mtype == "counter":
        m.counter.value = int(d["value"])
    elif mtype == "gauge":
        m.gauge.value = float(d["value"])
    return m


class HttpApi:
    """The ops HTTP listener; `submit(digest, pb_metric)` routes an
    imported metric onto a worker queue (the Server provides it)."""

    def __init__(self, address: str, submit=None, healthy=None,
                 ledger=None, debug_state=None, profile=None,
                 observer=None, fleet_state=None, health=None,
                 submit_batch=None, engine_stamp=None, note_stamp=None,
                 merge_sketches=None, query=None):
        """`debug_state()` (optional) returns the JSON-ready dict for
        GET /debug/flush; `profile(ticks)` (optional) schedules an
        on-demand jax.profiler capture — absent means the knob is off
        and the endpoint answers 403, so an operator can tell "not
        enabled" from "not a server with an engine" (404).

        `observer` (optional, observe.ImportObserver) phase-attributes
        each POST /import and parents its spans on the remote sender's
        flush span. `fleet_state()` serves GET /debug/fleet (the
        per-sender e2e/freshness view). `health()` serves GET /healthz
        and /ready with STRUCTURED verdicts (schema in README
        "Observability"): a dict with `healthy`/`ready` booleans and a
        per-check breakdown — unhealthy answers 503, so a wedged
        flusher is detectable from OUTSIDE the process, not only by
        absence of data. Without `health`, /healthz degrades to the
        legacy boolean `healthy` callback.

        `query` (optional, ISSUE 14): the time-travel query tier —
        GET /query?metric=&q=&t0=&t1= serves historical percentiles /
        counts / cardinalities reconstructed from the durability
        journal's retained checkpoint generations. Absent means the
        tier is not armed on this server (history retention off, or
        not an import tier) and the endpoint answers 404. The callback
        runs the query on the tier's OWN executor — never this handler
        thread beyond the wait, never the ingest/flush path.

        `submit_batch` (optional, `submit_batch([(digest, pb), ...])`)
        routes one request's decoded metrics as a unit — the Server's
        durable implementation write-aheads the batch to the engine
        journal before any worker queue (and therefore before the 200
        ack) sees it.

        `engine_stamp` (ISSUE 10): the server's sketch-engine/wire
        stamp; a POST /import whose declared stamp (or implied legacy
        default) does not match is 400'd BEFORE any decode work —
        incompatible sketch payloads must never merge. Verdicts are
        recorded via `note_stamp(sender, stamp, ok)`; advisory
        per-prefix cardinality rows (X-Veneur-Prefix-Sketches) feed
        `merge_sketches(items)`."""
        host, _, port = address.rpartition(":")
        host = host.strip("[]") or "0.0.0.0"
        self._submit = submit
        self._submit_batch = submit_batch
        self._healthy = healthy or (lambda: True)
        self._ledger = ledger   # cluster.importsrv.DedupeLedger or None
        self._debug_state = debug_state
        self._profile = profile
        self._observer = observer
        self._fleet_state = fleet_state
        self._health = health
        self._engine_stamp = engine_stamp
        self._note_stamp = note_stamp
        self._merge_sketches = merge_sketches
        self._query = query
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet; logrus-style app logs
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str = "text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/healthcheck", "/healthcheck/tcp"):
                    if api._healthy():
                        self._reply(200, b"ok\n")
                    else:
                        self._reply(503, b"unhealthy\n")
                elif self.path in ("/healthz", "/ready"):
                    self._health_verdict(self.path == "/ready")
                elif self.path.rstrip("/") == "/debug/fleet":
                    if api._fleet_state is None:
                        self._reply(404, b"no fleet state on this "
                                         b"listener\n")
                        return
                    self._reply(200, json.dumps(
                        api._fleet_state(), default=str).encode(),
                        "application/json")
                elif self.path == "/version":
                    self._reply(200, __version__.encode() + b"\n")
                elif self.path == "/builddate":
                    self._reply(200, BUILD_DATE.encode() + b"\n")
                elif self.path == "/debug/threads":
                    frames = sys._current_frames()
                    out = []
                    for t in threading.enumerate():
                        out.append(f"--- {t.name} ({t.ident}) ---")
                        f = frames.get(t.ident)
                        if f is not None:
                            out.extend(traceback.format_stack(f))
                    self._reply(200, "\n".join(out).encode())
                elif self.path.startswith("/debug/flush"):
                    self._debug_flush()
                elif urlparse(self.path).path.rstrip("/") == "/query":
                    self._serve_query()
                else:
                    self._reply(404, b"not found\n")

            def _serve_query(self):
                """GET /query (ISSUE 14): time-travel reads from the
                durability journal's retained generations. Schema in
                README 'Time-travel queries'."""
                if api._query is None:
                    self._reply(404, b"no time-travel query tier on "
                                     b"this server (set "
                                     b"history_retention_generations "
                                     b"with durability enabled)\n")
                    return
                # keep_blank_values: `tags=` (empty) means "untagged
                # keys only", distinct from no tags filter at all
                qs = parse_qs(urlparse(self.path).query,
                              keep_blank_values=True)
                params = {k: v[0] for k, v in qs.items() if v}
                try:
                    body = api._query(params)
                except Exception as e:
                    status = getattr(e, "status", 500)
                    detail = getattr(e, "detail", f"query failed: {e}")
                    self._reply(status, json.dumps(
                        {"error": detail}).encode(),
                        "application/json")
                    return
                self._reply(200, json.dumps(
                    body, default=str).encode(), "application/json")

            def _health_verdict(self, readiness: bool):
                """GET /healthz | /ready: structured verdicts, 503 on
                a failing verdict so supervisors/probes need no JSON
                parsing — the body carries the why."""
                if api._health is None:
                    ok = bool(api._healthy())
                    body = {"healthy": ok, "ready": ok, "checks": {}}
                else:
                    body = api._health()
                ok = body.get("ready" if readiness else "healthy", False)
                self._reply(200 if ok else 503,
                            json.dumps(body, default=str).encode(),
                            "application/json")

            def _debug_flush(self):
                u = urlparse(self.path)
                if u.path.rstrip("/") == "/debug/flush/profile":
                    if api._profile is None:
                        self._reply(403, b"profiler capture disabled "
                                         b"(set debug_flush_profile)\n")
                        return
                    try:
                        ticks = int(parse_qs(u.query).get(
                            "ticks", ["1"])[0])
                    except ValueError:
                        self._reply(400, b"ticks must be an integer\n")
                        return
                    self._reply(200, json.dumps(
                        api._profile(ticks)).encode(),
                        "application/json")
                    return
                if u.path.rstrip("/") != "/debug/flush":
                    self._reply(404, b"not found\n")
                    return
                if api._debug_state is None:
                    self._reply(404, b"no flush state on this "
                                     b"listener\n")
                    return
                state = api._debug_state()
                self._reply(200, json.dumps(
                    state, default=str).encode(), "application/json")

            def do_POST(self):
                if self.path != "/import":
                    self._reply(404, b"not found\n")
                    return
                if api._submit is None:
                    self._reply(503, b"not a global veneur\n")
                    return
                # jsonmetric-v1 contract: reject a declared format we
                # don't speak rather than misparse it; absent header =
                # v1 (curl/operator tooling)
                ver = self.headers.get("X-Veneur-Forward-Version")
                if ver is not None and ver != "jsonmetric-v1":
                    self._reply(400, f"unsupported forward format "
                                     f"{ver!r}\n".encode())
                    return
                # idempotency envelope (exactly-once forward): decoded
                # up front so a malformed one 400s before any work, but
                # NOT admitted to the ledger until the body has fully
                # decoded — admitting first would record a chunk whose
                # read/parse then failed as "applied", and the sender's
                # safe re-send (a 400 promises nothing was imported)
                # would be dropped as a duplicate.
                try:
                    env = wire.envelope_from_headers(self.headers)
                except ValueError as e:
                    self._reply(400, f"bad forward envelope: "
                                     f"{e}\n".encode())
                    return
                # sketch-engine/wire stamp (ISSUE 10): a mismatched
                # fleet degrades LOUDLY — 400 before any decode work,
                # verdict counted + recorded per sender
                obs_kw = {}
                if api._engine_stamp is not None:
                    from . import sketches
                    remote = wire.sketch_stamp_from_headers(self.headers)
                    ok = sketches.stamp_compatible(api._engine_stamp,
                                                   remote)
                    if not ok:
                        # mismatch: counted + the sender's row marked
                        # (it IS alive, just misconfigured); accepted
                        # stamps annotate via the observer scope only
                        # after the body proves decodable
                        if api._note_stamp is not None:
                            api._note_stamp(
                                env[0] if env else "(unknown)",
                                remote, False)
                        self._reply(400, b"sketch engine/wire-format "
                                         b"mismatch\n")
                        return
                    obs_kw["stamp"] = remote
                # delta-over-gap refusal (ISSUE 13): a delta chunk may
                # only apply over an unbroken per-sender seq chain —
                # checked from the HEADERS, before any body decode,
                # like the stamp gate. 409 + the marker body is the
                # wire shape the sender's fallback recognizes (spill
                # the payload, force a full resync); the refused delta
                # was never applied so nothing is lost or doubled.
                if (env is not None and api._ledger is not None
                        and wire.forward_kind_from_headers(self.headers)
                        == "delta"
                        and not api._ledger.check_delta(env[0], env[1])):
                    self._reply(409, json.dumps(
                        {"error": wire.DELTA_GAP_DETAIL,
                         "sender": env[0], "seq": env[1]}).encode(),
                        "application/json")
                    return
                if api._merge_sketches is not None:
                    raw = self.headers.get(wire.PREFIX_SKETCH_HEADER)
                    if raw:
                        items = wire.decode_prefix_sketches_header(raw)
                        if items:
                            api._merge_sketches(items)
                if api._observer is not None:
                    # tolerant trace decode (None on malformed) + the
                    # import ring / span-tree / fleet observation scope
                    trace = wire.trace_from_headers(self.headers)
                    with api._observer.request(env, trace, "http",
                                               **obs_kw) as scope:
                        self._import_body(env, scope)
                else:
                    self._import_body(env, None)

            def _import_body(self, env, scope):
                ph = -1 if scope is None else scope.start("decode")
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    if not isinstance(body, list):
                        raise ValueError("body must be a JSON array "
                                         "of metrics")
                    # decode the whole batch before submitting any of it
                    # (atomic like handleImport: a 400 means nothing was
                    # imported, so clients may safely re-send)
                    decoded = []
                    for d in body:
                        pb = json_metric_to_pb(d)
                        key = wire.metric_key_of(pb)
                        digest = metric_digest(key.name, key.type,
                                               key.joined_tags)
                        decoded.append((digest, pb))
                except (ValueError, KeyError, TypeError) as e:
                    if scope is not None:
                        scope.finish(ph, outcome="error")
                        scope.rejected = True
                    self._reply(400, f"bad import body: {e}\n".encode())
                    return
                if scope is not None:
                    scope.finish(ph, n_metrics=len(decoded))
                # payload fully in hand: NOW consult the ledger — a
                # chunk it has already admitted is dropped WHOLE, with
                # a 200 (the sender delivered it, it just can't know
                # that yet: the ambiguous-failure replay path)
                ph = -1 if scope is None else scope.start("dedupe")
                admitted = not (env is not None
                                and api._ledger is not None
                                and not api._ledger.admit(*env))
                if scope is not None:
                    scope.finish(ph, admitted=admitted)
                    scope.admitted = admitted
                if not admitted:
                    self._reply(200, json.dumps(
                        {"imported": 0, "deduped": True}).encode(),
                        "application/json")
                    return
                ph = -1 if scope is None else scope.start("apply")
                if api._submit_batch is not None:
                    api._submit_batch(decoded, env)
                    count = len(decoded)
                else:
                    count = 0
                    for digest, pb in decoded:
                        api._submit(digest, pb)
                        count += 1
                if scope is not None:
                    scope.finish(ph, n_metrics=count)
                    scope.n_metrics = count
                self._reply(200, json.dumps({"imported": count}).encode(),
                            "application/json")

        self._httpd = ThreadingHTTPServer((host, int(port or 0)), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="http-api", daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
