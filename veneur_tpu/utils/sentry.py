"""Minimal Sentry error reporting — the ConsumePanic analogue.

The reference wires raven/sentry-go so crashes reach Sentry before the
crash-only exit (server.go sym: ConsumePanic). No Sentry SDK is vendored
here, so this speaks the store API directly with stdlib urllib: parse
the DSN, build a minimal event (message, exception type, traceback),
POST fire-and-forget from a daemon thread so an unreachable Sentry can
never stall or crash the pipeline it is reporting on.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import traceback
import urllib.request
import uuid
from urllib.parse import urlparse

log = logging.getLogger("veneur_tpu.sentry")


class SentryClient:
    def __init__(self, dsn: str, timeout_s: float = 3.0):
        u = urlparse(dsn)
        if not (u.scheme and u.hostname and u.username and u.path):
            raise ValueError(f"malformed sentry DSN")
        project = u.path.rsplit("/", 1)[-1]
        port = f":{u.port}" if u.port else ""
        self.endpoint = (f"{u.scheme}://{u.hostname}{port}"
                         f"/api/{project}/store/")
        self.auth = ("Sentry sentry_version=7, "
                     f"sentry_key={u.username}, sentry_client=veneur-tpu/1")
        self.timeout_s = timeout_s
        self.sent = 0
        self.dropped = 0

    def capture(self, exc: BaseException | None, message: str = "",
                wait: bool = False):
        """Fire-and-forget capture; `wait` blocks (used right before a
        crash-only exit so the event escapes the dying process)."""
        event = {
            "event_id": uuid.uuid4().hex,
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S",
                                       time.gmtime()),
            "platform": "python",
            "logger": "veneur_tpu",
            "message": message or (str(exc) if exc else ""),
        }
        if exc is not None:
            event["exception"] = {"values": [{
                "type": type(exc).__name__,
                "value": str(exc),
                "stacktrace": {"frames": [
                    {"filename": f.filename, "function": f.name,
                     "lineno": f.lineno}
                    for f in traceback.extract_tb(exc.__traceback__)
                ]},
            }]}
        t = threading.Thread(target=self._send, args=(event,),
                             daemon=True)
        t.start()
        if wait:
            t.join(self.timeout_s + 0.5)

    def _send(self, event: dict):
        req = urllib.request.Request(
            self.endpoint, data=json.dumps(event).encode(),
            headers={"Content-Type": "application/json",
                     "X-Sentry-Auth": self.auth}, method="POST")
        try:
            # vlint: disable=RS01 reason=crash-path reporter: must fire
            # even when breakers are open and during the crash-only
            # exit, so it cannot depend on the resilience layer it
            # reports on; fire-and-forget with its own short timeout
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self.sent += 1
        except Exception as e:
            self.dropped += 1
            log.debug("sentry send failed: %s", e)
