"""Platform pinning for the image's sitecustomize axon-TPU trap.

The driver image registers the tunneled "axon" PJRT plugin at interpreter
boot (when cwd=/root/repo) and force-sets jax_platforms="axon,cpu" via
jax.config — the JAX_PLATFORMS env var is overridden and cannot keep a
process off the tunnel, which can hang for minutes. The only reliable
defense is jax.config.update("jax_platforms", "cpu") after importing jax
but before the first operation initializes a backend.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def pin_cpu(n_devices: int | None = None) -> None:
    """Pin JAX to the host CPU platform; optionally request a virtual
    n-device CPU mesh. Must run before any jax operation (backend init);
    the device-count flag additionally requires that no XLA CPU client
    exists yet in this process."""
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"{_COUNT_FLAG}={n_devices}"
        if _COUNT_FLAG in flags:
            flags = re.sub(rf"{_COUNT_FLAG}=\d+", opt, flags)
        else:
            flags = (flags + " " + opt).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; caller's device check will see


def tunnel_alive() -> bool:
    """Liveness check for the axon relay: in the tunneled environment
    the TPU is only reachable through local relay ports, and when the
    relay process is dead every backend init hangs in the client's
    connect-retry loop. Returns True when ANY probed relay port accepts
    (or when this isn't a tunneled environment at all); returns False
    only when every probe is refused/timed out — callers should then
    pin_cpu() and spend their budget on a real run."""
    import socket
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return True  # not a tunneled environment; let jax decide
    for port in (8082, 8092, 8102):
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=1.0):
                return True
        except OSError:
            continue
    return False


def tunnel_healthy(timeout_s: float = 90.0) -> bool:
    """Stronger liveness probe: port-accept alone can lie (the relay
    accepts TCP while the device session hangs — observed r4, see
    TUNNEL_PROBE_r04.jsonl). A disposable subprocess initializes the
    default backend, runs one op, and fetches the result under a hard
    timeout; only a full round trip counts as healthy. The subprocess
    runs from the repo root because the axon plugin only registers
    there."""
    if not tunnel_alive():
        return False
    import subprocess
    import sys
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    code = (
        "import jax, jax.numpy as jnp\n"
        "d = jax.devices()[0]\n"
        "x = jnp.ones((8,), jnp.float32)\n"
        "ok = float(x.sum()) == 8.0 and d.platform in ('tpu', 'axon')\n"
        "print('HEALTHY' if ok else 'BAD')\n"
    )
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=repo_root)
    except (subprocess.TimeoutExpired, OSError):
        return False
    return p.returncode == 0 and "HEALTHY" in p.stdout
