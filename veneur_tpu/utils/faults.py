"""Deterministic fault injection for the egress-resilience layer.

A `FaultHarness` bundles an injectable monotonic clock with scripted
transports so every retry / circuit-breaker / spill-re-merge transition
in `veneur_tpu/resilience.py` is unit-testable without sockets or real
sleeps: backoff sleeps advance the fake clock instead of the wall, and
each transport attempt consumes the next step of a failure schedule.

Schedule steps (a list, consumed left to right; the last step repeats
forever once the script is exhausted):

    "ok"              succeed (HTTP 200 / callable returns)
    "timeout"         raise TimeoutError
    "refused"         raise ConnectionRefusedError
    "reset"           raise ConnectionResetError
    "ack_lost"        the AMBIGUOUS failure: the receiver consumes and
                      applies the request body (the transport's
                      `deliver` callback runs / the callable's
                      delivery side effects happen), then the response
                      is dropped — raised as TimeoutError. The sender
                      cannot distinguish this from "timeout"; only an
                      idempotency envelope + receiver dedupe makes the
                      inevitable retry/replay safe.
    "kill"            simulated HARD KILL at this exact wire moment:
                      raises SimulatedKill, a BaseException, so it
                      escapes every `except Exception` recovery arm in
                      the egress/forwarder/flush stack exactly like
                      SIGKILL would end the process — no parking, no
                      journal appends, no breaker bookkeeping happen
                      after it. The kill-restart chaos harness uses it
                      to stop a sender mid-replay-ladder and then
                      rebuild it from the durability journal.
    "kill_after_send" the body is DELIVERED first (like "ack_lost"),
                      then the kill fires — the crash window between a
                      successful send and its journal DONE record,
                      where only receiver-side dedupe of the recovered
                      replay prevents a double count.
    503 (any int)     HTTP status: >=400 raises HTTPStatusError-shaped
                      failure via a fake response; <400 succeeds
    ("slow", dt)      advance the clock by dt seconds, then succeed
    ("slow", dt, s)   advance the clock by dt, then apply step `s`

`seeded_schedule` derives a reproducible random schedule from a seed —
the property-style way to exercise the retry ladder
(`ambiguous=True` mixes ack-loss into the fault pool).
"""

from __future__ import annotations

import random
import threading


class SimulatedKill(BaseException):
    """The scripted "kill" step. A BaseException on purpose: the
    resilience layer's retry loops, the forwarder's park-on-failure
    arms, and the flush loop's error counter all catch `Exception` —
    a real SIGKILL bypasses every one of them, so the simulation must
    too. Nothing (journal appends included) runs after this raises."""


def kill_journal_lock(journal_like):
    """Complete an in-process kill simulation: release the durability
    journal's advisory process lock the way a real SIGKILL would (the
    kernel closes the fd), WITHOUT flushing or closing the journal —
    everything the next incarnation knows it must learn from the bytes
    already on disk. Accepts a Journal or a ForwardJournal/
    WatermarkJournal façade."""
    journal = getattr(journal_like, "journal", journal_like)
    journal.release_lock()


class FakeClock:
    """Injected monotonic time. Use the instance itself as `clock=`
    (callable) and its .sleep as `sleep=`; sleeps advance time and are
    recorded so tests can assert the backoff ladder."""

    def __init__(self, start: float = 0.0):
        self._lock = threading.Lock()
        self._t = float(start)
        self.sleeps: list[float] = []

    def __call__(self) -> float:
        with self._lock:
            return self._t

    monotonic = __call__

    def sleep(self, dt: float):
        with self._lock:
            self.sleeps.append(dt)
            self._t += max(0.0, dt)

    def advance(self, dt: float):
        with self._lock:
            self._t += max(0.0, dt)


class _FakeResponse:
    """Duck-typed urllib response: .status, .close(), context manager."""

    def __init__(self, status: int = 200, body: bytes = b"{}"):
        self.status = status
        self._body = body

    def read(self) -> bytes:
        return self._body

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def seeded_schedule(seed: int, n: int, p_fail: float = 0.5,
                    ambiguous: bool = False):
    """Reproducible schedule of n steps ending in "ok" (so a bounded
    retry ladder can always terminate in tests that want delivery).
    `ambiguous=True` adds ack-loss (applied-then-dropped-response)
    to the fault pool — only safe to deliver through a deduping
    receiver."""
    rng = random.Random(seed)
    faults = ["timeout", "refused", 503, 500, ("slow", 0.05)]
    if ambiguous:
        faults = faults + ["ack_lost", "ack_lost"]
    steps = [rng.choice(faults) if rng.random() < p_fail else "ok"
             for _ in range(max(0, n - 1))]
    return steps + ["ok"]


class ScriptedTransport:
    """Scripted stand-in for the resilience layer's HTTP transport:
    `transport(req, timeout=None)` consumes one schedule step per call.
    Records every attempt as (monotonic_time, timeout, step, request)
    in `.calls` for timeline assertions.

    `deliver` (optional) is the RECEIVER: a callable(req) invoked for
    every step whose body reaches the other end — "ok", success
    statuses, and "ack_lost" (which applies the body, then drops the
    response). Wiring `deliver` to a real import endpoint turns the
    transport into an end-to-end ambiguous-failure chaos harness: the
    receiver's state advances while the sender sees a timeout. When
    `deliver` returns a response-like object, "ok" returns it."""

    def __init__(self, schedule, clock: FakeClock | None = None,
                 deliver=None):
        self.schedule = list(schedule) or ["ok"]
        self.clock = clock or FakeClock()
        self.deliver = deliver
        self.calls: list[tuple] = []
        self._lock = threading.Lock()
        self._i = 0

    def _next_step(self):
        with self._lock:
            step = self.schedule[min(self._i, len(self.schedule) - 1)]
            self._i += 1
        return step

    @property
    def attempts(self) -> int:
        with self._lock:
            return self._i

    def __call__(self, req=None, timeout=None):
        step = self._next_step()
        self.calls.append((self.clock(), timeout, step, req))
        return self._apply(step, req)

    def _deliver(self, req):
        return self.deliver(req) if self.deliver is not None else None

    def _apply(self, step, req=None):
        if isinstance(step, tuple) and step and step[0] == "slow":
            self.clock.advance(float(step[1]))
            inner = step[2] if len(step) > 2 else "ok"
            return self._apply(inner, req)
        if step == "kill":
            raise SimulatedKill("scripted hard kill (nothing sent)")
        if step == "kill_after_send":
            self._deliver(req)
            raise SimulatedKill("scripted hard kill (body was applied)")
        if step == "ack_lost":
            # the ambiguous failure: the body is consumed and APPLIED
            # by the receiver, then the response never makes it back
            self._deliver(req)
            raise TimeoutError("scripted ack lost (body was applied)")
        if isinstance(step, int):
            if step >= 400:
                # shaped like urllib: an error status raises, carrying
                # the code — classified retryable iff 5xx/408/429
                from ..resilience import HTTPStatusError
                raise HTTPStatusError("scripted", step)
            self._deliver(req)
            return _FakeResponse(step)
        if step == "ok":
            resp = self._deliver(req)
            return resp if resp is not None else _FakeResponse(200)
        if step == "timeout":
            raise TimeoutError("scripted timeout")
        if step == "refused":
            raise ConnectionRefusedError("scripted connection refused")
        if step == "reset":
            raise ConnectionResetError("scripted connection reset")
        raise ValueError(f"unknown fault step {step!r}")


class ScriptedCallable(ScriptedTransport):
    """The same schedule semantics for non-HTTP egress (forwarder
    callables, grpc sends, kafka producers): success returns the
    injected `result`, failures raise. Ignores its arguments so it can
    stand in for any call shape."""

    def __init__(self, schedule, clock: FakeClock | None = None,
                 result=None, on_success=None):
        super().__init__(schedule, clock)
        self.result = result
        self.on_success = on_success
        self.delivered: list = []

    def __call__(self, *args, timeout=None, **kwargs):
        step = self._next_step()
        self.calls.append((self.clock(), timeout, step, args))
        if step in ("ack_lost", "kill_after_send"):
            # ambiguous failure for callables: the delivery side
            # effects HAPPEN (recorded + on_success runs, e.g. a real
            # gRPC send underneath), then the ack is dropped — or the
            # whole process "dies" before observing it
            self.delivered.append(args)
            if self.on_success is not None:
                self.on_success(*args, **kwargs)
            if step == "kill_after_send":
                raise SimulatedKill(
                    "scripted hard kill (body was applied)")
            raise TimeoutError("scripted ack lost (body was applied)")
        out = self._apply(step)          # raises on fault steps
        self.delivered.append(args)
        if self.on_success is not None:
            return self.on_success(*args, **kwargs)
        return self.result if self.result is not None else out


class FaultHarness:
    """One-stop bundle for tests: a shared FakeClock, seeded RNG, and
    factories producing scripted transports and Egress objects wired to
    them. Constructed by the `fault_harness` conftest fixture."""

    def __init__(self, seed: int = 0):
        self.clock = FakeClock()
        self.rng = random.Random(seed)
        from ..resilience import ResilienceRegistry
        self.registry = ResilienceRegistry()

    def transport(self, schedule, deliver=None) -> ScriptedTransport:
        return ScriptedTransport(schedule, self.clock, deliver=deliver)

    def callable(self, schedule, **kw) -> ScriptedCallable:
        return ScriptedCallable(schedule, self.clock, **kw)

    def egress(self, destination: str = "test", schedule=("ok",),
               policy=None, transport=None):
        from ..resilience import Egress
        return Egress(
            destination, policy=policy,
            transport=(transport if transport is not None
                       else self.transport(schedule)),
            clock=self.clock, sleep=self.clock.sleep, rng=self.rng,
            registry=self.registry)
