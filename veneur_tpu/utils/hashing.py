"""Hashing used across the pipeline.

The reference computes a 32-bit FNV-1a digest over name+type+tags to shard
metrics onto workers (samplers/parser.go sym: ParseMetric's Digest field,
server.go `Workers[Digest % len(Workers)]`), and a 64-bit hash for HLL
member insertion inside the vendored hyperloglog. We keep FNV-1a exactly
(so a veneur-proxy hashing metrics at us agrees about key identity) and use
64-bit FNV-1a for set members.
"""

from __future__ import annotations

FNV32_OFFSET = 0x811C9DC5
FNV32_PRIME = 0x01000193
FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x00000100000001B3
_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_32(data: bytes, h: int = FNV32_OFFSET) -> int:
    for b in data:
        h = ((h ^ b) * FNV32_PRIME) & _M32
    return h


def fnv1a_64(data: bytes, h: int = FNV64_OFFSET) -> int:
    for b in data:
        h = ((h ^ b) * FNV64_PRIME) & _M64
    return h


def metric_digest(name: str, type_: str, joined_tags: str) -> int:
    """The worker-sharding digest over (name, type, tags) — parity with
    samplers.ParseMetric's fnv32a over the same fields."""
    h = fnv1a_32(name.encode())
    h = fnv1a_32(type_.encode(), h)
    h = fnv1a_32(joined_tags.encode(), h)
    return h


def fmix64(h: int) -> int:
    """murmur3 64-bit finalizer — full-avalanche post-mix."""
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _M64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _M64
    h ^= h >> 33
    return h


def set_member_hash(member: str) -> int:
    """64-bit hash of a set member for HLL insertion.

    FNV-1a alone has weak high-bit avalanche on similar strings (the HLL
    register index is the TOP 14 bits), so the digest is post-mixed with
    the murmur3 finalizer — the reference's vendored sketch likewise uses
    a full-avalanche hash (metro) rather than raw FNV.
    """
    return fmix64(fnv1a_64(member.encode()))
