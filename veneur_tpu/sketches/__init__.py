"""Pluggable sketch engines — the registry and selection layer.

ISSUE 10's engine-selection subsystem: the aggregation pipeline's
histogram/timer sketches and set-cardinality sketches are selected
here via the `histogram_backend` / `set_backend` config keys (the
`aggregation_backend` selection pattern), instead of being hard-wired
to the t-digest + HLL pair:

  histogram_backend:  "tdigest" (default) | "req"
  set_backend:        "hll" (default)     | "ull"

Every engine presents the fixed contract documented in
`sketches/base.py`; the pipeline (models/pipeline.py) holds ONE
histogram-engine and ONE set-engine object and never names a concrete
sketch again (vlint SK01 machine-checks the boundary: bank
constructions and sketch-ops imports outside this package + the
blessed ops/ kernels are flagged).

MIXED-FLEET SAFETY — the engine/wire-format stamp: both forward
contracts carry a compact engine stamp ("h=<id>/<ver>,s=<id>/<ver>")
per request; a receiver whose own stamp differs REJECTS the request
loudly (counted `veneur.import.engine_mismatch_total`, surfaced
per-sender at GET /debug/fleet) rather than silently merging
incompatible register banks. An absent stamp means a legacy peer and
is interpreted as the DEFAULT engine pair, so an un-upgraded fleet
keeps working and only a fleet that actually switched backends
refuses legacy senders. The header/field codecs live in
cluster/wire.py (the TR01 single-homing precedent); the stamp
STRINGS, and the set-register byte codec, live here.

Set-register wire codec: byte 0 tags the engine+format (1 = HLL v1 —
the pre-registry byte, so old payloads decode unchanged; 2 = ULL v1),
byte 1 the precision, then the raw u8 registers. Decoding returns
(engine_id, registers); feeding a payload into a bank of the other
engine raises before any register merges (belt to the stamp check's
suspenders).
"""

from __future__ import annotations

import numpy as np

from .hll_engine import HLLEngine
from .req import REQEngine
from .tdigest_engine import TDigestEngine
from .ull import ULLEngine

HISTOGRAM_BACKENDS = ("tdigest", "req")
SET_BACKENDS = ("hll", "ull")

# set-register wire codes (byte 0 of the payload)
_SET_WIRE_CODES = {"hll": 1, "ull": 2}
_SET_WIRE_IDS = {v: k for k, v in _SET_WIRE_CODES.items()}


def histogram_engine(cfg):
    """Engine object for an EngineConfig-like cfg (duck-typed: reads
    histogram_backend + the per-engine shape keys). Frozen dataclass —
    hashable, so it keys the pipeline's lru_cached executables."""
    backend = getattr(cfg, "histogram_backend", "tdigest")
    if backend == "tdigest":
        return TDigestEngine(compression=float(cfg.compression),
                             buffer_depth=int(cfg.buffer_depth))
    if backend == "req":
        return REQEngine(levels=int(getattr(cfg, "req_levels", 2)),
                         capacity=int(getattr(cfg, "req_capacity", 256)))
    raise ValueError(
        f"unknown histogram_backend {backend!r} "
        f"(known: {', '.join(HISTOGRAM_BACKENDS)})")


def set_engine(cfg):
    backend = getattr(cfg, "set_backend", "hll")
    if backend == "hll":
        return HLLEngine(precision=int(cfg.hll_precision))
    if backend == "ull":
        return ULLEngine(precision=int(getattr(cfg, "ull_precision", 13)))
    raise ValueError(
        f"unknown set_backend {backend!r} "
        f"(known: {', '.join(SET_BACKENDS)})")


def engine_stamp(heng, seng) -> str:
    """The wire stamp of an engine pair: "h=<id>/<ver>,s=<id>/<ver>"."""
    return (f"h={heng.id}/{heng.wire_version},"
            f"s={seng.id}/{seng.wire_version}")


def stamp_with_codec(stamp: str, centroid_codec: str) -> str:
    """Fold the forward centroid codec into an engine stamp (ISSUE
    13): "q16" appends a "q" marker to the histogram component's wire
    version ("h=tdigest/1" -> "h=tdigest/1q"), so a quantized-centroid
    fleet and a lossless fleet read as DIFFERENT wire formats and
    reject each other loudly before decode — quantized rows must never
    be mistaken for (or silently mixed with) lossless ones. "lossless"
    returns the stamp unchanged (legacy peers stay compatible)."""
    if centroid_codec != "q16":
        return stamp
    return ",".join(part + "q" if part.startswith("h=") else part
                    for part in stamp.split(","))


# what an unstamped (legacy) peer is running, by definition
DEFAULT_STAMP = engine_stamp(TDigestEngine(), HLLEngine())


def parse_stamp(stamp: str) -> dict | None:
    """"h=tdigest/1,s=hll/1" -> {"h": ("tdigest", 1, "lossless"),
    "s": ("hll", 1, "lossless")}; a trailing "q" on a version (the
    quantized-centroid marker, see stamp_with_codec) parses as codec
    "q16". None for a malformed stamp (the receiver then rejects — an
    unparseable stamp is a peer we cannot reason about, which is the
    mismatch case, not the legacy case)."""
    out = {}
    try:
        for part in stamp.split(","):
            kind, _, rest = part.partition("=")
            eng, _, ver = rest.partition("/")
            if kind not in ("h", "s") or not eng:
                return None
            codec = "lossless"
            if ver.endswith("q"):
                ver, codec = ver[:-1], "q16"
            out[kind] = (eng, int(ver or 1), codec)
    except ValueError:
        return None
    return out if ("h" in out and "s" in out) else None


def stamp_compatible(local: str, remote: str | None) -> bool:
    """Is a peer's stamp (None = legacy peer = DEFAULT_STAMP)
    mergeable into engines running `local`? Compared component-wise on
    (engine id, wire version) so ordering/whitespace never matter."""
    mine = parse_stamp(local)
    theirs = parse_stamp(remote) if remote is not None \
        else parse_stamp(DEFAULT_STAMP)
    if mine is None or theirs is None:
        return False
    return mine == theirs


def encode_set_registers(engine_id: str, registers) -> bytes:
    regs = np.asarray(registers, np.uint8)
    precision = int(np.log2(len(regs)))
    # vlint: disable=DR02 reason=the versioned set-register WIRE row
    # (u8 registers are exact either way); single-homed here per SK01
    return bytes([_SET_WIRE_CODES[engine_id], precision]) + regs.tobytes()


def decode_set_registers(data: bytes) -> tuple:
    """-> (engine_id, registers u8[m]); raises ValueError on an
    unknown code or a length mismatch (the poison-pill reject path)."""
    if len(data) < 2 or data[0] not in _SET_WIRE_IDS:
        raise ValueError("bad set-sketch payload (unknown engine code)")
    precision = data[1]
    # vlint: disable=DR02 reason=inverse of the set-register wire row
    # above — same single-homed wire codec, not a bank-leaf byte move
    regs = np.frombuffer(data[2:], np.uint8)
    if len(regs) != 1 << precision:
        raise ValueError("set-sketch register count mismatch")
    return _SET_WIRE_IDS[data[0]], regs


def set_engine_for_id(engine_id: str, precision: int):
    """Engine object for a decoded wire payload (spill re-merge joins
    registers by the payload's own engine, whatever the local bank
    runs — the stamp check keeps mixed payloads out of BANKS, but the
    sender-side spill buffer merges its own exports)."""
    if engine_id == "hll":
        return HLLEngine(precision=precision)
    if engine_id == "ull":
        return ULLEngine(precision=precision)
    raise ValueError(f"unknown set engine {engine_id!r}")


def merge_registers(engine_id: str, a, b):
    """Host-side register union under the payload's engine semantics
    (max for HLL, lattice join for ULL)."""
    if engine_id == "ull":
        from .ull import join_registers_np
        return join_registers_np(a, b)
    return np.maximum(np.asarray(a, np.uint8), np.asarray(b, np.uint8))


def describe(heng, seng) -> dict:
    """JSON-ready engine description for /debug/flush."""
    return {
        "stamp": engine_stamp(heng, seng),
        "histogram": {"id": heng.id, "wire_version": heng.wire_version,
                      "params": {k: getattr(heng, k)
                                 for k in heng.__dataclass_fields__},
                      "error_contract": heng.error_contract},
        "set": {"id": seng.id, "wire_version": seng.wire_version,
                "params": {k: getattr(seng, k)
                           for k in seng.__dataclass_fields__},
                "error_contract": seng.error_contract},
    }
