"""Engine interface contracts + helpers shared by every sketch engine.

A *sketch engine* is the pluggable unit behind the aggregation
pipeline's fixed call surface (ISSUE 10): the histogram/timer banks and
the set-cardinality banks are no longer hard-wired to one sketch pair
(t-digest + 6-bit-HLL-in-u8) but selected through the registry in
`veneur_tpu/sketches/__init__.py` via the `histogram_backend` /
`set_backend` config keys (the `aggregation_backend` selection pattern).

Engines are FROZEN dataclasses: their fields are the static shape/
accuracy parameters (compression, register precision, level budget), so
an engine instance is hashable and keys the pipeline's lru_cached
executable factories — every AggregationEngine with the same backend
and parameters shares one compiled program per device.

Contract (duck-typed; the default engines are the reference
implementations):

HISTOGRAM ENGINES — own a bank NamedTuple with:
  * item state of engine-specific layout, PLUS the shared exact-scalar
    leaves `vmin/vmax/vsum/count/recip` with `*_lo` 2Sum compensation
    twins (identical names across engines — the flush program and the
    generic aggregate/merge helpers below consume them by name);
  * `num_slots` / `num_centroids` / `buf_size` properties (buf_size =
    the per-slot batch headroom the hot-slot sidestep pre-clusters to).
  Methods (pure, jit-composable unless noted):
    init(num_slots) -> bank
    add_batch_impl(bank, slots, values, weights) -> bank
    compress_impl(bank) -> bank
    merge_centroids_impl(bank, slots, means, weights) -> bank
    merge_scalars_impl(bank, slots, mins, maxs, sums, counts, recips)
    quantile_impl(bank, qs) -> f32[K, P]
    aggregates_impl(bank) -> dict (min/max/sum/count/avg/hmean)
    forward_leaves(bank) -> dict of h_* arrays (h_mean/h_weight are the
        flattened weighted-point export every engine shares on the wire:
        a t-digest exports centroids, a compactor sketch exports its
        retained items — both merge at the global tier as weighted
        points, so ONE wire row shape serves every engine)
    donation_split() -> (core_names, buf_names) | None  (host)
    reassemble(core, bufs) -> bank                      (jit-composable)
    merge_banks(a, b) -> bank  (host-level, bit-commutative: the
        cross-engine property suite pins merge(a,b) == merge(b,a))
    state_bytes(num_slots=1) -> int                     (host)
  Attributes: id, wire_version, import_strategy ("cluster"|"direct"),
  bank_leaves (durability leaf order), error_contract (doc string).

INCREMENTAL-FLUSH CONTRACT (ISSUE 11 — holds for every engine, pinned
per backend by tests/test_incremental_flush.py): the flush body may be
evaluated over a row-gathered [D, ·] SLICE of the bank (the dirty
work set) instead of the full [K, ·] bank, so every jit-composable op
must be (a) shape-generic in the slot axis and (b) strictly
row-independent — no op may couple one slot's output to another
slot's state. Additionally a FRESH-INIT row must be a fixed point of
compress and must materialize to a constant baseline row (quantiles/
aggregates/estimate of an empty row depend on nothing but the engine
params): the incremental flush scatters dirty-row outputs over that
cached baseline, and cold piles keep their fresh-init state verbatim
— bit-identity to the full program is the acceptance bar, not an
approximation.

SET ENGINES — own a bank NamedTuple with `registers: u8[K, m]` plus
  `num_slots`/`num_registers` properties. Methods:
    init(num_slots) -> bank
    insert_impl(bank, slots, reg_idx, vals) -> bank
    merge_rows_impl(bank, slots, registers) -> bank
    merge_banks(a, b) -> bank   (bit-commutative lattice join)
    hash_update(h) -> (reg_idx, val)   (host hot path, python ints)
    estimate_device(bank, pallas_ok) -> dict  (flush-program outputs)
    estimate_finalize(host_dict) -> None      (host; writes "s_est")
    merge_registers_np(a, b) -> np.ndarray    (host join, spill re-merge)
    encode_registers(regs) -> bytes / decode via the registry codec
  Attributes: id, wire_version, precision, bank_leaves, error_contract.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.scalar import _two_sum

_INF = jnp.inf

# Shared exact-scalar leaves every histogram engine's bank carries (the
# durability codecs and the generic helpers below walk these by name).
SCALAR_LEAVES = ("vmin", "vmax", "vsum", "count", "recip",
                 "vsum_lo", "count_lo", "recip_lo")


def scalar_aggregates(bank):
    """The non-percentile flush aggregates from the shared exact-scalar
    leaves — one definition for every histogram engine (the t-digest
    engine delegates to ops/tdigest.aggregates, which this mirrors)."""
    cnt = bank.count + bank.count_lo
    vsum = bank.vsum + bank.vsum_lo
    recip = bank.recip + bank.recip_lo
    safe = jnp.where(cnt > 0, cnt, 1.0)
    return {
        "min": jnp.where(cnt > 0, bank.vmin, 0.0),
        "max": jnp.where(cnt > 0, bank.vmax, 0.0),
        "sum": vsum,
        "count": cnt,
        "avg": jnp.where(cnt > 0, vsum / safe, 0.0),
        "hmean": jnp.where(recip > 0, cnt / jnp.where(
            recip > 0, recip, 1.0), 0.0),
    }


def merge_scalar_stats(bank, slots, vmins, vmaxs, vsums, counts, recips):
    """Merge exact per-digest scalar stats into the shared leaves —
    engine-agnostic (min/max scatter, 2Sum-compensated sums). Returns
    a _replace'd bank."""
    K = bank.num_slots
    valid = slots >= 0
    sd = jnp.where(valid, slots, K)
    dsum = jnp.zeros_like(bank.vsum).at[sd].add(
        jnp.where(valid, vsums, 0.0), mode="drop")
    dcount = jnp.zeros_like(bank.count).at[sd].add(
        jnp.where(valid, counts, 0.0), mode="drop")
    drecip = jnp.zeros_like(bank.recip).at[sd].add(
        jnp.where(valid, recips, 0.0), mode="drop")
    vsum, vsum_lo = _two_sum(bank.vsum, dsum + bank.vsum_lo)
    count, count_lo = _two_sum(bank.count, dcount + bank.count_lo)
    recip, recip_lo = _two_sum(bank.recip, drecip + bank.recip_lo)
    return bank._replace(
        vmin=bank.vmin.at[sd].min(
            jnp.where(valid, vmins, _INF), mode="drop"),
        vmax=bank.vmax.at[sd].max(
            jnp.where(valid, vmaxs, -_INF), mode="drop"),
        vsum=vsum, count=count, recip=recip,
        vsum_lo=vsum_lo, count_lo=count_lo, recip_lo=recip_lo,
    )


def add_scalar_stats(bank, sd, valid, v, w):
    """Fold one batch's exact scalar deltas (per-sample form) into the
    shared leaves — the add_batch twin of merge_scalar_stats. `sd` is
    the drop-mapped slot vector (OOB for padding)."""
    dsum = jnp.zeros_like(bank.vsum).at[sd].add(w * v, mode="drop")
    dcount = jnp.zeros_like(bank.count).at[sd].add(w, mode="drop")
    drecip = jnp.zeros_like(bank.recip).at[sd].add(
        jnp.where(v != 0, w / jnp.where(v != 0, v, 1.0), 0.0),
        mode="drop")
    vsum, vsum_lo = _two_sum(bank.vsum, dsum + bank.vsum_lo)
    count, count_lo = _two_sum(bank.count, dcount + bank.count_lo)
    recip, recip_lo = _two_sum(bank.recip, drecip + bank.recip_lo)
    return bank._replace(
        vmin=bank.vmin.at[sd].min(jnp.where(valid, v, _INF), mode="drop"),
        vmax=bank.vmax.at[sd].max(jnp.where(valid, v, -_INF), mode="drop"),
        vsum=vsum, count=count, recip=recip,
        vsum_lo=vsum_lo, count_lo=count_lo, recip_lo=recip_lo,
    )


def merge_scalar_banks_np(a, b):
    """Bit-commutative whole-bank scalar merge for merge_banks: the
    exact value of each 2Sum pair is f64(hi) + f64(lo); f64 addition of
    the two exact values is commutative bit-for-bit, unlike chaining
    _two_sum folds in either order. Returns dict of numpy leaves."""
    import numpy as np
    out = {}
    out["vmin"] = np.minimum(np.asarray(a.vmin), np.asarray(b.vmin))
    out["vmax"] = np.maximum(np.asarray(a.vmax), np.asarray(b.vmax))
    for hi, lo in (("vsum", "vsum_lo"), ("count", "count_lo"),
                   ("recip", "recip_lo")):
        s = (np.asarray(getattr(a, hi), np.float64)
             + np.asarray(getattr(a, lo), np.float64)) \
            + (np.asarray(getattr(b, hi), np.float64)
               + np.asarray(getattr(b, lo), np.float64))
        h = s.astype(np.float32)
        out[hi] = h
        out[lo] = (s - h.astype(np.float64)).astype(np.float32)
    return out
