"""Relative-error quantile engine — adaptive compactors, batched.

The alternative histogram engine (arxiv 2511.17396's relative-error
streaming quantiles): per slot, a hierarchy of L fixed-capacity
compactors holds ACTUAL SAMPLE VALUES as weighted items. Level
buffers fill with weighted points; when one crosses its lazy trigger,
the HIGHEST 5/8 section is PROTECTED (kept verbatim — the
high-ranks-accurate mode, because the tail percentiles p99.9+ are
what absolute-rank sketches blur on heavy-tailed data), while the
lowest section is compacted pairwise — adjacent pairs collapse to one
survivor at the pair's weighted GEOMETRIC mean (arithmetic fallback
for non-positive values) carrying the pair's summed weight, so total
weight is conserved exactly — and the survivors promote one level up.

ERROR CONTRACT (documented, pinned by tests/test_sketches.py): the
TAIL is the accurate end — p99.9 relative value error stays ~1% at
the default budget even on pareto/log-uniform streams where a
same-budget t-digest's k1 clusters average across wide value ranges
(the config17 bench rows), because the top ranks live in protected
sections as exact sample values; count/sum/min/max/avg/hmean are
exact through the same 2Sum scalar leaves as the t-digest bank.
Mid-range quantiles (p50-p99) ride the repeatedly-averaged compacted
items and are DISTRIBUTION-DEPENDENT (tight on compact distributions,
tens of percent on extreme heavy tails) — a deployment needing tight
mid-range percentiles keeps `histogram_backend: tdigest`; this engine
is for tail-latency SLOs.

Bank layout ([K] slots, L levels x C capacity, T = L*C; default
L=2, C=256 — the same ~4 KiB/slot budget as the default t-digest
bank):
  value, weight : f32[K, T]   level l occupies columns [l*C, (l+1)*C);
                              live items are a dense prefix per level,
                              weight 0 == empty
  n             : i32[K, L]   per-level fill
  ncomp         : i32[K]      compaction counter (stats; merges by
                              SUM, keeping merge bit-commutative)
  vmin/vmax/vsum/count/recip (+ _lo twins) : the shared exact scalars

Wire/merge contract: the retained items ARE the export — they ride the
forward wire as the same weighted-point rows a t-digest's centroids
use, and merging is re-insertion (weights preserved), so the global
tier's Combine machinery is engine-agnostic. merge_banks canonically
sorts the union before re-inserting and sums ncomp, which makes
merge(a, b) == merge(b, a) bit-for-bit (the property suite pins it).

Zero canonicalization: -0.0 inputs are stored as +0.0 (matching the
comparator canonicalization the t-digest sort applies), so the
canonical item order — and therefore merge bit-identity — never
depends on zero signs.

Incremental-flush contract (sketches/base.py): every op here is
row-independent and shape-generic in K — the compaction cascade,
quantile sort, and scalar folds act per row — and a fresh-init row
(all-zero items, n=0) is a compress fixed point (nl=0 never crosses
the lazy trigger), so the [D, ·] dirty-slice evaluation is exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import scatter
from ..ops.tdigest import _interp_knots
from . import base

_INF = jnp.inf


class REQBank(NamedTuple):
    value: jax.Array      # f32[K, T]
    weight: jax.Array     # f32[K, T]
    n: jax.Array          # i32[K, L]
    ncomp: jax.Array      # i32[K]
    vmin: jax.Array       # f32[K]
    vmax: jax.Array       # f32[K]
    vsum: jax.Array       # f32[K]
    count: jax.Array      # f32[K]
    recip: jax.Array      # f32[K]
    vsum_lo: jax.Array    # f32[K]
    count_lo: jax.Array   # f32[K]
    recip_lo: jax.Array   # f32[K]

    @property
    def num_slots(self):
        return self.value.shape[0]

    @property
    def num_levels(self):
        return self.n.shape[1]

    @property
    def capacity(self):
        return self.value.shape[1] // self.n.shape[1]

    @property
    def buf_size(self):
        # the hot-slot sidestep's per-landing headroom = one level
        return self.capacity

    @property
    def num_centroids(self):
        # total item budget (the role C plays for the t-digest bank)
        return self.value.shape[1]


def init(num_slots: int, levels: int = 2, capacity: int = 256) -> REQBank:
    k, t = num_slots, levels * capacity
    return REQBank(
        value=jnp.zeros((k, t), jnp.float32),
        weight=jnp.zeros((k, t), jnp.float32),
        n=jnp.zeros((k, levels), jnp.int32),
        ncomp=jnp.zeros((k,), jnp.int32),
        vmin=jnp.full((k,), _INF, jnp.float32),
        vmax=jnp.full((k,), -_INF, jnp.float32),
        vsum=jnp.zeros((k,), jnp.float32),
        count=jnp.zeros((k,), jnp.float32),
        recip=jnp.zeros((k,), jnp.float32),
        vsum_lo=jnp.zeros((k,), jnp.float32),
        count_lo=jnp.zeros((k,), jnp.float32),
        recip_lo=jnp.zeros((k,), jnp.float32),
    )


def _compact_level(bank: REQBank, lev: int) -> REQBank:
    """One level's compaction, batched over K. A level triggers only
    when its fill crosses TRIG = C - (C-P)/2 (below that it is left
    intact — the lazy schedule that keeps compaction counts bounded
    instead of re-compacting everything every cascade). On trigger,
    the TOP P = 5C/8 items are protected verbatim (the high-ranks-
    accurate section serving the tail percentiles) and the rest
    collapse pairwise into weighted geometric means — exact weight
    conservation, deterministic — which promote one level up (the top
    level promotes into itself). The capacity arithmetic is
    load-bearing: a level starts each cascade below TRIG and receives
    at most (C-P)/2 promotions, and TRIG - 1 + (C-P)/2 <= C, so the
    scatter never spills past the level (the p_ok clamp is a safety
    net, not a steady-state path)."""
    K = bank.num_slots
    L, C = bank.num_levels, bank.capacity
    a = lev * C
    seg_v = bank.value[:, a:a + C]
    seg_w = bank.weight[:, a:a + C]
    # canonical ascending order: live items first by (value, weight),
    # empties keyed +inf last
    kv = jnp.where(seg_w > 0, seg_v, _INF)
    _k, w_s, v_s = jax.lax.sort((kv, seg_w, seg_v), dimension=-1,
                                num_keys=2)
    nl = jnp.sum(seg_w > 0, axis=1).astype(jnp.int32)         # [K]
    P = (5 * C) // 8
    trig = C - (C - P) // 2
    nb = jnp.where(nl >= trig, jnp.clip(nl - P, 0, C), 0)
    nb = nb - (nb & 1)                                        # even
    cols = jnp.arange(C, dtype=jnp.int32)[None, :]

    # survivors of the compacted section: pair (2j, 2j+1) -> one item
    # carrying the pair's summed weight at the pair's weighted
    # GEOMETRIC mean when both members are positive (metric values are
    # overwhelmingly positive and often heavy-tailed/log-symmetric —
    # the arithmetic mean of a wide pair span reads above the span's
    # rank midpoint and biases mid-quantiles high; the geometric mean
    # is the log-space midpoint), falling back to the weighted
    # arithmetic mean when either member is <= 0. Deterministic, so
    # merge stays bit-commutative.
    ev_v, od_v = v_s[:, 0::2], v_s[:, 1::2]
    ev_w, od_w = w_s[:, 0::2], w_s[:, 1::2]
    pw = ev_w + od_w
    safe = jnp.where(pw > 0, pw, 1.0)
    pv_arith = (ev_w * ev_v + od_w * od_v) / safe             # [K, C/2]
    both_pos = (ev_v > 0) & (od_v > 0)
    lv_e = jnp.log(jnp.where(ev_v > 0, ev_v, 1.0))
    lv_o = jnp.log(jnp.where(od_v > 0, od_v, 1.0))
    pv_geo = jnp.exp((ev_w * lv_e + od_w * lv_o) / safe)
    pv = jnp.where(both_pos, pv_geo, pv_arith)
    pj = jnp.arange(C // 2, dtype=jnp.int32)[None, :]
    p_ok = pj < (nb // 2)[:, None]

    # kept items (everything at/after nb) shift to the level's front
    idx = jnp.minimum(cols + nb[:, None], C - 1)
    keep_v = jnp.take_along_axis(v_s, idx, axis=1)
    keep_w = jnp.take_along_axis(w_s, idx, axis=1)
    keepm = cols < (nl - nb)[:, None]
    keep_v = jnp.where(keepm, keep_v, 0.0)
    keep_w = jnp.where(keepm, keep_w, 0.0)
    n_keep = nl - nb

    value = bank.value.at[:, a:a + C].set(keep_v)
    weight = bank.weight.at[:, a:a + C].set(keep_w)
    n = bank.n.at[:, lev].set(n_keep)

    tgt = min(lev + 1, L - 1)
    # for the self-promoting top level, n[:, tgt] was just set to the
    # keep count above, so this reads correctly for both cases
    bbase = n[:, tgt]
    p_ok = p_ok & (bbase[:, None] + pj < C)   # never spill past the level
    T = bank.value.shape[1]
    gcol = jnp.where(p_ok, tgt * C + bbase[:, None] + pj, T)
    rows = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None],
                            gcol.shape)
    value = value.at[rows, gcol].set(jnp.where(p_ok, pv, 0.0),
                                     mode="drop")
    weight = weight.at[rows, gcol].set(jnp.where(p_ok, pw, 0.0),
                                       mode="drop")
    n_add = jnp.sum(p_ok, axis=1).astype(jnp.int32)
    n = n.at[:, tgt].add(n_add)
    return bank._replace(value=value, weight=weight, n=n,
                         ncomp=bank.ncomp + (nb > 0).astype(jnp.int32))


def _compress_impl(bank: REQBank, levels: int, capacity: int) -> REQBank:
    """The full compaction cascade, bottom-up — after it, a full
    level 0 holds <= P items, so the add loop always makes progress."""
    for lev in range(levels):
        bank = _compact_level(bank, lev)
    return bank


def _add_items_impl(bank: REQBank, slots, values, weights,
                    levels: int, capacity: int) -> REQBank:
    """Scatter weighted items into level-0 buffers, compacting on
    overflow (the merge_centroids path: scalars are NOT touched)."""
    K = bank.num_slots
    C = capacity
    values = jnp.where(values == 0.0, 0.0, values)   # -0.0 -> +0.0
    slots = jnp.where(weights > 0, slots, -1)
    s, v, w = scatter.sort_by_slot(slots, values, weights, num_slots=K)
    rank = scatter.run_ranks(s)
    valid = s >= 0
    sc = jnp.where(valid, s, 0)

    def write_pass(bank, written):
        done = scatter.segment_count(s, written & valid, K)
        pos = bank.n[:, 0][sc] + rank - done[sc]
        can = valid & ~written & (pos < C)
        row = jnp.where(can, s, K)
        col = jnp.clip(pos, 0, C - 1)
        value = bank.value.at[row, col].set(v, mode="drop")
        weight = bank.weight.at[row, col].set(w, mode="drop")
        wrote = scatter.segment_count(s, can, K)
        bank = bank._replace(value=value, weight=weight,
                             n=bank.n.at[:, 0].add(wrote))
        return bank, written | can

    def cond(state):
        _, written = state
        return jnp.any(valid & ~written)

    def body(state):
        bank, written = state
        bank, written = write_pass(bank, written)
        leftover = jnp.any(valid & ~written)
        bank = jax.lax.cond(
            leftover,
            lambda b: _compress_impl(b, levels, capacity),
            lambda b: b, bank)
        return bank, written

    def loop_path(bank):
        bank, _ = jax.lax.while_loop(
            cond, body, (bank, jnp.zeros_like(valid)))
        return bank

    def fast_path(bank):
        pos = bank.n[:, 0][sc] + rank
        row = jnp.where(valid, s, K)
        col = jnp.clip(pos, 0, C - 1)
        return bank._replace(
            value=bank.value.at[row, col].set(v, mode="drop"),
            weight=bank.weight.at[row, col].set(w, mode="drop"),
            n=bank.n.at[:, 0].add(batch_per_slot))

    batch_per_slot = scatter.segment_count(s, valid, K)
    overflows = jnp.any(bank.n[:, 0] + batch_per_slot > C)
    return jax.lax.cond(overflows, loop_path, fast_path, bank)


def _add_batch_impl(bank: REQBank, slots, values, weights,
                    levels: int, capacity: int) -> REQBank:
    """Histo.Sample equivalent: exact scalar stats + weighted items."""
    K = bank.num_slots
    valid = slots >= 0
    sd = jnp.where(valid, slots, K)
    bank = base.add_scalar_stats(bank, sd, valid, values, weights)
    return _add_items_impl(bank, slots, values, weights, levels,
                           capacity)


def _quantile_impl(bank: REQBank, qs) -> jax.Array:
    """Batched quantiles over the retained weighted items: per row,
    sort the T items, place item i's mass center at (cum_i - w_i/2)/W
    and interpolate (the same knot scheme as the t-digest quantile,
    with exact min/max endpoints)."""
    K, T = bank.value.shape
    qs = jnp.asarray(qs, bank.value.dtype)
    kv = jnp.where(bank.weight > 0, bank.value, _INF)
    _k, w, v = jax.lax.sort((kv, bank.weight, bank.value), dimension=-1,
                            num_keys=2)
    total = jnp.sum(w, axis=1, keepdims=True)
    safe_total = jnp.where(total > 0, total, 1.0)
    cum = jnp.cumsum(w, axis=1)
    mid_q = (cum - w / 2.0) / safe_total
    mid_q = jnp.where(w > 0, mid_q, 1.0)
    knot_q = jnp.concatenate(
        [jnp.zeros((K, 1), mid_q.dtype), mid_q,
         jnp.full((K, 1), 1.0, mid_q.dtype)], axis=1)
    vmin = jnp.where(jnp.isfinite(bank.vmin), bank.vmin, 0.0)[:, None]
    vmax = jnp.where(jnp.isfinite(bank.vmax), bank.vmax, 0.0)[:, None]
    knot_v = jnp.concatenate(
        [vmin, jnp.where(w > 0, v, vmax), vmax], axis=1)
    out = _interp_knots(knot_q, knot_v, qs)
    # strictly-positive rows interpolate in LOG space (knots are
    # coarse in the averaged mid-range; heavy-tailed metric values
    # track their CDF far better between geometric knots), matching
    # the geometric pair survivors of the compactor
    pos = (bank.vmin > 0) & jnp.isfinite(bank.vmin)
    log_knots = jnp.log(jnp.maximum(knot_v, 1e-37))
    out_log = jnp.exp(_interp_knots(knot_q, log_knots, qs))
    out = jnp.where(pos[:, None], out_log, out)
    return jnp.where(total > 0, out, 0.0)


@dataclass(frozen=True)
class REQEngine:
    levels: int = 2
    capacity: int = 256

    id = "req"
    wire_version = 1
    import_strategy = "direct"    # re-insert foreign items, no clustering
    bank_leaves = ("value", "weight", "n", "ncomp",
                   "vmin", "vmax", "vsum", "count", "recip", "vsum_lo",
                   "count_lo", "recip_lo")
    error_contract = ("~1% relative value error at p99.9 (protected "
                      "tail items are exact samples); mid-range "
                      "distribution-dependent; exact count/sum/min/max")

    def init(self, num_slots: int):
        return init(num_slots, self.levels, self.capacity)

    def add_batch_impl(self, bank, slots, values, weights):
        return _add_batch_impl(bank, slots, values, weights,
                               self.levels, self.capacity)

    def compress_impl(self, bank):
        return _compress_impl(bank, self.levels, self.capacity)

    def merge_centroids_impl(self, bank, slots, means, weights):
        return _add_items_impl(bank, slots, means, weights,
                               self.levels, self.capacity)

    def merge_scalars_impl(self, bank, slots, vmins, vmaxs, vsums,
                           counts, recips):
        return base.merge_scalar_stats(bank, slots, vmins, vmaxs,
                                       vsums, counts, recips)

    def quantile_impl(self, bank, qs):
        return _quantile_impl(bank, qs)

    def aggregates_impl(self, bank):
        return base.scalar_aggregates(bank)

    def forward_leaves(self, bank) -> dict:
        return dict(
            h_mean=bank.value, h_weight=bank.weight,
            h_min=bank.vmin, h_max=bank.vmax,
            h_sum=bank.vsum, h_sum_lo=bank.vsum_lo,
            h_count=bank.count, h_count_lo=bank.count_lo,
            h_recip=bank.recip, h_recip_lo=bank.recip_lo)

    # ---- device-dispatching helpers (module-level jit cache) ----

    def compress(self, bank):
        return _compress_j(bank, self.levels, self.capacity)

    def merge_centroids(self, bank, slots, means, weights):
        return _add_items_j(bank, slots, means, weights, self.levels,
                            self.capacity)

    def merge_scalars(self, bank, slots, vmins, vmaxs, vsums, counts,
                      recips):
        return _merge_scalars_j(bank, slots, vmins, vmaxs, vsums,
                                counts, recips)

    # ---- donation ----

    def donation_split(self):
        """value/weight + the scalars alias h_* outputs verbatim; the
        n/ncomp bookkeeping has no same-shaped output."""
        return (("value", "weight", "vmin", "vmax", "vsum", "count",
                 "recip", "vsum_lo", "count_lo", "recip_lo"),
                ("n", "ncomp"))

    def reassemble(self, core, bufs):
        (value, weight, vmin, vmax, vsum, count, recip,
         vsum_lo, count_lo, recip_lo) = core
        return REQBank(value=value, weight=weight, n=bufs[0],
                       ncomp=bufs[1], vmin=vmin, vmax=vmax, vsum=vsum,
                       count=count, recip=recip, vsum_lo=vsum_lo,
                       count_lo=count_lo, recip_lo=recip_lo)

    # ---- host-level API ----

    def merge_banks(self, a, b):
        """Bit-commutative union: the canonical sort of the two item
        sets is order-independent, ncomp merges by SUM, and the exact
        scalars merge in f64 — merge(a, b) == merge(b, a) bit-for-bit."""
        K, T = a.value.shape
        vals = jnp.concatenate([a.value, b.value], axis=1)
        wts = jnp.concatenate([a.weight, b.weight], axis=1)
        kv = jnp.where(wts > 0, vals, _INF)
        _k, wts, vals = jax.lax.sort((kv, wts, vals), dimension=-1,
                                     num_keys=2)
        out = self.init(K)
        out = out._replace(ncomp=a.ncomp + b.ncomp,
                           **base.merge_scalar_banks_np(a, b))
        C = self.capacity
        slots_flat = jnp.repeat(jnp.arange(K, dtype=jnp.int32), C)
        for c0 in range(0, 2 * T, C):
            chunk = slice(c0, c0 + C)
            out = _add_items_j(out, slots_flat,
                               vals[:, chunk].reshape(-1),
                               wts[:, chunk].reshape(-1),
                               self.levels, self.capacity)
        return out

    def state_bytes(self, num_slots: int = 1) -> int:
        bank = init(1, self.levels, self.capacity)
        per = sum(np.asarray(leaf).nbytes for leaf in bank)
        return per * num_slots


_compress_j = partial(jax.jit,
                      static_argnames=("levels", "capacity"))(
    _compress_impl)
_add_items_j = partial(jax.jit,
                       static_argnames=("levels", "capacity"))(
    _add_items_impl)
_merge_scalars_j = jax.jit(base.merge_scalar_stats)
