"""UltraLogLog set engine — smaller register banks for equal error.

The UltraLogLog sketch (arxiv 2308.16862, Ertl) packs more information
per register than HyperLogLog: each u8 register stores
``u = 4*q + 2*b1 + b2`` where ``q`` is the LARGEST update value seen
(HLL's rho: 1 + leading zeros of the hash remainder) and the two low
bits record whether updates with values ``q-1`` (b1) and ``q-2`` (b2)
were ALSO seen. The retained event set is exact: an event at level k
survives every later max m' <= k+2, and the final max IS the largest
level, so (q, b1, b2) always reports E_q / E_{q-1} / E_{q-2} truthfully
(lower levels are forgotten). That extra information lets m = 2^13
registers match the estimation error of HLL's 2^14 — the ~28%-state
claim of the paper; in THIS repo's u8-register layout the bank is
literally half the bytes (8 KiB vs 16 KiB per slot) for the same
nominal ~1% error class, which shrinks forward-wire bytes, journal/
checkpoint bytes, and register-bank HBM alike.

Register update/merge is a lattice JOIN, not an elementwise max (the
state space is only partially ordered: (q=5,b=00) and (q=4,b=11) have
no order), so the insert kernel cannot ride a scatter-max. Batched
insert instead sorts the batch by flat register address, collapses
duplicates with a segmented associative-scan of the join, and lands
the now-unique updates with one gather-join-scatter — O(batch log
batch) on device, deterministic (unique scatter indices).

Estimation is the paper's ML estimator, split across the flush
contract: the DEVICE half reduces the register file to a per-slot
value histogram (u8 -> [K, 256] counts, one bincount — the only part
that touches the m-wide state); the HOST half solves the 1-D Poisson
maximum-likelihood per slot by vectorized geometric bisection over the
sufficient statistics. Under the standard Poisson model each register
contributes independent evidence: no event above q (prob e^{-lam z},
z = 2^-q), the event at q, and Bernoulli evidence at q-1 / q-2 from
the indicator bits; the derivative in lam is monotone, so bisection is
exact to float precision. Measured relative stderr at m = 8192 is
~0.85% (tests/test_sketches.py pins a 4-sigma bound).

Incremental-flush contract (sketches/base.py): _value_counts vmaps
per row and ml_estimate solves per slot — both row-independent and
shape-generic in K — and an all-zero register row yields the constant
baseline (counts[0] = m, estimate 0), so the [D, m] dirty-slice
evaluation is exact; only active rows reach the host ML solve either
way.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class ULLBank(NamedTuple):
    registers: jax.Array   # u8[K, m], m = 2^precision

    @property
    def num_slots(self):
        return self.registers.shape[0]

    @property
    def num_registers(self):
        return self.registers.shape[1]


def _join_i32(u, v):
    """Elementwise ULL register join on i32 operands (commutative,
    associative, idempotent — the lattice union of retained events)."""
    qu, qv = u >> 2, v >> 2
    qm = jnp.maximum(qu, qv)

    def ev(x, q, k):
        # does register x (max q) prove an event at level k >= 1?
        b1 = (x >> 1) & 1
        b2 = x & 1
        return ((q >= 1) & (k >= 1)
                & ((q == k) | ((q == k + 1) & (b1 == 1))
                   | ((q == k + 2) & (b2 == 1))))

    b1 = ev(u, qu, qm - 1) | ev(v, qv, qm - 1)
    b2 = ev(u, qu, qm - 2) | ev(v, qv, qm - 2)
    out = (qm << 2) | (b1.astype(jnp.int32) << 1) | b2.astype(jnp.int32)
    return jnp.where(qm > 0, out, 0)


def join_registers_np(a, b) -> np.ndarray:
    """Numpy twin of the register join (spill re-merge, oracle tests)."""
    u = np.asarray(a, np.uint8).astype(np.int32)
    v = np.asarray(b, np.uint8).astype(np.int32)
    qu, qv = u >> 2, v >> 2
    qm = np.maximum(qu, qv)

    def ev(x, q, k):
        return ((q >= 1) & (k >= 1)
                & ((q == k) | ((q == k + 1) & ((x >> 1) & 1 == 1))
                   | ((q == k + 2) & (x & 1 == 1))))

    b1 = ev(u, qu, qm - 1) | ev(v, qv, qm - 1)
    b2 = ev(u, qu, qm - 2) | ev(v, qv, qm - 2)
    out = (qm << 2) | (b1.astype(np.int32) << 1) | b2.astype(np.int32)
    return np.where(qm > 0, out, 0).astype(np.uint8)


def _insert_impl(bank: ULLBank, slots, reg_idx, vals) -> ULLBank:
    """Batched insert: join `vals` (pre-packed 4*q register values)
    into registers[slot, reg_idx]. slot == -1 marks padding. Duplicate
    (slot, idx) targets are collapsed with a segmented scan BEFORE the
    scatter so every landed index is unique (deterministic)."""
    K, m = bank.registers.shape
    n = slots.shape[0]
    valid = slots >= 0
    oob = jnp.uint32(K * m)
    flat = jnp.where(valid,
                     slots.astype(jnp.uint32) * jnp.uint32(m)
                     + reg_idx.astype(jnp.uint32),
                     oob)
    order = jnp.argsort(flat)
    f = flat[order]
    v = vals[order].astype(jnp.int32)

    def comb(a, b):
        fa, va = a
        fb, vb = b
        return fb, jnp.where(fa == fb, _join_i32(va, vb), vb)

    ff, vv = jax.lax.associative_scan(comb, (f, v))
    last = jnp.concatenate([ff[1:] != ff[:-1],
                            jnp.ones((1,), jnp.bool_)])
    live = last & (ff < oob)
    rows = jnp.where(live, (ff // jnp.uint32(m)).astype(jnp.int32), K)
    cols = jnp.where(live, (ff % jnp.uint32(m)).astype(jnp.int32), 0)
    cur = bank.registers[jnp.minimum(rows, K - 1), cols].astype(jnp.int32)
    joined = _join_i32(cur, vv).astype(jnp.uint8)
    return ULLBank(registers=bank.registers.at[rows, cols].set(
        joined, mode="drop"))


def _merge_rows_impl(bank: ULLBank, slots, registers) -> ULLBank:
    """Union forwarded register rows into local slots (the Combine
    path). `registers` is u8[n, m]; slots[n] == -1 padding; duplicate
    slots in one batch are pre-joined with a segmented scan so the row
    scatter lands unique indices."""
    K = bank.num_slots
    s = jnp.where(slots >= 0, slots, K)
    order = jnp.argsort(s)
    s = s[order]
    regs = registers[order].astype(jnp.int32)

    def comb(a, b):
        sa, va = a
        sb, vb = b
        return sb, jnp.where(sa == sb, _join_i32(va, vb), vb)

    ss, vv = jax.lax.associative_scan(
        comb, (s[:, None].astype(jnp.int32), regs))
    ss = ss[:, 0]
    last = jnp.concatenate([ss[1:] != ss[:-1],
                            jnp.ones((1,), jnp.bool_)])
    row = jnp.where(last & (ss < K), ss, K)
    cur = bank.registers[jnp.minimum(row, K - 1), :].astype(jnp.int32)
    joined = _join_i32(cur, vv).astype(jnp.uint8)
    return ULLBank(registers=bank.registers.at[row, :].set(
        joined, mode="drop"))


# module-level jit: one trace/compile per shape, shared by every
# engine instance (a per-call jax.jit wrapper would retrace each flush)
_merge_rows_j = jax.jit(_merge_rows_impl)


@jax.jit
def _value_counts(registers) -> jax.Array:
    """u8[K, m] -> i32[K, 256] per-slot register-value histogram — the
    ML estimator's sufficient statistic (the device half of estimate)."""
    return jax.vmap(
        lambda r: jnp.bincount(r.astype(jnp.int32), length=256))(
        registers).astype(jnp.int32)


@lru_cache(maxsize=None)
def _ml_terms():
    """Per-register-value likelihood terms: Z[256, 4] probability
    weights, OBS[256, 4] observed flags, MASK[256, 4] validity."""
    Z = np.zeros((256, 4))
    OBS = np.zeros((256, 4), bool)
    MASK = np.zeros((256, 4), bool)
    for u in range(256):
        q, b1, b2 = u >> 2, (u >> 1) & 1, u & 1
        terms = []
        if u == 0:
            terms.append((1.0, False))        # no event at any level
        elif q >= 1:
            terms.append((2.0 ** -q, False))  # nothing above q
            terms.append((2.0 ** -q, True))   # the max event itself
            if q >= 2:
                terms.append((2.0 ** -(q - 1), bool(b1)))
            if q >= 3:
                terms.append((2.0 ** -(q - 2), bool(b2)))
        for t, (z, obs) in enumerate(terms):
            Z[u, t] = z
            OBS[u, t] = obs
            MASK[u, t] = True
    return Z, OBS, MASK


def ml_estimate(counts, num_registers: int) -> np.ndarray:
    """Per-slot ML cardinality from register-value histograms
    (i32[K, 256] -> f64[K]). Solves d/dlam log-likelihood = 0 by
    vectorized geometric bisection (the derivative is strictly
    decreasing in lam); estimate = lam * m. Cost is bounded for the
    flush path: only slots with any nonzero register are solved, the
    observed-event terms collapse onto the <= ~60 distinct probability
    weights (z = 2^-k), and 40 bisection steps reach ~1e-8 relative
    resolution — far inside the sketch's own ~1% noise."""
    counts = np.asarray(counts, np.float64)
    K = counts.shape[0]
    m = float(num_registers)
    out = np.zeros(K)
    active = counts[:, 0] < m                 # any nonzero register
    if not active.any():
        return out
    c_all = counts[active]                    # [A, 256]
    Z, OBS, MASK = _ml_terms()
    used = np.nonzero(c_all.sum(axis=0) > 0)[0]
    c = c_all[:, used]                        # [A, U]
    z = Z[used]
    obs = OBS[used] & MASK[used]
    unobs = (~OBS[used]) & MASK[used]
    # constant part of the derivative: -sum of unobserved weights
    neg = -(c @ (z * unobs).sum(axis=1))      # [A]
    # group observed terms by their (few) distinct z values:
    # f(lam) = sum_z wz * z/expm1(lam*z) + neg
    zvals = np.unique(z[obs])                 # [nz]
    A_map = np.zeros((len(used), len(zvals)))
    for t in range(4):
        col = np.searchsorted(zvals, z[:, t])
        ok = obs[:, t] & (col < len(zvals))
        np.add.at(A_map, (np.nonzero(ok)[0], col[ok]), 1.0)
    wz = c @ A_map                            # [A, nz]

    lo = np.full(c.shape[0], 2.0 ** -40)
    hi = np.full(c.shape[0], 2.0 ** 44)
    with np.errstate(over="ignore", divide="ignore", invalid="ignore"):
        for _ in range(40):
            lam = np.sqrt(lo * hi)
            lz = np.minimum(lam[:, None] * zvals[None, :], 700.0)
            f = (wz * (zvals[None, :] / np.expm1(lz))).sum(axis=1) + neg
            bigger = f > 0                    # root is above lam
            lo = np.where(bigger, lam, lo)
            hi = np.where(bigger, hi, lam)
    out[active] = np.sqrt(lo * hi) * m
    return out


@dataclass(frozen=True)
class ULLEngine:
    precision: int = 13

    id = "ull"
    wire_version = 1
    bank_leaves = ("registers",)
    error_contract = ("ML estimation, relative stderr ~0.85% at "
                      "p=13 (8 KiB/slot — half the HLL p=14 bank)")

    @property
    def num_registers(self) -> int:
        return 1 << self.precision

    def init(self, num_slots: int):
        return ULLBank(registers=jnp.zeros(
            (num_slots, self.num_registers), jnp.uint8))

    def insert_impl(self, bank, slots, reg_idx, vals):
        return _insert_impl(bank, slots, reg_idx, vals)

    def insert_fused_impl(self, bank, slots, reg_idx, vals,
                          interpret: bool):
        """The Pallas scatter-join insert arm (ISSUE 15): one in-place
        read-join-write pass over the batch, replacing the XLA
        sort + segmented-scan + gather path — register-byte-identical
        (the join is associative/commutative/idempotent, so any
        application order folds to the same lattice value; pinned by
        tests/test_pallas.py). The ingest executable selects this when
        the resolved kernel arm is fused/interpret."""
        from ..kernels import ull_insert as kinsert
        return kinsert.fused_insert(bank, slots, reg_idx, vals,
                                    interpret)

    def merge_rows_impl(self, bank, slots, registers):
        return _merge_rows_impl(bank, slots, registers)

    def merge_rows(self, bank, slots, registers):
        return _merge_rows_j(bank, slots, registers)

    def merge_banks(self, a, b):
        return ULLBank(registers=_join_i32(
            a.registers.astype(jnp.int32),
            b.registers.astype(jnp.int32)).astype(jnp.uint8))

    def hash_update(self, h: int) -> tuple:
        """(register index, packed 4*q update value) from one 64-bit
        member hash — same index/rank decomposition as HLL, packed
        into the ULL register encoding."""
        p = self.precision
        idx = h >> (64 - p)
        rest = ((h << p) & 0xFFFFFFFFFFFFFFFF) | ((1 << p) - 1)
        q = 65 - rest.bit_length()
        return idx, q << 2

    def host_hash_to_updates(self, hashes64):
        from ..ops import hll as _hll
        idx, rho = _hll.host_hash_to_updates(hashes64, self.precision)
        return idx, (rho.astype(np.int32) << 2).astype(np.uint8)

    def estimate_device(self, bank, pallas_ok: bool) -> dict:
        return {"s_counts": _value_counts(bank.registers)}

    def estimate_finalize(self, host: dict) -> None:
        counts = host.pop("s_counts")
        host["s_est"] = ml_estimate(counts, self.num_registers).astype(
            np.float32)

    def merge_registers_np(self, a, b):
        return join_registers_np(a, b)

    def nominal_error(self) -> float:
        # measured ML-estimator stderr constant (~0.76/sqrt(m)); the
        # oracle suite pins a 4-sigma bound on a fixed stream
        return 0.76 / (self.num_registers ** 0.5)

    def state_bytes(self, num_slots: int = 1) -> int:
        return num_slots * self.num_registers
