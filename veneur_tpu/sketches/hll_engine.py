"""The default set engine: batched HyperLogLog register banks.

Adapter over `ops/hll.py` presenting the set-engine contract of
`sketches/base.py`. Selecting `set_backend: hll` (the default) is
behavior-identical to the pre-registry tree.

Error contract: LogLog-Beta estimation, relative standard error
~1.04/sqrt(m) (~0.81% at the default precision 14). State: m = 2^p
u8 registers per slot (16 KiB at p=14).

Incremental-flush contract (sketches/base.py): the register
scatter-max and the LogLog-Beta estimate are per-row and
shape-generic in K, and an all-zero row estimates to the constant
baseline 0.0 — the [D, m] dirty-slice evaluation is exact.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops import hll


@dataclass(frozen=True)
class HLLEngine:
    precision: int = 14

    id = "hll"
    wire_version = 1
    bank_leaves = ("registers",)
    error_contract = "relative stderr ~1.04/sqrt(m) (LogLog-Beta)"

    @property
    def num_registers(self) -> int:
        return 1 << self.precision

    def init(self, num_slots: int):
        return hll.init(num_slots, self.precision)

    def insert_impl(self, bank, slots, reg_idx, vals):
        return hll.insert.__wrapped__(bank, slots, reg_idx, vals)

    def merge_rows_impl(self, bank, slots, registers):
        return hll.merge_rows.__wrapped__(bank, slots, registers)

    def merge_rows(self, bank, slots, registers):
        return hll.merge_rows(bank, slots, registers)

    def merge_banks(self, a, b):
        return hll.merge_banks(a, b)

    def hash_update(self, h: int) -> tuple:
        """(register index, rho) from one 64-bit member hash — the
        per-sample ingest hot path (python ints, no numpy)."""
        p = self.precision
        idx = h >> (64 - p)
        rest = ((h << p) & 0xFFFFFFFFFFFFFFFF) | ((1 << p) - 1)
        rho = 65 - rest.bit_length()   # clz + 1; sentinel caps range
        return idx, rho

    def host_hash_to_updates(self, hashes64):
        return hll.host_hash_to_updates(hashes64, self.precision)

    def estimate_device(self, bank, pallas_ok: bool) -> dict:
        return {"s_est": hll.estimate(bank, force_jnp=not pallas_ok)}

    def estimate_finalize(self, host: dict) -> None:
        host["s_est"] = np.asarray(host["s_est"])

    def merge_registers_np(self, a, b):
        """Host-side register union (spill re-merge path)."""
        return np.maximum(np.asarray(a, np.uint8),
                          np.asarray(b, np.uint8))

    def nominal_error(self) -> float:
        return 1.04 / (self.num_registers ** 0.5)

    def state_bytes(self, num_slots: int = 1) -> int:
        return num_slots * self.num_registers
