"""The default histogram engine: batched merging t-digest banks.

A thin adapter over `ops/tdigest.py` (which stays the single home of
the centroid math and the SR02 ordering invariant) presenting the
engine contract of `sketches/base.py`. Selecting
`histogram_backend: tdigest` (the default) routes every pipeline call
through this object with behavior identical to the pre-registry tree —
the exactly-once / overload / kill-restart chaos suites run unmodified
against it.

Error contract: t-digest bounds ABSOLUTE rank error (~1/compression of
total rank per cluster, k1 tail-dense); value error at a quantile
follows the local density. count/sum/min/max are exact.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops import tdigest


@dataclass(frozen=True)
class TDigestEngine:
    compression: float = 100.0
    buffer_depth: int = 256

    id = "tdigest"
    wire_version = 1
    import_strategy = "cluster"   # precluster foreign piles (cluster_rows)
    bank_leaves = ("mean", "weight", "buf_value", "buf_weight", "buf_n",
                   "vmin", "vmax", "vsum", "count", "recip", "vsum_lo",
                   "count_lo", "recip_lo")
    error_contract = ("absolute rank error ~1/compression per cluster "
                      "(k1 tail-dense); exact count/sum/min/max")

    # ---- pure, jit-composable ops ----

    def init(self, num_slots: int):
        return tdigest.init(num_slots, self.compression,
                            self.buffer_depth)

    def add_batch_impl(self, bank, slots, values, weights):
        return tdigest._add_batch_impl(bank, slots, values, weights,
                                       self.compression)

    def compress_impl(self, bank):
        return tdigest._compress_impl(bank, self.compression)

    def compress_fused_impl(self, bank, interpret: bool):
        """The fused-kernel compress arm (ISSUE 15): one Pallas
        dispatch over the bank — sort + rank-merge + cluster with
        VMEM-resident intermediates — bit-identical to compress_impl
        (tests/test_pallas.py pins it). The flush program body selects
        this when the resolved kernel arm is fused/interpret."""
        from ..kernels import compress as kcompress
        return kcompress.fused_compress_bank(bank, self.compression,
                                             interpret)

    def merge_centroids_impl(self, bank, slots, means, weights):
        # caller compresses first (buffer headroom), like the ops
        # module's contract
        return tdigest.merge_centroids.__wrapped__(bank, slots, means,
                                                   weights)

    def merge_scalars_impl(self, bank, slots, vmins, vmaxs, vsums,
                           counts, recips):
        return tdigest.merge_scalars.__wrapped__(
            bank, slots, vmins, vmaxs, vsums, counts, recips)

    def quantile_impl(self, bank, qs):
        return tdigest.quantile.__wrapped__(bank, qs)

    def aggregates_impl(self, bank):
        return tdigest.aggregates.__wrapped__(bank)

    def forward_leaves(self, bank) -> dict:
        return dict(
            h_mean=bank.mean, h_weight=bank.weight,
            h_min=bank.vmin, h_max=bank.vmax,
            h_sum=bank.vsum, h_sum_lo=bank.vsum_lo,
            h_count=bank.count, h_count_lo=bank.count_lo,
            h_recip=bank.recip, h_recip_lo=bank.recip_lo)

    # ---- device-dispatching helpers (module-level jits) ----

    def compress(self, bank):
        return tdigest.compress(bank, compression=self.compression)

    def merge_centroids(self, bank, slots, means, weights):
        return tdigest.merge_centroids(bank, slots, means, weights)

    def merge_scalars(self, bank, slots, vmins, vmaxs, vsums, counts,
                      recips):
        return tdigest.merge_scalars(bank, slots, vmins, vmaxs, vsums,
                                     counts, recips)

    def cluster_rows(self, values, weights, num_centroids: int,
                     sorted_prefix: int = 0):
        return tdigest.cluster_rows(values, weights,
                                    compression=self.compression,
                                    num_centroids=num_centroids,
                                    sorted_prefix=sorted_prefix)

    # ---- donation (the fwd_out split the flush executable uses) ----

    def donation_split(self):
        """mean/weight + the eight scalar leaves alias h_* outputs of
        identical shape; the buffer leaves never do (donating them
        would bring the partial-donation warning back)."""
        return (("mean", "weight", "vmin", "vmax", "vsum", "count",
                 "recip", "vsum_lo", "count_lo", "recip_lo"),
                ("buf_value", "buf_weight", "buf_n"))

    def reassemble(self, core, bufs):
        (mean, weight, vmin, vmax, vsum, count, recip,
         vsum_lo, count_lo, recip_lo) = core
        # vlint: disable=SR02 reason=reassembling the caller's own bank
        # from its unmodified leaves — centroid order is untouched
        return tdigest.TDigestBank(
            mean=mean, weight=weight, buf_value=bufs[0],
            buf_weight=bufs[1], buf_n=bufs[2], vmin=vmin, vmax=vmax,
            vsum=vsum, count=count, recip=recip, vsum_lo=vsum_lo,
            count_lo=count_lo, recip_lo=recip_lo)

    # ---- host-level API ----

    def merge_banks(self, a, b):
        """Bit-commutative union for the cross-engine property suite
        (ops/tdigest.merge_banks owns the canonical-sort + recluster)."""
        return tdigest.merge_banks(a, b, compression=self.compression)

    def state_bytes(self, num_slots: int = 1) -> int:
        bank = tdigest.init(1, self.compression, self.buffer_depth)
        per = sum(np.asarray(leaf).nbytes for leaf in bank)
        return per * num_slots
