"""Unified egress resilience: retry, circuit breaking, sketch re-merge.

Every network egress in the pipeline — the vendor sinks, both cluster
forwarders, the proxy fan-out — routes its wire calls through an
`Egress` from this module instead of raw urllib/grpc (vlint RS01
enforces this). The layer owns three behaviors the call sites used to
lack:

  * **Retry with full-jitter exponential backoff** under a per-flush
    deadline budget: a transient failure (timeout, 5xx, connection
    refused, UNAVAILABLE) is retried up to `max_attempts` times with
    `delay ~ U(0, min(cap, base * 2^attempt))`, and the whole call —
    attempts plus backoff sleeps plus per-attempt socket timeouts —
    never exceeds `deadline_s`, so one wedged vendor cannot push the
    flush tick late.

  * **A per-destination circuit breaker**, so a dead endpoint costs one
    fast rejection per flush instead of a full retry ladder:

        closed ──(failure_threshold consecutive failed calls)──▶ open
          ▲                                                   │
          │                              (open_duration_s elapses)
          │                                                   ▼
          └──(half_open_successes probe successes)──── half-open
                       half-open ──(probe failure)──▶ open (timer
                                                      restarts)

    Half-open admits ONE in-flight probe at a time; concurrent callers
    are rejected until the probe resolves.

  * **An exactly-once spill/replay ledger** (`ResilientForwarder` +
    `SpillBuffer`): every interval's forward is stamped with an
    idempotency envelope (`ForwardEnvelope`: stable sender_id,
    monotonic interval_seq, chunk ids). When a forward fails
    terminally, the interval's `ForwardExport` sketches are NOT
    dropped — they are parked in a bounded replay ledger KEEPING their
    original envelope, and replayed oldest-first ahead of the next
    interval's send. The receiving global tier keeps a per-sender
    dedupe ledger (`cluster.importsrv.DedupeLedger`) and drops any
    chunk it already Combined, so an *ambiguous* failure (body
    applied, response lost) followed by a retry or replay cannot
    double-count. Ledger overflow demotes the oldest intervals into
    the same-key-merged `SpillBuffer` overflow tier (centroids
    concatenate, HLL registers fold by max, counters sum — lossless),
    whose contents ride the next interval's fresh envelope: those
    sketches degrade to at-least-once, counted as `reenveloped`.
    Gauges are last-write-wins and only meaningful fresh, so they ride
    along for `gauge_max_age_intervals` failed intervals and are then
    evicted (counted). The sketch budget bounds both tiers; overflow
    evicts oldest sketches first, also counted.

Everything observable is counted per destination in a
`ResilienceRegistry`; the server drains it each flush into
`veneur.resilience.*_total` self-metrics. The clock, sleep, RNG, and
transport are all injectable, so `utils/faults.py` can script every
retry/breaker/re-merge transition deterministically — no sockets, no
real sleeps.
"""

from __future__ import annotations

import logging
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

import numpy as np

from .observe.recorder import current_scope as _current_scope
from .observe.registry import DEFAULT_REGISTRY  # noqa: F401  (re-export)
from .observe.registry import TelemetryRegistry as ResilienceRegistry

log = logging.getLogger("veneur_tpu.resilience")


# --------------------------------------------------------------- errors

class EgressError(Exception):
    """Base for resilience-layer errors."""


class TransientEgressError(EgressError):
    """Marker for failures the retry loop should retry."""


class TerminalEgressError(EgressError):
    """Marker for failures that must not be retried."""


class CircuitOpenError(EgressError):
    """The destination's breaker is open; the call was not attempted."""


class PartialDeliveryError(EgressError):
    """Part of an export was delivered before a terminal failure; only
    `undelivered` may be spilled for re-merge — re-sending the whole
    export would double-count counters at the receiver's Combine.
    `delivered_chunks`/`chunk_count` record where in the interval's
    chunk sequence the failure hit, so the replay can resend the tail
    under the SAME chunk ids (the receiver's dedupe ledger then drops
    a chunk that was ambiguously applied before the failure)."""

    def __init__(self, undelivered, cause: BaseException | None = None,
                 delivered_chunks: int = 0, chunk_count: int = 0):
        super().__init__(f"partial delivery: {cause}")
        self.undelivered = undelivered
        self.delivered_chunks = delivered_chunks
        self.chunk_count = chunk_count


class DeltaGapRefusedError(TerminalEgressError):
    """The receiver refused a DELTA chunk because the sender's seq
    chain has a gap below it (or the receiver has no baseline for this
    sender at all — a restart without durable watermarks). Raised by
    the leaf forwarders when they recognize the refusal on the wire
    (HTTP 409 / gRPC FAILED_PRECONDITION "delta-over-gap"); the
    ResilientForwarder catches it and, instead of parking a delta that
    would be refused forever, spills the payload into the merged
    overflow tier and forces the next interval to be a FULL resync —
    the refused delta was never applied (refusal precedes decode), so
    no data is lost and nothing double-counts."""


class HTTPStatusError(EgressError):
    """A transport returned an HTTP error status without raising (fake
    transports and non-urllib stacks); retryability follows the code."""

    def __init__(self, destination: str, status: int):
        super().__init__(f"{destination}: HTTP {status}")
        self.status = status


_RETRYABLE_HTTP = (408, 429)


def is_retryable(exc: BaseException) -> bool:
    """Classify one attempt's failure. Retryable: timeouts, connection
    errors, HTTP 5xx/408/429, URLErrors (DNS, refused-inside-urllib),
    and the transient gRPC codes. Terminal: HTTP 4xx (the payload or
    auth is wrong — retrying re-fails), INVALID_ARGUMENT-class gRPC
    codes, and anything unrecognized (fail fast, count, spill)."""
    if isinstance(exc, TransientEgressError):
        return True
    if isinstance(exc, TerminalEgressError):
        return False
    if isinstance(exc, CircuitOpenError):
        # an open breaker is a transient condition for OUTER callers
        # deciding whether to buffer/requeue (Egress.call itself never
        # classifies it — rejection happens before any attempt)
        return True
    if isinstance(exc, HTTPStatusError):
        return exc.status >= 500 or exc.status in _RETRYABLE_HTTP
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 or exc.code in _RETRYABLE_HTTP
    # HTTPError subclasses URLError — this arm must come second
    if isinstance(exc, urllib.error.URLError):
        return True
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return True
    try:
        import grpc
    except ImportError:         # pragma: no cover - grpc ships in-image
        grpc = None
    if grpc is not None and isinstance(exc, grpc.RpcError):
        code = exc.code() if callable(getattr(exc, "code", None)) else None
        return code in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        grpc.StatusCode.ABORTED,
                        grpc.StatusCode.UNKNOWN)
    if isinstance(exc, OSError):
        return True
    return False


# ------------------------------------------------------------ envelope

@dataclass(frozen=True)
class ForwardEnvelope:
    """Idempotency identity of one interval's forward. The leaf
    forwarder stamps every wire chunk it emits with
    (sender_id, interval_seq, chunk_offset + j, chunk_count) — the
    receiver's dedupe ledger drops a chunk it has already Combined, so
    a retry or replay after an ambiguous failure (body applied,
    response lost) cannot double-count. chunk_count == 0 lets the leaf
    compute the total from its own chunking (the whole-interval case);
    a replayed partial tail carries the ORIGINAL total so its chunk ids
    line up with what the receiver already saw.

    `trace_id`/`span_id`/`close_ns` are the fleet-tracing context
    riding ALONGSIDE the identity (cluster/wire.py owns the wire
    encoding): the sender's flush-tick trace + root span id — so the
    receiver's import spans parent on the remote flush — and the
    interval-close wall time feeding the global's e2e latency. Zeros
    mean "no context" (recorder off, legacy sender) and encode to
    nothing; the dedupe path never reads them.

    `kind` is the delta-forwarding marker (ISSUE 13): "full" (the
    complete active sketch set — encodes to NOTHING, so legacy wire
    chunks stay byte-identical) or "delta" (only the sketches the
    dirty-slot bitmap saw touched this interval; the receiver applies
    it only over an unbroken seq chain)."""

    sender_id: str
    interval_seq: int
    chunk_offset: int = 0
    chunk_count: int = 0
    trace_id: int = 0
    span_id: int = 0
    close_ns: int = 0
    kind: str = "full"


def accepts_envelope(fn) -> bool:
    """Does a forwarder callable take an `envelope=` kwarg? Cached on
    the callable; plain test doubles and legacy forwarders that only
    take (export) keep working — they just forward un-enveloped
    (receiver applies everything: at-least-once, the old contract)."""
    # cache on the underlying function for bound methods — a method
    # object is recreated on every attribute access (and refuses
    # attribute writes), so caching on `fn` itself would re-run
    # signature introspection per call on the proxy fan-out hot path
    target = getattr(fn, "__func__", fn)
    cached = getattr(target, "_veneur_accepts_envelope", None)
    if cached is None:
        import inspect
        try:
            params = inspect.signature(fn).parameters.values()
            cached = any(p.name == "envelope"
                         or p.kind == p.VAR_KEYWORD for p in params)
        except (TypeError, ValueError):
            cached = False
        try:
            target._veneur_accepts_envelope = cached
        except AttributeError:
            pass
    return cached


def new_sender_id(hostname: str = "") -> str:
    """Default forward sender id: unique per process incarnation so a
    restart cannot collide with its predecessor's ledger entries (the
    old id's receiver state just ages out via the dedupe TTL)."""
    import os
    import uuid
    base = hostname or "veneur"
    return f"{base}-{os.getpid()}-{uuid.uuid4().hex[:8]}"


# ------------------------------------------------------------- policies

@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    base_backoff_s: float = 0.2
    max_backoff_s: float = 5.0
    # per-call (≈ per-flush, per-destination) wall budget: attempts,
    # socket timeouts and backoff sleeps all draw from it
    deadline_s: float = 8.0


@dataclass(frozen=True)
class BreakerPolicy:
    failure_threshold: int = 5
    open_duration_s: float = 30.0
    half_open_successes: int = 1


@dataclass(frozen=True)
class EgressPolicy:
    retry: RetryPolicy = RetryPolicy()
    breaker: BreakerPolicy = BreakerPolicy()


DEFAULT_POLICY = EgressPolicy()


def policy_from_config(cfg) -> EgressPolicy:
    """Build the shared egress policy from the Config knobs."""
    from .config import _parse_interval
    return EgressPolicy(
        retry=RetryPolicy(
            max_attempts=max(1, cfg.retry_max_attempts),
            base_backoff_s=_parse_interval(cfg.retry_backoff_base),
            max_backoff_s=_parse_interval(cfg.retry_backoff_cap),
            deadline_s=_parse_interval(cfg.retry_deadline)),
        breaker=BreakerPolicy(
            failure_threshold=max(1, cfg.breaker_failure_threshold),
            open_duration_s=_parse_interval(cfg.breaker_open_duration),
            half_open_successes=max(1, cfg.breaker_half_open_successes)))


# ------------------------------------------------------------- registry
#
# The per-destination counter registry grew into the process-wide
# telemetry spine (observe/registry.py) — one registry class for the
# egress counters here, the durability journal counters, AND the
# server's own accounting, with the veneur.* name mapping owned by the
# observe module (vlint TL01). `ResilienceRegistry` (imported at the
# top of this module) stays exported under the historical name; the
# contracts of incr/take/peek are unchanged.


# -------------------------------------------------------------- breaker

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-destination breaker (state diagram in the module docstring).
    Thread-safe: sinks flush on their own threads and the proxy fans
    out concurrently."""

    def __init__(self, destination: str = "", policy: BreakerPolicy
                 | None = None, clock=time.monotonic,
                 registry: ResilienceRegistry | None = None):
        self.destination = destination
        self.policy = policy or BreakerPolicy()
        self._clock = clock
        self._registry = registry or DEFAULT_REGISTRY
        self._lock = threading.Lock()
        self._state = _CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._half_open_successes = 0
        self._probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call proceed now? Open→half-open transition happens
        here (lazily, on the first allow() after the cooldown)."""
        with self._lock:
            if self._state == _CLOSED:
                return True
            if self._state == _OPEN:
                if (self._clock() - self._opened_at
                        >= self.policy.open_duration_s):
                    self._state = _HALF_OPEN
                    self._half_open_successes = 0
                    self._probe_inflight = False
                else:
                    return False
            # half-open: admit one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self):
        with self._lock:
            if self._state == _HALF_OPEN:
                self._probe_inflight = False
                self._half_open_successes += 1
                if (self._half_open_successes
                        >= self.policy.half_open_successes):
                    self._state = _CLOSED
            self._consecutive_failures = 0

    def record_failure(self):
        with self._lock:
            if self._state == _HALF_OPEN:
                self._probe_inflight = False
                self._trip_locked()
                return
            self._consecutive_failures += 1
            if (self._state == _CLOSED and self._consecutive_failures
                    >= self.policy.failure_threshold):
                self._trip_locked()

    def _trip_locked(self):
        self._state = _OPEN
        self._opened_at = self._clock()
        self._consecutive_failures = 0
        self._registry.incr(self.destination, "breaker_opened")


# --------------------------------------------------------------- egress

def _default_transport(req, timeout=None):
    """The layer's single raw HTTP call. urllib raises HTTPError for
    4xx/5xx, which is_retryable classifies by code."""
    return urllib.request.urlopen(req, timeout=timeout)


def grpc_channel(address: str):
    """The project's single gRPC channel constructor — egress channels
    are created here so raw grpc.insecure_channel calls elsewhere are
    vlint-RS01 strays."""
    import grpc
    return grpc.insecure_channel(address)


class Egress:
    """One destination's resilient call wrapper: breaker consult, retry
    with full-jitter backoff, deadline budget, telemetry. Clock/sleep/
    rng/transport are injectable for the fault harness."""

    def __init__(self, destination: str,
                 policy: EgressPolicy | None = None,
                 breaker: CircuitBreaker | None = None,
                 transport=None, clock=time.monotonic,
                 sleep=time.sleep, rng: random.Random | None = None,
                 registry: ResilienceRegistry | None = None):
        self.destination = destination
        self.policy = policy or DEFAULT_POLICY
        self.registry = registry or DEFAULT_REGISTRY
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._transport = transport or _default_transport
        self.breaker = breaker or CircuitBreaker(
            destination, self.policy.breaker, clock=clock,
            registry=self.registry)

    # -- generic call wrapper --

    def deadline(self) -> float:
        """An absolute deadline one policy budget from now — pass it to
        several call()s (e.g. the batches of one flush) so they share
        ONE budget instead of each getting its own."""
        return self._clock() + self.policy.retry.deadline_s

    def call(self, fn, *args, timeout_s: float | None = None,
             deadline: float | None = None, **kwargs):
        """Run fn(*args, **kwargs) under retry/breaker/deadline. When
        `timeout_s` is given, each attempt receives a `timeout=` kwarg
        clamped to min(timeout_s, remaining deadline budget), so socket
        timeouts can never overrun the flush budget.

        The breaker is consulted ONCE, at call start, and records the
        call's FINAL outcome: the retry ladder is one logical delivery,
        so breaker_failure_threshold counts failed deliveries — a
        threshold <= max_attempts cannot cut retries short or mask the
        underlying error with CircuitOpenError mid-ladder."""
        retry = self.policy.retry
        reg, dest = self.registry, self.destination
        # flight-recorder attribution: when a flush tick is in progress
        # on THIS thread (the forward path), every attempt/backoff gets
        # its own phase; egress from other threads (span sinks) sees no
        # tick and records nothing.
        sc = _current_scope()
        tick = sc.tick if sc is not None else None
        par = sc.parent if sc is not None else -1
        if not self.breaker.allow():
            reg.incr(dest, "breaker_rejected")
            if tick is not None:
                tick.finish(tick.start("egress.breaker_rejected", par),
                            destination=dest)
            raise CircuitOpenError(
                f"{dest}: circuit open, call rejected")
        if deadline is None:
            deadline = self._clock() + retry.deadline_s
        attempt = 0
        while True:
            attempt += 1
            reg.incr(dest, "attempts")
            ph = -1 if tick is None else tick.start("egress.attempt",
                                                    par)
            try:
                if timeout_s is not None:
                    remaining = deadline - self._clock()
                    kwargs["timeout"] = max(
                        0.001, min(timeout_s, remaining))
                out = fn(*args, **kwargs)
            except Exception as e:
                if tick is not None:
                    tick.finish(ph, destination=dest, attempt=attempt,
                                outcome=type(e).__name__)
                now = self._clock()
                if (not is_retryable(e) or attempt >= retry.max_attempts
                        or now >= deadline):
                    self.breaker.record_failure()
                    reg.incr(dest, "failures")
                    raise
                delay = self._rng.uniform(0.0, min(
                    retry.max_backoff_s,
                    retry.base_backoff_s * (2 ** (attempt - 1))))
                delay = min(delay, max(0.0, deadline - now))
                reg.incr(dest, "retries")
                if delay > 0:
                    bp = -1 if tick is None else \
                        tick.start("egress.backoff", par)
                    self._sleep(delay)
                    if tick is not None:
                        tick.finish(bp, destination=dest)
                continue
            if tick is not None:
                tick.finish(ph, destination=dest, attempt=attempt,
                            outcome="ok")
            self.breaker.record_success()
            reg.incr(dest, "success")
            return out

    # -- HTTP helpers --

    def _http(self, req, reader, timeout_s, deadline):
        def _send(timeout=None):
            resp = self._transport(req, timeout=timeout)
            try:
                status = getattr(resp, "status", None) or 200
                if status >= 400:
                    raise HTTPStatusError(self.destination, status)
                return reader(resp, status)
            finally:
                close = getattr(resp, "close", None)
                if close is not None:
                    close()

        return self.call(_send, timeout_s=timeout_s, deadline=deadline)

    def post(self, req, timeout_s: float | None = None,
             deadline: float | None = None) -> int:
        """Send one urllib-style Request through the transport with the
        full resilience treatment; returns the final HTTP status. Pass
        one `deadline` (from .deadline()) across a flush's chunked
        bodies so they share a single budget."""
        return self._http(req, lambda resp, status: status, timeout_s,
                          deadline)

    def fetch(self, req, timeout_s: float | None = None,
              deadline: float | None = None) -> bytes:
        """Like post(), but returns the response body (for callers that
        consume what the destination says, e.g. discovery)."""
        return self._http(req, lambda resp, status: resp.read(),
                          timeout_s, deadline)


# ---------------------------------------------------------------- spill

class SpillBuffer:
    """Bounded holding pen for ForwardExport sketches whose delivery
    failed terminally. Same-key sketches merge on spill (so a long
    outage stays O(live keys), not O(intervals)); `merge_into` hands
    everything back to the next interval's export. Not thread-safe by
    itself — the owning ResilientForwarder serializes access (the
    server forwards from the single flusher thread)."""

    # one spilled key's concatenated centroid pile is clustered down
    # when it exceeds this (sum/count stay exact; shape approximate —
    # the same trade the import path's pre-clustering makes)
    CENTROID_CAP = 2048

    def __init__(self, max_sketches: int = 65536,
                 gauge_max_age_intervals: int = 4,
                 destination: str = "forward",
                 registry: ResilienceRegistry | None = None):
        self.max_sketches = max_sketches
        self.gauge_max_age = gauge_max_age_intervals
        self.destination = destination
        self.registry = registry or DEFAULT_REGISTRY
        # key -> [means, weights, min, max, sum, count, recip]
        self._histos: dict = {}
        self._sets: dict = {}      # key -> registers u8[m]
        # which set engine produced the spilled registers (same-key
        # re-merge must use THAT engine's join — elementwise max for
        # HLL, the lattice join for ULL); one server runs one engine,
        # so the latest spilled export's id covers the whole buffer
        self.set_engine = "hll"
        self._counters: dict = {}  # key -> float
        self._gauges: dict = {}    # key -> [value, age_in_failed_flushes]
        # gauge ages at the last merge_into, so a re-spill of the same
        # (still-undelivered) gauges continues their age instead of
        # restarting at 0 — without this, the merge->fail->spill cycle
        # would keep every stale gauge young forever
        self._merged_gauge_ages: dict = {}

    def __len__(self):
        return (len(self._histos) + len(self._sets)
                + len(self._counters) + len(self._gauges))

    @staticmethod
    def _cluster(means: np.ndarray, weights: np.ndarray, cap: int):
        """Weight-preserving cluster-down of a sorted centroid pile to
        <= cap points (equal-cumulative-weight buckets). Keeps sum and
        count exact; receivers re-cluster with k1 anyway."""
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        if len(means) <= cap:
            return means, weights
        cum = np.cumsum(weights)
        edges = np.searchsorted(
            cum, np.linspace(0, cum[-1], cap + 1)[1:-1])
        edges = np.unique(np.concatenate([[0], edges]))
        wsum = np.add.reduceat(weights, edges)
        vsum = np.add.reduceat(means * weights, edges)
        keep = wsum > 0
        return (vsum[keep] / wsum[keep]).astype(means.dtype), \
            wsum[keep].astype(weights.dtype)

    def spill(self, export) -> int:
        """Absorb one failed interval's export; returns entries spilled.
        Ages + evicts stale gauges, then enforces the sketch budget."""
        n = 0
        for key, means, weights, vmin, vmax, vsum, cnt, recip in (
                export.histograms):
            means = np.asarray(means, np.float32)
            weights = np.asarray(weights, np.float32)
            live = weights > 0
            means, weights = means[live], weights[live]
            cur = self._histos.get(key)
            if cur is None:
                self._histos[key] = [means, weights, float(vmin),
                                     float(vmax), float(vsum),
                                     float(cnt), float(recip)]
            else:
                m = np.concatenate([cur[0], means])
                w = np.concatenate([cur[1], weights])
                if len(m) > self.CENTROID_CAP:
                    m, w = self._cluster(m, w, self.CENTROID_CAP)
                cur[0], cur[1] = m, w
                cur[2] = min(cur[2], float(vmin))
                cur[3] = max(cur[3], float(vmax))
                cur[4] += float(vsum)
                cur[5] += float(cnt)
                cur[6] += float(recip)
            n += 1
        self.set_engine = getattr(export, "set_engine", "hll")
        from . import sketches
        for key, regs in export.sets:
            regs = np.asarray(regs, np.uint8)
            cur = self._sets.get(key)
            self._sets[key] = (regs if cur is None
                               else sketches.merge_registers(
                                   self.set_engine, cur, regs))
            n += 1
        for key, value in export.counters:
            self._counters[key] = self._counters.get(key, 0.0) \
                + float(value)
            n += 1
        # gauges: age everything already pending by one failed
        # interval, evict over-age. An incoming gauge that was part of
        # the last merge_into is the SAME still-undelivered value
        # coming back — it continues its age (+1); a key re-reported
        # fresh this interval appears again later in the list (merge
        # prepends stale) and resets to 0 via the consumed-age pop.
        merged_ages, self._merged_gauge_ages = \
            self._merged_gauge_ages, {}
        evicted = 0
        for key in list(self._gauges):
            self._gauges[key][1] += 1
            if self._gauges[key][1] > self.gauge_max_age:
                del self._gauges[key]
                evicted += 1
        for key, value in export.gauges:
            age = merged_ages.pop(key, -1) + 1
            if age > self.gauge_max_age:
                evicted += 1
                continue
            self._gauges[key] = [float(value), age]
            n += 1
        evicted += self._enforce_budget()
        self.registry.incr(self.destination, "spilled", n)
        self.registry.incr(self.destination, "spill_evicted", evicted)
        return n

    def _enforce_budget(self) -> int:
        evicted = 0
        # oldest-inserted first, heaviest type first (dict order is
        # insertion order); counters/gauges are scalars and go last
        for d in (self._histos, self._sets, self._counters,
                  self._gauges):
            while len(self) > self.max_sketches and d:
                d.pop(next(iter(d)))
                evicted += 1
        return evicted

    def merge_into(self, export):
        """Merge everything pending into `export` (in place) and clear.
        Spilled entries PREPEND — they are strictly OLDER than the
        current interval's, and the receiver's import landing clusters
        piles in arrival order, so chronological order keeps a
        spill-carrying interval's merge as close as possible to what
        separate in-order deliveries would have produced (exactly what
        the delta gap-fallback's bit-identity probe pins; for gauges
        prepending is also what makes the current interval's fresher
        value win last-write-wins at the receiver). Gauge ages are
        remembered so that if THIS export fails too, the re-spill
        continues them (reset unconditionally: a successful delivery
        must not leak ages onto later fresh values)."""
        self._merged_gauge_ages = {key: age for key, (_v, age)
                                   in self._gauges.items()}
        if not len(self):
            return export
        n = len(self)
        export.histograms[:0] = (
            (key, h[0], h[1], h[2], h[3], h[4], h[5], h[6])
            for key, h in self._histos.items())
        if self._sets and self.set_engine != getattr(
                export, "set_engine", "hll"):
            # a journal-restored spill from a DIFFERENT set backend
            # (operator switched set_backend across a restart): the
            # outgoing export can only tag one engine, so mis-tagged
            # rows would merge under wrong semantics downstream —
            # drop them loudly instead (counted; registers are the
            # one spill type that cannot cross engines)
            self.registry.incr(self.destination, "spill_evicted",
                               len(self._sets))
            log.warning(
                "dropping %d spilled set sketches: spilled under "
                "set_backend %r, forwarding under %r",
                len(self._sets), self.set_engine,
                getattr(export, "set_engine", "hll"))
            n -= len(self._sets)
        else:
            export.sets[:0] = self._sets.items()
        export.counters[:0] = self._counters.items()
        export.gauges[:0] = [(key, v) for key, (v, _a)
                             in self._gauges.items()]
        self._histos, self._sets = {}, {}
        self._counters, self._gauges = {}, {}
        self.registry.incr(self.destination, "remerged", n)
        return export


def _export_size(export) -> int:
    return (len(export.histograms) + len(export.sets)
            + len(export.counters) + len(export.gauges))


class _ReplayEntry:
    """One failed interval awaiting replay under its ORIGINAL envelope.
    `chunk_offset`/`chunk_count` track partial-delivery progress: a
    tail replay carries the same chunk ids the first send used, so the
    receiver's ledger can drop a chunk that was ambiguously applied."""

    __slots__ = ("seq", "chunk_offset", "chunk_count", "export", "age",
                 "close_ns", "kind")

    def __init__(self, seq, export, chunk_offset=0, chunk_count=0,
                 close_ns=0, kind="full"):
        self.seq = seq
        self.export = export
        self.chunk_offset = chunk_offset
        self.chunk_count = chunk_count
        self.age = 0   # failed flushes survived (gauge eviction clock)
        # ORIGINAL interval-close time: a replay re-stamps the current
        # tick's trace ids (the replay runs inside this tick's span
        # tree) but keeps the close time it was born with, so the
        # global's e2e latency honestly includes replay-ladder delay.
        # 0 = unknown (journal-recovered entries; e2e is skipped).
        self.close_ns = close_ns
        # the full/delta kind the interval was BUILT as, pinned for its
        # whole ladder life: a replay re-declares what the payload IS,
        # not what the current tick would build (a delta re-stamped as
        # full would skip the receiver's gap check while still only
        # carrying the touched subset — harmless to merge, but it
        # would silently reset the gap baseline the check rides on).
        self.kind = kind


class ResilientForwarder:
    """Wraps the server's forwarder callable with the exactly-once
    spill/replay contract. Each interval's export is stamped with a
    fresh `ForwardEnvelope` (monotonic interval_seq under a stable
    sender_id); a failing send (terminal — the inner forwarder owns
    its own retry/breaker) parks the interval in a bounded replay
    ledger KEEPING that envelope. The next flush replays pending
    intervals oldest-first, each under its original ids, before the
    current interval goes out — so the receiver Combines seqs strictly
    in order (the bit-identical re-merge argument needs ordered
    Combine) and its dedupe ledger drops anything it already applied
    during an ambiguous failure. A replay failure stops the ladder:
    the current export is parked unsent rather than delivered out of
    order.

    The ledger holds at most `max_spill_intervals` entries /
    `max_spill_sketches` sketches; overflow demotes the OLDEST entries
    into the same-key-merged SpillBuffer, whose contents ride the
    current interval's fresh envelope instead (`reenveloped` counted:
    those sketches degrade to the old at-least-once contract — a
    duplicate is possible only if their original failure was ambiguous
    AND the outage outlived the ledger). Called only from the flusher
    thread, like the forwarder it wraps."""

    def __init__(self, inner, destination: str = "forward",
                 max_spill_sketches: int = 65536,
                 gauge_max_age_intervals: int = 4,
                 max_spill_intervals: int = 8,
                 sender_id: str | None = None,
                 seq_start: int | None = None,
                 replay_budget_s: float | None = None,
                 clock=time.monotonic,
                 journal=None,
                 delta_enabled: bool = True,
                 full_resync_intervals: int = 60,
                 registry: ResilienceRegistry | None = None):
        """`seq_start` seeds the interval_seq space. Auto-generated
        sender ids are unique per process incarnation, so they start at
        1; a CONFIGURED (stable) sender_id MUST seed from wall time —
        a restart that reset to 1 would put every new seq below the
        receiver ledger's persisted watermark for that sender and
        blackhole all forwards until the dedupe TTL (the sender keeps
        sending, so last_seen stays fresh and idle eviction never
        fires). Wall MILLISECONDS: seqs advance 1/interval per second
        while the seed advances 1000/s, so a restart's seed outruns the
        previous incarnation's watermark for any flush interval > 1ms
        (seconds-granularity seeding would lose that race below 1s
        intervals).

        `journal` (a durability.ForwardJournal, optional) makes the
        ladder crash-safe: every mutation appends one op record, the
        current interval is written ahead of any wire traffic, and
        construction REPLAYS the journal — parked intervals come back
        with their ORIGINAL envelopes (sender_id and seqs restored from
        the journal, overriding the arguments), so the receiver's
        dedupe ledger still drops anything it Combined before the
        crash. With journal=None behavior is bit-identical to the
        pre-durability forwarder."""
        self.inner = inner
        self.destination = destination
        self.registry = registry or DEFAULT_REGISTRY
        if sender_id:
            self.sender_id = sender_id
            if seq_start is None:
                seq_start = int(time.time() * 1000)
        else:
            self.sender_id = new_sender_id()
        self.max_spill_intervals = max(1, max_spill_intervals)
        self.max_spill_sketches = max_spill_sketches
        self.gauge_max_age = gauge_max_age_intervals
        # wall budget for ONE flush's whole replay ladder: without it,
        # max_spill_intervals slow-failing replays could each burn a
        # full inner retry_deadline and stall the flush tick for
        # N x deadline — the exact unbounded-stall shape the egress
        # layer's shared batch deadline exists to prevent. None = no
        # budget (unit-test / library use); the server wires
        # 2 x retry_deadline.
        self.replay_budget_s = replay_budget_s
        self._clock = clock
        self._takes_envelope = accepts_envelope(inner)
        self._next_seq = seq_start if seq_start is not None else 1
        # Delta forwarding (ISSUE 13): next_forward_kind() tells the
        # flush what to build. The FIRST interval of an incarnation is
        # always full (the receiver has no seq baseline for this
        # sender yet); thereafter deltas flow until a periodic resync
        # is due (`full_resync_intervals` — re-ships idle keys so the
        # global's series liveness refreshes) or a resync is FORCED:
        # a ladder demotion re-envelopes an interval, punching a hole
        # in the seq chain a receiver must never apply a delta over,
        # and a receiver's delta-over-gap refusal means its baseline
        # is gone — both set _force_full.
        self.delta_enabled = bool(delta_enabled)
        self.full_resync_intervals = max(0, int(full_resync_intervals))
        self._force_full = True
        self._since_full = 0
        self._entries: list[_ReplayEntry] = []
        self.spill = SpillBuffer(
            max_sketches=max_spill_sketches,
            gauge_max_age_intervals=gauge_max_age_intervals,
            destination=destination, registry=self.registry)
        self._journal = journal
        if journal is not None:
            self._recover(journal)
            # pin the (possibly recovered) identity so even a compacted
            # or fresh journal is self-describing
            self._jop("meta", self.sender_id, self._next_seq)

    def _jop(self, method: str, *args):
        """Run one journal op. A failing journal (disk full, I/O
        error) must never cost an interval the pre-durability code
        would have delivered or parked losslessly — so the op degrades:
        journaling is disabled for this process (counted, logged
        loudly) and the forward proceeds unjournaled. The on-disk
        journal keeps its last consistent state; a restart recovers
        from it (recovered intervals replay under their envelopes, so
        any that DID deliver after the degradation dedupe at the
        receiver)."""
        jrn = self._journal
        if jrn is None:
            return
        sc = _current_scope()
        tick = sc.tick if sc is not None else None
        ph = -1 if tick is None else tick.start("journal." + method,
                                                sc.parent)
        try:
            getattr(jrn, method)(*args)
            if tick is not None:
                tick.finish(ph)
        except Exception:
            if tick is not None:
                tick.finish(ph, outcome="error")
            self._journal = None
            self.registry.incr(self.destination,
                               "durability.journal_errors")
            log.exception(
                "durability journal %s failed for %s; DISABLING "
                "journaling for this process (forwarding continues "
                "unjournaled — the pre-durability lossless contract); "
                "state parked before this point recovers on restart",
                method, self.destination)
            try:
                jrn.close()
            except Exception:
                pass

    # ------------------------------------------------ durable recovery

    def _recover(self, journal):
        """Rebuild the ladder + spill tier by replaying the journal's
        op records in write order. The ops are deterministic given the
        export payloads stored in BEGIN/UPDATE records, so the
        recovered state matches the crashed incarnation's at its last
        append — counters are NOT re-incremented for sketches the
        previous incarnation already counted (a scratch registry
        absorbs them); only the durability.recovered_* counters fire."""
        from .durability import records as drec

        ops = journal.load_ops()
        scratch = ResilienceRegistry()
        real_reg, self.registry = self.registry, scratch
        real_spill_reg, self.spill.registry = self.spill.registry, scratch
        try:
            for rec_type, payload in ops:
                try:
                    self._apply_op(drec, rec_type, payload)
                except Exception as e:   # pragma: no cover - corrupt op
                    # a record that frames+CRCs clean but fails to parse
                    # (version skew) must not kill startup; everything
                    # before it is kept, it and later state-dependent
                    # drift is surfaced loudly
                    log.warning("durability: unreadable journal record "
                                "type=%d dropped during recovery: %s",
                                rec_type, e)
        finally:
            self.registry = real_reg
            self.spill.registry = real_spill_reg
        if self._entries or len(self.spill):
            self.registry.incr(self.destination,
                               "durability.recovered_intervals",
                               len(self._entries))
            self.registry.incr(self.destination,
                               "durability.recovered_sketches",
                               self.pending_spill)
            log.info(
                "durability: recovered %d parked interval(s) / %d "
                "sketch(es) for %s; replaying under their original "
                "envelopes (sender_id=%s)", len(self._entries),
                self.pending_spill, self.destination, self.sender_id)

    def _apply_op(self, drec, rec_type: int, payload: bytes):
        if rec_type == drec.REC_META:
            sender_id, next_seq = drec.decode_meta(payload)
            self.sender_id = sender_id
            self._next_seq = max(self._next_seq, next_seq)
        elif rec_type == drec.REC_BEGIN:
            seq, off, cnt, age, export, kind = \
                drec.decode_begin(payload)
            entry = _ReplayEntry(seq, export, off, cnt, kind=kind)
            entry.age = age
            self._entries.append(entry)
            self._next_seq = max(self._next_seq, seq + 1)
        elif rec_type == drec.REC_DONE:
            seq = drec.decode_done(payload)
            self._entries = [e for e in self._entries if e.seq != seq]
        elif rec_type == drec.REC_UPDATE:
            seq, off, cnt, export = drec.decode_update(payload)
            for entry in self._entries:
                if entry.seq == seq:
                    entry.export = export
                    entry.chunk_offset = off
                    if cnt:
                        entry.chunk_count = cnt
        elif rec_type == drec.REC_AGE:
            self._age_entries()
        elif rec_type == drec.REC_DEMOTE:
            if self._entries:
                self.spill.spill(self._entries.pop(0).export)
        elif rec_type == drec.REC_SPILL_MERGE:
            # the drained contents ride the current interval, whose
            # BEGIN/UPDATE record follows — here only clear + remember
            # gauge ages, exactly what merge_into did live
            from .models.pipeline import ForwardExport
            self.spill.merge_into(ForwardExport())
        elif rec_type == drec.REC_SPILL_STATE:
            drec.decode_spill_state(payload, self.spill)

    def durable_snapshot_records(self) -> list:
        """Full-state record list for snapshot compaction: replaying
        just these reconstructs the ladder + spill tier."""
        from .durability import records as drec
        out = [(drec.REC_META,
                drec.encode_meta(self.sender_id, self._next_seq)),
               (drec.REC_SPILL_STATE, drec.encode_spill_state(self.spill))]
        out.extend(
            (drec.REC_BEGIN,
             drec.encode_begin(e.seq, e.chunk_offset, e.chunk_count,
                               e.age, e.export, e.kind))
            for e in self._entries)
        return out

    def journal_tick(self):
        """Flush-boundary hook (the server calls it once per tick):
        fsync per policy and compact when the journal outgrew its
        budget. Failures degrade like any other journal op."""
        if self._journal is None:
            return
        self._jop("sync")
        self._jop("maybe_compact", self.durable_snapshot_records)

    @property
    def pending_spill(self) -> int:
        """Sketches awaiting replay or re-merge; the server forwards
        even an otherwise-empty interval while this is nonzero, so
        spilled data cannot strand when traffic stops."""
        return sum(_export_size(e.export) for e in self._entries) \
            + len(self.spill)

    def next_forward_kind(self) -> str:
        """What the NEXT interval's export build should be: "delta"
        (only dirty-bitmap-touched sketches) or "full" (the complete
        active set — the first interval, every `full_resync_intervals`
        thereafter, after any ladder demotion or receiver gap refusal,
        and always when the inner forwarder rotates across multiple
        destinations, where no single receiver sees a contiguous seq
        chain). Read-only: the resync bookkeeping advances in
        __call__, when an interval of that kind actually enters the
        ladder — an idle tick must not eat a scheduled resync."""
        if not self.delta_enabled or self._force_full:
            return "full"
        if not getattr(self.inner, "delta_capable", True):
            return "full"
        if self.full_resync_intervals and \
                self._since_full + 1 >= self.full_resync_intervals:
            return "full"
        return "delta"

    def _send(self, export, envelope: ForwardEnvelope):
        if self._takes_envelope:
            self.inner(export, envelope=envelope)
        else:
            self.inner(export)

    def _park(self, seq, export, chunk_offset=0, chunk_count=0,
              close_ns=0, kind="full"):
        n = _export_size(export)
        if n == 0:
            return 0
        self._entries.append(
            _ReplayEntry(seq, export, chunk_offset, chunk_count,
                         close_ns, kind))
        self.registry.incr(self.destination, "spilled", n)
        self._enforce_ledger_budget()
        return n

    def _demote_front_to_spill(self, counter: str):
        """Move the OLDEST ladder entry into the merged overflow tier
        (the one demotion shape the REC_DEMOTE journal op replays).
        Punches a permanent hole in the seq chain — that seq will
        never be delivered under its own envelope — so the next
        interval is forced to a full resync: a receiver must never be
        asked to apply a delta over the gap."""
        entry = self._entries.pop(0)
        self.registry.incr(self.destination, counter,
                           _export_size(entry.export))
        self._jop("demote")
        # SpillBuffer.spill counts these under "spilled" again;
        # compensate so spilled_total keeps meaning "sketches that
        # entered the resilience layer", not internal shuffles
        added = self.spill.spill(entry.export)
        self.registry.incr(self.destination, "spilled", -added)
        self._force_full = True

    def _enforce_ledger_budget(self):
        """Demote oldest entries to the merged overflow tier until the
        replay ledger fits its interval/sketch bounds."""
        def total():
            return sum(_export_size(e.export) for e in self._entries)
        while self._entries and (
                len(self._entries) > self.max_spill_intervals
                or total() > self.max_spill_sketches):
            self._demote_front_to_spill("reenveloped")

    def _age_entries(self):
        """One failed flush elapsed with these entries still pending:
        age them, and strip over-age gauges (last-write-wins data is
        only meaningful fresh). Gauges sit at the TAIL of the wire
        order, so stripping them never shifts an earlier metric across
        a frozen chunk boundary of a partially-delivered entry."""
        evicted = 0
        for entry in list(self._entries):
            entry.age += 1
            if entry.age > self.gauge_max_age and entry.export.gauges:
                evicted += len(entry.export.gauges)
                entry.export.gauges[:] = []
                if _export_size(entry.export) == 0:
                    self._entries.remove(entry)
                    # the emptied entry's seq will never be delivered —
                    # a hole in the chain, so the next interval must be
                    # a full resync (same rule as a demotion; without
                    # this every later delta eats one avoidable
                    # refusal round-trip)
                    self._force_full = True
        self.registry.incr(self.destination, "spill_evicted", evicted)

    def _note_interval_kind(self, kind: str):
        """Resync bookkeeping, called once per interval that entered
        the ladder or the wire: a FULL interval (even one merely
        parked — it replays under its pinned kind and delivers
        eventually) restarts the resync countdown; a delta advances
        it."""
        if kind == "full":
            self._force_full = False
            self._since_full = 0
        else:
            self._since_full += 1

    def __call__(self, export):
        reg, dest = self.registry, self.destination
        # what the engine actually built this interval ("full" unless
        # the flush consumed the dirty bitmap at the server's request)
        cur_kind = getattr(export, "kind", "full")
        replay_err = None
        # fleet-tracing context from the tick in progress: every wire
        # chunk this call emits (replays included) is stamped with the
        # CURRENT tick's trace identity — the receiver parents its
        # import spans under this flush — while close_ns keeps each
        # interval's ORIGINAL close time (replay honesty). No tick
        # (recorder off, library use) stamps nothing.
        _sc0 = _current_scope()
        _tick0 = _sc0.tick if _sc0 is not None else None
        trace_id = _tick0.trace_id if _tick0 is not None else 0
        span_id = _tick0.span_id if _tick0 is not None else 0
        cur_close = _tick0.close_ns if _tick0 is not None else 0
        # -- durability write-ahead: the current interval enters the
        # journal (seq allocated now) BEFORE any wire traffic, so a
        # hard kill anywhere in this tick — mid-replay-ladder included
        # — cannot lose it; a clean delivery appends DONE below. With
        # no journal the seq is allocated at the same points as before.
        # Journal ops go through _jop: a failing disk degrades to
        # unjournaled forwarding instead of costing the interval.
        cur_seq = None
        if self._journal is not None and _export_size(export):
            cur_seq = self._next_seq
            self._next_seq += 1
            self._jop("begin", cur_seq, 0, 0, 0, export, cur_kind)
        # -- replay phase: pending intervals first, oldest seq first,
        # under their ORIGINAL envelopes; stop at the first failure so
        # the receiver observes seqs strictly in order.
        budget_deadline = (None if self.replay_budget_s is None
                           else self._clock() + self.replay_budget_s)
        while self._entries and replay_err is None:
            if budget_deadline is not None \
                    and self._clock() >= budget_deadline:
                replay_err = TransientEgressError(
                    f"{dest}: replay ladder budget "
                    f"({self.replay_budget_s:.1f}s) exhausted; "
                    f"{len(self._entries)} intervals deferred to the "
                    "next flush")
                break
            entry = self._entries[0]
            env = ForwardEnvelope(self.sender_id, entry.seq,
                                  entry.chunk_offset, entry.chunk_count,
                                  trace_id=trace_id, span_id=span_id,
                                  close_ns=entry.close_ns,
                                  kind=entry.kind)
            sc = _current_scope()
            tick = sc.tick if sc is not None else None
            rp = -1 if tick is None else \
                tick.start("forward.replay", sc.parent)
            if tick is not None:
                tick.annotate(rp, seq=entry.seq)
            try:
                self._send(entry.export, env)
            except DeltaGapRefusedError:
                # the receiver has no unbroken chain below this delta
                # (its baseline died — restart without watermarks — or
                # an earlier demotion holed the chain). Parking it for
                # replay would be a livelock: the same delta refused
                # forever. Its data is intact (refusal precedes any
                # apply), so demote it to the merged tier — it rides
                # the NEXT interval, which _demote_front_to_spill just
                # forced to a full resync — and keep draining the
                # ladder (later deltas above the same gap fall back
                # the same way).
                if tick is not None:
                    tick.finish(rp, outcome="delta_gap")
                reg.incr(dest, "delta_gap_refused")
                log.warning(
                    "forward to %s: receiver refused delta seq %d over "
                    "a seq gap; payload re-routed through the overflow "
                    "tier, next interval forced to a full resync",
                    dest, entry.seq)
                self._demote_front_to_spill("delta_gap_fallback")
                continue
            except PartialDeliveryError as e:
                entry.export = e.undelivered
                entry.chunk_offset += e.delivered_chunks
                if e.chunk_count:
                    entry.chunk_count = e.chunk_count
                if tick is not None:
                    tick.finish(rp, outcome="partial")
                self._jop("update", entry.seq, entry.chunk_offset,
                          entry.chunk_count, entry.export)
                replay_err = e
            except Exception as e:
                if tick is not None:
                    tick.finish(rp, outcome=type(e).__name__)
                replay_err = e
            else:
                if tick is not None:
                    tick.finish(rp, outcome="ok")
                reg.incr(dest, "replayed", _export_size(entry.export))
                self._entries.pop(0)
                self._jop("done", entry.seq)
        if replay_err is not None:
            # park the current interval unsent: delivering it ahead of
            # the failed replay would reorder seqs at the receiver.
            # The overflow tier stays put — absorbing it here would
            # just bounce its sketches back into the ledger.
            if _export_size(export):
                if cur_seq is None:
                    cur_seq = self._next_seq
                    self._next_seq += 1
                self._park(cur_seq, export, close_ns=cur_close,
                           kind=cur_kind)
                self._note_interval_kind(cur_kind)
            self._age_entries()
            self._jop("age")
            log.warning(
                "forward to %s failed on replay; current interval "
                "parked for in-order retry (%d sketches pending)",
                dest, self.pending_spill)
            raise replay_err
        # -- overflow tier: sketches that outlived the replay ledger
        # ride the CURRENT interval's envelope (their at-least-once
        # degradation was already counted as reenveloped)
        had_spill = len(self.spill) > 0
        export = self.spill.merge_into(export)
        if had_spill:
            self._jop("spill_merge")
        if _export_size(export) == 0:
            return
        if cur_seq is None:
            cur_seq = self._next_seq
            self._next_seq += 1
            # the interval only materialized from the spill tier (or
            # journaling is off); write it ahead now
            if self._journal is not None:
                self._jop("begin", cur_seq, 0, 0, 0, export, cur_kind)
        elif had_spill:
            # the spill merge changed the written-ahead payload
            self._jop("update", cur_seq, 0, 0, export)
        seq = cur_seq
        sc = _current_scope()
        tick = sc.tick if sc is not None else None
        sp = -1 if tick is None else tick.start("forward.send",
                                                sc.parent)
        if tick is not None:
            tick.annotate(sp, seq=seq)
        try:
            self._send(export, ForwardEnvelope(
                self.sender_id, seq, trace_id=trace_id,
                span_id=span_id, close_ns=cur_close, kind=cur_kind))
        except DeltaGapRefusedError:
            # same fallback as the replay arm: the refused delta was
            # never applied, so its payload spills to the merged tier
            # and rides the next interval — which the demotion forces
            # to a full resync. NOT re-raised: nothing was lost, the
            # counters carry the signal (delta_gap_refused/_fallback).
            if tick is not None:
                tick.finish(sp, outcome="delta_gap")
            reg.incr(dest, "delta_gap_refused")
            self._park(seq, export, close_ns=cur_close, kind=cur_kind)
            if self._entries and self._entries[0].seq == seq:
                self._demote_front_to_spill("delta_gap_fallback")
            else:
                # _park's budget enforcement already demoted the entry
                # (an export past max_spill_sketches) — the demotion
                # counted it as reenveloped and the resync must still
                # be forced
                self._force_full = True
            log.warning(
                "forward to %s: receiver refused delta seq %d over a "
                "seq gap (no baseline for sender %s); payload rides "
                "the next interval's full resync", dest, seq,
                self.sender_id)
            return
        except PartialDeliveryError as e:
            # some chunks landed: park only what didn't, resuming at
            # the failed chunk's id. The UPDATE record goes first so
            # recovery shrinks the written-ahead payload to the
            # undelivered tail BEFORE any demote the park may trigger.
            if tick is not None:
                tick.finish(sp, outcome="partial")
            self._jop("update", seq, e.delivered_chunks, e.chunk_count,
                      e.undelivered)
            n = self._park(seq, e.undelivered,
                           chunk_offset=e.delivered_chunks,
                           chunk_count=e.chunk_count,
                           close_ns=cur_close, kind=cur_kind)
            self._note_interval_kind(cur_kind)
            self._age_entries()
            self._jop("age")
            log.warning(
                "forward to %s partially failed; %d undelivered "
                "sketches parked for replay under their original "
                "envelope", dest, n)
            raise
        except Exception as e:
            if tick is not None:
                tick.finish(sp, outcome=type(e).__name__)
            n = self._park(seq, export, close_ns=cur_close,
                           kind=cur_kind)
            self._note_interval_kind(cur_kind)
            self._age_entries()
            self._jop("age")
            log.warning(
                "forward to %s failed; %d sketches parked for replay "
                "under their original envelope", dest, n)
            raise
        else:
            if tick is not None:
                tick.finish(sp, outcome="ok")
            self._note_interval_kind(cur_kind)
            self._jop("done", seq)

    def debug_state(self) -> dict:
        """JSON-ready ladder/spill/journal/breaker state for the
        /debug/flush introspection endpoint. Reads only (flusher-thread
        sizes may be one tick stale from another thread — fine for a
        debug surface)."""
        egress = (getattr(self.inner, "egress", None)
                  or getattr(self.inner, "_egress", None))
        breaker = getattr(egress, "breaker", None)
        jrn = self._journal
        return {
            "destination": self.destination,
            "sender_id": self.sender_id,
            "next_seq": self._next_seq,
            "ladder": [{"seq": e.seq, "age": e.age,
                        "chunk_offset": e.chunk_offset,
                        "chunk_count": e.chunk_count,
                        "kind": e.kind,
                        "sketches": _export_size(e.export)}
                       for e in self._entries],
            "spill_sketches": len(self.spill),
            "pending_spill": self.pending_spill,
            "breaker_state": (None if breaker is None
                              else breaker.state),
            "journal": (None if jrn is None else {
                "bytes": jrn.size_bytes()}),
            # delta-forwarding posture (ISSUE 13): what the next
            # interval will build and why
            "delta": {
                "enabled": self.delta_enabled,
                "next_kind": self.next_forward_kind(),
                "force_full": self._force_full,
                "since_full": self._since_full,
                "full_resync_intervals": self.full_resync_intervals,
            },
        }

    def close(self):
        if self._journal is not None:
            self._journal.close()
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
