"""Unified egress resilience: retry, circuit breaking, sketch re-merge.

Every network egress in the pipeline — the vendor sinks, both cluster
forwarders, the proxy fan-out — routes its wire calls through an
`Egress` from this module instead of raw urllib/grpc (vlint RS01
enforces this). The layer owns three behaviors the call sites used to
lack:

  * **Retry with full-jitter exponential backoff** under a per-flush
    deadline budget: a transient failure (timeout, 5xx, connection
    refused, UNAVAILABLE) is retried up to `max_attempts` times with
    `delay ~ U(0, min(cap, base * 2^attempt))`, and the whole call —
    attempts plus backoff sleeps plus per-attempt socket timeouts —
    never exceeds `deadline_s`, so one wedged vendor cannot push the
    flush tick late.

  * **A per-destination circuit breaker**, so a dead endpoint costs one
    fast rejection per flush instead of a full retry ladder:

        closed ──(failure_threshold consecutive failed calls)──▶ open
          ▲                                                   │
          │                              (open_duration_s elapses)
          │                                                   ▼
          └──(half_open_successes probe successes)──── half-open
                       half-open ──(probe failure)──▶ open (timer
                                                      restarts)

    Half-open admits ONE in-flight probe at a time; concurrent callers
    are rejected until the probe resolves.

  * **A bounded re-merge spill buffer** (`SpillBuffer` +
    `ResilientForwarder`): when a forward fails terminally, the
    interval's `ForwardExport` sketches are NOT dropped — they are
    spilled and merged into the next interval's export. t-digest
    centroids concatenate (the receiver's Combine re-clusters), HLL
    registers fold by max, counters sum: all lossless. Gauges are
    last-write-wins and only meaningful fresh, so they ride along for
    `gauge_max_age_intervals` failed intervals and are then evicted
    (counted). The budget bounds total spilled entries; overflow evicts
    oldest sketches first, also counted.

Everything observable is counted per destination in a
`ResilienceRegistry`; the server drains it each flush into
`veneur.resilience.*_total` self-metrics. The clock, sleep, RNG, and
transport are all injectable, so `utils/faults.py` can script every
retry/breaker/re-merge transition deterministically — no sockets, no
real sleeps.
"""

from __future__ import annotations

import logging
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

import numpy as np

log = logging.getLogger("veneur_tpu.resilience")


# --------------------------------------------------------------- errors

class EgressError(Exception):
    """Base for resilience-layer errors."""


class TransientEgressError(EgressError):
    """Marker for failures the retry loop should retry."""


class TerminalEgressError(EgressError):
    """Marker for failures that must not be retried."""


class CircuitOpenError(EgressError):
    """The destination's breaker is open; the call was not attempted."""


class PartialDeliveryError(EgressError):
    """Part of an export was delivered before a terminal failure; only
    `undelivered` may be spilled for re-merge — re-sending the whole
    export would double-count counters at the receiver's Combine."""

    def __init__(self, undelivered, cause: BaseException | None = None):
        super().__init__(f"partial delivery: {cause}")
        self.undelivered = undelivered


class HTTPStatusError(EgressError):
    """A transport returned an HTTP error status without raising (fake
    transports and non-urllib stacks); retryability follows the code."""

    def __init__(self, destination: str, status: int):
        super().__init__(f"{destination}: HTTP {status}")
        self.status = status


_RETRYABLE_HTTP = (408, 429)


def is_retryable(exc: BaseException) -> bool:
    """Classify one attempt's failure. Retryable: timeouts, connection
    errors, HTTP 5xx/408/429, URLErrors (DNS, refused-inside-urllib),
    and the transient gRPC codes. Terminal: HTTP 4xx (the payload or
    auth is wrong — retrying re-fails), INVALID_ARGUMENT-class gRPC
    codes, and anything unrecognized (fail fast, count, spill)."""
    if isinstance(exc, TransientEgressError):
        return True
    if isinstance(exc, TerminalEgressError):
        return False
    if isinstance(exc, CircuitOpenError):
        # an open breaker is a transient condition for OUTER callers
        # deciding whether to buffer/requeue (Egress.call itself never
        # classifies it — rejection happens before any attempt)
        return True
    if isinstance(exc, HTTPStatusError):
        return exc.status >= 500 or exc.status in _RETRYABLE_HTTP
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500 or exc.code in _RETRYABLE_HTTP
    # HTTPError subclasses URLError — this arm must come second
    if isinstance(exc, urllib.error.URLError):
        return True
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return True
    try:
        import grpc
    except ImportError:         # pragma: no cover - grpc ships in-image
        grpc = None
    if grpc is not None and isinstance(exc, grpc.RpcError):
        code = exc.code() if callable(getattr(exc, "code", None)) else None
        return code in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        grpc.StatusCode.ABORTED,
                        grpc.StatusCode.UNKNOWN)
    if isinstance(exc, OSError):
        return True
    return False


# ------------------------------------------------------------- policies

@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    base_backoff_s: float = 0.2
    max_backoff_s: float = 5.0
    # per-call (≈ per-flush, per-destination) wall budget: attempts,
    # socket timeouts and backoff sleeps all draw from it
    deadline_s: float = 8.0


@dataclass(frozen=True)
class BreakerPolicy:
    failure_threshold: int = 5
    open_duration_s: float = 30.0
    half_open_successes: int = 1


@dataclass(frozen=True)
class EgressPolicy:
    retry: RetryPolicy = RetryPolicy()
    breaker: BreakerPolicy = BreakerPolicy()


DEFAULT_POLICY = EgressPolicy()


def policy_from_config(cfg) -> EgressPolicy:
    """Build the shared egress policy from the Config knobs."""
    from .config import _parse_interval
    return EgressPolicy(
        retry=RetryPolicy(
            max_attempts=max(1, cfg.retry_max_attempts),
            base_backoff_s=_parse_interval(cfg.retry_backoff_base),
            max_backoff_s=_parse_interval(cfg.retry_backoff_cap),
            deadline_s=_parse_interval(cfg.retry_deadline)),
        breaker=BreakerPolicy(
            failure_threshold=max(1, cfg.breaker_failure_threshold),
            open_duration_s=_parse_interval(cfg.breaker_open_duration),
            half_open_successes=max(1, cfg.breaker_half_open_successes)))


# ------------------------------------------------------------- registry

class ResilienceRegistry:
    """Per-destination counters, drained once per flush by the server
    into veneur.resilience.*_total self-metrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, str], int] = {}

    def incr(self, destination: str, counter: str, n: int = 1):
        if n == 0:
            return
        with self._lock:
            key = (destination, counter)
            self._counters[key] = self._counters.get(key, 0) + n

    def take(self) -> dict[tuple[str, str], int]:
        """Drain: return-and-reset (interval-delta semantics, like the
        server's other self-telemetry counters)."""
        with self._lock:
            out, self._counters = self._counters, {}
        return out

    def peek(self, destination: str, counter: str) -> int:
        with self._lock:
            return self._counters.get((destination, counter), 0)


# The process-default registry: egress objects constructed without an
# explicit registry (config-built sinks, forwarders) count here, and
# Server._self_metrics drains it.
DEFAULT_REGISTRY = ResilienceRegistry()


# -------------------------------------------------------------- breaker

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-destination breaker (state diagram in the module docstring).
    Thread-safe: sinks flush on their own threads and the proxy fans
    out concurrently."""

    def __init__(self, destination: str = "", policy: BreakerPolicy
                 | None = None, clock=time.monotonic,
                 registry: ResilienceRegistry | None = None):
        self.destination = destination
        self.policy = policy or BreakerPolicy()
        self._clock = clock
        self._registry = registry or DEFAULT_REGISTRY
        self._lock = threading.Lock()
        self._state = _CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._half_open_successes = 0
        self._probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call proceed now? Open→half-open transition happens
        here (lazily, on the first allow() after the cooldown)."""
        with self._lock:
            if self._state == _CLOSED:
                return True
            if self._state == _OPEN:
                if (self._clock() - self._opened_at
                        >= self.policy.open_duration_s):
                    self._state = _HALF_OPEN
                    self._half_open_successes = 0
                    self._probe_inflight = False
                else:
                    return False
            # half-open: admit one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self):
        with self._lock:
            if self._state == _HALF_OPEN:
                self._probe_inflight = False
                self._half_open_successes += 1
                if (self._half_open_successes
                        >= self.policy.half_open_successes):
                    self._state = _CLOSED
            self._consecutive_failures = 0

    def record_failure(self):
        with self._lock:
            if self._state == _HALF_OPEN:
                self._probe_inflight = False
                self._trip_locked()
                return
            self._consecutive_failures += 1
            if (self._state == _CLOSED and self._consecutive_failures
                    >= self.policy.failure_threshold):
                self._trip_locked()

    def _trip_locked(self):
        self._state = _OPEN
        self._opened_at = self._clock()
        self._consecutive_failures = 0
        self._registry.incr(self.destination, "breaker_opened")


# --------------------------------------------------------------- egress

def _default_transport(req, timeout=None):
    """The layer's single raw HTTP call. urllib raises HTTPError for
    4xx/5xx, which is_retryable classifies by code."""
    return urllib.request.urlopen(req, timeout=timeout)


def grpc_channel(address: str):
    """The project's single gRPC channel constructor — egress channels
    are created here so raw grpc.insecure_channel calls elsewhere are
    vlint-RS01 strays."""
    import grpc
    return grpc.insecure_channel(address)


class Egress:
    """One destination's resilient call wrapper: breaker consult, retry
    with full-jitter backoff, deadline budget, telemetry. Clock/sleep/
    rng/transport are injectable for the fault harness."""

    def __init__(self, destination: str,
                 policy: EgressPolicy | None = None,
                 breaker: CircuitBreaker | None = None,
                 transport=None, clock=time.monotonic,
                 sleep=time.sleep, rng: random.Random | None = None,
                 registry: ResilienceRegistry | None = None):
        self.destination = destination
        self.policy = policy or DEFAULT_POLICY
        self.registry = registry or DEFAULT_REGISTRY
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._transport = transport or _default_transport
        self.breaker = breaker or CircuitBreaker(
            destination, self.policy.breaker, clock=clock,
            registry=self.registry)

    # -- generic call wrapper --

    def deadline(self) -> float:
        """An absolute deadline one policy budget from now — pass it to
        several call()s (e.g. the batches of one flush) so they share
        ONE budget instead of each getting its own."""
        return self._clock() + self.policy.retry.deadline_s

    def call(self, fn, *args, timeout_s: float | None = None,
             deadline: float | None = None, **kwargs):
        """Run fn(*args, **kwargs) under retry/breaker/deadline. When
        `timeout_s` is given, each attempt receives a `timeout=` kwarg
        clamped to min(timeout_s, remaining deadline budget), so socket
        timeouts can never overrun the flush budget.

        The breaker is consulted ONCE, at call start, and records the
        call's FINAL outcome: the retry ladder is one logical delivery,
        so breaker_failure_threshold counts failed deliveries — a
        threshold <= max_attempts cannot cut retries short or mask the
        underlying error with CircuitOpenError mid-ladder."""
        retry = self.policy.retry
        reg, dest = self.registry, self.destination
        if not self.breaker.allow():
            reg.incr(dest, "breaker_rejected")
            raise CircuitOpenError(
                f"{dest}: circuit open, call rejected")
        if deadline is None:
            deadline = self._clock() + retry.deadline_s
        attempt = 0
        while True:
            attempt += 1
            reg.incr(dest, "attempts")
            try:
                if timeout_s is not None:
                    remaining = deadline - self._clock()
                    kwargs["timeout"] = max(
                        0.001, min(timeout_s, remaining))
                out = fn(*args, **kwargs)
            except Exception as e:
                now = self._clock()
                if (not is_retryable(e) or attempt >= retry.max_attempts
                        or now >= deadline):
                    self.breaker.record_failure()
                    reg.incr(dest, "failures")
                    raise
                delay = self._rng.uniform(0.0, min(
                    retry.max_backoff_s,
                    retry.base_backoff_s * (2 ** (attempt - 1))))
                delay = min(delay, max(0.0, deadline - now))
                reg.incr(dest, "retries")
                if delay > 0:
                    self._sleep(delay)
                continue
            self.breaker.record_success()
            reg.incr(dest, "success")
            return out

    # -- HTTP helpers --

    def _http(self, req, reader, timeout_s, deadline):
        def _send(timeout=None):
            resp = self._transport(req, timeout=timeout)
            try:
                status = getattr(resp, "status", None) or 200
                if status >= 400:
                    raise HTTPStatusError(self.destination, status)
                return reader(resp, status)
            finally:
                close = getattr(resp, "close", None)
                if close is not None:
                    close()

        return self.call(_send, timeout_s=timeout_s, deadline=deadline)

    def post(self, req, timeout_s: float | None = None,
             deadline: float | None = None) -> int:
        """Send one urllib-style Request through the transport with the
        full resilience treatment; returns the final HTTP status. Pass
        one `deadline` (from .deadline()) across a flush's chunked
        bodies so they share a single budget."""
        return self._http(req, lambda resp, status: status, timeout_s,
                          deadline)

    def fetch(self, req, timeout_s: float | None = None,
              deadline: float | None = None) -> bytes:
        """Like post(), but returns the response body (for callers that
        consume what the destination says, e.g. discovery)."""
        return self._http(req, lambda resp, status: resp.read(),
                          timeout_s, deadline)


# ---------------------------------------------------------------- spill

class SpillBuffer:
    """Bounded holding pen for ForwardExport sketches whose delivery
    failed terminally. Same-key sketches merge on spill (so a long
    outage stays O(live keys), not O(intervals)); `merge_into` hands
    everything back to the next interval's export. Not thread-safe by
    itself — the owning ResilientForwarder serializes access (the
    server forwards from the single flusher thread)."""

    # one spilled key's concatenated centroid pile is clustered down
    # when it exceeds this (sum/count stay exact; shape approximate —
    # the same trade the import path's pre-clustering makes)
    CENTROID_CAP = 2048

    def __init__(self, max_sketches: int = 65536,
                 gauge_max_age_intervals: int = 4,
                 destination: str = "forward",
                 registry: ResilienceRegistry | None = None):
        self.max_sketches = max_sketches
        self.gauge_max_age = gauge_max_age_intervals
        self.destination = destination
        self.registry = registry or DEFAULT_REGISTRY
        # key -> [means, weights, min, max, sum, count, recip]
        self._histos: dict = {}
        self._sets: dict = {}      # key -> registers u8[m]
        self._counters: dict = {}  # key -> float
        self._gauges: dict = {}    # key -> [value, age_in_failed_flushes]
        # gauge ages at the last merge_into, so a re-spill of the same
        # (still-undelivered) gauges continues their age instead of
        # restarting at 0 — without this, the merge->fail->spill cycle
        # would keep every stale gauge young forever
        self._merged_gauge_ages: dict = {}

    def __len__(self):
        return (len(self._histos) + len(self._sets)
                + len(self._counters) + len(self._gauges))

    @staticmethod
    def _cluster(means: np.ndarray, weights: np.ndarray, cap: int):
        """Weight-preserving cluster-down of a sorted centroid pile to
        <= cap points (equal-cumulative-weight buckets). Keeps sum and
        count exact; receivers re-cluster with k1 anyway."""
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        if len(means) <= cap:
            return means, weights
        cum = np.cumsum(weights)
        edges = np.searchsorted(
            cum, np.linspace(0, cum[-1], cap + 1)[1:-1])
        edges = np.unique(np.concatenate([[0], edges]))
        wsum = np.add.reduceat(weights, edges)
        vsum = np.add.reduceat(means * weights, edges)
        keep = wsum > 0
        return (vsum[keep] / wsum[keep]).astype(means.dtype), \
            wsum[keep].astype(weights.dtype)

    def spill(self, export) -> int:
        """Absorb one failed interval's export; returns entries spilled.
        Ages + evicts stale gauges, then enforces the sketch budget."""
        n = 0
        for key, means, weights, vmin, vmax, vsum, cnt, recip in (
                export.histograms):
            means = np.asarray(means, np.float32)
            weights = np.asarray(weights, np.float32)
            live = weights > 0
            means, weights = means[live], weights[live]
            cur = self._histos.get(key)
            if cur is None:
                self._histos[key] = [means, weights, float(vmin),
                                     float(vmax), float(vsum),
                                     float(cnt), float(recip)]
            else:
                m = np.concatenate([cur[0], means])
                w = np.concatenate([cur[1], weights])
                if len(m) > self.CENTROID_CAP:
                    m, w = self._cluster(m, w, self.CENTROID_CAP)
                cur[0], cur[1] = m, w
                cur[2] = min(cur[2], float(vmin))
                cur[3] = max(cur[3], float(vmax))
                cur[4] += float(vsum)
                cur[5] += float(cnt)
                cur[6] += float(recip)
            n += 1
        for key, regs in export.sets:
            regs = np.asarray(regs, np.uint8)
            cur = self._sets.get(key)
            self._sets[key] = (regs if cur is None
                               else np.maximum(cur, regs))
            n += 1
        for key, value in export.counters:
            self._counters[key] = self._counters.get(key, 0.0) \
                + float(value)
            n += 1
        # gauges: age everything already pending by one failed
        # interval, evict over-age. An incoming gauge that was part of
        # the last merge_into is the SAME still-undelivered value
        # coming back — it continues its age (+1); a key re-reported
        # fresh this interval appears again later in the list (merge
        # prepends stale) and resets to 0 via the consumed-age pop.
        merged_ages, self._merged_gauge_ages = \
            self._merged_gauge_ages, {}
        evicted = 0
        for key in list(self._gauges):
            self._gauges[key][1] += 1
            if self._gauges[key][1] > self.gauge_max_age:
                del self._gauges[key]
                evicted += 1
        for key, value in export.gauges:
            age = merged_ages.pop(key, -1) + 1
            if age > self.gauge_max_age:
                evicted += 1
                continue
            self._gauges[key] = [float(value), age]
            n += 1
        evicted += self._enforce_budget()
        self.registry.incr(self.destination, "spilled", n)
        self.registry.incr(self.destination, "spill_evicted", evicted)
        return n

    def _enforce_budget(self) -> int:
        evicted = 0
        # oldest-inserted first, heaviest type first (dict order is
        # insertion order); counters/gauges are scalars and go last
        for d in (self._histos, self._sets, self._counters,
                  self._gauges):
            while len(self) > self.max_sketches and d:
                d.pop(next(iter(d)))
                evicted += 1
        return evicted

    def merge_into(self, export):
        """Merge everything pending into `export` (in place) and clear.
        Spilled gauges PREPEND so the current interval's fresher value
        wins last-write-wins at the receiver; sketch types append —
        the receiver's Combine path merges same-key entries anyway.
        Gauge ages are remembered so that if THIS export fails too, the
        re-spill continues them (reset unconditionally: a successful
        delivery must not leak ages onto later fresh values)."""
        self._merged_gauge_ages = {key: age for key, (_v, age)
                                   in self._gauges.items()}
        if not len(self):
            return export
        n = len(self)
        export.histograms.extend(
            (key, h[0], h[1], h[2], h[3], h[4], h[5], h[6])
            for key, h in self._histos.items())
        export.sets.extend(self._sets.items())
        export.counters.extend(self._counters.items())
        export.gauges[:0] = [(key, v) for key, (v, _a)
                             in self._gauges.items()]
        self._histos, self._sets = {}, {}
        self._counters, self._gauges = {}, {}
        self.registry.incr(self.destination, "remerged", n)
        return export


class ResilientForwarder:
    """Wraps the server's forwarder callable with the spill/re-merge
    contract: pending sketches from failed intervals are merged into
    each outgoing export; a failing send (terminal — the inner
    forwarder owns its own retry/breaker) spills the merged export
    back. Called only from the flusher thread, like the forwarder it
    wraps."""

    def __init__(self, inner, destination: str = "forward",
                 max_spill_sketches: int = 65536,
                 gauge_max_age_intervals: int = 4,
                 registry: ResilienceRegistry | None = None):
        self.inner = inner
        self.destination = destination
        self.registry = registry or DEFAULT_REGISTRY
        self.spill = SpillBuffer(
            max_sketches=max_spill_sketches,
            gauge_max_age_intervals=gauge_max_age_intervals,
            destination=destination, registry=self.registry)

    @property
    def pending_spill(self) -> int:
        """Sketches awaiting re-merge; the server forwards even an
        otherwise-empty interval while this is nonzero, so spilled data
        cannot strand when traffic stops."""
        return len(self.spill)

    def __call__(self, export):
        export = self.spill.merge_into(export)
        try:
            self.inner(export)
        except PartialDeliveryError as e:
            # some batches landed: spill only what didn't
            n = self.spill.spill(e.undelivered)
            log.warning(
                "forward to %s partially failed; %d undelivered "
                "sketches spilled for re-merge into the next interval",
                self.destination, n)
            raise
        except Exception:
            n = self.spill.spill(export)
            log.warning(
                "forward to %s failed; %d sketches spilled for "
                "re-merge into the next interval", self.destination, n)
            raise

    def close(self):
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
