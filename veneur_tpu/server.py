"""The Server: listeners + sharded engines + flush loop + watchdog.

Parity: server.go (sym: Server, NewFromConfig, Server.Start,
Server.HandleMetricPacket, Server.ReadMetricSocket, Server.Shutdown),
flusher.go (sym: Server.Flush, Server.FlushWatchdog), networking.go.

Threading model (the Go goroutine topology, reshaped):
  * `num_readers` UDP reader threads per listen address (SO_REUSEPORT
    sockets — same kernel-level fan-in as the reference).
  * Readers parse inline and route each sample by digest to one of
    `num_workers` worker queues (`Workers[Digest % len(Workers)]`).
  * Each worker thread owns one AggregationEngine feeding the device —
    engines own disjoint hash-space shards, so flush is a union, never a
    merge. Device calls release the GIL, so workers overlap.
  * One flush thread ticks every `interval`, drains all engines, fans out
    to sinks (thread per sink, timed), hands exports to the forwarder.
  * A watchdog thread kills the process if flushes stop completing
    (crash-only design: Server.FlushWatchdog panics for the supervisor
    to restart).
"""

from __future__ import annotations

import logging
import os
import queue
import socket
import threading
import time

from . import observe, resilience
from .config import Config, _parse_interval
from .ingest import parser
from .metrics import FrameSet, InterMetric, MetricType
from .models.pipeline import AggregationEngine, EngineConfig, ForwardExport
from .sinks import MetricSink
from .sinks.basic import (BlackholeMetricSink, DebugMetricSink,
                          LocalFilePlugin)

log = logging.getLogger("veneur_tpu.server")

_STOP = object()


def _fold_rewrite(pb, fr) -> int:
    """Apply an ImportFoldReroute's rewrite to the pb IN PLACE (the
    fold key is tagless by construction) and return the fold key's
    digest — the single-homed fold routing basis. One definition for
    all three sites that re-route a fold (per-metric worker path,
    ImportedBatch worker path, recovery replay): the rewrite diverging
    between live and replay would silently break the kill-restart
    bit-identity."""
    pb.name = fr.key.name
    del pb.tags[:]
    return fr.digest


class Server:
    def __init__(self, cfg: Config, sinks: list[MetricSink] | None = None,
                 plugins=None, forwarder=None, span_sinks=None):
        self.cfg = cfg
        self.hostname = cfg.hostname or (
            "" if cfg.omit_empty_hostname else socket.gethostname())
        # Native ingest: the C++ bridge owns interning over ONE engine's
        # slot space; its reader threads are the parallelism. A mesh
        # engine likewise owns the whole slot space (sharded over chips).
        self._mesh_mode = cfg.tpu_num_devices > 1
        n_workers = (1 if cfg.native_ingest or self._mesh_mode
                     else max(1, cfg.num_workers))
        ecfg_kw = dict(
            histogram_slots=max(256, cfg.tpu_histogram_slots // n_workers),
            counter_slots=max(128, cfg.tpu_counter_slots // n_workers),
            gauge_slots=max(128, cfg.tpu_gauge_slots // n_workers),
            set_slots=max(64, cfg.tpu_set_slots // n_workers),
            batch_size=cfg.tpu_batch_size,
            buffer_depth=cfg.tpu_buffer_depth,
            compression=cfg.tpu_compression,
            hll_precision=cfg.tpu_hll_precision,
            histogram_backend=cfg.histogram_backend,
            set_backend=cfg.set_backend,
            ull_precision=cfg.tpu_ull_precision,
            req_levels=cfg.tpu_req_levels,
            req_capacity=cfg.tpu_req_capacity,
            percentiles=tuple(cfg.percentiles),
            aggregates=tuple(cfg.aggregates),
            idle_ttl_intervals=cfg.tpu_slot_idle_ttl_intervals,
            flush_fetch=cfg.tpu_flush_fetch,
            flush_fetch_f16=cfg.tpu_flush_fetch_f16,
            flush_incremental=cfg.tpu_flush_incremental,
            flush_incremental_threshold=
            cfg.tpu_flush_incremental_threshold,
            flush_double_buffer=cfg.tpu_flush_double_buffer,
            fused_kernels=cfg.tpu_fused_kernels,
            forward_enabled=bool(cfg.forward_address
                                 or cfg.consul_forward_service_name),
            # a server with a gRPC import listener is (also) a global tier
            is_global=cfg.is_global or bool(cfg.grpc_listen_addresses),
            hostname=self.hostname,
        )
        if self._mesh_mode:
            # multi-chip serving: ONE engine whose banks are sharded
            # over a device mesh; slot routing replaces worker sharding
            # (SURVEY §7 step 7). Forward/import stay on the cluster
            # tier — the engine constructor enforces it.
            from .parallel.engine import MeshAggregationEngine
            self.engines = [MeshAggregationEngine(
                EngineConfig(**ecfg_kw),
                n_devices=cfg.tpu_num_devices)]
        else:
            self.engines = [AggregationEngine(EngineConfig(**ecfg_kw))
                            for _ in range(n_workers)]
        self.worker_queues: list[queue.Queue] = [
            queue.Queue(maxsize=65536) for _ in range(n_workers)]
        # Sketch-engine/wire stamp (ISSUE 10): declared on every
        # forwarded chunk and enforced on every import request — a
        # mixed fleet (peer running different sketch backends) is
        # refused loudly, never silently merged. One stamp per server:
        # all engines share the config's backends. The forward
        # centroid codec folds in (ISSUE 13, "h=tdigest/1q"): a
        # quantized-centroid fleet is a DIFFERENT wire format, and a
        # lossless peer must be refused before decode, not fed packed
        # rows it would misread as empty centroid lists.
        from . import sketches as _sketches
        self.engine_stamp = _sketches.stamp_with_codec(
            self.engines[0].engine_stamp, cfg.forward_centroid_codec)
        # Fleet-wide per-prefix cardinality (overload-defense
        # satellite): received Huffman-Bucket sketches merge-by-max
        # here, keyed by prefix; /debug/fleet serves the estimates.
        self._fleet_sketch_lock = threading.Lock()
        self._fleet_sketches: dict[str, bytearray] = {}
        self.native_bridge = None
        self.native_pump = None
        if cfg.native_ingest:
            self._setup_native_ingest()
        # Unified telemetry spine (observe/registry.py): every counter
        # this server owns — ingest, span pipeline, flush, sinks —
        # lives in a PER-SERVER registry (two servers in one process,
        # the chaos-harness topology, must never cross-count), while
        # egress/durability objects keep counting into the process
        # DEFAULT_REGISTRY; _self_metrics drains both. The historical
        # counter attributes (packets_received, ...) remain as
        # read-only properties over the registry. Built before the
        # sinks: the Prometheus scrape surface captures it.
        self.telemetry = observe.TelemetryRegistry()
        # Overload defense (ingest/admission.py): ONE controller shared
        # by every engine's KeyInterners (per-prefix key budgets +
        # fold-to-other) and by handle_packet (adaptive shed governor).
        # None = defense off, the regression-pinned pre-defense path.
        self.admission = None
        self._rate_corrected_types = None
        if cfg.overload_defense_enabled:
            if self.native_bridge is not None:
                log.warning(
                    "overload_defense_enabled has no effect with "
                    "native_ingest (the C++ bridge owns interning); "
                    "defense disabled")
            else:
                from .ingest import admission as _admission
                self.admission = _admission.from_config(cfg,
                                                        self.telemetry)
                self._rate_corrected_types = \
                    _admission.RATE_CORRECTED_TYPES
                # index/n/reroute single-home each fold key on the
                # engine its digest routes to — one flush, one row
                # per `__other__` series, however many workers
                for i, eng in enumerate(self.engines):
                    eng.attach_admission(
                        self.admission, index=i, n=len(self.engines),
                        reroute=self._route_metric)
        # one shared egress policy (retry/breaker knobs) for every
        # config-built sink and forwarder; per-destination breakers are
        # created inside each Egress
        self._egress_policy = resilience.policy_from_config(cfg)
        self.sinks = sinks if sinks is not None else self._sinks_from_config()
        if plugins is not None:
            self.plugins = plugins
        else:
            self.plugins = []
            if cfg.flush_file:
                self.plugins.append(LocalFilePlugin(
                    cfg.flush_file, max(1, round(cfg.interval_seconds))))
            if cfg.aws_s3_bucket:
                from .sinks.s3 import S3Plugin
                self.plugins.append(S3Plugin(
                    bucket=cfg.aws_s3_bucket, region=cfg.aws_region,
                    access_key=cfg.aws_access_key_id,
                    secret_key=cfg.aws_secret_access_key,
                    interval_s=max(1, round(cfg.interval_seconds)),
                    egress_policy=self._egress_policy))
        if forwarder is None and cfg.forward_address:
            if cfg.forward_use_grpc:
                from .cluster.forward import GrpcForwarder
                forwarder = GrpcForwarder(
                    cfg.forward_address,
                    timeout_s=cfg.flush_timeout_seconds,
                    egress_policy=self._egress_policy,
                    engine_stamp=self.engine_stamp,
                    centroid_codec=cfg.forward_centroid_codec)
            else:
                from .cluster.forward import HttpJsonForwarder
                forwarder = HttpJsonForwarder(
                    cfg.forward_address,
                    timeout_s=cfg.flush_timeout_seconds,
                    max_per_body=cfg.flush_max_per_body,
                    egress_policy=self._egress_policy,
                    engine_stamp=self.engine_stamp,
                    centroid_codec=cfg.forward_centroid_codec)
        elif forwarder is None and cfg.consul_forward_service_name:
            # discover the global tier via Consul and re-resolve on the
            # refresh interval (consul.go; Server.RefreshDestinations)
            from .cluster.discovery import ConsulDiscoverer
            from .cluster.forward import DiscoveringForwarder
            forwarder = DiscoveringForwarder(
                ConsulDiscoverer(),
                cfg.consul_forward_service_name,
                refresh_interval_s=_parse_interval(
                    cfg.consul_refresh_interval),
                use_grpc=cfg.forward_use_grpc,
                timeout_s=cfg.flush_timeout_seconds,
                max_per_body=cfg.flush_max_per_body,
                egress_policy=self._egress_policy,
                engine_stamp=self.engine_stamp,
                centroid_codec=cfg.forward_centroid_codec)
        # Durable state (off by default): crash-safe journals for the
        # sender's replay ladder + spill tier and the receiver's dedupe
        # watermarks. Recovery runs HERE, in the constructor — before
        # start() binds any listener — so a restarted process resumes
        # its ladder under the original envelopes and a restarted
        # global refuses ancient replays before the first RPC arrives.
        self._forward_journal = None
        self._dedupe_journal = None
        # (by here a configured forward_address/consul service has
        # already produced a concrete forwarder, so "will wrap" is
        # simply "a forwarder exists and is not already resilient")
        will_wrap = forwarder is not None and not isinstance(
            forwarder, resilience.ResilientForwarder)
        if cfg.durability_enabled and will_wrap:
            from .durability import ForwardJournal
            self._forward_journal = ForwardJournal(
                cfg.durability_dir,
                fsync=cfg.durability_fsync,
                fsync_interval_s=_parse_interval(
                    cfg.durability_fsync_interval),
                snapshot_journal_bytes=(
                    cfg.durability_snapshot_journal_bytes))
        if will_wrap:
            # lossless-forward contract: terminal failures spill the
            # interval's sketches for re-merge into the next flush
            # instead of dropping them (resilience.SpillBuffer)
            forwarder = resilience.ResilientForwarder(
                forwarder,
                destination=(cfg.forward_address
                             or cfg.consul_forward_service_name
                             or "forward"),
                max_spill_sketches=cfg.spill_max_sketches,
                gauge_max_age_intervals=(
                    cfg.spill_gauge_max_age_intervals),
                max_spill_intervals=cfg.spill_max_intervals,
                sender_id=(cfg.forward_sender_id or
                           resilience.new_sender_id(self.hostname)),
                # one wall budget for the whole replay ladder (plus the
                # current send's own retry_deadline): a flush tick can
                # stall at most ~3x retry_deadline, not
                # spill_max_intervals x retry_deadline
                replay_budget_s=2 * _parse_interval(cfg.retry_deadline),
                # delta forwarding (ISSUE 13): the flush loop asks
                # next_forward_kind() what to build each tick
                delta_enabled=cfg.forward_delta,
                full_resync_intervals=cfg.forward_full_resync_intervals,
                # recovery happens inside the constructor: parked
                # intervals come back with their original envelopes
                journal=self._forward_journal)
        self.forwarder = forwarder   # callable(ForwardExport) or None
        # Receiver side of the exactly-once contract: one dedupe ledger
        # shared by the gRPC importsrv and the HTTP /import path, so a
        # sender that fails over between contracts still dedupes.
        self.dedupe_ledger = None
        if cfg.forward_dedupe_enabled and (
                cfg.grpc_listen_addresses or cfg.http_address
                or cfg.is_global):
            from .cluster.importsrv import DedupeLedger
            self.dedupe_ledger = DedupeLedger(
                max_seqs_per_sender=(
                    cfg.forward_dedupe_max_seqs_per_sender),
                max_senders=cfg.forward_dedupe_max_senders,
                ttl_s=_parse_interval(cfg.forward_dedupe_ttl))
            if cfg.durability_enabled:
                # recovery-before-listen: restore the per-sender
                # watermarks the last incarnation flushed under, so an
                # ancient replay (already flushed downstream before the
                # crash) is dropped, not double-counted
                from .durability import WatermarkJournal
                self._dedupe_journal = WatermarkJournal(
                    cfg.durability_dir,
                    fsync=cfg.durability_fsync,
                    fsync_interval_s=_parse_interval(
                        cfg.durability_fsync_interval))
                marks = self._dedupe_journal.load()
                if marks:
                    n = self.dedupe_ledger.restore_watermarks(marks)
                    resilience.DEFAULT_REGISTRY.incr(
                        "import", "durability.recovered_watermarks", n)
                # watermarks are journaled ONE TICK BEHIND (see
                # flush_once): a seq admitted mid-tick may still be
                # sitting in a worker queue when this tick's engines
                # drain, so only the PREVIOUS tick's snapshot — whose
                # data has had a full interval to land and flush — is
                # safe to make a durable hard-drop floor
                self._pending_watermarks: dict = {}
        # Global-tier engine checkpointing (durability/ ISSUE 9): the
        # piece the watermark journal alone cannot give — an interval
        # the global ADMITTED AND ACKED is never replayed by its
        # sender, so its merged sketch state used to die with the
        # process. When armed, every admitted import op is write-ahead
        # journaled (inside _submit_import_batch, before the worker
        # queues and therefore before the ack), and each flush
        # boundary appends a self-contained per-engine delta
        # checkpoint (dirty piles + interner tables + staged imports +
        # the applied-op watermark). Recovery runs HERE, before any
        # listener binds: restore the latest checkpoint group per
        # engine, then replay ops above each engine's watermark
        # through the normal digest routing — the restarted global
        # flushes BIT-IDENTICAL state (chaos-gated in
        # tests/test_exactly_once_chaos.py).
        self._engine_journal = None
        self._engine_journal_armed = False
        self._recovery = None            # restore stats for /debug, health
        self._recovering = False         # True until start() completes
        self._next_import_op = 0
        self._recent_import_ops: list = []   # (op_id, bytes), 2-tick window
        self._import_ops_evicted = False     # cap evicted since last seal
        self._ops_at_last_checkpoint = 0
        self._last_checkpoint_sig = None
        self._last_checkpoint_t = None
        self._last_checkpoint_stats = (0, 0)   # (dirty, total) piles
        self._import_submit_lock = threading.Lock()
        # Time-travel query tier (durability/history.py, ISSUE 14):
        # retained window of committed checkpoint generations + the
        # GET /query read path. Armed below, with the engine journal.
        self._history = None
        self._query_tier = None
        self._history_baseline = None      # (recs, marks, empty) of
        #                                    the prev boundary — the
        #                                    next generation's baseline
        self._history_prev_close_ns = 0
        # Arming keys on the IMPORT tiers (a gRPC import listener or a
        # declared global), NOT on http_address alone: http_address is
        # also just the ops/healthcheck listener on sending-tier
        # servers, which would otherwise pay dirty-bitmap marking on
        # the UDP hot path plus a per-tick checkpoint+fsync for state
        # that is never write-aheaded (UDP is lossy by contract). A
        # global that receives ONLY over HTTP /import must set
        # `is_global: true` to get checkpointing.
        if cfg.durability_enabled and cfg.durability_engine_snapshot \
                and (cfg.grpc_listen_addresses or cfg.is_global):
            if self._mesh_mode or self.native_bridge is not None:
                log.warning(
                    "durability_engine_snapshot has no effect with a "
                    "mesh engine or native_ingest (the %s owns the "
                    "banks/interner); engine checkpointing disabled",
                    "mesh" if self._mesh_mode else "native bridge")
            else:
                from .durability import EngineJournal
                self._engine_journal_armed = True
                self._recovering = True
                self._engine_journal = EngineJournal(
                    cfg.durability_dir,
                    fsync=cfg.durability_fsync,
                    fsync_interval_s=_parse_interval(
                        cfg.durability_fsync_interval),
                    snapshot_journal_bytes=(
                        cfg.durability_snapshot_journal_bytes))
                for eng in self.engines:
                    eng.enable_dirty_tracking(
                        cfg.durability_engine_delta_threshold)
                self._recover_engine_state()
        # Fleet-scope tracing, receiver half (observe/fleet.py): the
        # per-sender e2e/freshness view plus the import observer that
        # phase-attributes each import request and parents its spans on
        # the remote sender's flush span. Built for the same servers
        # that can receive forwards; observability only — admission and
        # apply behavior is identical with it on or off.
        self.fleet = None
        self.import_observer = None
        if cfg.grpc_listen_addresses or cfg.http_address or cfg.is_global:
            self.fleet = observe.FleetView(
                max_senders=cfg.fleet_max_senders,
                window=cfg.fleet_e2e_window)
            import_ring = None
            if cfg.flight_recorder:
                import_ring = observe.FlightRecorder(
                    capacity=cfg.flight_recorder_ticks, max_phases=16)
            self.import_observer = observe.ImportObserver(
                fleet=self.fleet, flight=import_ring,
                client=lambda: self.trace_client)
        self._grpc_servers = []
        # tags_exclude strips tag names BEFORE key construction (metrics
        # differing only in an excluded tag aggregate together), in both
        # the Python parser and the C++ bridge's.
        self._exclude_tags = frozenset(cfg.tags_exclude) or None
        # parser hardening bounds (counted rejection, never an
        # unbounded interned key)
        self._max_name_len = cfg.metric_max_name_length
        self._max_tag_len = cfg.metric_max_tag_length
        if self.native_bridge is not None and (
                self._max_name_len != parser.MAX_NAME_LENGTH
                or self._max_tag_len != parser.MAX_TAG_LENGTH):
            log.warning(
                "metric_max_name_length/metric_max_tag_length have no "
                "effect with native_ingest (the C++ bridge parses and "
                "interns without the bounds)")
        if self._exclude_tags and self.native_bridge is not None:
            self.native_bridge.set_tags_exclude(sorted(
                self._exclude_tags))
        # stats_address: ship veneur.* self-metrics there as DogStatsD
        # over UDP (the reference's scopedstatsd client, usually pointed
        # at the local veneur itself); unset = inject into our own flush.
        self._stats_sock = None
        if cfg.stats_address:
            host, _, port = cfg.stats_address.rpartition(":")
            fam = (socket.AF_INET6 if ":" in host.strip("[]")
                   else socket.AF_INET)
            self._stats_sock = socket.socket(fam, socket.SOCK_DGRAM)
            self._stats_dest = (host.strip("[]") or "127.0.0.1",
                                int(port))
        # Self-tracing (flusher.go: spans around flush/forward): when an
        # SSF UDP listener exists, point a trace client back at it so
        # the server traces itself through its own ingest path.
        self.trace_client = None
        self._ssf_udp_sock = None
        self.ssf_native_port = None   # set by the native SSF listener
        self._sentry = None
        if cfg.sentry_dsn:
            from .utils.sentry import SentryClient
            self._sentry = SentryClient(cfg.sentry_dsn)
        # in-flight fan-out threads (flusher-thread-only): a sink whose
        # previous flush is still running skips the interval instead of
        # delaying the tick (flusher.go's per-sink goroutines never
        # block the ticker). Per-sink flush stats/skips now ride the
        # telemetry registry (scope "sink:<name>") and drain next
        # interval like every other counter.
        self._sink_inflight: dict[tuple, threading.Thread] = {}

        self._threads: list[threading.Thread] = []
        self._sockets: list[socket.socket] = []
        self._listen_socks: list[socket.socket] = []  # stream accept socks
        self._stream_conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False        # flipped at the end of start()
        self._last_flush_ok = time.monotonic()
        # Flight recorder: the bounded ring of per-tick phase trees
        # behind /debug/flush, SSF self-tracing, and the
        # veneur.flush.phase.* dogfood timers. Strictly process-local.
        self.flight = None
        if cfg.flight_recorder:
            self.flight = observe.FlightRecorder(
                capacity=cfg.flight_recorder_ticks,
                max_phases=cfg.flight_recorder_max_phases)
        # Time-travel query tier (ISSUE 14): armed with the engine
        # journal (recovery already ran above), built HERE because its
        # query ticks adopt into the flight ring just created
        if self._engine_journal_armed \
                and cfg.history_retention_generations > 0:
            self._setup_history()
        # on-demand jax.profiler capture around flush ticks (see
        # _maybe_profile); written under _stats_lock
        self._profile_ticks = 0
        self._profile_active = False
        self._last_forward_err = None   # sentry dedupe, under _stats_lock
        # last interval's forward bytes by destination/kind (sampled
        # around the forward call each tick; under _stats_lock)
        self._last_forward_bytes = None
        self._stats_lock = threading.Lock()
        # SSF span pipeline (SpanWorker + SpanSinks)
        self.span_queue: queue.Queue = queue.Queue(
            maxsize=max(1, cfg.ssf_buffer_size))
        self.span_sinks = (span_sinks if span_sinks is not None
                           else self._span_sinks_from_config())
        # Native SSF fast path: when the only span consumer is the
        # ssfmetrics bridge, the C++ bridge decodes span datagrams and
        # stages their embedded samples straight into the rings — the
        # Python span pipeline (decode -> queue -> worker -> extract ->
        # re-submit -> per-sample process) costs ~75us/span where the
        # native path is a few us. Spans the fast path can't express
        # (STATUS samples -> service checks) fall back per-datagram.
        from .sinks.ssfmetrics import SSFMetricsSink
        self._native_ssf = (
            self.native_bridge is not None
            and len(self.span_sinks) == 1
            and type(self.span_sinks[0]) is SSFMetricsSink)
        if self._native_ssf:
            # the sink's configured timer name, not cfg's: a caller may
            # construct the sink directly with its own name, and the
            # fallback (Python) path would use that — both paths must
            # derive the same indicator timer
            timer_name = self.span_sinks[0]._timer_name
            if timer_name:
                self.native_bridge.set_indicator_timer(timer_name)

    # ------------- telemetry accessors (registry-backed) -------------
    # The historical counter attributes, preserved as read-only views
    # over the unified registry: interval-delta (reset at each flush's
    # drain), exactly like the attribute counters they replace.

    def _peek(self, name: str) -> int:
        return self.telemetry.peek(observe.SERVER_SCOPE, name)

    def _count(self, name: str, n: int = 1):
        self.telemetry.incr(observe.SERVER_SCOPE, name, n)

    @property
    def packets_received(self) -> int:
        return self._peek("packet.received")

    @property
    def parse_errors(self) -> int:
        return self._peek("packet.error")

    @property
    def queue_drops(self) -> int:
        return self._peek("worker.dropped")

    @property
    def spans_received(self) -> int:
        return self._peek("ssf.received")

    @property
    def ssf_errors(self) -> int:
        return self._peek("ssf.error")

    @property
    def flush_errors(self) -> int:
        return self._peek("flush.error")

    @property
    def import_rejected(self) -> int:
        return self._peek("import.rejected")

    @property
    def flush_count(self) -> int:
        """Completed flush ticks since start (a level: never drained)."""
        return self.telemetry.level(observe.SERVER_SCOPE, "flush.count")

    # ------------- construction helpers -------------

    def _setup_native_ingest(self):
        """Swap the single engine's KeyInterners for views over the C++
        interning bridge, and build the pump that drains its sample
        rings into the engine's batch kernels."""
        from .ingest.native import BridgeKeyView, NativeBridge, NativePump

        eng = self.engines[0]
        ecfg = eng.cfg
        self.native_bridge = NativeBridge(
            histo_slots=ecfg.histogram_slots,
            counter_slots=ecfg.counter_slots,
            gauge_slots=ecfg.gauge_slots,
            set_slots=ecfg.set_slots,
            hll_precision=ecfg.hll_precision,
            idle_ttl=ecfg.idle_ttl_intervals,
            ring_capacity=self.cfg.native_ring_capacity,
            max_packet=self.cfg.metric_max_length)
        views = {b: BridgeKeyView(self.native_bridge, b)
                 for b in ("histo", "counter", "gauge", "set")}
        eng.histo_keys = views["histo"]
        eng.counter_keys = views["counter"]
        eng.gauge_keys = views["gauge"]
        eng.set_keys = views["set"]

        def slow_path(line: bytes):
            """Lines the C++ parser routes to Python: events, service
            checks, CPython-float oddities, invalid UTF-8. Must apply
            the same tags_exclude as the fast path or one logical
            metric splits into two series."""
            try:
                item = parser.parse_packet(line, self._exclude_tags,
                                           self._max_name_len,
                                           self._max_tag_len)
            except parser.ParseError:
                self._count("packet.error")
                return
            self._route_metric(item)

        def ssf_slow_path(payload: bytes):
            """SSF datagrams the native listener routed back (STATUS
            samples -> service checks need Python semantics)."""
            from .ssf import framing
            try:
                span = framing.parse_ssf_datagram(payload)
            except framing.FramingError:
                self._count("ssf.error")
                return
            self.handle_ssf_span(span)

        self.native_pump = NativePump(
            self.native_bridge, eng, views, slow_path,
            batch=self.cfg.native_pump_batch,
            ssf_slow_path=ssf_slow_path)

    def _sinks_from_config(self) -> list[MetricSink]:
        out: list[MetricSink] = []
        cfg = self.cfg
        # every network sink gets the configured per-attempt timeout
        # (flush_timeout) and the shared retry/breaker policy — the
        # CF01-class bug was each constructor keeping its hardcoded 10s
        pol = self._egress_policy
        to = cfg.flush_timeout_seconds
        if cfg.datadog_api_key:
            from .sinks.datadog import DatadogMetricSink
            out.append(DatadogMetricSink(
                api_key=cfg.datadog_api_key,
                api_url=cfg.datadog_api_hostname,
                hostname=self.hostname,
                tags=list(cfg.tags),
                interval_s=max(1, round(cfg.interval_seconds)),
                flush_max_per_body=cfg.datadog_flush_max_per_body,
                timeout_s=to, egress_policy=pol))
        if cfg.signalfx_api_key:
            from .sinks.signalfx import SignalFxMetricSink
            out.append(SignalFxMetricSink(
                api_key=cfg.signalfx_api_key,
                endpoint=cfg.signalfx_endpoint_base,
                hostname=self.hostname, tags=list(cfg.tags),
                vary_key_by=cfg.signalfx_vary_key_by,
                timeout_s=to, egress_policy=pol))
        if cfg.kafka_broker and (cfg.kafka_metric_topic or cfg.kafka_topic):
            from .sinks.kafka import KafkaMetricSink
            out.append(KafkaMetricSink(
                broker=cfg.kafka_broker,
                metric_topic=cfg.kafka_metric_topic or cfg.kafka_topic,
                egress_policy=pol))
        if cfg.newrelic_insert_key:
            from .sinks.newrelic import NewRelicMetricSink
            out.append(NewRelicMetricSink(
                insert_key=cfg.newrelic_insert_key,
                account_id=cfg.newrelic_account_id,
                tags=list(cfg.tags),
                interval_s=cfg.interval_seconds,
                timeout_s=to, egress_policy=pol))
        if cfg.prometheus_repeater_address:
            from .sinks.prometheus import PrometheusMetricSink
            out.append(PrometheusMetricSink(
                listen_address=cfg.prometheus_repeater_address,
                # one scrape surface for ALL veneur.* self-metrics:
                # this server's telemetry spine + the process-default
                # egress/durability registry
                registries=(self.telemetry,
                            resilience.DEFAULT_REGISTRY)))
        if cfg.debug:
            out.append(DebugMetricSink())
        if not out:
            out.append(BlackholeMetricSink())
        return out

    def _span_sinks_from_config(self):
        """Span egress: always include the ssfmetrics bridge so embedded
        samples reach the metric pipeline (sinks/ssfmetrics)."""
        from .sinks.ssfmetrics import SSFMetricsSink

        pol = self._egress_policy
        to = self.cfg.flush_timeout_seconds
        out = [SSFMetricsSink(
            self._route_metric,
            indicator_span_timer_name=self.cfg.indicator_span_timer_name)]
        if self.cfg.datadog_trace_api_address:
            from .sinks.datadog import DatadogSpanSink
            out.append(DatadogSpanSink(
                trace_api_address=self.cfg.datadog_trace_api_address,
                buffer_size=self.cfg.ssf_buffer_size,
                timeout_s=to, egress_policy=pol))
        if self.cfg.splunk_hec_address:
            from .sinks.splunk import SplunkSpanSink
            out.append(SplunkSpanSink(
                hec_address=self.cfg.splunk_hec_address,
                token=self.cfg.splunk_hec_token,
                hostname=self.hostname,
                timeout_s=to, egress_policy=pol))
        if self.cfg.xray_address:
            from .sinks.xray import XRaySpanSink
            out.append(XRaySpanSink(daemon_address=self.cfg.xray_address))
        if self.cfg.falconer_address:
            from .sinks.grpsink import GrpcSpanSink
            out.append(GrpcSpanSink(self.cfg.falconer_address,
                                    timeout_s=to, egress_policy=pol))
        if self.cfg.kafka_broker and self.cfg.kafka_span_topic:
            from .sinks.kafka import KafkaSpanSink
            out.append(KafkaSpanSink(
                broker=self.cfg.kafka_broker,
                span_topic=self.cfg.kafka_span_topic,
                egress_policy=pol))
        if self.cfg.lightstep_access_token:
            from .sinks.lightstep import LightStepSpanSink
            out.append(LightStepSpanSink(
                access_token=self.cfg.lightstep_access_token,
                collector_url=self.cfg.lightstep_collector_host,
                hostname=self.hostname,
                timeout_s=to, egress_policy=pol))
        if self.cfg.debug:
            from .sinks.basic import BlackholeSpanSink
            out.append(BlackholeSpanSink())
        return out

    # ------------- lifecycle -------------

    def start(self):
        # Precompile the device programs BEFORE any listener or the
        # watchdog exists: a cold backend pays the whole compile bill
        # here (~tens of seconds on a tunneled TPU), not inside flush 0
        # where it would overrun watchdog_missed_flushes intervals.
        # Engines with identical shapes share executables, so this
        # compiles once and executes cheaply n_workers times.
        t0 = time.monotonic()
        for eng in self.engines:
            eng.warmup()
        if self.native_pump is not None and \
                self.native_pump.batch != self.engines[0].cfg.batch_size:
            # the pump dispatches at its own width; compile those
            # executables now, not inline under the ingest lock
            self.engines[0].warm_ingest_kernels(self.native_pump.batch)
        warm_s = time.monotonic() - t0
        if warm_s > 1.0:
            log.info("engine warmup (device program compile): %.1fs",
                     warm_s)
        for s in self.sinks:
            try:
                s.start()
            except Exception as e:
                log.error("sink %s failed to start: %s", s.name(), e)
        for i, q in enumerate(self.worker_queues):
            t = threading.Thread(target=self._worker_loop, args=(i, q),
                                 name=f"worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        for addr in self.cfg.statsd_listen_addresses:
            self._start_statsd_listener(addr)
        for addr in self.cfg.ssf_listen_addresses:
            self._start_ssf_listener(addr)
        if self.trace_client is None:
            trace_port = None
            if self._ssf_udp_sock is not None:
                trace_port = self._ssf_udp_sock.getsockname()[1]
            elif getattr(self, "ssf_native_port", None):
                trace_port = self.ssf_native_port  # native SSF listener
            if trace_port is not None:
                from . import trace
                self.trace_client = trace.Client(
                    f"udp://127.0.0.1:{trace_port}")
        if self.cfg.enable_profiling:
            self._start_profiling()
        for addr in self.cfg.grpc_listen_addresses:
            self._start_import_listener(addr)
        for ss in self.span_sinks:
            try:
                ss.start()
            except Exception as e:
                log.error("span sink %s failed to start: %s",
                          ss.name(), e)
        t = threading.Thread(target=self._span_worker, name="span-worker",
                             daemon=True)
        t.start()
        self._threads.append(t)
        if self.cfg.http_address:
            self._start_http_api(self.cfg.http_address)
        if self.native_pump is not None:
            self.native_pump.start()
        # watchdog epoch starts after warmup — compile time is not lag
        self._last_flush_ok = time.monotonic()
        t = threading.Thread(target=self._flush_loop, name="flusher",
                             daemon=True)
        t.start()
        self._threads.append(t)
        # the watchdog thread ALWAYS runs: it counts overdue ticks
        # (veneur.watchdog.stalled_ticks_total — the outside-visible
        # stall signal behind /healthz) every interval; the crash-only
        # exit stays gated on flush_watchdog_missed_flushes > 0
        t = threading.Thread(target=self._watchdog, name="watchdog",
                             daemon=True)
        t.start()
        self._threads.append(t)
        # vlint: disable=TH01 reason=monotonic one-way flag; readers
        # (health probes) tolerate either order around startup
        self._recovering = False
        # vlint: disable=TH01 reason=monotonic one-way flag; readers
        # (health probes) tolerate either order around startup
        self._started = True

    def stop(self, *, grace: float | None = None, clock=time.monotonic,
             sleep=time.sleep):
        self._stop.set()
        if getattr(self, "http_api", None) is not None:
            try:
                self.http_api.stop()
            except Exception:
                pass
        # graceful importsrv shutdown: reject new RPCs immediately but
        # let in-flight SendMetrics finish routing onto the worker
        # queues — their chunks are already recorded in the dedupe
        # ledger, so killing them mid-stream would strand entries the
        # sender will never replay. clock/sleep are injectable (fault
        # harness) so the grace-expiry path is testable without real
        # waiting.
        from .cluster.importsrv import stop_import_server
        if grace is None:
            grace = min(2.0, self.cfg.interval_seconds)
        for g in self._grpc_servers:
            try:
                stop_import_server(g, grace, clock=clock, sleep=sleep)
            except Exception:
                pass
        if self.dedupe_ledger is not None:
            self.dedupe_ledger.clear()   # torn down only after drain
        for q in self.worker_queues:
            try:
                q.put_nowait(_STOP)
            except queue.Full:
                pass
        try:
            self.span_queue.put_nowait(_STOP)
        except queue.Full:
            pass
        with self._conns_lock:
            conns = list(self._stream_conns)
        for c in conns:
            # shutdown (not just close) so reader threads blocked in
            # recv() wake up immediately
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for s in self._sockets + self._listen_socks + conns:
            try:
                s.close()
            except OSError:
                pass
        if self.native_pump is not None:
            self.native_pump.stop()
        if self.native_bridge is not None:
            self.native_bridge.stop()
        # the fan-out never joins sink threads; drain them here (bounded)
        # so the final interval's data isn't killed mid-POST at exit and
        # sinks aren't stop()ed under an in-flight flush
        deadline = time.monotonic() + min(
            10.0, self.cfg.interval_seconds)
        for t in list(self._sink_inflight.values()):
            while True:
                try:
                    t.join(max(0.0, deadline - time.monotonic()))
                    break
                except RuntimeError:   # registered but not yet started
                    if time.monotonic() >= deadline:
                        break
                    time.sleep(0.005)
        for s in self.sinks + self.span_sinks:
            try:
                s.stop()
            except Exception:
                pass
        # durable shutdown: push every journal record to disk and
        # release the file handles, so a restart from the same
        # durability_dir starts clean (the crash path skips this — the
        # journal's torn-write tolerance covers it)
        for j in (self._forward_journal, self._dedupe_journal,
                  self._engine_journal):
            if j is not None:
                try:
                    j.close()
                except Exception:
                    log.exception("durability journal close failed")
        if self._query_tier is not None:
            # the history store itself holds no file handles (segments
            # and the manifest publish atomically per boundary); only
            # the query executor needs shutting down
            try:
                self._query_tier.close()
            except Exception:
                pass
        if self.trace_client is not None:
            try:
                self.trace_client.close()
            except Exception:
                pass
        if self._stats_sock is not None:
            try:
                self._stats_sock.close()
            except OSError:
                pass

    # ------------- ingest -------------

    @staticmethod
    def _resolve_inet(scheme: str, rest: str):
        """'host:port' (+scheme suffix 4/6, brackets allowed) → (family,
        bind_addr). udp6://[::1]:8126 must bind an AF_INET6 socket."""
        host, _, port = rest.rpartition(":")
        host = host.strip("[]")
        if scheme.endswith("6"):
            family = socket.AF_INET6
            host = host or "::"
        elif scheme.endswith("4"):
            family = socket.AF_INET
            host = host or "0.0.0.0"
        else:
            family = socket.AF_INET6 if ":" in host else socket.AF_INET
            host = host or "0.0.0.0"
        return family, (host, int(port))

    def _start_statsd_listener(self, addr: str):
        scheme, _, rest = addr.partition("://")
        if scheme in ("udp", "udp4", "udp6"):
            family, bind_addr = self._resolve_inet(scheme, rest)
            if self.native_bridge is not None:
                # the bridge only accepts numeric addresses; resolve
                # hostnames here (the Python path's bind() would too)
                host = socket.getaddrinfo(
                    bind_addr[0], bind_addr[1], family,
                    socket.SOCK_DGRAM)[0][4][0]
                self.native_bridge.start_udp(
                    host, bind_addr[1], max(1, self.cfg.num_readers),
                    rcvbuf=self.cfg.read_buffer_size_bytes)
                return
            for ri in range(max(1, self.cfg.num_readers)):
                sock = socket.socket(family, socket.SOCK_DGRAM)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                if hasattr(socket, "SO_REUSEPORT"):
                    sock.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEPORT, 1)
                try:
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                    self.cfg.read_buffer_size_bytes)
                except OSError:
                    pass
                sock.bind(bind_addr)
                self._sockets.append(sock)
                t = threading.Thread(
                    target=self._read_metric_socket, args=(sock,),
                    name=f"udp-reader-{ri}", daemon=True)
                t.start()
                self._threads.append(t)
        elif scheme in ("tcp", "tcp4", "tcp6", "unix"):
            # statsd over streams (networking.go: StartStatsd's TCP/UNIX
            # arms), newline-delimited; TLS (incl. mutual) when the
            # config's tls_* triple is set
            if scheme != "unix":
                family, bind_addr = self._resolve_inet(scheme, rest)
                lsock = socket.socket(family, socket.SOCK_STREAM)
                lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                lsock.bind(bind_addr)
            else:
                if os.path.exists(rest):
                    os.unlink(rest)
                lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                lsock.bind(rest)
            lsock.listen(128)
            self._listen_socks.append(lsock)
            ssl_ctx = self._tls_context() if scheme != "unix" else None
            t = threading.Thread(
                target=self._accept_statsd_streams, args=(lsock, ssl_ctx),
                name=f"statsd-{scheme}-accept", daemon=True)
            t.start()
            self._threads.append(t)
        else:
            raise ValueError(f"unsupported statsd listener {addr!r}")

    def _tls_context(self):
        """Server-side TLS from the config triple (networking.go: the
        tls_key / tls_certificate pair enables TLS on TCP statsd;
        tls_authority_certificate additionally demands client certs —
        mutual TLS)."""
        if not (self.cfg.tls_key and self.cfg.tls_certificate):
            return None
        import ssl
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certfile=self.cfg.tls_certificate,
                            keyfile=self.cfg.tls_key)
        if self.cfg.tls_authority_certificate:
            ctx.load_verify_locations(
                cafile=self.cfg.tls_authority_certificate)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def _accept_statsd_streams(self, lsock: socket.socket, ssl_ctx):
        while not self._stop.is_set():
            try:
                conn, _ = lsock.accept()
            except OSError:
                break
            if ssl_ctx is not None:
                try:
                    conn = ssl_ctx.wrap_socket(conn, server_side=True)
                except Exception:
                    self._count("packet.error")
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
            with self._conns_lock:
                self._stream_conns.add(conn)
            threading.Thread(target=self._read_statsd_stream, args=(conn,),
                             name="statsd-stream", daemon=True).start()

    def _read_statsd_stream(self, conn: socket.socket):
        """Newline-delimited metric lines over a stream connection; a
        line split across reads is reassembled. An oversized line is
        dropped IN FULL: after the drop the reader stays in discard
        mode until the line's terminating newline arrives, so the
        line's later bytes can never be parsed as a fresh metric."""
        max_len = self.cfg.metric_max_length
        tail = b""
        discarding = False
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        data = conn.recv(65536)
                    except OSError:
                        return
                    if not data:
                        if tail and not discarding:
                            self.handle_packet(tail)
                        return
                    if discarding:
                        nl = data.find(b"\n")
                        if nl < 0:
                            continue
                        data = data[nl + 1:]
                        discarding = False
                        if not data:
                            continue
                    buf = tail + data
                    nl = buf.rfind(b"\n")
                    if nl < 0:
                        tail = buf
                        if len(tail) > max_len:
                            # oversized garbage line: drop, count, and
                            # swallow the rest of it
                            self._count("packet.error")
                            tail = b""
                            discarding = True
                        continue
                    self.handle_packet(buf[:nl])
                    tail = buf[nl + 1:]
                    if len(tail) > max_len:
                        self._count("packet.error")
                        tail = b""
                        discarding = True
        finally:
            with self._conns_lock:
                self._stream_conns.discard(conn)

    def _start_ssf_listener(self, addr: str):
        """SSF ingest (Server.StartSSF): udp:// datagrams carry bare
        SSFSpan protobufs; tcp:// and unix:// carry framed streams
        (protocol.ReadSSF)."""
        scheme, _, rest = addr.partition("://")
        if scheme in ("udp", "udp4", "udp6"):
            family, bind_addr = self._resolve_inet(scheme, rest)
            if self._native_ssf and family != socket.AF_INET6:
                # C++ SSF readers: recvmmsg + native decode + ring
                # staging; no Python thread owns this socket. Fallback
                # datagrams come back through the pump's ssf_slow_path.
                self.ssf_native_port = self.native_bridge.start_ssf_udp(
                    bind_addr[0], bind_addr[1],
                    n_readers=max(1, self.cfg.num_readers),
                    rcvbuf=self.cfg.read_buffer_size_bytes,
                    max_dgram=self.cfg.trace_max_length_bytes)
                log.info("native SSF listener on udp://%s:%d",
                         bind_addr[0], self.ssf_native_port)
                return
            sock = socket.socket(family, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(bind_addr)
            self._sockets.append(sock)
            if self._ssf_udp_sock is None:
                self._ssf_udp_sock = sock  # self-trace target
            t = threading.Thread(target=self._read_ssf_packet_socket,
                                 args=(sock,), name="ssf-udp-reader",
                                 daemon=True)
        elif scheme in ("tcp", "tcp4", "tcp6", "unix"):
            if scheme != "unix":
                family, bind_addr = self._resolve_inet(scheme, rest)
                lsock = socket.socket(family, socket.SOCK_STREAM)
                lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                lsock.bind(bind_addr)
            else:
                if os.path.exists(rest):
                    os.unlink(rest)
                lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                lsock.bind(rest)
            lsock.listen(128)
            self._listen_socks.append(lsock)
            t = threading.Thread(target=self._accept_ssf_streams,
                                 args=(lsock,), name=f"ssf-{scheme}-accept",
                                 daemon=True)
        else:
            raise ValueError(f"unsupported SSF listener {addr!r}")
        t.start()
        self._threads.append(t)

    def _read_ssf_packet_socket(self, sock: socket.socket):
        """Server.ReadSSFPacketSocket: one datagram = one SSFSpan."""
        from .ssf import framing

        max_len = self.cfg.trace_max_length_bytes
        native_ssf = self._native_ssf
        while not self._stop.is_set():
            try:
                data, _ = sock.recvfrom(max_len)
            except OSError:
                break
            if native_ssf:
                rc = self.native_bridge.handle_ssf(data)
                if rc == 1:
                    # samples staged in the rings; the pump lands them.
                    # Counted by the bridge's ssf_spans (folded into
                    # telemetry) — NOT spans_received, which would
                    # double-report the same span.
                    continue
                if rc < 0:
                    self._count("ssf.error")
                    continue
                # rc == 0: STATUS samples present — Python path below
            try:
                span = framing.parse_ssf_datagram(data)
            except framing.FramingError:
                self._count("ssf.error")
                continue
            self.handle_ssf_span(span)

    def _accept_ssf_streams(self, lsock: socket.socket):
        while not self._stop.is_set():
            try:
                conn, _ = lsock.accept()
            except OSError:
                break
            with self._conns_lock:
                self._stream_conns.add(conn)
            threading.Thread(target=self._read_ssf_stream, args=(conn,),
                             name="ssf-stream", daemon=True).start()

    def _read_ssf_stream(self, conn: socket.socket):
        """Server.HandleTracePacket over a framed stream; a corrupt
        frame poisons only its own connection."""
        from .ssf import framing

        native_ssf = self._native_ssf
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        payload = framing.read_ssf_frame(conn)
                        if payload is None:
                            return
                        if native_ssf:
                            rc = self.native_bridge.handle_ssf(payload)
                            if rc == 1:
                                # counted via the bridge's ssf_spans
                                continue
                            if rc < 0:
                                self._count("ssf.error")
                                return
                        span = framing.parse_ssf_datagram(payload)
                    except (framing.FramingError, EOFError, OSError):
                        self._count("ssf.error")
                        return
                    self.handle_ssf_span(span)
        finally:
            with self._conns_lock:
                self._stream_conns.discard(conn)

    def handle_ssf_span(self, span):
        """Route one ingested span to the SpanWorker (drop-on-full,
        counted, like the reference's SpanChan)."""
        try:
            self.span_queue.put_nowait(span)
        except queue.Full:
            self._count("worker.dropped")
        # counted after the enqueue so a waiter that observes the count
        # and then drain()s cannot race ahead of the item
        self._count("ssf.received")

    def _span_worker(self):
        """SpanWorker: fan each span out to every span sink."""
        while True:
            span = self.span_queue.get()
            try:
                if span is _STOP:
                    break
                for ss in self.span_sinks:
                    try:
                        ss.ingest(span)
                    except Exception:
                        log.exception("span sink %s ingest failed",
                                      ss.name())
            finally:
                self.span_queue.task_done()

    def _route_metric(self, item):
        """Digest-route one item onto a worker queue — the single
        dispatch point shared by the packet path and the ssfmetrics
        bridge. Events/service checks have no digest and ride on
        queue 0. Drop-on-full is deliberate lossiness under
        backpressure, counted, like veneur's full worker channels."""
        qi = item.digest % len(self.worker_queues) \
            if hasattr(item, "digest") else 0
        try:
            self.worker_queues[qi].put_nowait(item)
        except queue.Full:
            self._count("worker.dropped")

    # -------- engine checkpoint/restore (durability, ISSUE 9) --------

    # in-memory write-ahead retention cap: ops kept for snapshot
    # compaction (a two-checkpoint window normally holds a handful;
    # thousands means compaction stopped running — bound it anyway)
    MAX_RETAINED_IMPORT_OPS = 65536

    def _engine_journal_failed(self, what: str):
        """A failing disk must not fail imports or the flush tick: the
        process degrades to the pre-durability in-memory contract,
        counted and loud (same policy as the watermark journal)."""
        resilience.DEFAULT_REGISTRY.incr("import",
                                         "durability.journal_errors")
        log.exception(
            "engine %s journal op failed; DISABLING engine "
            "checkpointing for this process (in-memory aggregation "
            "unaffected; crash-restart recovery degrades)", what)
        j, self._engine_journal = self._engine_journal, None
        if j is not None:
            try:
                j.close()
            except Exception:
                pass

    def _submit_import_batch(self, pairs, envelope=None):
        """The durable import submit path (wired into importsrv and the
        HTTP /import handler when engine checkpointing is armed): one
        admitted request = one journal op, write-ahead BEFORE any
        worker queue — and therefore before the sender's ack — then
        grouped per target engine so the worker applies each engine's
        share atomically under the op id (the watermark's consistent
        cut). The submit lock makes journal order == queue order, so
        recovery's replay reproduces the original per-engine
        application order exactly. `envelope` (the request's already-
        admitted idempotency envelope) rides in the op record so
        recovery can re-seed the dedupe ledger — recovered state plus
        a forgotten envelope would double-count the sender's replay."""
        from .cluster.importsrv import ImportedBatch
        from .durability import records as drecords
        nq = len(self.worker_queues)
        with self._import_submit_lock:
            op_id = self._next_import_op = self._next_import_op + 1
            if self._engine_journal is not None:
                try:
                    payload = drecords.encode_engine_import(
                        op_id, [pb for _d, pb in pairs], envelope)
                    self._engine_journal.append_import(payload)
                    self._recent_import_ops.append((op_id, payload))
                    if len(self._recent_import_ops) > \
                            self.MAX_RETAINED_IMPORT_OPS:
                        self._recent_import_ops.pop(0)
                        # the history tier seals generations from this
                        # list; an eviction means the next generation
                        # would silently under-count — flag it so the
                        # seal degrades LOUDLY (crash recovery is
                        # unaffected: it reads the full journal)
                        self._import_ops_evicted = True
                except Exception:
                    self._engine_journal_failed("import write-ahead")
            groups: dict[int, list] = {}
            for digest, pb in pairs:
                groups.setdefault(digest % nq, []).append(pb)
            for qi, pbs in groups.items():
                try:
                    self.worker_queues[qi].put_nowait(
                        ImportedBatch(op_id, pbs))
                except queue.Full:
                    # journaled but shed: recovery replays it, live
                    # processing loses it — the pre-durability
                    # backpressure contract, counted per metric
                    self._count("worker.dropped", len(pbs))

    def _recover_engine_state(self):
        """Recovery-before-listen: rebuild the engines from the engine
        journal — the LATEST self-contained checkpoint group per
        engine, then every import op above that engine's applied-op
        watermark, replayed in journal order through the same digest
        routing and grouped apply the live path uses (what makes the
        next flush bit-identical to a zero-crash oracle). Never raises
        on corrupt state: a shape-fingerprint mismatch or undecodable
        group drops the WHOLE recovery loudly (fresh start) rather
        than scattering rows into wrong slots."""
        from .cluster import wire
        from .durability import records as drecords
        from .durability.history import collect_checkpoint_groups
        from .utils.hashing import metric_digest
        tel, S = self.telemetry, observe.SERVER_SCOPE
        t0 = time.monotonic_ns()
        recs = self._engine_journal.load_records()
        # ONE committed-group walk (durability/history.py owns it —
        # the time-travel tier reconstructs generations through the
        # SAME state machine, so the COMMIT discipline cannot drift
        # between what recovery restores and what queries serve):
        # a group counts only once its COMMIT arrived — a crash
        # mid-append leaves META (whose watermark would suppress op
        # replay) without the KEYS/BANK rows that back it, and
        # restoring that would be silent data loss. BANK payloads come
        # back ENCODED (their leaf order is engine-aware) and decode
        # below against the engines this server runs — a journal
        # written by DIFFERENT backends is refused at the fingerprint
        # check before any decoded rows can land.
        latest, op_payloads, torn, errors = \
            collect_checkpoint_groups(recs)
        ops: list = []
        for payload in op_payloads:
            try:
                ops.append(drecords.decode_engine_import(payload))
            except Exception:
                errors += 1
                log.exception(
                    "engine recovery: undecodable import op skipped")
        if errors:
            tel.incr(S, "durability.engine_recovery_errors", errors)
            log.warning("engine recovery: %d undecodable record(s) "
                        "skipped", errors)
        if torn:
            tel.incr(S, "durability.engine_recovery_errors", torn)
            log.warning(
                "engine recovery: %d torn (uncommitted) checkpoint "
                "group(s) dropped — falling back to the previous "
                "complete group(s); ops above their watermark replay",
                torn)
        n = len(self.engines)
        for idx, g in latest.items():
            n_eng = g["meta"][0]
            if idx >= n or n_eng != n:
                log.error(
                    "engine recovery REFUSED: checkpoint was taken "
                    "under %d engine(s), this server runs %d — "
                    "starting fresh (replaying ops against a "
                    "different shard map would double/misplace data)",
                    n_eng, n)
                tel.incr(S, "durability.engine_recovery_errors")
                self._recovery = {"refused": "engine count mismatch"}
                return
        restored = 0
        try:
            for idx, g in latest.items():
                _n_eng, wm, gseq, fpr = g["meta"]
                banks: dict = {}
                for payload in g["banks"]:
                    _i, kind, ids, leaves = \
                        drecords.decode_engine_bank(
                            payload,
                            leaf_names_of=self.engines[idx]
                            .bank_leaf_names)
                    banks[kind] = (ids, leaves)
                self.engines[idx].restore_checkpoint(
                    fpr, gseq, wm, g["keys"], banks, g["staged"])
                restored += 1
        except Exception as e:
            # fingerprint mismatch (ValueError) or an undecodable bank
            # row: refuse the WHOLE recovery loudly — a partial
            # restore would flush silently-wrong state
            log.error("engine recovery REFUSED: %s — starting fresh", e)
            tel.incr(S, "durability.engine_recovery_errors")
            self._recovery = {"refused": str(e)}
            return
        replayed = metrics_replayed = 0
        for op_id, pbs, env in ops:
            if op_id > self._next_import_op:
                self._next_import_op = op_id
            if env is not None and self.dedupe_ledger is not None:
                # re-seed the ledger with the envelope this op was
                # admitted under: its merged state is being recovered,
                # so the sender's ambiguous-failure replay of the same
                # chunk must dedupe, not double-count (ops the
                # retention window compacted away are covered by the
                # durable watermark journal instead — the two windows
                # interlock)
                self.dedupe_ledger.admit(*env)
            by_engine: dict[int, list] = {}
            for pb in pbs:
                try:
                    key = wire.metric_key_of(pb)
                    digest = metric_digest(key.name, key.type,
                                           key.joined_tags)
                except Exception:
                    self._count("import.rejected")
                    continue
                by_engine.setdefault(digest % n, []).append(pb)
            applied = False
            reroutes: list = []
            for ei, epbs in by_engine.items():
                eng = self.engines[ei]
                if op_id <= eng.last_import_op:
                    continue   # inside the restored checkpoint already
                rerouted, rejected = eng.import_list(op_id, epbs)
                reroutes.extend(rerouted)
                for _pb, e in rejected:
                    self._count("import.rejected")
                    log.warning("engine recovery: rejected corrupted "
                                "journaled metric: %s", e)
                applied = True
                metrics_replayed += len(epbs)
            # overload-defense folds homed on other engines replay
            # AFTER every direct share: a reroute stamps the target's
            # watermark to op_id, and doing that before the target's
            # own direct share would make the loop above skip it
            for fr, pb in reroutes:
                digest = _fold_rewrite(pb, fr)
                self.engines[digest % n].import_list(op_id, [pb])
            if applied:
                replayed += 1
            # retain for the next compaction (recovery's conservative
            # window: everything not provably inside every checkpoint)
            self._recent_import_ops.append(
                (op_id, drecords.encode_engine_import(op_id, pbs, env)))
        restore_ns = time.monotonic_ns() - t0
        tel.incr(S, "durability.engine_recovered_ops", replayed)
        tel.incr(S, "durability.engine_recovered_metrics",
                 metrics_replayed)
        tel.set_gauge(S, "durability.engine_restore_ns", restore_ns)
        self._recovery = {
            "engines_restored": restored,
            "ops_replayed": replayed,
            "metrics_replayed": metrics_replayed,
            "restore_ns": restore_ns,
            "generation": self._engine_journal.generation(),
        }
        if restored or replayed:
            log.info("engine recovery: %d engine checkpoint(s) "
                     "restored, %d import op(s) (%d metrics) replayed "
                     "in %.1fms", restored, replayed, metrics_replayed,
                     restore_ns / 1e6)

    def _engine_checkpoint(self, ts: int | None = None,
                           retired_wms: list | None = None):
        """The flush-boundary hook: append one self-contained delta
        checkpoint group per engine (dirty piles only — the swap
        re-zeroed everything else), skip entirely when nothing changed
        (an idle global must not grow the journal), and compact when
        the journal outgrew its budget — the snapshot is the latest
        groups plus the ops the two-checkpoint retention window still
        holds (an op admitted longer ago has had a full interval to
        drain into an engine and be covered by a watermark; the same
        one-interval fuzz the watermark journal documents).

        With the history tier armed (ISSUE 14), the boundary ALSO
        seals the closing interval as a query generation: `ts` is the
        interval-close wall time and `retired_wms` the per-engine
        swap-time watermarks the flush results reported — the
        interval's exact per-engine replay cut."""
        from .durability import records as drecords
        tel, S = self.telemetry, observe.SERVER_SCOPE
        recs: list = []
        dirty = total = 0
        staged_any = interned_any = False
        marks = []
        n = len(self.engines)
        for i, eng in enumerate(self.engines):
            snap = eng.checkpoint_state()
            recs.extend(drecords.encode_engine_checkpoint(i, n, snap))
            dirty += snap["piles_dirty"]
            total += snap["piles_total"]
            staged_any = staged_any or any(
                snap["staged"][f] for f in ("centroids", "sets",
                                            "counters", "gauges"))
            interned_any = interned_any or any(
                entries for _iv, entries in snap["interner"].values())
            marks.append(snap["last_import_op"])
        # a baseline with no bank rows, nothing staged, and no interned
        # keys reconstructs to NOTHING — the next interval can seal as
        # a zero-cost empty generation if it also gets no ops (the
        # history tier's idle path; interner idle-TTL eviction makes a
        # quiet server converge here)
        empty_next = not dirty and not staged_any and not interned_any
        if self._history is not None and ts is not None:
            self._history_seal(ts, retired_wms or [0] * n, recs, marks,
                               empty_next)
        sig = (tuple(marks),
               tuple(len(ki) for eng in self.engines
                     for _k, _a, ki in eng._bank_table()))
        # vlint: disable=TH01 reason=flush-path-only state; flushes are
        # serialized (one flusher thread, tests call flush_once
        # synchronously) and readers (debug/health) tolerate staleness
        self._last_checkpoint_stats = (dirty, total)
        if not dirty and not staged_any \
                and sig == self._last_checkpoint_sig:
            # nothing to persist: every pile is fresh, nothing staged,
            # no new ops, no interner churn — the delta encoding's
            # degenerate (and steady-state idle) case
            tel.incr(S, "durability.engine_delta_skipped_piles", total)
            return
        nbytes = self._engine_journal.append_checkpoint(recs)
        self._engine_journal.sync()
        tel.set_gauge(S, "durability.engine_snapshot_bytes", nbytes)
        tel.incr(S, "durability.engine_delta_skipped_piles",
                 total - dirty)
        # vlint: disable=TH01 reason=flush-path-only state; flushes are
        # serialized (one flusher thread, tests call flush_once
        # synchronously)
        self._last_checkpoint_sig = sig
        # vlint: disable=TH01 reason=flush-path-only state; debug-page
        # readers tolerate staleness
        self._last_checkpoint_t = time.monotonic()
        with self._import_submit_lock:
            cut = self._ops_at_last_checkpoint
            self._recent_import_ops = [
                o for o in self._recent_import_ops if o[0] > cut]
            self._ops_at_last_checkpoint = self._next_import_op
            retained = [(drecords.REC_ENGINE_IMPORT, p)
                        for _id, p in self._recent_import_ops]
            # compaction must run under the submit lock: an op
            # appended between the retention snapshot and the journal
            # truncate would be lost from both
            self._engine_journal.maybe_compact(recs + retained)

    # ---------- time-travel history + query tier (ISSUE 14) ----------

    def _setup_history(self):
        """Arm the retention store + query tier (called from __init__,
        inside the engine-journal-armed branch, AFTER recovery): the
        post-recovery consistent cut becomes the FIRST generation's
        baseline, and the query tier gets a factory minting SCRATCH
        engines from a copy of the live engine shape — it never holds
        a reference to the live pipeline (read-path isolation, vlint
        QT01)."""
        import dataclasses

        from .durability import HistoryStore, QueryTier
        cfg = self.cfg
        self._history = HistoryStore(
            cfg.durability_dir,
            retention_generations=cfg.history_retention_generations,
            retention_seconds=_parse_interval(
                cfg.history_retention_seconds),
            fsync=cfg.durability_fsync != "never",
            registry=self.telemetry)
        self._history_baseline = self._capture_history_baseline()
        # the next generation's open edge: the newest RETAINED close
        # stamp (a restart continues the timeline where it left off —
        # the first post-restart interval absorbs the crash window),
        # else 0 — NOT wall-now, because flush timestamps may be
        # scripted (tests, replay rigs) and an epoch open edge would
        # postdate the first scripted close; a fresh store's first
        # generation simply claims everything before its close
        retained = self._history.entries()
        self._history_prev_close_ns = (retained[-1].close_ns
                                       if retained else 0)
        ecfg = self.engines[0].cfg

        def scratch_factory(percentiles=None, aggregates=None,
                            merge=False):
            # merge=False: a per-generation reconstruction engine —
            # forward-enabled so its flush builds the export rows the
            # merge stage consumes. merge=True: the cross-interval
            # merge engine — global-tier presentation so its frame
            # carries percentiles (the requested quantiles) for every
            # live key. Neither flag is part of the checkpoint
            # fingerprint, so restores match the live shape exactly.
            kw = dict(forward_enabled=not merge, is_global=merge)
            if percentiles is not None:
                kw["percentiles"] = tuple(percentiles)
            if aggregates is not None:
                kw["aggregates"] = tuple(aggregates)
            return AggregationEngine(dataclasses.replace(ecfg, **kw))

        self._query_tier = QueryTier(
            self._history, scratch_factory, len(self.engines),
            flight=self.flight, registry=self.telemetry,
            scope=observe.SERVER_SCOPE,
            engines_describe=self.engines[0].engines_describe(),
            max_concurrent=cfg.query_max_concurrent,
            cache_entries=cfg.query_cache_entries,
            timeout_s=_parse_interval(cfg.query_timeout))

    def _capture_history_baseline(self):
        """(records, per-engine watermarks, provably-empty flag) of a
        consistent cut across every engine — the baseline the NEXT
        closed interval reconstructs on top of."""
        from .durability import records as drecords
        recs: list = []
        marks: list = []
        empty = True
        n = len(self.engines)
        for i, eng in enumerate(self.engines):
            snap = eng.checkpoint_state()
            recs.extend(drecords.encode_engine_checkpoint(i, n, snap))
            marks.append(snap["last_import_op"])
            if snap["piles_dirty"] or any(
                    snap["staged"][f] for f in ("centroids", "sets",
                                                "counters", "gauges")) \
                    or any(entries for _iv, entries
                           in snap["interner"].values()):
                empty = False
        return recs, marks, empty

    def _history_seal(self, ts: int, retired_wms: list, recs: list,
                      marks: list, empty_next: bool = False):
        """Seal the interval that just flushed as one query
        generation: its baseline is the PREVIOUS boundary's checkpoint
        groups, its ops everything write-aheaded above the baseline's
        lowest watermark (the per-engine exact cut — baseline wm <
        op_id <= retire wm — is applied at query time, exactly like
        recovery's replay filter), its close stamp the flush's wall
        timestamp (scripted clocks stay scripted end to end). Runs on
        the flusher thread; a failing disk degrades history loudly
        without failing the tick (the journal-error policy)."""
        tel, S = self.telemetry, observe.SERVER_SCOPE
        try:
            base_recs, base_marks, base_empty = self._history_baseline
            min_wm = min(base_marks) if base_marks else 0
            with self._import_submit_lock:
                op_recs = [(i, p) for i, p in self._recent_import_ops
                           if i > min_wm]
                evicted, self._import_ops_evicted = \
                    self._import_ops_evicted, False
            if evicted:
                # the in-memory retention cap dropped ops this
                # interval: the generation seals INCOMPLETE. Loud +
                # counted — a silent under-count would violate the
                # tier's exactness contract (crash recovery still has
                # the full journal; only history is lossy here)
                tel.incr(S, "durability.history_truncated")
                log.warning(
                    "history: MAX_RETAINED_IMPORT_OPS (%d) evicted "
                    "import ops this interval — the sealed generation "
                    "under-counts; raise the cap or shorten the flush "
                    "interval", self.MAX_RETAINED_IMPORT_OPS)
            close_ns = int(ts) * 1_000_000_000
            if base_empty and not op_recs:
                # provably-empty interval: a manifest row, not a
                # segment (consecutive ones coalesce — an idle tier
                # must not write a segment + fsyncs per tick)
                self._history.append_empty(
                    close_ns, self._history_prev_close_ns)
            else:
                self._history.append(close_ns,
                                     self._history_prev_close_ns,
                                     retired_wms, base_recs, op_recs)
            # vlint: disable=TH01 reason=flush-path-only state; flushes
            # are serialized (one flusher thread, tests call flush_once
            # synchronously)
            self._history_baseline = (recs, marks, empty_next)
            # vlint: disable=TH01 reason=flush-path-only state (above)
            self._history_prev_close_ns = close_ns
            hs = self._history.debug_state()
            tel.set_gauge(S, "history.generations", hs["generations"])
            tel.set_gauge(S, "history.bytes", hs["bytes"])
        except Exception:
            tel.incr(S, "durability.journal_errors")
            log.exception(
                "history generation seal failed; DISABLING the "
                "time-travel tier for this process (aggregation and "
                "crash recovery unaffected)")
            # vlint: disable=TH01 reason=monotone one-way degrade on
            # the flusher thread; readers (query path, debug page)
            # tolerate either value across the flip
            self._history = None
            if self._query_tier is not None:
                self._query_tier.close()
                # vlint: disable=TH01 reason=same one-way degrade; the
                # http wiring null-checks per request
                self._query_tier = None

    def _serve_query(self, params: dict) -> dict:
        """GET /query backend (http_api wires it when the tier is
        armed): runs on the query tier's dedicated executor, never on
        the ingest/flush path."""
        from .durability import QueryError
        tier = self._query_tier
        if tier is None:    # disk-error degrade after the listener bound
            raise QueryError(
                503, "time-travel tier disabled after a disk error "
                     "(see veneur.durability.journal_errors_total)")
        return tier.query(params)

    def _start_import_listener(self, addr: str):
        """Global-mode gRPC receive path (importsrv): forwarded metrics
        are re-hashed onto the worker queues and merged via Combine."""
        from .cluster.importsrv import start_import_server

        nq = len(self.worker_queues)

        def submit(digest, imported):
            try:
                self.worker_queues[digest % nq].put_nowait(imported)
            except queue.Full:
                self._count("worker.dropped")

        server, port = start_import_server(
            addr, submit, ledger=self.dedupe_ledger,
            observer=self.import_observer,
            submit_batch=(self._submit_import_batch
                          if self._engine_journal is not None else None),
            engine_stamp=self.engine_stamp,
            note_stamp=self._note_sketch_stamp,
            merge_sketches=self.merge_prefix_sketches)
        self._grpc_servers.append(server)
        self.grpc_port = port

    def _start_http_api(self, addr: str):
        """Ops HTTP listener (handlers.go): healthchecks + the legacy
        POST /import path, which feeds the same Combine machinery as
        gRPC import."""
        from .cluster.importsrv import ImportedMetric
        from .http_api import HttpApi

        nq = len(self.worker_queues)

        def submit(digest, pb):
            try:
                self.worker_queues[digest % nq].put_nowait(
                    ImportedMetric(pb))
            except queue.Full:
                self._count("worker.dropped")

        self.http_api = HttpApi(
            addr, submit=submit, ledger=self.dedupe_ledger,
            debug_state=self._debug_flush_state,
            observer=self.import_observer,
            fleet_state=self._debug_fleet_state,
            health=self.health_state,
            submit_batch=(self._submit_import_batch
                          if self._engine_journal is not None else None),
            engine_stamp=self.engine_stamp,
            note_stamp=self._note_sketch_stamp,
            merge_sketches=self.merge_prefix_sketches,
            # time-travel query tier (ISSUE 14): absent = 404, so an
            # operator can tell "not armed" from "bad query"
            query=(self._serve_query
                   if self._query_tier is not None else None),
            # the profiler trigger only exists when the operator opted
            # in via debug_flush_profile (a capture is a debug action)
            profile=(self.request_profile_capture
                     if self.cfg.debug_flush_profile else None))
        self.http_api.start()

    def bound_port(self) -> int:
        """Port of the first UDP socket (for tests binding port 0)."""
        if self.native_bridge is not None and not self._sockets:
            return self.native_bridge.bound_port()
        return self._sockets[0].getsockname()[1]

    def _read_metric_socket(self, sock: socket.socket):
        """[HOT LOOP 1] recvfrom -> split -> parse -> route
        (Server.ReadMetricSocket + HandleMetricPacket)."""
        max_len = self.cfg.metric_max_length
        while not self._stop.is_set():
            try:
                data, _ = sock.recvfrom(max_len)
            except OSError:
                break
            self.handle_packet(data)

    def handle_packet(self, data: bytes):
        if self.native_bridge is not None:
            # the bridge counts packets/errors itself; folded into
            # self-metrics at flush
            self.native_bridge.handle_packet(data)
            return
        # Overload backpressure (ingest/admission.py): when the
        # governor is engaged, shed WHOLE datagrams pre-parse at the
        # adaptive rate (the cheapest possible drop — no parse, no
        # queue; counted as veneur.overload.shed_packets_total) and
        # rate-correct the surviving counter/timer/histogram samples
        # so flushed totals stay unbiased. Disengaged (the steady
        # state, and always when the defense is off) this costs one
        # attribute load + None check per datagram.
        adm = self.admission
        shed_rate = 1.0
        if adm is not None and adm.shed_rate < 1.0:
            if adm.admit_packet() is None:
                # the datagram WAS received; its loss is the counted
                # degradation (received == applied + counted_degraded)
                self._count("packet.received")
                return
            shed_rate = adm.shed_rate
        for line in data.split(b"\n"):
            if not line:
                continue
            try:
                item = parser.parse_packet(line, self._exclude_tags,
                                           self._max_name_len,
                                           self._max_tag_len)
            except parser.ParseError:
                self._count("packet.error")
                continue
            if shed_rate < 1.0 and isinstance(item, parser.UDPMetric) \
                    and item.key.type in self._rate_corrected_types:
                # survivor of the shed lottery: weight it up so
                # counter totals / histogram weights stay unbiased
                item.sample_rate = max(item.sample_rate * shed_rate,
                                       1e-9)
            self._route_metric(item)
        # counted after routing so a waiter that observes the count and
        # then drain()s cannot race ahead of the lines
        self._count("packet.received")

    def _worker_loop(self, idx: int, q: queue.Queue):
        """[HOT LOOP 2] queue -> engine (Worker.Work +
        Worker.ImportMetricGRPC for forwarded metrics)."""
        from .cluster.importsrv import ImportedBatch, ImportedMetric
        from .cluster.wire import apply_metric_to_engine
        from .models import pipeline

        eng = self.engines[idx]
        while True:
            item = q.get()
            try:
                if item is _STOP:
                    break
                if isinstance(item, parser.UDPMetric):
                    eng.process(item)
                elif isinstance(item, ImportedBatch):
                    # durable import path: one journaled op's share for
                    # this engine, applied atomically so the engine's
                    # applied-op watermark is an exact replay cut
                    rerouted, rejected = eng.import_list(item.op_id,
                                                         item.pbs)
                    for fr, pb in rerouted:
                        # fold key homed on another engine: rewrite and
                        # re-route under the SAME op id (single-homed
                        # folds, as the per-metric path does)
                        digest = _fold_rewrite(pb, fr)
                        try:
                            self.worker_queues[
                                digest
                                % len(self.worker_queues)].put_nowait(
                                ImportedBatch(item.op_id, [pb]))
                        except queue.Full:
                            self._count("worker.dropped")
                    for pb, e in rejected:
                        self._count("import.rejected")
                        log.warning(
                            "rejected corrupted imported metric "
                            "%r: %s", getattr(pb, "name", "?"), e)
                elif isinstance(item, ImportedMetric):
                    # poison-pill guard: a corrupted forwarded payload
                    # (bad HLL blob, malformed centroid list) must
                    # reject THAT metric, not kill this worker loop —
                    # without the catch, one bad sender starves a
                    # whole queue shard forever
                    try:
                        apply_metric_to_engine(eng, item.pb)
                    except pipeline.ImportFoldReroute as fr:
                        # overload defense: the fold key is homed on
                        # another engine — rewrite the aggregate onto
                        # it and re-route (single-homed folds; the
                        # home engine admits it as an ordinary import)
                        digest = _fold_rewrite(item.pb, fr)
                        try:
                            self.worker_queues[
                                digest
                                % len(self.worker_queues)].put_nowait(item)
                        except queue.Full:
                            self._count("worker.dropped")
                    except Exception as e:
                        self._count("import.rejected")
                        log.warning(
                            "rejected corrupted imported metric "
                            "%r: %s", getattr(item.pb, "name", "?"), e)
                elif isinstance(item, parser.Event):
                    eng.process_event(item)
                else:
                    eng.process_service_check(item)
            finally:
                q.task_done()

    def drain(self, timeout: float = 10.0, *, clock=time.monotonic,
              sleep=time.sleep) -> bool:
        """Block until every enqueued span and metric has been fully
        processed by its worker (not merely popped). Deterministic
        replacement for sleep-based settling in tests: uses the queues'
        unfinished-task accounting, so an item mid-`eng.process` still
        counts as in flight. `clock`/`sleep` are injectable (the fault
        harness's FakeClock) so the deadline-expiry path is testable
        without real waiting."""
        deadline = clock() + timeout
        if self.native_pump is not None:
            # bridge rings + slow path first; slow-path items land on the
            # worker queues, which the loop below then settles
            if not self.native_pump.drain(timeout):
                return False
        queues = [self.span_queue] + self.worker_queues
        while True:
            if all(q.unfinished_tasks == 0 for q in queues):
                return True
            if clock() >= deadline:
                return False
            sleep(0.005)

    # ------------- flush -------------

    def _flush_loop(self):
        interval = self.cfg.interval_seconds
        next_t = time.monotonic() + interval
        if self.cfg.synchronize_with_interval:
            # align ticks to wall-clock multiples of the interval
            now = time.time()
            next_t = time.monotonic() + (interval - now % interval)
        while not self._stop.wait(max(0.0, next_t - time.monotonic())):
            next_t += interval
            try:
                self.flush_once()
                self._last_flush_ok = time.monotonic()
            except Exception as e:
                log.exception("flush failed")
                self._count("flush.error")
                if self._sentry is not None:
                    self._sentry.capture(e, "flush failed")

    def flush_once(self, timestamp: int | None = None):
        """One flush tick: drain engines, fan out, forward
        (Server.Flush). Returns the flush's FrameSet — iterable of
        InterMetrics; frame-native consumers read .frames directly and
        InterMetric objects are only ever built lazily, inside whichever
        sink thread first needs them.

        With the flight recorder on, the tick's phase tree (engine
        drain / device dispatch / device exec / materialize / per-sink
        fan-out / forward ladder / durability ops) lands in the ring
        behind /debug/flush, replays as an SSF span tree through the
        server's own trace client (flusher.go self-tracing parity), and
        its top-level durations are re-ingested as LOCAL-ONLY
        veneur.flush.phase.* timers — the engine serving percentiles of
        its own flush."""
        t0 = time.monotonic()
        ts = int(timestamp if timestamp is not None else time.time())
        tick = token = None
        if self.flight is not None:
            tick = self.flight.begin_tick(ts)
            if timestamp is not None:
                # scripted/explicit timestamps stay scripted all the
                # way through the e2e accounting: the interval-close
                # stamp the forward envelopes carry (and the fleet
                # view's merge clock) derives from the SAME value, so
                # close->merged latency is deterministic under the
                # fault harness's pinned clocks
                tick.close_ns = int(timestamp * 1_000_000_000)
            token = observe.set_current_tick(tick)
        self._maybe_profile_start()
        try:
            if tick is None and self.trace_client is not None:
                # flight_recorder: false must not silence the flush
                # self-trace entirely — emit the root veneur.flush
                # span the pre-recorder wrapper always produced (the
                # per-phase children do require the recorder)
                from . import trace as trace_mod
                from .observe.registry import flush_span_name
                with trace_mod.start_span(self.trace_client,
                                          flush_span_name(),
                                          service="veneur"):
                    frameset = self._flush_tick(ts, t0, tick)
            else:
                frameset = self._flush_tick(ts, t0, tick)
        finally:
            # a failing (or killed — SimulatedKill/SIGKILL chaos) tick
            # still closes its record: the ring is process-local state
            # with no journal interaction, so a crash can never leave
            # it half-written for the next incarnation
            if token is not None:
                observe.reset_current_tick(token)
            if tick is not None:
                self.flight.end_tick(tick)
                if self.trace_client is not None:
                    self.flight.emit_spans(tick, self.trace_client)
            self._maybe_profile_stop()
        if tick is not None and self.cfg.flush_phase_timers:
            # dogfood loop: the NEXT tick's flush serves percentiles of
            # THIS tick's phases, flushed like any tenant metric
            for m in observe.phase_timer_samples(tick):
                self._route_metric(m)
        if tick is not None and tick.dropped:
            # ring-overflow export: phases the slot budget dropped are
            # counted in the tick AND surfaced as a self-metric
            # (veneur.observe.phases_dropped_total, drained next
            # interval) so attribution gaps are visible in dashboards,
            # not only to a /debug/flush reader
            self._count("observe.phases_dropped", tick.dropped)
        self.telemetry.incr_level(observe.SERVER_SCOPE, "flush.count")
        return frameset

    def _flush_tick(self, ts: int, t0: float, tick):
        """The tick body (split from flush_once so recorder lifecycle
        wraps it exactly once). `tick` is the TickRecord or None."""
        frames = []
        merged_export = ForwardExport()
        events, checks = [], []
        status_metrics = []
        eng_stats = {"samples": 0, "dropped_no_slot": 0,
                     "swap_ns": 0, "merge_ns": 0, "assembly_ns": 0}
        # Engines flush concurrently so their device→host transfers
        # overlap: on the tunneled backend each device_get pays a
        # ~65-90ms wire floor, and N engines in sequence pay it N
        # times; in parallel they pay ~1×. Single engine = no thread.
        results: list = [None] * len(self.engines)
        eng_ph: list = [-1] * len(self.engines)
        # Delta forwarding (ISSUE 13): ask the forwarder what THIS
        # interval's export build should be — "delta" (dirty-bitmap
        # subset) unless a full resync is due/forced or deltas are off.
        # Engines that cannot honor it (mesh, tracking off) degrade to
        # full and say so in export.kind.
        fkind = "full"
        if self.forwarder is not None:
            nfk = getattr(self.forwarder, "next_forward_kind", None)
            if nfk is not None:
                fkind = nfk()
        ep = -1 if tick is None else tick.start("engine")
        if len(self.engines) == 1:
            eng_ph[0] = -1 if tick is None else \
                tick.start("engine.flush", ep)
            results[0] = self.engines[0].flush(timestamp=ts,
                                               forward_kind=fkind)
            if tick is not None:
                tick.finish(eng_ph[0], engine=0)
        else:
            def _one(i, eng):
                ph = -1 if tick is None else \
                    tick.start("engine.flush", ep)
                eng_ph[i] = ph
                try:
                    results[i] = eng.flush(timestamp=ts,
                                           forward_kind=fkind)
                except BaseException as e:
                    results[i] = e
                finally:
                    if tick is not None:
                        tick.finish(ph, engine=i)
            ths = [threading.Thread(target=_one, args=(i, eng),
                                    daemon=True,
                                    name=f"engine-flush-{i}")
                   for i, eng in enumerate(self.engines)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
        for i, (eng, res) in enumerate(zip(self.engines, results)):
            if isinstance(res, BaseException):
                raise res
            if res is None:   # a flush thread died; surface it
                raise RuntimeError("engine flush failed")
            for k in eng_stats:
                eng_stats[k] += res.stats.get(k, 0)
            if tick is not None:
                # graft the engine's own stamps (drain / device
                # dispatch / device exec / fetch / materialize) under
                # its engine.flush phase, with their real edges
                for nm, p0, p1 in res.stats.get("phases", ()):
                    tick.add("engine." + nm, p0, p1, parent=eng_ph[i])
            frames.append(res.frame)
            status_metrics.extend(res.status_metrics)
            merged_export.histograms.extend(res.export.histograms)
            merged_export.sets.extend(res.export.sets)
            merged_export.counters.extend(res.export.counters)
            merged_export.gauges.extend(res.export.gauges)
            merged_export.set_engine = res.export.set_engine
            ev, ch = eng.drain_events()
            events.extend(ev)
            checks.extend(ch)
        # the merged interval is a FULL resync only if EVERY engine
        # actually built one; any delta share makes the whole payload
        # incomplete, so stamp it delta (which claims less — a safe
        # under-claim; in practice engines share one config and agree).
        # The forwarder's resync bookkeeping keys off this.
        merged_export.kind = ("delta" if any(
            r.export.kind == "delta" for r in results) else "full")
        if tick is not None:
            tick.finish(ep)

        if self.fleet is not None:
            # e2e boundary: every interval admitted before this drain
            # is now merged into flushed state — turn the pending close
            # stamps into close->merged latency samples. The timers
            # dogfood through the engine NEXT tick (like phase timers)
            # and are LOCAL-ONLY; the freshness watermark rides the
            # registry as a per-sender gauge. One-interval fuzz for
            # chunks still in a worker queue at drain time — the same
            # documented fuzz as the dedupe watermark journal.
            fp = -1 if tick is None else tick.start("fleet")
            now_ns = (tick.close_ns if tick is not None
                      else int(ts) * 1_000_000_000)
            e2e = self.fleet.on_flush(now_ns)
            for sid, age in self.fleet.freshness(now_ns).items():
                self.telemetry.set_gauge(f"sender:{sid}",
                                         "e2e.freshness_age_ns", age)
            for m in observe.e2e_timer_samples(e2e):
                self._route_metric(m)
            if tick is not None:
                tick.finish(fp, senders=len(e2e),
                            intervals=sum(len(v) for v in e2e.values()))

        tp = -1 if tick is None else tick.start("telemetry")
        frameset = FrameSet(
            frames,
            status_metrics + self._self_metrics(ts, t0, eng_stats))
        if tick is not None:
            tick.finish(tp)
        fo = -1 if tick is None else tick.start("fanout")
        self._fan_out(frameset, events, checks, tick=tick, parent=fo)
        if tick is not None:
            tick.finish(fo)

        # per-prefix cardinality sketches ride to the global tier when
        # the defense is on (merge-by-max there; advisory, excluded
        # from the replay journal — a lost interval's rows are
        # strictly dominated by the next interval's)
        if self.admission is not None and self.forwarder is not None:
            merged_export.prefix_sketches = \
                self.admission.export_sketches()

        # forward when the interval produced exports OR earlier spilled
        # sketches await re-merge — an idle interval must still retry a
        # recovered endpoint, or spilled data strands in the buffer
        if self.forwarder is not None and (
                merged_export.histograms or merged_export.sets
                or merged_export.counters or merged_export.gauges
                or getattr(self.forwarder, "pending_spill", 0)):
            fw = -1 if tick is None else tick.start("forward")
            # re-scope the contextvar so the ladder's attempt/replay/
            # journal phases nest under `forward`, not beside it
            ftok = observe.set_current_tick(tick, fw) \
                if tick is not None else None
            # bytes-on-the-wire accounting (ISSUE 13): the leaf
            # forwarders count veneur.forward.bytes* per delivered
            # chunk; sample the cumulative totals around the call so
            # /debug/fleet can show THIS interval's bytes next to e2e
            bytes_before = resilience.DEFAULT_REGISTRY \
                .totals_by_name_prefix("forward.bytes")
            try:
                self.forwarder(merged_export)
                with self._stats_lock:
                    self._last_forward_err = None
            except Exception as e:
                log.exception("forward failed")
                # a sustained outage (breaker open / no destinations)
                # fails every tick with the same error; capture each
                # DISTINCT failure once, not one event per interval —
                # the resilience counters carry the per-tick signal
                sig = f"{type(e).__name__}: {e}"
                with self._stats_lock:
                    repeat = sig == self._last_forward_err
                    self._last_forward_err = sig
                if self._sentry is not None and not repeat:
                    self._sentry.capture(e, "forward failed")
            finally:
                if ftok is not None:
                    observe.reset_current_tick(ftok)
                if tick is not None:
                    tick.finish(fw)
                bytes_after = resilience.DEFAULT_REGISTRY \
                    .totals_by_name_prefix("forward.bytes")
                sample = {}
                for (scope, name), v in bytes_after.items():
                    d = v - bytes_before.get((scope, name), 0)
                    if d:
                        sample.setdefault(scope, {})[name] = d
                with self._stats_lock:
                    self._last_forward_bytes = {
                        "kind": merged_export.kind,
                        "by_destination": sample,
                    }
        # durability flush boundary: fsync + compact the forward
        # journal, and record the dedupe ledger's per-sender admitted
        # watermarks (everything admitted up to here rides in flushed
        # state no later than the NEXT tick — the one-interval fuzz is
        # documented in README "Durable state")
        dp = -1
        dtok = None
        if tick is not None and (
                self._forward_journal is not None
                or self._engine_journal is not None
                or (self._dedupe_journal is not None
                    and self.dedupe_ledger is not None)):
            dp = tick.start("durability")
            dtok = observe.set_current_tick(tick, dp)
        try:
            if self._engine_journal is not None:
                try:
                    # engine delta checkpoint: the banks were just
                    # swapped, so `fresh + dirty rows` is the whole
                    # post-flush state; everything admitted since rides
                    # the write-ahead import ops. The per-engine
                    # swap-time watermarks seal the closed interval as
                    # a time-travel generation (ISSUE 14).
                    self._engine_checkpoint(
                        ts, [r.stats.get("retired_import_op", 0)
                             for r in results])
                except Exception:
                    self._engine_journal_failed("checkpoint")
            if self._forward_journal is not None:
                jt = getattr(self.forwarder, "journal_tick", None)
                if jt is not None:
                    jt()  # journal failures degrade inside the forwarder
            if self._dedupe_journal is not None and \
                    self.dedupe_ledger is not None:
                try:
                    # record LAST tick's snapshot, capture this tick's:
                    # a seq admitted during this tick may not be in the
                    # state this tick flushed (worker-queue residency),
                    # so it only becomes a durable floor once a full
                    # interval has carried it into a flush. A crash
                    # loses at most the watermark advance of the last
                    # two ticks — replays of those seqs re-admit, which
                    # the receiver-side dedupe ledger bounds exactly as
                    # before durability existed.
                    marks = self._pending_watermarks
                    # vlint: disable=TH01 reason=flush-path-only state;
                    # flushes are serialized (one flusher thread, tests
                    # call flush_once synchronously)
                    self._pending_watermarks = \
                        self.dedupe_ledger.max_admitted()
                    self._dedupe_journal.record(marks)
                    self._dedupe_journal.sync()
                except Exception:
                    # a failing disk must not fail the flush tick; the
                    # in-memory ledger keeps deduping, only crash-restart
                    # watermark durability degrades (counted, loud)
                    resilience.DEFAULT_REGISTRY.incr(
                        "import", "durability.journal_errors")
                    log.exception(
                        "dedupe watermark journal failed; DISABLING it "
                        "for this process (in-memory dedupe unaffected)")
                    try:
                        self._dedupe_journal.close()
                    except Exception:
                        pass
                    # vlint: disable=TH01 reason=flush-path-only state;
                    # flushes are serialized (one flusher thread, tests
                    # call flush_once synchronously) and stop() reads it
                    # only after the last tick ended
                    self._dedupe_journal = None
        finally:
            if dtok is not None:
                observe.reset_current_tick(dtok)
            if dp != -1:
                tick.finish(dp)

        # Overload governor boundary: adapt the shed rate from this
        # tick's wall duration (overrun = the flush can't keep up with
        # ingest) and the worst worker-queue fill, then record the
        # interval's degradation as phases — a storm tick shows its
        # fold/shed volume in the flight-recorder ring, next to the
        # phases explaining WHY the tick overran.
        adm = self.admission
        if adm is not None:
            op = -1 if tick is None else tick.start("overload")
            qfill = max((q.qsize() / q.maxsize
                         for q in self.worker_queues), default=0.0)
            delta = adm.on_tick(time.monotonic() - t0,
                                self.cfg.interval_seconds, qfill)
            if tick is not None:
                if delta["folded"] or delta["sampled_out"] \
                        or delta["over_budget"]:
                    tick.finish(
                        tick.start("overload.fold", op),
                        folded=delta["folded"],
                        sampled_out=delta["sampled_out"],
                        keys_over_budget=delta["over_budget"])
                if delta["shed"]:
                    tick.finish(tick.start("overload.shed", op),
                                shed=delta["shed"])
                tick.finish(op, rate=delta["rate"],
                            overloaded=delta["overloaded"])
        return frameset

    # ------------- on-demand jax.profiler capture -------------
    # GET /debug/flush/profile?ticks=N schedules a capture (gated by
    # debug_flush_profile); the flusher starts the trace before the
    # next tick and stops it after N ticks — the window
    # capture_tpu_window.sh needs for TPU-live phase evidence.

    def request_profile_capture(self, ticks: int = 1) -> dict:
        ticks = max(1, int(ticks))
        with self._stats_lock:
            self._profile_ticks = max(self._profile_ticks, ticks)
            pending = self._profile_ticks
        return {"capture_ticks": pending,
                "dir": self.cfg.debug_flush_profile_dir}

    def _maybe_profile_start(self):
        with self._stats_lock:
            want = self._profile_ticks > 0 and not self._profile_active
            if want:
                self._profile_active = True
        if not want:
            return
        try:
            import jax
            jax.profiler.start_trace(self.cfg.debug_flush_profile_dir)
            log.info("debug/flush: jax profiler capture started -> %s",
                     self.cfg.debug_flush_profile_dir)
        except Exception as e:
            log.warning("debug/flush: jax profiler unavailable: %s", e)
            with self._stats_lock:
                self._profile_active = False
                self._profile_ticks = 0

    def _maybe_profile_stop(self):
        with self._stats_lock:
            if not self._profile_active:
                return
            self._profile_ticks -= 1
            done = self._profile_ticks <= 0
        if not done:
            return
        try:
            import jax
            jax.profiler.stop_trace()
            log.info("debug/flush: jax profiler capture complete")
        except Exception as e:
            log.warning("debug/flush: profiler stop failed: %s", e)
        with self._stats_lock:
            self._profile_active = False

    def _debug_flush_state(self) -> dict:
        """GET /debug/flush payload: the flight-recorder ring plus the
        breaker/ladder/journal/dedupe-ledger state a flush-latency
        investigation needs next (schema in README 'Observability')."""
        fwd = self.forwarder
        state = {
            "flush_count": self.flush_count,
            # active sketch engines + wire stamp (ISSUE 10): what this
            # server merges and declares on every forwarded chunk
            "sketch_engines": self.engines[0].engines_describe(),
            "flight_recorder": (None if self.flight is None
                                else self.flight.debug_state()),
            "forward": (fwd.debug_state()
                        if hasattr(fwd, "debug_state") else None),
            # overload defense: budgets, per-prefix cardinality
            # estimates, governor rate, fold/shed counters
            "admission": (self.admission.debug_state()
                          if self.admission is not None
                          else {"enabled": False}),
            "dedupe_ledger": None,
            "durability": {
                "forward_journal_bytes": (
                    self._forward_journal.size_bytes()
                    if self._forward_journal is not None else None),
                "watermark_journal_bytes": (
                    self._dedupe_journal.size_bytes()
                    if self._dedupe_journal is not None else None),
                "engine_checkpoint": self._engine_checkpoint_state(),
                # time-travel history tier (ISSUE 14): retained
                # generations + query-path counters/cache
                "history": (self._history.debug_state()
                            if self._history is not None else None),
            },
            "query": (self._query_tier.debug_state()
                      if self._query_tier is not None else None),
            "registry": {
                "server": self.telemetry.debug_state(),
                "process": resilience.DEFAULT_REGISTRY.debug_state(),
            },
        }
        if self.dedupe_ledger is not None:
            state["dedupe_ledger"] = {
                "size": self.dedupe_ledger.size(),
                "senders": self.dedupe_ledger.sender_count(),
                "watermarks": self.dedupe_ledger.max_admitted(),
            }
        return state

    def _engine_checkpoint_state(self) -> dict | None:
        """The /debug/flush checkpoint block: generation, journal and
        last-delta bytes, the dirty/total pile ratio of the last
        boundary, the last-checkpoint age, and the restore stats of
        this incarnation's recovery (None when the feature is off)."""
        if not self._engine_journal_armed:
            return None
        j = self._engine_journal
        dirty, total = self._last_checkpoint_stats
        return {
            "enabled": j is not None,   # False = degraded (disk error)
            "generation": j.generation() if j is not None else None,
            "journal_bytes": j.size_bytes() if j is not None else None,
            "last_snapshot_bytes": (j.last_checkpoint_bytes
                                    if j is not None else None),
            "piles_dirty": dirty,
            "piles_total": total,
            "dirty_ratio": round(dirty / total, 6) if total else 0.0,
            "last_checkpoint_age_s": (
                round(time.monotonic() - self._last_checkpoint_t, 3)
                if self._last_checkpoint_t is not None else None),
            "pending_import_ops": len(self._recent_import_ops),
            "restore": self._recovery,
        }

    # health verdict threshold: a flush is STALLED once its lag exceeds
    # this many intervals (1.5 = the check flips within one interval of
    # the first missed tick, without flapping on ordinary jitter)
    HEALTH_STALL_INTERVALS = 1.5

    def health_state(self, now: float | None = None,
                     fwd_state: dict | None = None) -> dict:
        """Structured verdicts for GET /healthz and /ready. `healthy`
        is the hard bit — false ONLY when the flush loop is stalled
        (the crash-only failure mode made observable from outside);
        the remaining checks are degradation signals (breaker open,
        journal degraded, governor shedding, queue fill) that flag
        `status: degraded` without failing the probe — supervisors
        must not restart a server that is correctly load-shedding.
        `now` is injectable (fault harness); `fwd_state` lets a caller
        that already computed the forwarder's debug_state (the
        /debug/fleet page embeds this verdict) pass it in instead of
        rebuilding the per-entry ladder list."""
        now = time.monotonic() if now is None else now
        interval = self.cfg.interval_seconds
        lag = now - self._last_flush_ok
        started = self._started
        stalled = started and lag > self.HEALTH_STALL_INTERVALS * interval
        checks = {
            "flush": {"ok": not stalled, "lag_s": round(lag, 3),
                      "interval_s": interval,
                      "stalled_ticks_total": self.telemetry.total(
                          observe.SERVER_SCOPE, "watchdog.stalled_ticks")},
        }
        fwd = self.forwarder
        if fwd_state is not None or hasattr(fwd, "debug_state"):
            # same introspection path /debug/flush and /debug/fleet
            # consume — ONE owner of the forwarder-internals dig
            st = fwd_state if fwd_state is not None else fwd.debug_state()
            bstate = st["breaker_state"]
            pending = st["pending_spill"]
            checks["forward"] = {
                "ok": bstate != "open" and not pending,
                "breaker_state": bstate,
                "pending_spill": pending,
                "ladder_depth": len(st["ladder"]),
            }
        degraded_journals = []
        if self.cfg.durability_enabled:
            if (self._forward_journal is not None
                    and getattr(self.forwarder, "_journal", None) is None
                    and isinstance(self.forwarder,
                                   resilience.ResilientForwarder)):
                degraded_journals.append("forward")
            if self.dedupe_ledger is not None \
                    and self._dedupe_journal is None:
                degraded_journals.append("dedupe_watermarks")
            if self._engine_journal_armed and self._engine_journal is None:
                degraded_journals.append("engine")
            checks["journal"] = {"ok": not degraded_journals,
                                 "degraded": degraded_journals}
        if self._engine_journal_armed or self._recovery is not None:
            # recovery-before-listen verdict: in_progress until start()
            # completes (the /ready "recovering" window), then the
            # restore stats — what was restored/replayed and how long
            # it took — stay on the page. A REFUSED recovery (shape
            # fingerprint / engine-count mismatch: journaled state was
            # discarded, fresh start) keeps ok=false so a monitor
            # keying on status sees the data-loss condition, like the
            # disk-failure path does via the journal check.
            refused = bool((self._recovery or {}).get("refused"))
            checks["recovery"] = {
                "ok": not self._recovering and not refused,
                "in_progress": self._recovering,
                **(self._recovery or {}),
            }
        if self.admission is not None:
            rate = self.admission.shed_rate
            checks["overload"] = {"ok": rate >= 1.0, "shed_rate": rate}
        qfill = max((q.qsize() / q.maxsize for q in self.worker_queues),
                    default=0.0)
        checks["queues"] = {"ok": qfill < 0.9, "fill": round(qfill, 4)}
        degraded = any(not c["ok"] for c in checks.values())
        recovering = self._recovering
        return {
            "healthy": not stalled,
            "ready": started and not recovering
                     and not self._stop.is_set(),
            "status": ("recovering" if recovering
                       else "stalled" if stalled
                       else "degraded" if degraded else "ok"),
            "checks": checks,
        }

    def _note_sketch_stamp(self, sender_id: str, stamp, ok: bool):
        """Record one import request's engine-stamp verdict (both the
        gRPC and HTTP paths route here): per-sender row in the fleet
        view + the veneur.import.engine_mismatch_total counter on
        reject — the loud half of the mixed-fleet contract."""
        if self.fleet is not None:
            self.fleet.note_stamp(sender_id, stamp, ok)
        if not ok:
            resilience.DEFAULT_REGISTRY.incr("import",
                                             "import.engine_mismatch")

    # distinct prefixes the fleet cardinality map will hold — the same
    # bounded-memory posture as the admission controller's own
    # max_prefixes (a network-facing receiver must stay bounded however
    # many prefixes senders churn through); overflow rows are dropped
    # and counted
    MAX_FLEET_SKETCH_PREFIXES = 4096

    def merge_prefix_sketches(self, items):
        """Merge received per-prefix Huffman-Bucket cardinality rows
        (merge-by-max — idempotent under replays) into the fleet map
        served at /debug/fleet, so fleet-wide cardinality is ONE
        estimate, not per-shard guesses. Bounded: prefixes past
        MAX_FLEET_SKETCH_PREFIXES are dropped (counted), never grown."""
        dropped = 0
        with self._fleet_sketch_lock:
            for prefix, regs in items:
                cur = self._fleet_sketches.get(prefix)
                if cur is None:
                    if len(self._fleet_sketches) \
                            >= self.MAX_FLEET_SKETCH_PREFIXES:
                        dropped += 1
                        continue
                    self._fleet_sketches[prefix] = bytearray(regs)
                elif len(cur) != len(regs):
                    # senders configured with different sketch_buckets
                    # cannot merge: DROP the row (counted) rather than
                    # replace — a replace would flip-flop the prefix's
                    # estimate between single-sender views per request
                    dropped += 1
                else:
                    for i, r in enumerate(regs):
                        if r > cur[i]:
                            cur[i] = r
        if dropped:
            resilience.DEFAULT_REGISTRY.incr(
                "import", "fleet.sketch_prefixes_dropped", dropped)

    def _fleet_cardinality(self, top: int = 50) -> dict:
        """JSON-ready fleet-wide per-prefix cardinality estimates:
        received sketches merged (at read time) with this server's own
        admission-controller sketches, so a global that also ingests
        locally reports one number per prefix."""
        from .ingest.admission import estimate_registers
        with self._fleet_sketch_lock:
            merged = {p: bytes(r) for p, r in self._fleet_sketches.items()}
        if self.admission is not None:
            for prefix, regs in self.admission.export_sketches():
                cur = merged.get(prefix)
                if cur is None:
                    merged[prefix] = bytes(regs)
                elif len(cur) == len(regs):
                    merged[prefix] = bytes(
                        max(a, b) for a, b in zip(cur, regs))
                # width mismatch: keep the fleet row (local estimate
                # is a subset of it anyway), never replace
        rows = sorted(
            ((p, round(estimate_registers(r), 1))
             for p, r in merged.items()),
            key=lambda kv: -kv[1])
        return dict(rows[:top])

    def _debug_fleet_state(self) -> dict:
        """GET /debug/fleet payload: the per-sender fleet view (e2e
        p50/p99, freshness, last-seen, dedupe watermark) on a receiving
        tier, this server's OWN forward ladder summary (depth, replay
        ages, spill, breaker) on a sending tier, the bounded import
        ring, and the health verdict — the one page that answers
        'which sender is stalled, which interval is stuck in a replay
        ladder, how stale is the global's view'."""
        now_ns = time.time_ns()
        senders: dict = {}
        if self.fleet is not None:
            fleet = self.fleet.debug_state(now_ns)
            senders = fleet["senders"]
        if self.dedupe_ledger is not None:
            for sid, mark in self.dedupe_ledger.max_admitted().items():
                # a sender known only from restored watermarks (journal
                # recovery, no forward yet this incarnation) still gets
                # the FULL documented row shape — a dashboard indexing
                # row["e2e_ms"] must not crash on a restarted fleet
                senders.setdefault(sid, {
                    "last_seen_age_s": None,
                    "newest_close_ns": 0,
                    "freshness_age_ms": None,
                    "intervals_merged": 0,
                    "pending": 0,
                    "e2e_ms": {"count": 0, "p50": 0.0, "p99": 0.0},
                })["dedupe_watermark"] = mark
        forward = None
        fwd_state = None
        fwd = self.forwarder
        if hasattr(fwd, "debug_state"):
            fwd_state = fwd.debug_state()
            ages = [e["age"] for e in fwd_state["ladder"]]
            forward = {
                "sender_id": fwd_state["sender_id"],
                "ladder_depth": len(fwd_state["ladder"]),
                "replay_ages": ages,
                "oldest_replay_age": max(ages, default=0),
                "pending_spill": fwd_state["pending_spill"],
                "breaker_state": fwd_state["breaker_state"],
            }
        obs = self.import_observer
        # forward bytes (ISSUE 13): cumulative per destination per
        # kind from the process registry, plus the last interval's
        # sample — the bytes/interval row an operator reads next to
        # e2e latency to see what delta/quantized forwarding saves
        fbytes: dict = {}
        for (scope, name), v in resilience.DEFAULT_REGISTRY \
                .totals_by_name_prefix("forward.bytes").items():
            fbytes.setdefault(scope, {})[name] = v
        with self._stats_lock:
            last_bytes = self._last_forward_bytes
        return {
            "now_ns": now_ns,
            "flush_count": self.flush_count,
            "senders": senders,
            "forward": forward,
            "forward_bytes": {
                "cumulative": fbytes,
                "last_interval": last_bytes,
            },
            # mixed-fleet visibility (ISSUE 10): this server's engine
            # stamp next to each sender's declared stamp above, plus
            # the mismatch-reject total
            "sketch_engines": {
                "local": self.engine_stamp,
                "mismatch_rejects": resilience.DEFAULT_REGISTRY.total(
                    "import", "import.engine_mismatch"),
            },
            # fleet-wide per-prefix cardinality (merged received +
            # local Huffman-Bucket sketches)
            "fleet_cardinality": self._fleet_cardinality(),
            "import_recorder": (obs.debug_state() if obs is not None
                                else None),
            "health": self.health_state(fwd_state=fwd_state),
        }

    def _self_metrics(self, ts: int, t0: float,
                      eng_stats: dict | None = None) -> list[InterMetric]:
        """veneur.* self-telemetry: stage the per-tick gauges/deltas
        into the unified registry, then drain BOTH registries — this
        server's spine and the process-default egress/durability one —
        through the single name mapping in observe/registry.py (the
        internal statsd client's names, unchanged)."""
        tel, S = self.telemetry, observe.SERVER_SCOPE
        # the core counters report every interval, zeros included, as
        # the pre-unification attribute drain always did
        for name in ("packet.received", "packet.error", "worker.dropped",
                     "ssf.received", "ssf.error", "flush.error",
                     "import.rejected", "watchdog.stalled_ticks"):
            tel.mark(S, name, 0)
        if self.flight is not None:
            # ring-overflow accounting reports every interval; its
            # steady-state ZERO is the signal that phase attribution
            # is complete (no phases dropped to the slot budget)
            tel.mark(S, "observe.phases_dropped", 0)
        if self.native_bridge is not None:
            # UDP in native mode is counted in the bridge; fold in the
            # per-interval deltas. Drop taxonomy: ring/backpressure
            # drops -> worker.dropped_total; bank-full drops -> the
            # dropped_no_slot metric, REPLACING the engine's own count
            # (the BridgeKeyView only sees the slow-path subset, which
            # the bridge counter already includes — adding both would
            # double-report).
            st = self.native_bridge.stats()
            last = getattr(self, "_last_bridge_stats", None) or {}
            tel.incr(S, "packet.received",
                     int(st["packets"]) - int(last.get("packets", 0)))
            tel.incr(S, "packet.error",
                     int(st["parse_errors"])
                     - int(last.get("parse_errors", 0)))
            tel.incr(S, "worker.dropped",
                     int(st["ring_drops"])
                     - int(last.get("ring_drops", 0)))
            # natively-decoded spans + their decode errors (fallback
            # datagrams re-enter the Python path and are counted there)
            tel.incr(S, "ssf.received",
                     int(st["ssf_spans"]) - int(last.get("ssf_spans", 0)))
            tel.incr(S, "ssf.error",
                     int(st["ssf_errors"])
                     - int(last.get("ssf_errors", 0)))
            if eng_stats is not None:
                eng_stats["dropped_no_slot"] = (
                    int(st["drops_no_slot"])
                    - int(last.get("drops_no_slot", 0)))
            # vlint: disable=TH01 reason=flush-path-only state; flushes
            # are serialized (one flusher thread, tests call flush_once
            # synchronously), so no concurrent writer exists
            self._last_bridge_stats = st
        if self.admission is not None:
            # overload counters report every interval, zeros included
            # (a zero IS the steady-state signal: the defense is armed
            # and degrading nothing), plus the live governor rate
            for name in ("overload.folded_samples",
                         "overload.fold_sampled_out",
                         "overload.keys_over_budget",
                         "overload.shed_packets"):
                tel.mark(S, name, 0)
            tel.set_gauge(S, "overload.adaptive_sample_rate",
                          self.admission.shed_rate)
        tel.set_gauge(S, "flush.total_duration_ns",
                      (time.monotonic() - t0) * 1e9)
        if self.dedupe_ledger is not None:
            tel.set_gauge(S, "forward.dedupe_ledger_size",
                          self.dedupe_ledger.size())
        if self._engine_journal is not None:
            # engine-checkpoint self-metrics, present-at-zero while the
            # feature is armed (a zero delta-skip/dirty tick IS the
            # steady-state signal); the recovered_* counters were
            # incr'd during recovery-before-listen and drain here
            for name in ("durability.engine_delta_skipped_piles",
                         "durability.engine_recovered_ops",
                         "durability.engine_recovered_metrics",
                         "durability.engine_recovery_errors"):
                tel.mark(S, name, 0)
            dirty, total = self._last_checkpoint_stats
            tel.set_gauge(S, "durability.engine_snapshot_piles_dirty",
                          dirty)
            tel.set_gauge(S, "durability.engine_snapshot_piles_total",
                          total)
            tel.set_gauge(S, "durability.engine_snapshot_bytes",
                          self._engine_journal.last_checkpoint_bytes)
            tel.set_gauge(S, "durability.engine_restore_ns",
                          (self._recovery or {}).get("restore_ns", 0))
        journals = [j for j in (self._forward_journal,
                                self._dedupe_journal,
                                self._engine_journal) if j is not None]
        if journals:
            # counters (journal_appends/truncated_frames/recovered_*)
            # ride the process registry's drain below; the level-style
            # metrics are gauges and come straight from the journals
            tel.set_gauge(S, "durability.journal_bytes",
                          sum(j.size_bytes() for j in journals))
            tel.set_gauge(S, "durability.snapshot_duration_ns",
                          max(j.journal.last_snapshot_ns
                              for j in journals))
        if eng_stats is not None:
            tel.mark(S, "samples.processed", eng_stats["samples"])
            tel.mark(S, "samples.dropped_no_slot",
                     eng_stats["dropped_no_slot"])
            tel.set_gauge(S, "flush.swap_duration_ns",
                          eng_stats["swap_ns"])
            tel.set_gauge(S, "flush.merge_duration_ns",
                          eng_stats["merge_ns"])
            tel.set_gauge(S, "flush.assembly_duration_ns",
                          eng_stats["assembly_ns"])
        # ---- drop taxonomy ----
        # Losses are counted exactly once, at the layer that owns them:
        #   veneur.worker.dropped_total          ingest backpressure —
        #     full worker queues / native rings (queue_drops). Data is
        #     GONE; it never reached a bank.
        #   veneur.samples.dropped_no_slot_total bank capacity — key
        #     churn beyond the slot budget. Also gone.
        #   veneur.sink.flush_errors_total       a sink's delivery
        #     failed AFTER the resilience layer's retries; that sink's
        #     copy of the interval is gone (other sinks unaffected).
        #   veneur.resilience.*                  the egress layer's own
        #     accounting (per destination:) — attempts/retries/
        #     failures/breaker_* describe delivery effort;
        #     spilled/remerged_total are NOT drops OR deliveries: a
        #     failed forward's sketches are spilled, then re-merged
        #     into the next interval's forward (lossless), and only
        #     spill_evicted_total (budget/gauge-age eviction) is loss.
        #
        # Per-sink counts/durations drain from the PREVIOUS interval's
        # fan-out (this interval's sinks haven't run yet) — the sink
        # threads recorded them into scope "sink:<name>" as they
        # finished. Dotted counter names carry their own namespace;
        # plain names are the egress layer's veneur.resilience.* — the
        # mapping lives in observe/registry.py.
        out = (tel.drain(ts, self.hostname)
               + resilience.DEFAULT_REGISTRY.drain(ts, self.hostname))
        if self._stats_sock is not None:
            # scopedstatsd mode: ship veneur.* over the wire to
            # stats_address (usually this server's own statsd port)
            # instead of injecting into this flush.
            lines = []
            for m in out:
                kind = "c" if m.type == MetricType.COUNTER else "g"
                tags = ("|#" + ",".join(m.tags)) if m.tags else ""
                lines.append(f"{m.name}:{m.value:g}|{kind}{tags}")
            try:
                self._stats_sock.sendto("\n".join(lines).encode(),
                                        self._stats_dest)
            except OSError:
                pass
            return []
        return out

    def _fan_out(self, frameset, events, checks, tick=None, parent=-1):
        """Per-sink parallel flush, decoupled from the tick (one
        independent goroutine per sink in Server.Flush — the flusher
        NEVER joins them). Sinks receive the columnar FrameSet; legacy
        sinks materialize InterMetrics lazily in their own thread
        (cached once, shared), frame-native sinks never do. A sink whose
        previous flush is still in flight skips this interval — counted
        as veneur.sink.flush_skipped_total — so one wedged vendor can't
        push the next tick late or starve the other sinks.

        With a tick active, every sink/plugin/span-sink flush gets its
        own phase under `fanout` (the sink threads hold explicit
        handles); a sink still running when the flush tick ends shows
        `in_flight` in /debug/flush — the wedged-vendor signature."""
        tel = self.telemetry
        phase_timers = self.cfg.flush_phase_timers

        def spawn(key, target):
            prev = self._sink_inflight.get(key)
            if prev is not None and prev.is_alive():
                # tagged by component kind so a wedged plugin named
                # like a sink doesn't masquerade as that sink
                tel.incr(f"{key[0]}:{key[1]}", "sink.flush_skipped")
                if tick is not None:
                    tick.finish(tick.start("sink.skip", parent),
                                kind=key[0], name=key[1])
                return
            t = threading.Thread(target=target, daemon=True,
                                 name=f"{key[0]}-{key[1]}")
            # register BEFORE start so stop()'s drain can never miss an
            # in-flight sink; stop() tolerates the not-yet-started window
            # vlint: disable=TH01 reason=flusher-thread-only map; stop()
            # only reads it after _stop is set and the last tick ended
            self._sink_inflight[key] = t
            t.start()

        for s in self.sinks:
            def run(sink=s):
                ph = -1 if tick is None else \
                    tick.start("sink.flush", parent)
                t0 = time.monotonic()
                ok = False
                n = None
                try:
                    n = sink.flush_frames(frameset)
                    if events or checks:
                        sink.flush_other(events, checks)
                    ok = True
                except Exception:
                    log.exception("sink %s flush failed", sink.name())
                finally:
                    # drained in the NEXT interval's veneur.sink.*
                    # self-metrics (flusher.go per-sink spans); a failed
                    # flush reports 0 flushed + an error count, so a
                    # down vendor is visible, not masked. flush_frames
                    # returns the count actually serialized (after sink
                    # routing / STATUS drops); None = everything.
                    count = 0
                    if ok:
                        count = n if isinstance(n, int) else len(frameset)
                    dur_s = time.monotonic() - t0
                    scope = f"sink:{sink.name()}"
                    tel.mark(scope, "sink.metrics_flushed", count)
                    tel.set_gauge(scope, "sink.flush_duration_ns",
                                  dur_s * 1e9)
                    tel.mark(scope, "sink.flush_errors", 0 if ok else 1)
                    if tick is not None:
                        tick.finish(ph, sink=sink.name(), ok=ok,
                                    flushed=count)
                        if phase_timers:
                            # per-sink fan-out child timer
                            # (veneur.flush.phase.fanout.<sink>):
                            # emitted HERE, by the sink's own thread,
                            # because the tick-end dogfood sampler
                            # would race sinks still in flight — a
                            # slow vendor is exactly the one a
                            # tick-end sample would miss
                            self._route_metric(observe.fanout_timer_sample(
                                sink.name(), dur_s * 1e3))
            spawn(("sink", s.name()), run)
        for p in self.plugins:
            def runp(plugin=p):
                ph = -1 if tick is None else \
                    tick.start("plugin.flush", parent)
                t0 = time.monotonic()
                ok = True
                try:
                    plugin.flush_frames(frameset, self.hostname)
                except Exception:
                    ok = False
                    log.exception("plugin %s flush failed", plugin.name())
                finally:
                    if tick is not None:
                        tick.finish(ph, plugin=plugin.name(), ok=ok)
                        if phase_timers:
                            self._route_metric(observe.fanout_timer_sample(
                                plugin.name(),
                                (time.monotonic() - t0) * 1e3))
            spawn(("plugin", p.name()), runp)
        for ss in self.span_sinks:
            def runs(sink=ss):
                ph = -1 if tick is None else \
                    tick.start("spansink.flush", parent)
                t0 = time.monotonic()
                ok = True
                try:
                    sink.flush()
                except Exception:
                    ok = False
                    log.exception("span sink %s flush failed",
                                  sink.name())
                finally:
                    if tick is not None:
                        tick.finish(ph, sink=sink.name(), ok=ok)
                        if phase_timers:
                            self._route_metric(observe.fanout_timer_sample(
                                sink.name(),
                                (time.monotonic() - t0) * 1e3))
            spawn(("spansink", ss.name()), runs)

    def _start_profiling(self):
        """enable_profiling: expose the JAX/XLA profiler (xprof) — the
        TPU build's analogue of the reference's net/http/pprof wiring
        (server.go). mutex_profile_fraction / block_profile_rate are
        Go-runtime knobs with no XLA equivalent; they are accepted for
        YAML compatibility and warned about, not silently eaten."""
        if self.cfg.mutex_profile_fraction or self.cfg.block_profile_rate:
            log.warning("mutex_profile_fraction/block_profile_rate are "
                        "Go-runtime profiling knobs with no effect in "
                        "the TPU build; use enable_profiling (JAX "
                        "profiler) instead")
        try:
            import jax
            port = self.cfg.profile_port
            jax.profiler.start_server(port)
            log.info("JAX profiler server on :%d", port)
        except Exception as e:
            log.warning("enable_profiling: JAX profiler unavailable: %s",
                        e)

    # ------------- watchdog -------------

    def _watchdog(self):
        """Stall accounting + crash-only supervision. Every interval
        the watchdog compares now against the last COMPLETED flush;
        an overdue tick increments veneur.watchdog.stalled_ticks_total
        (a wedged flusher is detectable from outside the process —
        /healthz and the counter — instead of only by absence of
        data). The hard exit (Server.FlushWatchdog panics after
        watchdog_max_ticks) stays opt-in via
        flush_watchdog_missed_flushes."""
        interval = self.cfg.interval_seconds
        max_lag = (self.cfg.flush_watchdog_missed_flushes * interval)
        while not self._stop.wait(interval):
            lag = time.monotonic() - self._last_flush_ok
            if lag > interval:
                self._count("watchdog.stalled_ticks")
            if max_lag > 0 and lag > max_lag:
                log.critical(
                    "flush watchdog: no completed flush in %.1fs "
                    "(max %.1fs) — exiting for supervisor restart",
                    lag, max_lag)
                if self._sentry is not None:
                    # ConsumePanic: the event must escape the dying
                    # process, so this send blocks (bounded)
                    self._sentry.capture(
                        None, "flush watchdog expired; crash-only exit",
                        wait=True)
                os._exit(2)
