"""Host-side key management: interning MetricKeys to device slots.

The reference shards metrics onto workers by digest and each worker owns Go
maps keyed by MetricKey (worker.go sym: WorkerMetrics, Worker.ProcessMetric).
Here the device owns fixed-K banks, so the host keeps the (only) string-keyed
structure: MetricKey -> slot, with a free list and idle-interval eviction to
survive unbounded key churn against fixed K (SURVEY §7 "slot management").
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ingest.parser import MetricKey

# Sentinel returned by KeyInterner.lookup when the attached admission
# controller refuses to mint a slot for a new key (over its prefix's
# budget): the caller folds the sample into the prefix's `__other__`
# key instead (models/pipeline.py `_fold`). Distinct from -1 (bank
# full), which remains a counted drop.
FOLD_SLOT = -2


@dataclass
class SlotInfo:
    slot: int
    last_interval: int
    scope: int
    # Flush presentation cache (per-key metric names / split tag lists),
    # owned by the engine's assembly; dies with the entry on eviction.
    pres: object = None


class KeyInterner:
    """MetricKey -> slot map for one bank, with eviction.

    Not thread-safe by design: one interner is owned by one ingest thread,
    mirroring the single-goroutine ownership of WorkerMetrics maps.
    """

    def __init__(self, capacity: int, idle_ttl_intervals: int = 16):
        self.capacity = capacity
        self.idle_ttl = idle_ttl_intervals
        self._map: dict[MetricKey, SlotInfo] = {}
        self._free = list(range(capacity - 1, -1, -1))
        self._by_slot: list[MetricKey | None] = [None] * capacity
        self.interval = 0
        self.dropped_no_slot = 0
        # Overload defense (ingest/admission.py), attached by
        # AggregationEngine.attach_admission: consulted ONLY on the
        # allocation path — a key already holding a slot pays zero
        # admission cost (the map hit above is the whole hot path).
        self.admission = None

    def __len__(self):
        return len(self._map)

    def lookup(self, key: MetricKey, scope: int) -> int:
        """Return the slot for `key`, allocating if new. -1 if the bank is
        full (caller counts the drop — the analogue of worker channel
        backpressure drops, which veneur also counts rather than blocks);
        FOLD_SLOT (-2) if the admission controller refused the slot
        (over-budget key: caller folds into the prefix's other-key)."""
        info = self._map.get(key)
        if info is not None:
            info.last_interval = self.interval
            info.scope = scope
            return info.slot
        adm = self.admission
        if adm is not None and adm.admit_key(key) is None:
            return FOLD_SLOT
        if not self._free:
            if adm is not None:
                adm.release_key(key)   # admitted, but no slot to mint
            self.dropped_no_slot += 1
            return -1
        slot = self._free.pop()
        self._map[key] = SlotInfo(slot, self.interval, scope)
        self._by_slot[slot] = key
        return slot

    def key_of(self, slot: int) -> MetricKey | None:
        return self._by_slot[slot]

    def scope_of(self, slot: int) -> int:
        key = self._by_slot[slot]
        return self._map[key].scope if key is not None else 0

    def active_items(self):
        """(key, slot, scope, info) tuples for keys touched in the
        *current* interval — the set a flush reports (bank state is
        interval-scoped, so stale slots hold zeros and are skipped).
        Returning scope and the SlotInfo directly spares the flush a
        per-key MetricKey hash (scope_of) at 100k keys."""
        cur = self.interval
        return [(k, i.slot, i.scope, i) for k, i in self._map.items()
                if i.last_interval == cur]

    def all_items(self):
        """EVERY interned key, touched or idle (same row shape as
        active_items) — what a FULL forward resync ships (ISSUE 13):
        idle keys' zero/empty bank rows refresh the receiving tier's
        series liveness, which steady-state deltas deliberately skip.
        Keys idle past the TTL have already evicted and are gone from
        here too — a resync re-ships the interner's world, not
        history."""
        return [(k, i.slot, i.scope, i) for k, i in self._map.items()]

    def snapshot_entries(self) -> list:
        """The full table as (slot, scope, last_interval, name, type,
        joined_tags) rows — the engine checkpoint's ENGINE_KEYS payload
        (durability/ ISSUE 9). Map order (= insertion order) is
        preserved so a restored interner iterates like the original."""
        return [(info.slot, info.scope, info.last_interval,
                 k.name, k.type, k.joined_tags)
                for k, info in self._map.items()]

    def restore(self, interval: int, entries: list):
        """Rebuild the table from a checkpoint (recovery-before-listen).
        The free list is reconstructed canonically (unused slots,
        allocation resuming from the lowest) — free-list ORDER only
        decides which slot a future key gets, and slots are internal:
        flushed values are keyed by metric name either way. The
        presentation cache starts cold (it re-fills on first flush)."""
        self.interval = int(interval)
        self._map.clear()
        self._by_slot = [None] * self.capacity
        for slot, scope, last_interval, name, mtype, tags in entries:
            key = MetricKey(name, mtype, tags)
            self._map[key] = SlotInfo(int(slot), int(last_interval),
                                      int(scope))
            self._by_slot[int(slot)] = key
        used = {info.slot for info in self._map.values()}
        self._free = [s for s in range(self.capacity - 1, -1, -1)
                      if s not in used]

    def advance_interval(self):
        """Called at each flush boundary: ages entries and evicts those
        idle longer than the TTL, returning their slots to the free list."""
        self.interval += 1
        if self.idle_ttl <= 0:
            return
        horizon = self.interval - self.idle_ttl
        if horizon < 0:
            return
        dead = [k for k, info in self._map.items()
                if info.last_interval < horizon]
        adm = self.admission
        for k in dead:
            info = self._map.pop(k)
            self._by_slot[info.slot] = None
            self._free.append(info.slot)
            if adm is not None:
                adm.release_key(k)   # budget follows bank occupancy
