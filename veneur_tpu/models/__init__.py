"""The aggregation engine — the framework's flagship "model".

Replaces the reference's Worker goroutines + flusher (worker.go sym: Worker;
flusher.go sym: Server.Flush) with device-resident sketch banks driven by
batched XLA programs.
"""
