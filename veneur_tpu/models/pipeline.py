"""AggregationEngine — banks + staging + the jitted flush program.

This is the TPU-native replacement for the reference's hot path from
Worker.ProcessMetric down through Server.Flush (worker.go, flusher.go):

  ingest thread:  parsed UDPMetric -> host staging buffers (numpy, fixed
                  batch shape) -> one scatter program per full batch
  flush tick:     ONE fused XLA program over all four banks (compress +
                  quantiles + aggregates + HLL estimate + scalar
                  finalization) -> one device_get of compact arrays ->
                  host assembles a columnar MetricFrame from the
                  slot->key map

Interval semantics match Worker.Flush's map swap: flush takes the current
immutable device arrays (JAX arrays are persistent, so the "swap" is just
rebinding fresh banks) and ingest continues immediately; the merge program
runs on the snapshot — double buffering for free.

Scope routing (flusher.go semantics):
  * no forwarding configured -> everything flushes locally in full.
  * forwarding on: mixed-scope histograms/timers emit the configured local
    aggregates and forward their digest (percentiles are computed globally);
    mixed sets forward the sketch; `veneurlocalonly` keys flush fully
    locally; `veneurglobalonly` keys only forward. Counters/gauges stay
    local unless global-only.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from dataclasses import dataclass, field as dc_field

import jax
import jax.numpy as jnp
import numpy as np

from .. import kernels, sketches
from ..ingest.parser import (
    GLOBAL_ONLY, LOCAL_ONLY, MetricKey, UDPMetric)
from ..metrics import InterMetric, MetricFrame, MetricType
from ..ops import scalar
from ..utils import hashing
from .worker import FOLD_SLOT, KeyInterner

logger = logging.getLogger(__name__)


# Widest per-slot centroid pile the import path will hand to one device
# program; wider (untrusted) forwarded digests are pre-clustered in
# chunks of this size first.
_IMPORT_W_CAP = 4096

# Stage forwarded digests until this many centroids (or digests) are
# pending, then land them in one batched round. Bigger piles = fewer
# device dispatches AND higher merge fidelity (one k1 clustering over
# more of the interval's data — measured ~0.3pp closer to the Go oracle
# at p99 than landing every 512 digests); the bounds cap host staging
# memory at ~8MB of float32 centroids.
_IMPORT_STAGE_CENTROIDS = 1 << 20
_IMPORT_STAGE_DIGESTS = 8192


def _precluster_k1(v, w, n_points, keep_extremes=False):
    """Sort one hot slot's (value, weight) samples and cluster them into
    <= n_points weighted points over k1-spaced (tail-dense) bucket edges
    — the shared core of both engines' hot-slot sidesteps. Weighted sum
    and count are exactly preserved; with keep_extremes the true min and
    max survive as singleton points (for paths with no separate exact-
    stats merge). Returns (means f64[n], weights f64[n])."""
    order = np.argsort(v, kind="stable")
    v, w = v[order], w[order]
    if keep_extremes:
        if len(v) <= 2 or n_points <= 3:
            return v, w  # nothing (or no room) to cluster between ends
        nb = n_points - 2
        qi = (np.sin(np.pi * np.arange(nb + 1) / nb - np.pi / 2)
              + 1.0) / 2.0
        edges = np.unique(
            np.floor(1 + qi * (len(v) - 2)).astype(np.int64))
        edges = edges[(edges >= 1) & (edges < len(v) - 1)]
        if edges.size == 0:
            edges = np.array([1], np.int64)
        wsum = np.add.reduceat(w[1:-1], edges - 1)
        vsum = np.add.reduceat((v * w)[1:-1], edges - 1)
        keep = wsum > 0
        return (np.concatenate([[v[0]], vsum[keep] / wsum[keep],
                                [v[-1]]]),
                np.concatenate([[w[0]], wsum[keep], [w[-1]]]))
    nb = max(1, n_points)
    qi = (np.sin(np.pi * np.arange(nb + 1) / nb - np.pi / 2) + 1.0) / 2.0
    edges = np.unique(np.floor(qi * len(v)).astype(np.int64))
    edges = edges[edges < len(v)]
    wsum = np.add.reduceat(w, edges)
    vsum = np.add.reduceat(v * w, edges)
    keep = wsum > 0
    return vsum[keep] / wsum[keep], wsum[keep]


# ---------------- compiled flush programs (shared across engines) --------
#
# The flush must be ONE XLA dispatch, not a chain (compress -> quantile ->
# aggregates -> estimate as separate jits measured ~2000x slower than the
# fused program on a tunneled TPU backend, r2 bench), and its inputs and
# outputs must be COMMITTED to a concrete device: executables built against
# uncommitted arrays take a drastically slower path on that backend (see
# parallel/mesh.py's matching notes). Both factories are lru_cached on the
# static config so every engine with the same shape shares one executable
# and one compile.

@functools.lru_cache(maxsize=None)
def _fresh_banks_executable(device, heng, seng, histogram_slots,
                            counter_slots, gauge_slots, set_slots):
    """One jitted program materializing a full set of fresh interval banks
    on `device` — the Worker.Flush map-swap costs one dispatch, not ~15
    host-built zero arrays. `heng`/`seng` are the selected sketch
    engines (frozen dataclasses — hashable cache keys carrying the
    static shape params)."""
    sds = jax.sharding.SingleDeviceSharding(device)

    def make():
        return (heng.init(histogram_slots),
                scalar.init_counters(counter_slots),
                scalar.init_gauges(gauge_slots),
                seng.init(set_slots))

    return jax.jit(make, out_shardings=sds)


@functools.lru_cache(maxsize=None)
def _ingest_executables(device, heng, seng, set_arm="xla"):
    """Committed-output builds of the four ingest scatter kernels.

    The module-level ops (tdigest.add_batch & co) are plain jits: their
    outputs are UNCOMMITTED, and an executable whose bank inputs are
    uncommitted is the ~1000x-slow variant on the tunneled TPU backend —
    which would put every ingest batch AND the following flush on the
    slow path. Pinning out_shardings keeps the whole bank lineage
    committed from _fresh_banks onward. Every sketch op routes through
    the engine objects — the registry boundary (vlint SK01).

    `set_arm` (ISSUE 15) selects the set-insert build: engines with a
    fused Pallas insert (ULL's scatter-join) route through it under
    the fused/interpret arms; everything else keeps the XLA program.
    The arm is part of this cache's key, so an engine pair serves
    exactly one arm per process and /debug reports it truthfully."""
    sds = jax.sharding.SingleDeviceSharding(device)

    jit = functools.partial(jax.jit, donate_argnums=(0,),
                            out_shardings=sds)
    if set_arm != "xla" and hasattr(seng, "insert_fused_impl"):
        set_insert = functools.partial(
            seng.insert_fused_impl, interpret=(set_arm == "interpret"))
    else:
        set_insert = seng.insert_impl
    return {
        "histo": jit(heng.add_batch_impl),
        "counter": jit(scalar.counter_add.__wrapped__),
        "gauge": jit(scalar.gauge_set.__wrapped__),
        "set": jit(set_insert),
        # hot-slot sidestep programs (see _add_histo_batch)
        "compress": jit(heng.compress_impl),
        "merge_centroids": jit(heng.merge_centroids_impl),
        "merge_scalars": jit(heng.merge_scalars_impl),
    }


def _flush_program_body(heng, seng, fwd_out, agg_emit, pallas_ok,
                        compact, kernel_arm="xla"):
    """The flush computation itself — compress + quantiles + the
    configured aggregates + counter/gauge/set finalization — as a
    jit-composable closure over (hb, cb, gb, sb, qs). Shared by the
    full-bank executable (_flush_executable) and the incremental
    dirty-slot executable (_inc_flush_executable), so both paths run
    the IDENTICAL math and differ only in which rows they see.

    `kernel_arm` (ISSUE 15, "fused"/"interpret"/"xla") selects the
    compress build for engines with a fused Pallas kernel: the whole
    sort + rank-merge + cluster pipeline collapses into ONE pallas_call
    embedded in this program (VMEM-resident intermediates — no HBM
    round-trips between the stages), bit-identical to compress_impl by
    the tests/test_pallas.py contract. Engines without a fused kernel
    (REQ) ignore the arm. The arm keys every cached executable build,
    so /debug's per-engine arm stamp can never lie about what compiled.

    Output contract (all f32 unless noted):
      q        [K, P']      quantile matrix (P' includes a median column
                            when configured)
      aggcols  [K, A]       one column per configured aggregate, in
                            `agg_emit` order; `count`/`sum` columns carry
                            the 2Sum hi term only
      lo_count/lo_sum [K]   the matching lo terms (only when configured):
                            exact value = f64(hi) + f64(lo) on host
      cnt      [K]          folded count for liveness (only when `count`
                            is NOT a configured aggregate)
      c_hi/c_lo [Kc], g_value [Kg], g_seq i32[Kg], s_est [Ks]
      h_* / s_regs          raw forward-export state (fwd_out only)

    `compact=True` (flush_fetch_f16) swaps the two big [K, ·] matrices
    for a half-width wire encoding, halving the device->host fetch on
    transport-constrained rigs (the tunneled dev backend moves ~20 MB/s;
    VERDICT r4 item 1 contingency):
      q16/lp16 f16          quantiles + non-exact aggregate columns
      aggcols_hp            count/sum hi columns, f32 (exactness)
      overflow_mag scalar   max |value| across q16/lp16's sources — the
                            host re-fetches full precision iff any value
                            sits in f16's saturation zone
      lo_mag scalar         max |2Sum lo| — lo_count/lo_sum are fetched
                            iff nonzero (they are zero in steady state)
      q32/lp32, lo_*        full-precision twins, fetched lazily (see
                            fetch_flush_outputs) — emitting them costs
                            device memory, not wire
    """
    def program(hb, cb, gb, sb, qs):
        if kernel_arm != "xla" and hasattr(heng, "compress_fused_impl"):
            hb = heng.compress_fused_impl(
                hb, interpret=(kernel_arm == "interpret"))
        else:
            hb = heng.compress_impl(hb)
        agg = heng.aggregates_impl(hb)
        q = heng.quantile_impl(hb, qs)
        out = {
            "c_hi": cb.hi, "c_lo": cb.lo,
            "g_value": gb.value, "g_seq": gb.seq,
        }
        # set estimate: HLL emits the finished per-slot estimate; ULL
        # emits its device-side sufficient statistic and the host half
        # of estimate (estimate_finalize) finishes it after the fetch
        out.update(seng.estimate_device(sb, pallas_ok))
        cols, hp_cols, lp_cols, lo_terms = [], [], [], []
        for a in agg_emit:
            if a == "count":
                hp_cols.append(hb.count)
                out["lo_count"] = hb.count_lo
                lo_terms.append(hb.count_lo)
                cols.append(hb.count)
            elif a == "sum":
                hp_cols.append(hb.vsum)
                out["lo_sum"] = hb.vsum_lo
                lo_terms.append(hb.vsum_lo)
                cols.append(hb.vsum)
            else:
                lp_cols.append(agg[a])
                cols.append(agg[a])
        if compact:
            out["q16"] = q.astype(jnp.float16)
            out["q32"] = q
            mag = jnp.max(jnp.abs(q))
            if hp_cols:
                out["aggcols_hp"] = jnp.stack(hp_cols, axis=1)
            if lp_cols:
                lp = jnp.stack(lp_cols, axis=1)
                out["lp16"] = lp.astype(jnp.float16)
                out["lp32"] = lp
                mag = jnp.maximum(mag, jnp.max(jnp.abs(lp)))
            out["overflow_mag"] = mag
            # smallest nonzero magnitude: values below f16's normal range
            # (~6.1e-5) lose relative precision, so the host falls back
            # to the full-precision twins for them too
            srcs = [q] + ([lp] if lp_cols else [])
            tiny = jnp.inf
            for s in srcs:
                tiny = jnp.minimum(tiny, jnp.min(
                    jnp.where(s == 0, jnp.inf, jnp.abs(s))))
            out["tiny_mag"] = tiny
            out["lo_mag"] = (
                jnp.max(jnp.stack([jnp.max(jnp.abs(t))
                                   for t in lo_terms]))
                if lo_terms else jnp.float32(0.0))
        else:
            out["q"] = q
            if cols:
                out["aggcols"] = jnp.stack(cols, axis=1)
        if "count" not in agg_emit:
            out["cnt"] = agg["count"]
        if fwd_out:
            out.update(heng.forward_leaves(hb))
            out["s_regs"] = sb.registers
        return out

    return program


@functools.lru_cache(maxsize=None)
def _flush_executable(device, heng, seng, fwd_out, agg_emit, pallas_ok,
                      donate=True, compact=False, kernel_arm="xla"):
    """The fused interval-flush program over the FULL banks: ONE XLA
    call over every slot (see _flush_program_body for the output
    contract). The incremental dirty-slot path (_inc_flush_executable)
    is the serving default when most slots are cold; this full build
    remains the oracle, the warmup/baseline program, and the serving
    path above the dirty-fraction threshold."""
    sds = jax.sharding.SingleDeviceSharding(device)
    program = _flush_program_body(heng, seng, fwd_out, agg_emit,
                                  pallas_ok, compact, kernel_arm)

    # donate=False builds a variant safe to dispatch repeatedly on the
    # same banks (bench.py's chained exec estimator); serving always
    # donates. Donation audit (ISSUE 3 satellite): an argument is
    # donated iff EVERY one of its leaves aliases an output of
    # identical shape — partial donation is what made every compile
    # warn "Some donated buffers were not usable" since r3. Counter and
    # gauge banks always qualify (c_hi/c_lo, g_value/g_seq); nothing
    # else does in the local-only build (the t-digest/HLL state reduces
    # to compact [K, P']/[K] outputs).
    if not donate:
        return jax.jit(program, out_shardings=sds)
    if not fwd_out:
        return jax.jit(program, donate_argnums=(1, 2),
                       out_shardings=sds)

    # fwd_out: the histo bank's item matrices and eight scalar leaves
    # are echoed verbatim (h_*), as are the set registers (s_regs) —
    # real aliasing worth ~2 x [K, C] f32 of transient memory per flush
    # at 100k slots. The engine's donation_split names the leaves with
    # same-shaped outputs; the rest (sample buffers, level counters)
    # would bring the partial-donation warning back, so the bank is
    # split into a donated core and an un-donated remainder behind a
    # signature-preserving wrapper (engine.reassemble).
    split = heng.donation_split()
    if split is None:
        return jax.jit(program, donate_argnums=(1, 2),
                       out_shardings=sds)
    core_names, buf_names = split

    def flat(core, bufs, cb, gb, sb, qs):
        hb = heng.reassemble(core, bufs)
        return program(hb, cb, gb, sb, qs)

    jitted = jax.jit(flat, donate_argnums=(0, 2, 3, 4),
                     out_shardings=sds)

    def call(hb, cb, gb, sb, qs):
        core = tuple(getattr(hb, n) for n in core_names)
        bufs = tuple(getattr(hb, n) for n in buf_names)
        return jitted(core, bufs, cb, gb, sb, qs)

    return call


def _inc_bucket(n: int, num_slots: int) -> int:
    """Padded work-set width for `n` dirty slots of a `num_slots` bank:
    powers of two up to 4096 (one executable per bucket, compiled once
    and cached), then 4096-aligned (tight enough that the exec-time
    ratio tracks the touched ratio at 100k — a pure power-of-two ladder
    would pad 10% dirty to 16% of the bank). Never below 64 (tiny
    buckets would mint executables per handful of slots) and never
    above the bank itself."""
    b = 64
    while b < n and b < 4096:
        b *= 2
    if n > 4096:
        b = -(-n // 4096) * 4096
    return min(b, num_slots)


def pad_dirty_ids(ids, num_slots: int):
    """One bank's dirty-id vector padded to its _inc_bucket width with
    index 0 (padding rows duplicate row 0's compute; consumers read
    only the true-D prefix) — the EXACT work-set shape
    _flush_device_incremental dispatches, shared with bench_suite's
    exec-only A/B so the bench can never drift to a stale shape."""
    b = _inc_bucket(max(ids.size, 1), num_slots)
    pad = np.zeros(b, np.int32)
    pad[:ids.size] = ids
    return pad


@functools.lru_cache(maxsize=None)
def _inc_flush_executable(device, heng, seng, fwd_out, agg_emit,
                          pallas_ok, compact=False, kernel_arm="xla"):
    """The INCREMENTAL interval-flush program (ISSUE 11 tentpole):
    gather only the dirty piles into a compact [D, ·] work set, run the
    SAME flush body (_flush_program_body) over that slice, and return
    compact [D, ·] outputs the host scatters over the cached
    empty-bank baseline (_flush_device). Cold piles are fresh-init by
    construction (the swap re-zeroes every row; restore re-marks
    restored rows dirty), and the flush body maps a fresh row to the
    baseline row bit-for-bit, so skipping cold rows is exact — the
    oracle suite pins incremental == full per engine backend.

    `ih/ic/ig/is_` are per-bank dirty-slot index vectors, padded to
    their _inc_bucket width with index 0 (a padding row duplicates row
    0's compute; the host scatter consumes only the true-D prefix, so
    the duplicate work is dropped). One executable per (engine pair,
    bucket-shape) combination — jit retraces per input shape under the
    one cached wrapper.

    No donation: the gathered outputs cannot alias the full-bank
    inputs (different shapes), and requesting donation anyway would
    re-introduce the "donated buffers were not usable" warning the
    ISSUE 3 audit pins at zero."""
    sds = jax.sharding.SingleDeviceSharding(device)
    program = _flush_program_body(heng, seng, fwd_out, agg_emit,
                                  pallas_ok, compact, kernel_arm)

    def gather(bank, idx):
        return jax.tree_util.tree_map(lambda leaf: leaf[idx], bank)

    def inc(hb, cb, gb, sb, qs, ih, ic, ig, is_):
        return program(gather(hb, ih), gather(cb, ic), gather(gb, ig),
                       gather(sb, is_), qs)

    return jax.jit(inc, out_shardings=sds)


@functools.lru_cache(maxsize=None)
def _flush_baseline_cached(device, heng, seng, fwd_out, agg_emit,
                           pallas_ok, compact, qs, kernel_arm="xla"):
    """Empty-flush baseline rows (see _flush_baseline_rows), cached at
    module level so every engine with the same sketch pair + flush
    config shares one K=1 compile. Treat the returned rows as
    immutable. `kernel_arm` rides the key so the baseline is built by
    the same program arm that serves (bit-identical either way — the
    fresh row is a compress fixed point under both — but the arm
    accounting at /debug stays truthful)."""
    from ..ops import scalar as _scalar
    body = _flush_program_body(heng, seng, fwd_out, agg_emit,
                               pallas_ok, compact, kernel_arm)
    fresh = jax.device_put(
        (heng.init(1), _scalar.init_counters(1),
         _scalar.init_gauges(1), seng.init(1)), device)
    host = fetch_flush_outputs(
        jax.jit(body)(*fresh, np.asarray(qs, np.float32)), "sync")
    host = decompact_flush_host(host, agg_emit)
    if "s_est" in host or "s_counts" in host:
        seng.estimate_finalize(host)
    return {k: np.asarray(v)[0]
            for k, v in host.items() if np.asarray(v).ndim}


def _out_bank_kind(key: str) -> int:
    """Which bank's dirty-index vector an incremental output key is
    scattered under: 0=histogram, 1=counter, 2=gauge, 3=set. Keys are
    grouped by prefix — h_*/q/agg* and the 2Sum lo_* terms ride the
    histogram bank, c_* the counter bank, g_* the gauge bank, s_* the
    set bank."""
    if key.startswith("c_"):
        return 1
    if key.startswith("g_"):
        return 2
    if key.startswith("s_"):
        return 3
    return 0


def stage_copy_executable(sharding=None):
    """A jitted tree-copy program used as a fetch 'staging' hop: the
    host fetch targets THIS cheap executable's outputs, so a relayed
    backend's fetch-side invalidation (TPU_EVIDENCE_r04.md §2) costs a
    tiny recompile, not the serving program's. `sharding=None` keeps the
    inputs' shardings (the mesh case)."""
    kw = {} if sharding is None else {"out_shardings": sharding}
    return jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t), **kw)


# compact-mode outputs that stay on device unless their sentinel scalar
# says they're needed (full-precision twins + 2Sum lo terms)
_LAZY_KEYS = ("q32", "lp32", "lo_count", "lo_sum")
_F16_SAT = 61440.0      # |x| beyond this rounds into f16's overflow zone
# f16 min normal (2^-14 exactly): below this, values encode as f16
# subnormals with reduced relative precision, so the sentinel must sit
# AT the boundary — 6.1e-5 (the old value) left a [6.1e-5, 2^-14) band
# that skipped the full-precision refetch yet lost precision on the wire
_F16_TINY = 2.0 ** -14


def fetch_flush_outputs(out, mode: str, stage_exec=None):
    """device_get under a flush_fetch mode — the one definition shared
    by both engines and bench.py's mode probe.

    Compact (f16 wire) outputs carry sentinel scalars; the full-precision
    twins and 2Sum lo arrays ride along ONLY when a sentinel demands it
    (out-of-range values, nonzero lo terms) — the common case moves half
    the bytes. The rare second device_get is a plain sync fetch: on a
    relayed backend it re-poisons the serving executable, which is
    accepted for the exactness path."""
    lazy = {}
    if "lo_mag" in out:
        lazy = {k: out[k] for k in _LAZY_KEYS if k in out}
        out = {k: v for k, v in out.items() if k not in lazy}
    if stage_exec is not None:
        out = stage_exec(out)
    elif mode == "async":
        for leaf in jax.tree_util.tree_leaves(out):
            leaf.copy_to_host_async()
    host = jax.device_get(out)
    if lazy:
        need = []
        if float(host["lo_mag"]) != 0.0:
            need += [k for k in ("lo_count", "lo_sum") if k in lazy]
        if (float(host["overflow_mag"]) >= _F16_SAT
                or float(host["tiny_mag"]) < _F16_TINY):
            need += [k for k in ("q32", "lp32") if k in lazy]
        if need:
            host.update(jax.device_get({k: lazy[k] for k in need}))
    return host


def decompact_flush_host(host: dict, agg_emit: tuple) -> dict:
    """Rebuild the standard flush-host contract (q [K, P], aggcols
    [K, A], lo_*) from a compact (f16 wire) fetch so the assembly code
    is one implementation for both wire modes. No-op for standard
    fetches."""
    if "lo_mag" not in host:
        return host
    q = host.pop("q32", None)
    host_q16 = host.pop("q16")
    host["q"] = (np.asarray(host_q16, np.float32) if q is None
                 else np.asarray(q))
    lp = host.pop("lp32", None)
    lp16 = host.pop("lp16", None)
    if lp is None and lp16 is not None:
        lp = np.asarray(lp16, np.float32)
    hp = host.pop("aggcols_hp", None)
    if agg_emit:
        hi = li = 0
        cols = []
        for a in agg_emit:
            if a in ("count", "sum"):
                cols.append(np.asarray(hp[:, hi], np.float32))
                hi += 1
            else:
                cols.append(np.asarray(lp[:, li], np.float32))
                li += 1
        host["aggcols"] = np.stack(cols, axis=1)
    k = host["q"].shape[0]
    if "count" in agg_emit and "lo_count" not in host:
        host["lo_count"] = np.zeros(k, np.float32)
    if "sum" in agg_emit and "lo_sum" not in host:
        host["lo_sum"] = np.zeros(k, np.float32)
    return host


class ImportFoldReroute(Exception):
    """An over-budget IMPORTED key's fold target is homed on another
    engine (overload defense, multi-worker server): raised out of the
    engine's import_* before any staging, carrying the fold key so the
    worker loop can rewrite the aggregate's pb onto it and re-route.
    Deliberately an Exception subclass raised BEFORE the worker loop's
    poison-pill guard gets to see it (the loop catches this type
    first); it must never escape to a caller that treats it as a
    corrupted metric."""

    def __init__(self, key: MetricKey, digest: int):
        super().__init__(f"fold of imported key rehomes to {key.name}")
        self.key = key
        self.digest = digest


@dataclass
class EngineConfig:
    histogram_slots: int = 1 << 15
    counter_slots: int = 1 << 14
    gauge_slots: int = 1 << 14
    set_slots: int = 1 << 12
    compression: float = 100.0
    buffer_depth: int = 256
    hll_precision: int = 14
    # Sketch-engine selection (veneur_tpu/sketches/ registry, ISSUE
    # 10): which sketch implements the histogram/timer banks and the
    # set-cardinality banks. The defaults are the pre-registry pair
    # (behavior-identical); "req" = relative-error adaptive-compactor
    # quantiles (tail-accurate), "ull" = UltraLogLog registers (half
    # the state at equal nominal error). The per-engine shape knobs
    # below only apply to their engine.
    histogram_backend: str = "tdigest"
    set_backend: str = "hll"
    ull_precision: int = 13
    req_levels: int = 2
    req_capacity: int = 256
    batch_size: int = 8192
    percentiles: tuple = (0.5, 0.75, 0.99)
    aggregates: tuple = ("min", "max", "count")
    idle_ttl_intervals: int = 16
    forward_enabled: bool = False
    is_global: bool = False      # global tier: emit percentiles for imports
    hostname: str = ""
    # How flush results leave the device. "sync" is one device_get (the
    # production pattern on directly-attached TPUs). The alternatives
    # exist for relayed/tunneled backends where a synchronous fetch of an
    # executable's outputs invalidates its loaded state and the NEXT
    # dispatch pays a full recompile (~6.7s @100k slots, measured — see
    # TPU_EVIDENCE_r04.md §2):
    #   "staged" — a tiny jitted copy program re-materializes the outputs
    #              and the fetch targets ITS outputs, so only the cheap
    #              staging executable is invalidated;
    #   "host"   — the staging copy writes to pinned_host memory, putting
    #              the D2H transfer inside the program (falls back to
    #              "staged" when the backend lacks host memory kinds);
    #   "async"  — copy_to_host_async on every leaf before the gather.
    flush_fetch: str = "sync"
    # Compact wire mode: quantile + inexact aggregate columns cross the
    # device->host wire as f16 (half the fetch bytes @ >=2x fewer than
    # the dominant [K, ·] matrices), with sentinel-gated fallback to the
    # full-precision twins when values leave f16's safe range and to the
    # 2Sum lo arrays when they are nonzero. count/sum stay f32+lo-exact.
    # Worth it only on transport-constrained rigs (the ~20 MB/s tunnel);
    # directly-attached TPUs move the full payload in well under 1 ms.
    flush_fetch_f16: bool = False
    # Incremental dirty-slot flush (ISSUE 11): the flush program
    # consumes the SAME dirty-slot bitmap the delta checkpoints mark at
    # every device-landing site, gathers only touched piles into a
    # compact [D, ·] work set, and scatters results over the cached
    # empty-bank baseline — cold piles keep their (fresh-init) state
    # and materialized rows verbatim, bit-identical to the full
    # program by construction. Above `flush_incremental_threshold`
    # dirty fraction on the histogram bank the full program runs
    # instead (a near-full gather costs more than it saves).
    flush_incremental: bool = True
    flush_incremental_threshold: float = 0.75
    # Fused Pallas kernels (ISSUE 15): "auto" compiles the fused
    # compress / ULL scatter-join on real TPU backends (counted, loud
    # fallback to the XLA programs when Mosaic refuses) and keeps XLA
    # on CPU; "on" additionally serves the interpret-mode kernel on
    # CPU (the testing stance — the oracle/chaos suites run the actual
    # kernel math end to end, bit-identical by contract); "off" pins
    # the XLA programs everywhere. /debug/flush's sketch_engines block
    # reports the arm each engine's executables were built with.
    fused_kernels: str = "auto"
    # Double-buffered flush (ISSUE 11): the tick boundary only RETIRES
    # the interval under the ingest lock (stage buffers, staged
    # imports, banks, dirty bitmaps swap against fresh shadows in one
    # rebind); draining the retired stages, landing the retired
    # imports, and the flush program itself all run outside the lock —
    # admit/ingest never stalls behind them. Off = the legacy ordering
    # (drain+land under the lock before the swap; the mesh engine
    # always uses it — its landing paths write sharded banks in
    # place).
    flush_double_buffer: bool = True


@dataclass
class ForwardExport:
    """Global-scope state to send upstream, one entry per key — the
    Export()/Metric() payloads of samplers (samplers.go sym: Histo.Metric,
    Set.Export, Counter.Export)."""
    histograms: list = dc_field(default_factory=list)
    # (key, means f32[n], weights f32[n], min, max, sum, count, recip)
    sets: list = dc_field(default_factory=list)        # (key, registers u8[m])
    counters: list = dc_field(default_factory=list)    # (key, value)
    gauges: list = dc_field(default_factory=list)      # (key, value)
    # which set engine produced `sets` (selects the register wire code
    # and the spill re-merge join); histograms are engine-agnostic
    # weighted points on the wire
    set_engine: str = "hll"
    # per-prefix Huffman-Bucket cardinality sketches riding to the
    # global tier (overload-defense satellite): [(prefix, bytes regs)];
    # merge-by-max, advisory — excluded from the durability journal
    prefix_sketches: list = dc_field(default_factory=list)
    # What this export IS (ISSUE 13 delta forwarding): "full" = the
    # sender's COMPLETE interned counter/set key set (idle keys ship
    # their zero totals / empty register banks — the receiver-liveness
    # refresh a resync exists for); "delta" = only the keys the
    # dirty-slot bitmap saw land this interval. Histograms and gauges
    # are touched-only under EITHER kind, deliberately: a zero-count
    # histogram row would be live-filtered out of the receiver's own
    # flush anyway (pure wire waste), and a synthetic zero gauge would
    # CLOBBER the receiver's last-write-wins state. The forwarder
    # stamps the kind onto the interval's envelope so the receiver can
    # gap-check deltas.
    kind: str = "full"


class FlushResult:
    """Flush output. `frame` is the columnar MetricFrame the engine
    assembles (cheap); `metrics` materializes the InterMetric list from it
    lazily, so callers that re-serialize anyway can consume the frame."""

    __slots__ = ("frame", "export", "stats", "_metrics",
                 "status_metrics")

    def __init__(self, frame=None, export=None, stats=None, metrics=None,
                 status_metrics=None):
        self.frame = frame
        self.export = export if export is not None else ForwardExport()
        self.stats = stats if stats is not None else {}
        self._metrics = metrics
        self.status_metrics = status_metrics or []

    @property
    def metrics(self) -> list:
        if self._metrics is None:
            self._metrics = ((self.frame.to_list() if self.frame else [])
                             + self.status_metrics)
        return self._metrics


class _Stage:
    """Fixed-shape numpy staging buffer feeding one scatter kernel."""

    def __init__(self, batch_size, fields):
        self.n = 0
        self.batch_size = batch_size
        self.arrays = {
            name: np.full(batch_size, fill, dtype)
            for name, (dtype, fill) in fields.items()}

    def full(self):
        return self.n >= self.batch_size

    def put(self, **vals):
        i = self.n
        for k, v in vals.items():
            self.arrays[k][i] = v
        self.n = i + 1

    def drain(self):
        """Return padded arrays and reset. Rows past self.n keep their
        fill value (slot -1 => dropped by the kernels)."""
        out = {k: a.copy() for k, a in self.arrays.items()}
        n = self.n
        if n < self.batch_size:
            out["slots"][n:] = -1
        self.n = 0
        return out


class AggregationEngine:
    # Subclass gates for the ISSUE 11 flush paths: the mesh engine owns
    # sharded banks (no per-slot bitmaps, landing paths write banks in
    # place) and turns both off in its constructor.
    _incremental_capable = True
    _double_buffer_capable = True

    def _setup_device(self):
        """Build the device-side state: committed banks plus the shared
        fresh-banks and ingest executables (see the factory comments
        above). Overridden by the mesh engine, which owns sharded banks
        over a Mesh instead of single-device ones."""
        cfg = self.cfg
        self._device = jax.devices()[0]
        # Fused-kernel arm resolution (ISSUE 15): ONE resolved arm per
        # engine construction, split per sketch engine by capability —
        # an engine without a fused kernel (REQ/HLL insert) stays on
        # "xla" no matter the knob, so the /debug arm stamps name what
        # each engine's executables were ACTUALLY built with.
        arm = kernels.resolve_arm(cfg.fused_kernels,
                                  self._device.platform)
        self._kernel_arms = kernels.verify_engine_kernels(
            self._heng, self._seng,
            {
                "histogram": arm if hasattr(self._heng,
                                            "compress_fused_impl")
                else "xla",
                "set": arm if hasattr(self._seng, "insert_fused_impl")
                else "xla",
            },
            set_slots=cfg.set_slots, batch_size=cfg.batch_size)
        self._fresh_fn = _fresh_banks_executable(
            self._device, self._heng, self._seng, cfg.histogram_slots,
            cfg.counter_slots, cfg.gauge_slots, cfg.set_slots)
        # vlint: disable=DS01 reason=initial fresh-bank build, not a
        # data landing — every row is exactly fresh init (zero dirty)
        (self.histo_bank, self.counter_bank,
         self.gauge_bank, self.set_bank) = self._fresh_fn()
        self._kern = _ingest_executables(self._device, self._heng,
                                         self._seng,
                                         self._kernel_arms["set"])

    def _setup_flush_exec(self):
        cfg = self.cfg
        self._flush_exec = _flush_executable(
            self._device, self._heng, self._seng, self._fwd_out,
            tuple(self._agg_emit),
            self._device.platform in ("tpu", "axon"),
            compact=cfg.flush_fetch_f16,
            kernel_arm=self._kernel_arms["histogram"])
        self._stage_exec = None
        mode = cfg.flush_fetch
        if mode in ("staged", "host"):
            if mode == "host":
                # pinned_host support only shows up at compile/run time
                # (CPU constructs the sharding fine, then fails with "no
                # registered implementation ... for Host") — probe it.
                try:
                    stage = stage_copy_executable(
                        jax.sharding.SingleDeviceSharding(
                            self._device, memory_kind="pinned_host"))
                    jax.device_get(stage(jnp.zeros(8, jnp.float32)))
                    self._stage_exec = stage
                except Exception:
                    logger.warning("flush_fetch=host: backend lacks "
                                   "pinned_host memory; using staged")
            if self._stage_exec is None:
                self._stage_exec = stage_copy_executable(
                    jax.sharding.SingleDeviceSharding(self._device))

    def __init__(self, config: EngineConfig | None = None):
        self.cfg = config or EngineConfig()
        if self.cfg.buffer_depth < 8:
            raise ValueError("buffer_depth must be >= 8 (hot-slot "
                             "pre-clustering needs usable bucket room)")
        if self.cfg.flush_fetch not in ("sync", "staged", "host", "async"):
            raise ValueError(
                f"flush_fetch={self.cfg.flush_fetch!r}: must be "
                "sync/staged/host/async")
        if not (0.0 < self.cfg.flush_incremental_threshold <= 1.0):
            raise ValueError(
                "flush_incremental_threshold must be in (0, 1]: it is "
                "the dirty fraction above which the full flush program "
                f"runs, got {self.cfg.flush_incremental_threshold!r}")
        if self.cfg.fused_kernels not in kernels.MODES:
            raise ValueError(
                f"fused_kernels={self.cfg.fused_kernels!r}: must be "
                f"{'/'.join(kernels.MODES)}")
        # One ingest thread owns process(); flush() may run from another
        # thread. The lock is the Worker.Flush mutex-swap equivalent:
        # ingest holds it per item; flush holds it ONLY across
        # drain+swap+bookkeeping, then runs the merge program on the
        # immutable snapshot lock-free while ingest continues.
        self.lock = threading.Lock()
        cfg = self.cfg
        # Selected sketch engines (sketches/ registry): frozen
        # dataclasses carrying the static shape params; every sketch
        # call in this module routes through them (vlint SK01).
        self._heng = sketches.histogram_engine(cfg)
        self._seng = sketches.set_engine(cfg)
        self._setup_device()

        self.histo_keys = KeyInterner(cfg.histogram_slots,
                                      cfg.idle_ttl_intervals)
        self.counter_keys = KeyInterner(cfg.counter_slots,
                                        cfg.idle_ttl_intervals)
        self.gauge_keys = KeyInterner(cfg.gauge_slots,
                                      cfg.idle_ttl_intervals)
        self.set_keys = KeyInterner(cfg.set_slots, cfg.idle_ttl_intervals)

        b = cfg.batch_size
        f32, i32 = (np.float32, 0.0), (np.int32, 0)
        self._histo_stage = _Stage(b, {"slots": (np.int32, -1),
                                       "values": f32, "weights": f32})
        self._counter_stage = _Stage(b, {"slots": (np.int32, -1),
                                         "values": f32, "weights": f32})
        self._gauge_stage = _Stage(b, {"slots": (np.int32, -1),
                                       "values": f32, "seqs": i32})
        self._set_stage = _Stage(b, {"slots": (np.int32, -1),
                                     "reg_idx": i32, "rho": (np.uint8, 0)})
        self._gauge_seq = 0
        # Quantile program input: configured percentiles, plus 0.5 when the
        # `median` aggregate is requested (veneur's median IS quantile(0.5)).
        qs = list(cfg.percentiles)
        self._median_idx = None
        if "median" in cfg.aggregates:
            self._median_idx = len(qs)
            qs.append(0.5)
        self._qs = np.asarray(qs, np.float32)
        # %g formatting matches veneur's suffixes ("99percentile",
        # "99.9percentile") and avoids int() truncation (0.29 -> 28).
        self._pct_names = [f".{p * 100:g}percentile" for p in cfg.percentiles]
        # Flush-assembly presentation caches: per-key metric names and
        # split tag lists are immutable across flushes, so they're built
        # once and re-used; the columnar frame then only moves numpy
        # values. Bounded (cleared when oversized) because the native
        # bridge's interner evicts keys without telling us.
        self._pct_sufs = list(self._pct_names)
        if self._median_idx is not None:
            self._pct_sufs.append(".median")
        self._agg_emit = [a for a in cfg.aggregates
                          if a in ("min", "max", "sum", "count",
                                   "avg", "hmean")]
        agg_types = tuple(MetricType.COUNTER if a == "count"
                          else MetricType.GAUGE for a in self._agg_emit)
        self._histo_full_types = (
            (MetricType.GAUGE,) * len(self._pct_sufs) + agg_types)
        self._histo_agg_types = agg_types
        self._agg_idx = {a: i for i, a in enumerate(self._agg_emit)}
        self._fwd_out = cfg.forward_enabled and not cfg.is_global
        self._setup_flush_exec()
        self._tags_cache: dict[str, list] = {}
        self._pres_bound = 4 * (cfg.histogram_slots + cfg.counter_slots
                                + cfg.gauge_slots + cfg.set_slots)
        self.samples_processed = 0
        # Dirty-slot bitmaps per bank, with TWO consumers (ISSUE 9 +
        # ISSUE 11): flush-boundary delta checkpoints serialize only
        # dirty rows, and the incremental flush program compresses only
        # dirty piles. Marked at every DEVICE LANDING site (scatter/
        # merge dispatch — machine-checked by vlint DS01), retired at
        # the flush swap (the retiring interval's bitmap travels with
        # its bank snapshot; a FRESH zero bitmap replaces it in the
        # same rebind), so at any instant `fresh init + dirty rows` is
        # exactly the live bank state — what keeps delta checkpoints
        # self-contained with the flush as a second consumer.
        # Armed by default for the incremental flush; None only when
        # flush_incremental is off AND enable_dirty_tracking was never
        # called (then landing sites cost one attribute load).
        # last_import_op is the applied-op watermark recovery filters
        # the replay log by.
        self._dirty = None
        self._delta_threshold = 0.5
        self._use_incremental = (cfg.flush_incremental
                                 and self._incremental_capable)
        self._use_double_buffer = (cfg.flush_double_buffer
                                   and self._double_buffer_capable)
        if self._use_incremental:
            self._dirty = [
                np.zeros(getattr(self, attr).num_slots, bool)
                for _kind, attr, _ki in self._bank_table()]
        # per-output-key baseline rows of an EMPTY flush (what every
        # cold pile materializes to) — computed lazily on a 1-slot
        # fresh bank set (engine-pair-shaped, slot-count-independent)
        self._flush_baseline = None
        self._last_flush_info = {"path": "full"}
        self.last_import_op = 0
        # Overload defense (ingest/admission.py): attached by the
        # Server via attach_admission; None = every key mints freely
        # (direct engine construction, the pre-defense behavior).
        self._adm = None
        self._adm_index = 0
        self._adm_n = 1
        self._adm_reroute = None
        # Imported (Combine) staging for the global tier — everything is
        # batched so a 32-shard import costs a handful of device calls,
        # not one per key.
        self._import_centroids: list = []
        self._import_centroid_total = 0
        self._import_sets: list = []          # (slot, registers u8[m])
        self._import_counter_acc: dict = {}   # slot -> host f64 sum
        self._import_gauge_acc: dict = {}     # slot -> last value
        self._pending_events: list = []
        # StatusCheck sampler state (samplers.go sym: StatusCheck): last
        # status/message per (name, tags) per interval, flushed as
        # status-typed InterMetrics — NOT passed through raw.
        self._status: dict = {}

    # ---------------- ingest ----------------

    def attach_admission(self, adm, *, index: int = 0, n: int = 1,
                         reroute=None):
        """Wire the Server's admission controller into this engine's
        slot minting (overload defense): each KeyInterner consults it
        before allocating, and over-budget keys' samples re-stage onto
        their prefix's `__other__` key via `_fold` instead of minting
        a bank slot. A map-hit key never touches the controller, so
        the steady-state ingest path is unchanged.

        `index`/`n`/`reroute` single-home the fold keys in a
        multi-worker server: a fold rewrite whose digest routes to a
        DIFFERENT engine is handed back to the server's router
        (`reroute`) instead of minting a local slot, so one flush
        never emits the same `__other__` series from two engines —
        duplicate same-name rows are last-write-wins on several
        backends, which would silently lose folded volume."""
        self._adm = adm
        self._adm_index = index
        self._adm_n = n
        self._adm_reroute = reroute
        for ki in (self.histo_keys, self.counter_keys,
                   self.gauge_keys, self.set_keys):
            ki.admission = adm

    def _fold(self, interner, m: UDPMetric):
        """Resolve an over-budget sample (lookup returned FOLD_SLOT)
        into (fold-rewritten metric, slot), or (None, -1) when the
        sample left this engine: sampled out (admission counts it —
        and it must then not count as processed either, the accounting
        identity `received == applied + counted_degraded` is exact),
        re-routed to the fold key's home engine (counted as folded
        here; the home engine processes it as an ordinary sample), or
        refused by the full bank (the interner's dropped_no_slot
        accounting, exactly like any over-full sample — NOT counted
        as a fold)."""
        fm = self._adm.fold_metric(m, self._fwd_out)
        if fm is None:
            self.samples_processed -= 1
            return None, -1
        if self._adm_n > 1 and fm.digest % self._adm_n != self._adm_index:
            self.samples_processed -= 1      # the home engine counts it
            self._adm.count_folded()
            self._adm_reroute(fm)
            return None, -1
        slot = interner.lookup(fm.key, fm.scope)
        if slot < 0:
            return None, -1
        self._adm.count_folded()
        return fm, slot

    def _fold_import_slot(self, interner, key: MetricKey) -> int:
        """Import-path fold (the global tier's Combine): redirect an
        over-budget forwarded key's slot to the fold key — the merge
        machinery is unchanged, the aggregate just lands in
        `<prefix>.__other__` (no sampling: a forwarded digest is an
        interval aggregate, not a sample). In a multi-worker server a
        fold key homed on another engine raises ImportFoldReroute so
        the worker loop re-routes the aggregate there (single-homed,
        like the ingest path)."""
        if self._adm is None:
            return -1
        fk, digest = self._adm.fold_key(key)
        if self._adm_n > 1 and digest % self._adm_n != self._adm_index:
            self._adm.count_folded()
            raise ImportFoldReroute(fk, digest)
        slot = interner.lookup(fk, GLOBAL_ONLY)
        if slot >= 0:
            self._adm.count_folded()
        return slot

    def process(self, m: UDPMetric):
        """Route one parsed sample to its bank's staging buffer — the
        Worker.ProcessMetric equivalent. Thread-safe against flush()."""
        with self.lock:
            self._process_locked(m)

    def _process_locked(self, m: UDPMetric):
        t = m.key.type
        self.samples_processed += 1
        if t in ("timer", "histogram"):
            slot = self.histo_keys.lookup(m.key, m.scope)
            if slot < 0:
                if slot != FOLD_SLOT:
                    return
                m, slot = self._fold(self.histo_keys, m)
                if m is None:
                    return
            st = self._histo_stage
            st.put(slots=slot, values=m.value, weights=1.0 / m.sample_rate)
            if st.full():
                self._dispatch_histos()
        elif t == "counter":
            slot = self.counter_keys.lookup(m.key, m.scope)
            if slot < 0:
                if slot != FOLD_SLOT:
                    return
                m, slot = self._fold(self.counter_keys, m)
                if m is None:
                    return
            st = self._counter_stage
            st.put(slots=slot, values=m.value, weights=1.0 / m.sample_rate)
            if st.full():
                self._dispatch_counters()
        elif t == "gauge":
            slot = self.gauge_keys.lookup(m.key, m.scope)
            if slot < 0:
                if slot != FOLD_SLOT:
                    return
                m, slot = self._fold(self.gauge_keys, m)
                if m is None:
                    return
            st = self._gauge_stage
            self._gauge_seq += 1
            st.put(slots=slot, values=m.value, seqs=self._gauge_seq)
            if st.full():
                self._dispatch_gauges()
        elif t == "set":
            slot = self.set_keys.lookup(m.key, m.scope)
            if slot < 0:
                if slot != FOLD_SLOT:
                    return
                m, slot = self._fold(self.set_keys, m)
                if m is None:
                    return
            # Engine-specific hash decomposition (int bit ops, no
            # numpy round-trip) — this is the per-sample hot path.
            h = hashing.set_member_hash(str(m.value))
            idx, val = self._seng.hash_update(h)
            st = self._set_stage
            st.put(slots=slot, reg_idx=idx, rho=val)
            if st.full():
                self._dispatch_sets()

    # ---- pre-interned batch ingest (the native C++ bridge's path) ----
    # Slots were assigned by the bridge's interner; rows with slot -1 are
    # padding the kernels drop. `mark` (if given) runs under the engine
    # lock so the caller's touched-set stays consistent with the bank the
    # samples land in across a concurrent flush swap.
    #
    # ALIASING CONTRACT: callers must not mutate the passed arrays after
    # the call returns. The dispatch is async and jax's CPU client
    # zero-copies page-aligned numpy arrays into executable arguments,
    # so a later overwrite races the kernel's read (the native pump
    # copies its reused poll buffers for exactly this reason).

    def _ingest_batch(self, slots, count, mark, apply):
        with self.lock:
            n = int(count if count is not None else len(slots))
            if mark is not None:
                mark(slots[:n])
            self.samples_processed += n
            apply(n)

    def ingest_histo_batch(self, slots, values, weights, count=None,
                           mark=None):
        def apply(n):
            self._add_histos(slots, values, weights)
        self._ingest_batch(slots, count, mark, apply)

    def _add_histos(self, slots, values, weights):
        """Live-bank histogram landing (ingest path; mesh overrides
        this wholesale — its landing routes the sharded ingest)."""
        self.histo_bank = self._land_histos(
            self.histo_bank, self._dirty, slots, values, weights)

    def _land_histos(self, bank, dirty, slots, values, weights):
        """Land one histogram batch into `bank` (live or a retired
        double-buffer snapshot — the caller owns the rebind), marking
        `dirty`, sidestepping the hot-slot worst case: add_batch's
        while-loop pays a full-bank [K, C+B] sort per buffer-depth's
        worth of samples landing on ONE slot, so a batch where
        max-per-slot is 8192/B=32x over depth costs 32 sorts. When
        a batch overfills any slot, pre-cluster the hot slots' samples
        on host to <= B weighted points each (numpy sort + bucketed
        segment means — the same two-level scheme the digest itself
        uses, so accuracy is unchanged within the k1 clustering's own
        granularity), then land everything with ONE compress +
        merge_centroids + exact merge_scalars."""
        slots = np.asarray(slots)
        B = bank.buf_size
        valid = slots >= 0
        vs = slots[valid]
        if dirty is not None and vs.size:
            dirty[0][vs] = True
        # Hot-slot detection, cheapest-first (this runs on EVERY pump
        # batch): a batch with <= B valid rows cannot overfill any slot,
        # so skip counting entirely. Otherwise bincount — one O(n + max)
        # pass — EXCEPT when the live slot ids dwarf the batch (sparse
        # high-slot batches against a 1M-slot bank would allocate and
        # scan a multi-MB count array per batch); there np.unique's
        # O(n log n) on the small batch is the cheaper form.
        if vs.size <= B:
            return self._kern["histo"](bank, slots, values, weights)
        if vs.max() > 16 * vs.size:
            uniq, cnt = np.unique(vs, return_counts=True)
            hot_ids = uniq[cnt > B]
        else:
            cnt = np.bincount(vs, minlength=1)
            hot_ids = np.nonzero(cnt > B)[0]
        if hot_ids.size == 0:
            return self._kern["histo"](bank, slots, values, weights)
        values = np.asarray(values)
        weights = np.asarray(weights)
        hot = set(hot_ids.tolist())
        hot_m = np.isin(slots, list(hot)) & valid
        cold_slots = np.where(hot_m, -1, slots).astype(np.int32)
        bank = self._kern["histo"](bank, cold_slots, values, weights)

        out_s, out_m, out_w = [], [], []
        sc_s, sc_min, sc_max, sc_sum, sc_cnt, sc_rcp = \
            [], [], [], [], [], []
        for s in hot:
            m = (slots == s) & valid
            v = values[m].astype(np.float64)
            w = weights[m].astype(np.float64)
            cm, cw = _precluster_k1(v, w, B)
            out_s.append(np.full(len(cm), s, np.int32))
            out_m.append(cm.astype(np.float32))
            out_w.append(cw.astype(np.float32))
            sc_s.append(s)
            sc_min.append(float(v.min()))
            sc_max.append(float(v.max()))
            sc_sum.append(float((v * w).sum()))
            sc_cnt.append(float(w.sum()))
            nz = v != 0
            sc_rcp.append(float((w[nz] / v[nz]).sum()))

        flat_s = np.concatenate(out_s)
        flat_m = np.concatenate(out_m)
        flat_w = np.concatenate(out_w)
        # ONE fixed shape per engine (worst case: every sample in the
        # batch belongs to a hot slot) — a varying width would JIT a new
        # executable inline, under the ingest lock, per width
        width, swidth = self._hot_widths()
        pad_s = np.full(width, -1, np.int32)
        pad_m = np.zeros(width, np.float32)
        pad_w = np.zeros(width, np.float32)
        pad_s[:len(flat_s)] = flat_s
        pad_m[:len(flat_s)] = flat_m
        pad_w[:len(flat_s)] = flat_w
        nh = len(sc_s)
        spad = np.full(swidth, -1, np.int32)
        spad[:nh] = np.asarray(sc_s, np.int32)
        f = lambda a: np.pad(np.asarray(a, np.float32), (0, swidth - nh))
        # compress first so merge_centroids has a full buffer of headroom
        bank = self._kern["compress"](bank)
        bank = self._kern["merge_centroids"](bank, pad_s, pad_m, pad_w)
        return self._kern["merge_scalars"](
            bank, spad, f(sc_min), f(sc_max), f(sc_sum),
            f(sc_cnt), f(sc_rcp))

    def ingest_counter_batch(self, slots, values, weights, count=None,
                             mark=None):
        def apply(n):
            self.counter_bank = self._land_counters(
                self.counter_bank, self._dirty, slots, values, weights)
        self._ingest_batch(slots, count, mark, apply)

    def ingest_gauge_batch(self, slots, values, count=None, mark=None):
        # Sequence numbers are assigned HERE (arrival order at the
        # engine), not by the producer: the per-interval reset then
        # happens under the same lock as the bank swap, so a stale
        # pre-flush sample can never outrank a newer post-flush one and
        # the counter cannot wrap within an interval.
        def apply(n):
            seqs = np.arange(1, len(slots) + 1, dtype=np.int32) \
                + self._gauge_seq
            self._gauge_seq += n
            self.gauge_bank = self._land_gauges(
                self.gauge_bank, self._dirty, slots, values, seqs)
        self._ingest_batch(slots, count, mark, apply)

    def ingest_set_batch(self, slots, reg_idx, rho, count=None, mark=None):
        def apply(n):
            self.set_bank = self._land_sets(
                self.set_bank, self._dirty, slots, reg_idx, rho)
        self._ingest_batch(slots, count, mark, apply)

    def process_event(self, ev):
        with self.lock:
            self._pending_events.append(ev)

    def process_service_check(self, sc):
        """Aggregate one service check: last write wins per
        (name, tags) within the interval (samplers.go sym:
        StatusCheck.Sample — a gauge over status codes)."""
        with self.lock:
            self._status[(sc.name, tuple(sc.tags))] = sc

    def _dispatch_histos(self):
        a = self._histo_stage.drain()
        self._add_histos(a["slots"], a["values"], a["weights"])

    def _dispatch_counters(self):
        a = self._counter_stage.drain()
        self.counter_bank = self._land_counters(
            self.counter_bank, self._dirty, a["slots"], a["values"],
            a["weights"])

    def _dispatch_gauges(self):
        a = self._gauge_stage.drain()
        self.gauge_bank = self._land_gauges(
            self.gauge_bank, self._dirty, a["slots"], a["values"],
            a["seqs"])

    def _dispatch_sets(self):
        a = self._set_stage.drain()
        self.set_bank = self._land_sets(
            self.set_bank, self._dirty, a["slots"], a["reg_idx"],
            a["rho"])

    # ---- scalar/set landing cores: take and return the bank, mark
    # the PASSED bitmap — shared by the live ingest path (live banks +
    # live bitmap) and the double-buffered flush's retired landing
    # (retired banks + retired bitmap).

    def _land_counters(self, bank, dirty, slots, values, weights):
        if dirty is not None:
            self._mark_dirty_into(dirty, 1, slots)
        return self._kern["counter"](bank, slots, values, weights)

    def _land_gauges(self, bank, dirty, slots, values, seqs):
        if dirty is not None:
            self._mark_dirty_into(dirty, 2, slots)
        return self._kern["gauge"](bank, slots, values, seqs)

    def _land_sets(self, bank, dirty, slots, reg_idx, rho):
        if dirty is not None:
            self._mark_dirty_into(dirty, 3, slots)
        return self._kern["set"](bank, slots, reg_idx, rho)

    def drain_all(self):
        for st, fn in ((self._histo_stage, self._dispatch_histos),
                       (self._counter_stage, self._dispatch_counters),
                       (self._gauge_stage, self._dispatch_gauges),
                       (self._set_stage, self._dispatch_sets)):
            if st.n:
                fn()

    def _hot_widths(self):
        """Fixed pad shapes for the hot-slot sidestep: at most
        batch/B slots can be hot in one batch, each contributing <= B
        pre-clustered points. B is the BANK's per-landing headroom
        (the engine's buf_size — t-digest buffer depth, compactor
        level capacity), which need not equal cfg.buffer_depth."""
        B = self.histo_bank.buf_size
        n_hot = max(1, self.cfg.batch_size // max(1, B))
        return n_hot * min(B, self.cfg.batch_size), max(1, n_hot)

    def warmup(self):
        """Precompile every device program the serving path dispatches.

        Without this, flush 0 pays the full compile bill inline — ~100s
        on a cold tunneled-TPU backend (r2 bench), i.e. more than ten
        flush intervals, which would trip the server's crash-only
        watchdog before the first flush ever completes. Ingest kernels
        compile against all-padding batches (slot -1 rows are dropped by
        the kernels, so live state is untouched); the flush program runs
        on throwaway fresh banks, which it donates away."""
        self.warm_ingest_kernels(self.cfg.batch_size)
        with self.lock:
            # hot-slot sidestep programs, at their (fixed) shapes
            width, swidth = self._hot_widths()
            # vlint: disable=DS01 reason=warmup compiles against
            # all-padding batches (slot -1 rows are dropped by the
            # kernels) — no live data lands, nothing to mark
            self.histo_bank = self._kern["compress"](self.histo_bank)
            self.histo_bank = self._kern["merge_centroids"](
                self.histo_bank, np.full(width, -1, np.int32),
                np.zeros(width, np.float32), np.zeros(width, np.float32))
            sz = np.zeros(swidth, np.float32)
            self.histo_bank = self._kern["merge_scalars"](
                self.histo_bank, np.full(swidth, -1, np.int32),
                sz, sz, sz, sz, sz)
        # Run the full configured flush path (program + staging/fetch
        # mode) so flush 0 hits only warm executables.
        self._flush_device(self._fresh_fn())
        if self._use_incremental:
            # the incremental path too: build the empty-flush baseline
            # and compile the smallest-bucket incremental program (one
            # dirty slot per bank — flush 0's common shape; bigger
            # dirty sets compile their bucket inline, like the
            # cluster_rows width ladder)
            warm_dirty = [np.zeros_like(d) for d in self._dirty]
            for d in warm_dirty:
                d[0] = True
            self._flush_device(self._fresh_fn(), dirty=warm_dirty)
        jax.block_until_ready(self.histo_bank)

    def warm_ingest_kernels(self, b: int):
        """Precompile the batch-ingest kernels at an ADDITIONAL batch
        width (the native pump dispatches at native_pump_batch, which
        may differ from the staging batch_size warmup() covers). Padding
        batches: slot -1 rows are dropped, live state untouched."""
        pad = np.full(b, -1, np.int32)
        zf = np.zeros(b, np.float32)
        zi = np.zeros(b, np.int32)
        zu = np.zeros(b, np.uint8)
        with self.lock:
            # vlint: disable=DS01 reason=all-padding warmup batches
            # (slot -1 rows dropped by the kernels) — no live data
            # lands, nothing to mark
            self.histo_bank = self._kern["histo"](
                self.histo_bank, pad, zf, zf)
            self.counter_bank = self._kern["counter"](
                self.counter_bank, pad, zf, zf)
            self.gauge_bank = self._kern["gauge"](
                self.gauge_bank, pad, zf, zi)
            self.set_bank = self._kern["set"](self.set_bank, pad, zi, zu)
        jax.block_until_ready(self.histo_bank)

    # ---------------- import (global tier Combine path) ----------------

    def import_histogram(self, key: MetricKey, means, weights, vmin, vmax,
                         vsum, count, recip=0.0):
        """Stage a forwarded digest for merging — Histo.Combine
        (importsrv path, worker.go sym: Worker.ImportMetricGRPC)."""
        with self.lock:
            self._import_histogram_locked(key, means, weights, vmin,
                                          vmax, vsum, count, recip)

    def _import_histogram_locked(self, key, means, weights, vmin, vmax,
                                 vsum, count, recip=0.0):
        slot = self.histo_keys.lookup(key, GLOBAL_ONLY)
        if slot == FOLD_SLOT:
            slot = self._fold_import_slot(self.histo_keys, key)
        if slot < 0:
            return
        means = np.asarray(means, np.float32)
        self._import_centroids.append(
            (slot, means, np.asarray(weights, np.float32),
             float(vmin), float(vmax), float(vsum), float(count),
             float(recip)))
        self._import_centroid_total += len(means)
        if (len(self._import_centroids) >= _IMPORT_STAGE_DIGESTS
                or self._import_centroid_total
                >= _IMPORT_STAGE_CENTROIDS):
            self._flush_import_centroids()

    def import_set(self, key: MetricKey, registers, engine_id=None):
        with self.lock:
            self._import_set_locked(key, registers, engine_id)

    def _import_set_locked(self, key, registers, engine_id=None):
        # belt to the request-level stamp check's suspenders: a
        # register row of the wrong engine or width must reject THIS
        # metric (the poison-pill counter), never join a bank whose
        # update rule it does not share
        if engine_id is not None and engine_id != self._seng.id:
            raise ValueError(
                f"set sketch engine mismatch: payload {engine_id!r}, "
                f"bank runs {self._seng.id!r}")
        regs = np.asarray(registers, np.uint8)
        if regs.shape[-1] != self.set_bank.num_registers:
            raise ValueError(
                f"set register width {regs.shape[-1]} != bank width "
                f"{self.set_bank.num_registers}")
        slot = self.set_keys.lookup(key, GLOBAL_ONLY)
        if slot == FOLD_SLOT:
            slot = self._fold_import_slot(self.set_keys, key)
        if slot < 0:
            return
        self._import_sets.append((slot, regs))
        if len(self._import_sets) >= 256:
            self._flush_import_sets()

    def import_counter(self, key: MetricKey, value: float):
        with self.lock:
            self._import_counter_locked(key, value)

    def _import_counter_locked(self, key, value):
        slot = self.counter_keys.lookup(key, GLOBAL_ONLY)
        if slot == FOLD_SLOT:
            slot = self._fold_import_slot(self.counter_keys, key)
        if slot < 0:
            return
        # Host-side f64 accumulation — exact, one device call per flush.
        self._import_counter_acc[slot] = (
            self._import_counter_acc.get(slot, 0.0) + float(value))

    def import_gauge(self, key: MetricKey, value: float):
        with self.lock:
            self._import_gauge_locked(key, value)

    def _import_gauge_locked(self, key, value):
        slot = self.gauge_keys.lookup(key, GLOBAL_ONLY)
        if slot == FOLD_SLOT:
            slot = self._fold_import_slot(self.gauge_keys, key)
        if slot < 0:
            return
        self._import_gauge_acc[slot] = float(value)  # last write wins

    def import_list(self, op_id: int, pbs) -> tuple:
        """Atomically apply one journaled import op's metrics for this
        engine (durability/ ISSUE 9): the whole group lands under ONE
        lock hold and the applied-op watermark advances in the same
        critical section, so a concurrent checkpoint_state() sees
        either none of the op or all of it — the exactness the
        watermark's replay filter depends on. Returns
        (rerouted, rejected): fold keys homed on other engines as
        (ImportFoldReroute, pb) pairs the worker loop re-routes, and
        per-metric poison pills as (pb, exception) pairs it counts —
        one corrupt metric must reject itself, not the op."""
        from ..cluster import wire
        rerouted, rejected = [], []
        with self.lock:
            for pb in pbs:
                try:
                    wire.apply_metric_to_engine_locked(self, pb)
                except ImportFoldReroute as fr:
                    rerouted.append((fr, pb))
                except Exception as e:
                    rejected.append((pb, e))
            if op_id > self.last_import_op:
                self.last_import_op = op_id
        return rerouted, rejected

    def _flush_import_sets(self):
        items, self._import_sets = self._import_sets, []
        self.set_bank = self._land_import_sets(self.set_bank, items,
                                               self._dirty)

    def _land_import_sets(self, bank, items, dirty):
        if not items:
            return bank
        slots = np.array([s for s, _ in items], np.int32)
        if dirty is not None:
            self._mark_dirty_into(dirty, 3, slots)
        return jax.device_put(self._seng.merge_rows(
            bank, slots, np.stack([r for _, r in items])), self._device)

    def _flush_import_scalars(self):
        counters, self._import_counter_acc = self._import_counter_acc, {}
        gauges, self._import_gauge_acc = self._import_gauge_acc, {}
        (self.counter_bank, self.gauge_bank,
         self._gauge_seq) = self._land_import_scalars(
            self.counter_bank, self.gauge_bank, counters, gauges,
            self._dirty, self._gauge_seq)

    def _land_import_scalars(self, cbank, gbank, counters, gauges,
                             dirty, gauge_seq):
        if counters:
            slots = np.fromiter(counters.keys(), np.int32, len(counters))
            if dirty is not None:
                self._mark_dirty_into(dirty, 1, slots)
            cbank = jax.device_put(scalar.counter_merge(
                cbank, slots,
                np.fromiter(counters.values(), np.float32,
                            len(counters))), self._device)
        if gauges:
            slots = np.fromiter(gauges.keys(), np.int32, len(gauges))
            if dirty is not None:
                self._mark_dirty_into(dirty, 2, slots)
            seqs = np.arange(len(gauges), dtype=np.int32) + gauge_seq + 1
            gauge_seq += len(gauges)
            gbank = jax.device_put(scalar.gauge_set(
                gbank, slots,
                np.fromiter(gauges.values(), np.float32, len(gauges)),
                seqs), self._device)
        return cbank, gbank, gauge_seq

    def _flush_import_centroids(self):
        items = self._import_centroids
        self._import_centroids = []
        self._import_centroid_total = 0
        self.histo_bank = self._land_import_centroids(
            self.histo_bank, items, self._dirty)

    def _land_import_centroids(self, bank, items, dirty):
        """Land staged foreign digests into `bank` under the engine's
        import strategy: "cluster" (t-digest — precluster each slot's
        pile to <= C centroids with ONE batched cluster_rows program,
        then one merge + one compress) or "direct" (compactor engines —
        the items re-insert as weighted points in fixed-width batches;
        the engine's own compaction bounds memory, no preclustering)."""
        if not items:
            return bank
        if self._heng.import_strategy == "direct":
            return self._land_imports_direct(bank, items, dirty)
        C = bank.num_centroids

        by_slot: dict[int, list] = {}
        for s, means, weights, *_ in items:
            by_slot.setdefault(s, []).append((means, weights))

        # Forwarded payloads are untrusted: a digest with millions of
        # centroids must not size the [S, W] device matrix (resource
        # exhaustion + a fresh XLA compile per W bucket). Pre-cluster any
        # oversized pile in fixed-width chunks — each pass reduces a chunk
        # of `cap` raw centroids to C clustered ones, so with cap >= 2C
        # the loop converges geometrically and every program shape stays
        # bounded (cap must exceed C or re-chunking could never shrink a
        # pile at high compression settings). Pass 1 full-sorts (foreign
        # rows are unordered AND untrusted); later passes re-merge OUR OWN
        # cluster_rows outputs — each pile a [C] cluster-ordered row — so
        # chunks are built pile-aligned and take cluster_rows'
        # sorted_prefix=C fast arm (the importsrv re-merge case: the
        # leading run's order is proven, only the tail needs sorting).
        cap = max(_IMPORT_W_CAP, 2 * C)
        trusted: set = set()   # slots whose piles are all re-clustered
        while True:
            oversized = [
                s for s, piles in by_slot.items()
                if sum(len(m) for m, _ in piles) > cap]
            if not oversized:
                break
            batches = {0: ([], [], []),        # sorted_prefix -> chunks
                       C: ([], [], [])}
            piles_per_chunk = cap // C
            for s in oversized:
                piles = by_slot[s]
                if s in trusted:
                    owners, chunks_v, chunks_w = batches[C]
                    for i in range(0, len(piles), piles_per_chunk):
                        group = piles[i:i + piles_per_chunk]
                        cv = np.zeros(piles_per_chunk * C, np.float32)
                        cw = np.zeros(piles_per_chunk * C, np.float32)
                        for g, (m, w) in enumerate(group):
                            cv[g * C:g * C + len(m)] = m
                            cw[g * C:g * C + len(m)] = w
                        owners.append(s)
                        chunks_v.append(cv)
                        chunks_w.append(cw)
                else:
                    owners, chunks_v, chunks_w = batches[0]
                    m = np.concatenate([np.asarray(p[0], np.float32)
                                        for p in piles])
                    w = np.concatenate([np.asarray(p[1], np.float32)
                                        for p in piles])
                    for i in range(0, len(m), cap):
                        cv = np.zeros(cap, np.float32)
                        cw = np.zeros(cap, np.float32)
                        seg = slice(i, min(len(m), i + cap))
                        cv[:seg.stop - seg.start] = m[seg]
                        cw[:seg.stop - seg.start] = w[seg]
                        owners.append(s)
                        chunks_v.append(cv)
                        chunks_w.append(cw)
                by_slot[s] = []
            for prefix, (owners, chunks_v, chunks_w) in batches.items():
                if not owners:
                    continue
                cm, cw = self._heng.cluster_rows(
                    np.stack(chunks_v), np.stack(chunks_w),
                    num_centroids=C, sorted_prefix=prefix)
                cm, cw = np.asarray(cm), np.asarray(cw)
                for row, s in enumerate(owners):
                    by_slot[s].append((cm[row], cw[row]))
            trusted.update(oversized)

        slot_ids = np.fromiter(by_slot.keys(), np.int32, len(by_slot))
        if dirty is not None:
            self._mark_dirty_into(dirty, 0, slot_ids)
        widths = [sum(len(m) for m, _ in piles)
                  for piles in by_slot.values()]
        W = max(128, int(np.ceil(max(widths) / 128.0) * 128))
        S = len(slot_ids)
        vals = np.zeros((S, W), np.float32)
        wts = np.zeros((S, W), np.float32)
        for row, piles in enumerate(by_slot.values()):
            off = 0
            for m, w in piles:
                n = len(m)
                vals[row, off:off + n] = m
                wts[row, off:off + n] = w
                off += n
        cmeans, cwts = self._heng.cluster_rows(
            vals, wts, num_centroids=C)
        cmeans, cwts = np.asarray(cmeans), np.asarray(cwts)
        # land the clustered centroids; merge_centroids drops on buffer
        # overflow, so chunk the C columns to the buffer depth (one
        # iteration in the default config where B >= C)
        B = bank.buf_size
        for c0 in range(0, C, B):
            chunk = slice(c0, min(C, c0 + B))
            width = chunk.stop - chunk.start
            bank = self._heng.compress(bank)
            rows = np.repeat(slot_ids, width)
            bank = self._heng.merge_centroids(
                bank, rows, cmeans[:, chunk].reshape(-1),
                cwts[:, chunk].reshape(-1))
        bank = self._heng.compress(bank)

        sl = np.array([it[0] for it in items], np.int32)
        bank = self._heng.merge_scalars(
            bank, sl,
            np.array([it[3] for it in items], np.float32),
            np.array([it[4] for it in items], np.float32),
            np.array([it[5] for it in items], np.float32),
            np.array([it[6] for it in items], np.float32),
            np.array([it[7] for it in items], np.float32))
        # the merge chain above ran through plain jits whose outputs are
        # uncommitted; recommit so the ingest kernels and the flush
        # program stay on their committed (fast) executables
        return jax.device_put(bank, self._device)

    # fixed flat-batch width for the direct import landing: one program
    # shape however many centroids an interval staged
    _DIRECT_LAND_WIDTH = 4096

    def _land_imports_direct(self, bank, items, dirty):
        """The "direct" import strategy (compactor engines): re-insert
        every forwarded weighted point through the engine's own
        merge_centroids — its internal compaction bounds memory, so no
        host-side preclustering pass is needed. Batches are fixed-width
        (padded, slot -1 dropped) so the program shape never varies."""
        W = self._DIRECT_LAND_WIDTH
        slots = np.concatenate([
            np.full(len(it[1]), it[0], np.int32) for it in items])
        means = np.concatenate([
            np.asarray(it[1], np.float32) for it in items])
        wts = np.concatenate([
            np.asarray(it[2], np.float32) for it in items])
        if dirty is not None:
            self._mark_dirty_into(dirty, 0, np.unique(slots))
        for i in range(0, len(slots), W):
            seg = slice(i, min(len(slots), i + W))
            n = seg.stop - seg.start
            ps = np.full(W, -1, np.int32)
            pm = np.zeros(W, np.float32)
            pw = np.zeros(W, np.float32)
            ps[:n] = slots[seg]
            pm[:n] = means[seg]
            pw[:n] = wts[seg]
            bank = self._heng.merge_centroids(bank, ps, pm, pw)
        sl = np.array([it[0] for it in items], np.int32)
        bank = self._heng.merge_scalars(
            bank, sl,
            np.array([it[3] for it in items], np.float32),
            np.array([it[4] for it in items], np.float32),
            np.array([it[5] for it in items], np.float32),
            np.array([it[6] for it in items], np.float32),
            np.array([it[7] for it in items], np.float32))
        return jax.device_put(bank, self._device)

    # ---------------- flush ----------------

    def _swap_banks(self):
        """Under the lock: return the interval's bank snapshot and hand
        ingest fresh banks — the Worker.Flush swap, ONE async dispatch
        of the committed-output zeros program. Overridden by the mesh
        engine (its reset donates the sharded banks). Dirty-bitmap
        retirement happens in _retire_dirty (the caller), not here —
        the retiring bitmap must travel WITH this snapshot to its
        consumer (the incremental flush), while the fresh banks get a
        fresh zero bitmap in the same critical section."""
        snap = (self.histo_bank, self.counter_bank,
                self.gauge_bank, self.set_bank)
        # vlint: disable=DS01 reason=the fresh-bank swap, not a data
        # landing — the caller pairs it with _retire_dirty, which
        # installs a zero bitmap matching these all-fresh rows
        (self.histo_bank, self.counter_bank,
         self.gauge_bank, self.set_bank) = self._fresh_fn()
        return snap

    def _retire_dirty(self):
        """Under the lock, with the bank swap: hand the retiring
        interval's dirty bitmaps to the flush and install fresh zero
        bitmaps for the new banks. The swap re-zeroed every row, so
        `fresh init + dirty rows` describes the new banks exactly —
        the invariant BOTH consumers (delta checkpoints, incremental
        flush) rely on; a checkpoint taken after this tick sees only
        post-swap marks, never the flushed interval's."""
        retired = self._dirty
        if retired is not None:
            self._dirty = [np.zeros_like(d) for d in retired]
        return retired

    def _flush_device(self, snap, phases=None, dirty=None) -> dict:
        """Run the flush program on the snapshot and fetch the compact
        host arrays: ONE program dispatch + ONE device_get (on a
        tunneled TPU backend the transfer IS the flush cost; the program
        itself is ~0.2ms at 100k slots, TPU_EVIDENCE_r04.md §1).
        `flush_fetch` picks how the fetch is performed (see EngineConfig).
        Overridden by the mesh engine.

        `dirty` is the retired interval's dirty-slot bitmap set: when
        given (and incremental flush is on), only the touched piles
        run through the device — the ISSUE 11 tentpole
        (_flush_device_incremental); above the dirty-fraction
        threshold, or with dirty=None (warmup, bench harnesses, mesh),
        the full program runs.

        `phases` (flight-recorder stamp list, appended in place) splits
        the merge into dispatch / device exec / fetch — but ONLY under
        the sync fetch mode: the split's block_until_ready is a plain
        host sync, and on a relayed backend an extra sync can poison the
        serving executable exactly like an eager device_get (the reason
        the staged/host/async modes exist), so those modes record one
        combined `device` phase instead of paying a second sync."""
        if dirty is not None and self._use_incremental:
            host = self._flush_device_incremental(snap, phases, dirty)
            if host is not None:
                return host
        self._last_flush_info = {"path": "full"}
        hb, cb, gb, sb = snap
        if phases is None:
            return self._fetch_flush(
                self._flush_exec(hb, cb, gb, sb, self._qs))
        t0 = time.monotonic_ns()
        out = self._flush_exec(hb, cb, gb, sb, self._qs)
        t1 = time.monotonic_ns()
        return self._timed_fetch(out, t0, t1, phases)

    def _timed_fetch(self, out, t0, t1, phases):
        """Fetch flush outputs with the device.dispatch/exec/fetch (or
        combined `device`) phase stamps — shared by the full and
        incremental dispatch paths."""
        if self.cfg.flush_fetch == "sync":
            jax.block_until_ready(out)
            t2 = time.monotonic_ns()
            host = self._fetch_flush(out)
            t3 = time.monotonic_ns()
            phases.append(("device.dispatch", t0, t1))
            phases.append(("device.exec", t1, t2))
            phases.append(("device.fetch", t2, t3))
        else:
            host = self._fetch_flush(out)
            t3 = time.monotonic_ns()
            phases.append(("device.dispatch", t0, t1))
            phases.append(("device", t1, t3))
        return host

    def _flush_baseline_rows(self) -> dict:
        """Per-output-key baseline row of an EMPTY flush — what every
        cold pile materializes to. Computed ONCE per (engine pair,
        flush config) on a 1-slot fresh bank set through the same
        program body + fetch post-processing as the serving path
        (slot-count-independent: fresh rows are identical), shared
        process-wide via the module cache. The incremental flush
        scatters dirty-row outputs over these rows; bit-identity to
        the full program holds because the flush body maps a fresh
        bank row to exactly this row (pinned by the oracle suite)."""
        if self._flush_baseline is None:
            self._flush_baseline = _flush_baseline_cached(
                self._device, self._heng, self._seng, self._fwd_out,
                tuple(self._agg_emit),
                self._device.platform in ("tpu", "axon"),
                self.cfg.flush_fetch_f16,
                tuple(float(q) for q in self._qs),
                kernel_arm=self._kernel_arms["histogram"])
        return self._flush_baseline

    def _flush_device_incremental(self, snap, phases, dirty):
        """The incremental dirty-slot flush (ISSUE 11 tentpole):
        gather only touched piles into a compact [D, ·] work set, run
        the shared flush body over that slice, and scatter the compact
        outputs over the cached empty-bank baseline on host — cold
        piles keep their prior (fresh-init) compressed state and
        materialized rows verbatim. Returns None to fall back to the
        full program when the histogram bank's dirty fraction exceeds
        flush_incremental_threshold (a near-full gather costs more
        than it saves). Phase stamps: `gather` (host dirty-index
        extraction + padding), the usual device phases over the
        compact program, `scatter` (host baseline overlay)."""
        t0 = time.monotonic_ns()
        ids = [np.nonzero(d)[0].astype(np.int32) for d in dirty]
        if ids[0].size > (self.cfg.flush_incremental_threshold
                          * dirty[0].size):
            return None
        base = self._flush_baseline_rows()
        self._last_flush_info = {
            "path": "incremental",
            "dirty": [int(i.size) for i in ids],
            "piles": [int(d.size) for d in dirty],
        }
        if all(i.size == 0 for i in ids):
            # an idle interval: every output IS the baseline — no
            # device dispatch at all
            host = self._scatter_host({}, ids, dirty, base)
            t1 = time.monotonic_ns()
            if phases is not None:
                phases.append(("gather", t0, t1))
            return host
        hb, cb, gb, sb = snap
        idx = [pad_dirty_ids(i, d.size) for d, i in zip(dirty, ids)]
        self._last_flush_info["buckets"] = [len(p) for p in idx]
        exec_ = _inc_flush_executable(
            self._device, self._heng, self._seng, self._fwd_out,
            tuple(self._agg_emit),
            self._device.platform in ("tpu", "axon"),
            compact=self.cfg.flush_fetch_f16,
            kernel_arm=self._kernel_arms["histogram"])
        t1 = time.monotonic_ns()
        if phases is not None:
            phases.append(("gather", t0, t1))
        t2 = time.monotonic_ns()
        out = exec_(hb, cb, gb, sb, self._qs, *idx)
        t3 = time.monotonic_ns()
        if phases is not None:
            host_c = self._timed_fetch(out, t2, t3, phases)
        else:
            host_c = self._fetch_flush(out)
        t4 = time.monotonic_ns()
        host = self._scatter_host(host_c, ids, dirty, base)
        t5 = time.monotonic_ns()
        if phases is not None:
            phases.append(("scatter", t4, t5))
        return host

    def _scatter_host(self, host_c, ids, dirty, base) -> dict:
        """Rebuild the full-[K] flush-host contract from a compact
        [D, ·] fetch: each per-slot output starts as its baseline row
        broadcast over the bank and the dirty rows overlay it — the
        assembly code downstream is one implementation for both
        paths. Non-per-slot keys (the compact-mode sentinel scalars)
        pass through."""
        out = {}
        for k, row in base.items():
            kind = _out_bank_kind(k)
            K = dirty[kind].size
            v = host_c.get(k)
            full = np.empty((K,) + row.shape, row.dtype)
            full[...] = row
            n = ids[kind].size
            if v is not None and n:
                full[ids[kind]] = np.asarray(v)[:n]
            out[k] = full
        for k, v in host_c.items():
            if k not in out:
                out[k] = np.asarray(v)
        return out

    def _fetch_flush(self, out):
        """device_get under the configured flush_fetch mode (shared with
        the mesh engine's _flush_device)."""
        host = fetch_flush_outputs(out, self.cfg.flush_fetch,
                                   self._stage_exec)
        host = decompact_flush_host(host, tuple(self._agg_emit))
        # host half of the set estimate (ULL's ML solve; identity for
        # engines whose device program emits the finished estimate)
        if "s_est" in host or "s_counts" in host:
            self._seng.estimate_finalize(host)
        return host

    def _flush_bookkeeping(self, full_export: bool = False) -> tuple:
        """Under the lock, at the tick boundary: snapshot the active
        key sets and per-interval counters, reset them, and advance
        the interner intervals — shared by both flush orderings.

        `full_export` (a FULL-kind forward build, ISSUE 13)
        additionally snapshots the counter/set interners' COMPLETE
        tables: the resync ships idle keys' zero/empty rows to refresh
        the receiving tier's series liveness. Snapshotted here, under
        the same lock hold as the active sets, so the full export and
        the bank snapshot describe the same instant."""
        active = {
            "histo": self.histo_keys.active_items(),
            "counter": self.counter_keys.active_items(),
            "gauge": self.gauge_keys.active_items(),
            "set": self.set_keys.active_items(),
        }
        if full_export:
            active["counter_all"] = self.counter_keys.all_items()
            active["set_all"] = self.set_keys.all_items()
        status, self._status = self._status, {}
        stats_samples = self.samples_processed
        self.samples_processed = 0
        dropped = 0
        for ki in (self.histo_keys, self.counter_keys,
                   self.gauge_keys, self.set_keys):
            dropped += ki.dropped_no_slot
            ki.dropped_no_slot = 0  # per-interval, like `samples`
        histo_key_count = len(self.histo_keys)
        for ki in (self.histo_keys, self.counter_keys,
                   self.gauge_keys, self.set_keys):
            ki.advance_interval()
        return active, status, stats_samples, dropped, histo_key_count

    def _land_retired(self, snap, dirty, stages, imports,
                      gauge_seq) -> tuple:
        """Outside the lock (double-buffered flush): drain the retired
        interval's stage buffers and land its staged imports into the
        retired bank snapshot — the same work the legacy ordering does
        under the lock, in the same order (stages first, then staged
        imports), so both orderings produce bit-identical banks. Marks
        go to the RETIRED bitmap: they belong to this flush's dirty
        set, not the new banks' checkpoint bitmap. Safe lock-free: the
        retired banks, stages, and import lists are no longer
        reachable from the ingest path, and the shared ingest
        executables are thread-safe to dispatch."""
        hb, cb, gb, sb = snap
        a = stages.get("histo")
        if a is not None:
            hb = self._land_histos(hb, dirty, a["slots"], a["values"],
                                   a["weights"])
        a = stages.get("counter")
        if a is not None:
            cb = self._land_counters(cb, dirty, a["slots"], a["values"],
                                     a["weights"])
        a = stages.get("gauge")
        if a is not None:
            gb = self._land_gauges(gb, dirty, a["slots"], a["values"],
                                   a["seqs"])
        a = stages.get("set")
        if a is not None:
            sb = self._land_sets(sb, dirty, a["slots"], a["reg_idx"],
                                 a["rho"])
        centroids, sets, counters, gauges = imports
        hb = self._land_import_centroids(hb, centroids, dirty)
        sb = self._land_import_sets(sb, sets, dirty)
        cb, gb, _seq = self._land_import_scalars(
            cb, gb, counters, gauges, dirty, gauge_seq)
        return hb, cb, gb, sb

    def flush(self, timestamp: int | None = None,
              forward_kind: str = "full") -> FlushResult:
        """The Server.Flush equivalent: snapshot banks, run the merge
        program, assemble InterMetrics + forward exports, reset state.

        `forward_kind` (ISSUE 13): "delta" asks the export build to
        consume the retired dirty-slot bitmap — the THIRD consumer,
        after the incremental flush and the delta checkpoints, under
        the same retire discipline — and ship only touched counter/set
        rows (histograms and gauges are touched-only either way, see
        ForwardExport.kind). Honored only when the bitmap exists
        (dirty tracking armed, not the mesh engine) and this engine
        forwards; the result's export.kind records what was actually
        built, so the forwarder stamps the envelope truthfully. The
        locally-flushed frame is NEVER delta-filtered — only the
        forward path is byte-bound.

        Double-buffered (the default): the lock is held ONLY across
        the retire-and-swap — stage buffers, staged imports, banks and
        dirty bitmaps swap against fresh shadows in one rebind
        (`engine.swap` phase) — and ingest proceeds into the shadow
        bank immediately; draining the retired stages, landing the
        retired imports, the merge program, and host assembly all run
        on the retired snapshot outside the lock. Legacy ordering
        (flush_double_buffer off, and always on the mesh engine):
        drain+land under the lock before the swap, as before."""
        ts = int(timestamp if timestamp is not None else time.time())
        cfg = self.cfg
        full_export = self._fwd_out and forward_kind != "delta"
        t_start = time.monotonic_ns()
        if self._use_double_buffer:
            with self.lock:
                stages = {}
                for name, st in (("histo", self._histo_stage),
                                 ("counter", self._counter_stage),
                                 ("gauge", self._gauge_stage),
                                 ("set", self._set_stage)):
                    if st.n:
                        stages[name] = st.drain()
                imports = (self._import_centroids, self._import_sets,
                           self._import_counter_acc,
                           self._import_gauge_acc)
                self._import_centroids = []
                self._import_centroid_total = 0
                self._import_sets = []
                self._import_counter_acc = {}
                self._import_gauge_acc = {}
                retired_seq = self._gauge_seq
                self._gauge_seq = 0
                snap = self._swap_banks()
                dirty = self._retire_dirty()
                # the applied-op watermark AT THE SWAP: per-queue
                # application is FIFO, so every op <= this id is in the
                # retiring snapshot and every later one in the shadow
                # banks — the per-interval replay cut the time-travel
                # history tier records (ISSUE 14)
                retired_wm = self.last_import_op
                (active, status, stats_samples, dropped,
                 histo_key_count) = self._flush_bookkeeping(full_export)
            t_swap = time.monotonic_ns()
            # flight-recorder stamps: (name, t0_ns, t1_ns) on the
            # shared monotonic_ns clock, returned in stats["phases"]
            # so the server grafts them into the tick's phase tree
            phases = [("swap", t_start, t_swap)]
            snap = self._land_retired(snap, dirty, stages, imports,
                                      retired_seq)
            t_drain = time.monotonic_ns()
            phases.append(("drain", t_swap, t_drain))
        else:
            with self.lock:
                self.drain_all()
                self._flush_import_centroids()
                self._flush_import_sets()
                self._flush_import_scalars()
                snap = self._swap_banks()
                dirty = self._retire_dirty()
                self._gauge_seq = 0
                retired_wm = self.last_import_op
                (active, status, stats_samples, dropped,
                 histo_key_count) = self._flush_bookkeeping(full_export)
            t_swap = time.monotonic_ns()
            phases = [("drain", t_start, t_swap)]

        fwd_out = self._fwd_out
        host = self._flush_device(snap, phases=phases, dirty=dirty)
        t_device = time.monotonic_ns()

        # Delta export build (ISSUE 13): honor the request only when
        # the retired bitmap exists — it travels with exactly the bank
        # snapshot this assembly reads, so "dirty" and "this
        # interval's rows" can never skew.
        want_delta = (forward_kind == "delta" and fwd_out
                      and dirty is not None)
        frame = MetricFrame(ts, cfg.hostname)
        export = ForwardExport(set_engine=self._seng.id,
                               kind="delta" if want_delta else "full")

        # ---- histograms: vectorized gathers over the active set ----
        infos = active["histo"]
        if infos:
            # Aggregate matrix in f64 with the 2Sum lo terms folded back
            # in — count/sum are exact past 2^24 here, unlike any f32.
            qmat = np.asarray(host["q"], np.float64)
            if self._agg_emit:
                aggmat = np.asarray(host["aggcols"]).astype(np.float64)
                ci = self._agg_idx.get("count")
                if ci is not None:
                    aggmat[:, ci] += np.asarray(host["lo_count"],
                                                np.float64)
                si = self._agg_idx.get("sum")
                if si is not None:
                    aggmat[:, si] += np.asarray(host["lo_sum"],
                                                np.float64)
            else:
                aggmat = np.zeros((qmat.shape[0], 0), np.float64)
            ci = self._agg_idx.get("count")
            live_cnt = (aggmat[:, ci] if ci is not None
                        else np.asarray(host["cnt"], np.float64))
            n = len(infos)
            slots = np.fromiter((t[1] for t in infos), np.int64, n)
            scopes = np.fromiter((t[2] for t in infos), np.int64, n)
            live = live_cnt[slots] > 0
            if fwd_out:
                h_sum = (np.asarray(host["h_sum"], np.float64)
                         + np.asarray(host["h_sum_lo"], np.float64))
                h_count = (np.asarray(host["h_count"], np.float64)
                           + np.asarray(host["h_count_lo"], np.float64))
                h_recip = (np.asarray(host["h_recip"], np.float64)
                           + np.asarray(host["h_recip_lo"], np.float64))
                exp_m = live & (scopes != LOCAL_ONLY)
                full_m = live & (scopes == LOCAL_ONLY)
                aggonly_m = exp_m & (scopes != GLOBAL_ONLY)
                for i in np.nonzero(exp_m)[0].tolist():
                    key, slot = infos[i][0], infos[i][1]
                    w = host["h_weight"][slot]
                    nz = w > 0
                    export.histograms.append((
                        key, host["h_mean"][slot][nz], w[nz],
                        float(host["h_min"][slot]),
                        float(host["h_max"][slot]),
                        float(h_sum[slot]),
                        float(h_count[slot]),
                        float(h_recip[slot])))
            else:
                full_m = live
                aggonly_m = None

            idx = np.nonzero(full_m)[0].tolist()
            if idx:
                pres = [self._histo_pres_of(infos[i]) for i in idx]
                frame.add_block(
                    [p[0] for p in pres], [p[2] for p in pres],
                    np.concatenate(
                        [qmat[slots[idx]], aggmat[slots[idx]]], axis=1),
                    self._histo_full_types)
            if aggonly_m is not None and self._agg_emit:
                idx = np.nonzero(aggonly_m)[0].tolist()
                if idx:
                    pres = [self._histo_pres_of(infos[i]) for i in idx]
                    frame.add_block(
                        [p[1] for p in pres], [p[2] for p in pres],
                        aggmat[slots[idx]], self._histo_agg_types)

        # ---- counters ----
        infos = active["counter"]
        all_infos = active.get("counter_all")
        c_tot = None
        if infos or (fwd_out and all_infos):
            c_tot = (np.asarray(host["c_hi"], np.float64)
                     + np.asarray(host["c_lo"], np.float64))
        if infos:
            n = len(infos)
            slots = np.fromiter((t[1] for t in infos), np.int64, n)
            totals = c_tot[slots]
            keep = range(n)
            if fwd_out:
                scopes = np.fromiter((t[2] for t in infos), np.int64, n)
                gm = scopes == GLOBAL_ONLY
                if want_delta:
                    # DELTA wire: only counters the dirty bitmap saw
                    # land this interval. `keep` (the local frame)
                    # stays scope-driven — delta filters the WIRE,
                    # never re-scopes a key into the local flush.
                    em = gm & dirty[1][slots]
                elif all_infos is not None:
                    em = None   # FULL: exported from the whole table
                else:
                    em = gm     # no full table (mesh): touched set
                if em is not None:
                    for i in np.nonzero(em)[0].tolist():
                        export.counters.append(
                            (infos[i][0], float(totals[i])))
                keep = np.nonzero(~gm)[0].tolist()
            keep = list(keep)
            if keep:
                frame.add_block(
                    [infos[i][0].name for i in keep],
                    [self._scalar_tags_of(infos[i]) for i in keep],
                    totals[keep], (MetricType.COUNTER,))
        if fwd_out and not want_delta and all_infos:
            # FULL resync: every interned global-only counter ships,
            # idle zeros included — the receiver-liveness refresh a
            # steady-state delta deliberately skips. Wire only; the
            # local frame above stays touched-keys-only.
            for key, slot, scope, _h in all_infos:
                if scope == GLOBAL_ONLY:
                    export.counters.append((key, float(c_tot[slot])))

        # ---- gauges ----
        infos = active["gauge"]
        if infos:
            n = len(infos)
            slots = np.fromiter((t[1] for t in infos), np.int64, n)
            live = np.asarray(host["g_seq"])[slots] >= 0
            vals = np.asarray(host["g_value"], np.float64)[slots]
            if fwd_out:
                scopes = np.fromiter((t[2] for t in infos), np.int64, n)
                gm = live & (scopes == GLOBAL_ONLY)
                for i in np.nonzero(gm)[0].tolist():
                    export.gauges.append((infos[i][0], float(vals[i])))
                keep = np.nonzero(live & ~gm)[0].tolist()
            else:
                keep = np.nonzero(live)[0].tolist()
            if keep:
                frame.add_block(
                    [infos[i][0].name for i in keep],
                    [self._scalar_tags_of(infos[i]) for i in keep],
                    vals[keep], (MetricType.GAUGE,))

        # ---- sets ----
        infos = active["set"]
        all_infos = active.get("set_all")
        if infos:
            n = len(infos)
            slots = np.fromiter((t[1] for t in infos), np.int64, n)
            ests = np.asarray(host["s_est"], np.float64)[slots]
            keep = range(n)
            if fwd_out:
                scopes = np.fromiter((t[2] for t in infos), np.int64, n)
                fm = scopes != LOCAL_ONLY
                if want_delta:
                    # untouched set slots hold all-zero registers —
                    # the single biggest idle-key wire cost (a full
                    # register bank per key per interval); a delta
                    # ships only touched ones. Local frame unchanged.
                    em = fm & dirty[3][slots]
                elif all_infos is not None:
                    em = None   # FULL: exported from the whole table
                else:
                    em = fm
                if em is not None:
                    for i in np.nonzero(em)[0].tolist():
                        export.sets.append(
                            (infos[i][0], host["s_regs"][infos[i][1]]))
                keep = np.nonzero(~fm)[0].tolist()
            keep = list(keep)
            if keep:
                frame.add_block(
                    [infos[i][0].name for i in keep],
                    [self._scalar_tags_of(infos[i]) for i in keep],
                    ests[keep], (MetricType.GAUGE,))
        if fwd_out and not want_delta and all_infos:
            # FULL resync: every interned non-local set ships its
            # registers (idle = all-zero banks, a merge no-op that
            # keeps the key alive at the receiver)
            for key, slot, scope, _h in all_infos:
                if scope != LOCAL_ONLY:
                    export.sets.append((key, host["s_regs"][slot]))

        # ---- status checks (StatusCheck sampler flush shape) ----
        status_metrics = [
            InterMetric(
                name=sc.name,
                timestamp=int(sc.timestamp or ts),
                value=float(sc.status),
                tags=list(sc.tags),
                type=MetricType.STATUS,
                message=sc.message,
                hostname=sc.hostname or cfg.hostname)
            for sc in status.values()]

        t_end = time.monotonic_ns()
        phases.append(("materialize", t_device, t_end))
        stats = {
            "samples": stats_samples,
            "histo_keys": histo_key_count,
            "dropped_no_slot": dropped,
            # Flush phase durations (veneur's flush.*_duration_ns
            # self-metrics; flusher.go sym: Server.Flush spans).
            # swap_ns is the LOCK-HELD window: under double buffering
            # that is the retire-and-swap only; merge_ns then includes
            # the out-of-lock retired drain + the device program.
            "swap_ns": t_swap - t_start,
            "merge_ns": t_device - t_swap,
            "assembly_ns": t_end - t_device,
            "phases": phases,
            # which device path ran (full vs incremental + dirty/pile
            # counts) — bench/test introspection, also what an
            # operator correlates the gather/scatter phases against
            "flush_path": dict(self._last_flush_info),
            # what the export build actually shipped (delta requests
            # degrade to full when no bitmap exists — mesh, tracking
            # off — or the engine does not forward)
            "forward_kind": export.kind,
            # the swap-time applied-op watermark (the history tier's
            # per-interval replay cut, ISSUE 14)
            "retired_import_op": retired_wm,
        }
        return FlushResult(frame=frame, export=export, stats=stats,
                           status_metrics=status_metrics)

    # ---- presentation caches (names/tags reused across flushes) ----
    # Cached on the interner's per-key SlotInfo holder: a plain attribute
    # read per key instead of a MetricKey hash, and the cache dies with
    # the entry on eviction. The joined-tags split is additionally shared
    # across keys (many keys carry identical tag sets).

    def _tags_of(self, joined: str) -> list:
        tl = self._tags_cache.get(joined)
        if tl is None:
            if len(self._tags_cache) > self._pres_bound:
                self._tags_cache.clear()
            tl = joined.split(",") if joined else []
            self._tags_cache[joined] = tl
        return tl

    def _scalar_tags_of(self, info) -> list:
        holder = info[3]
        tl = holder.pres
        if tl is None:
            tl = holder.pres = self._tags_of(info[0].joined_tags)
        return tl

    def _histo_pres_of(self, info) -> tuple:
        holder = info[3]
        pr = holder.pres
        if pr is None:
            key = info[0]
            nm = key.name
            full = tuple([nm + s for s in self._pct_sufs]
                         + [f"{nm}.{a}" for a in self._agg_emit])
            pr = holder.pres = (full, full[len(self._pct_sufs):],
                                self._tags_of(key.joined_tags))
        return pr

    def drain_events(self):
        with self.lock:
            evs, self._pending_events = self._pending_events, []
        return evs, []

    # ------------- engine checkpoint/restore (durability, ISSUE 9) ----
    # Serialization stays single-homed in durability/records.py (vlint
    # DR02): these methods move numpy arrays, never raw bytes.

    def _bank_table(self):
        """(kind, bank attr name, interner) rows in the fixed record
        order durability/records.py's BANK_* constants name."""
        return ((0, "histo_bank", self.histo_keys),
                (1, "counter_bank", self.counter_keys),
                (2, "gauge_bank", self.gauge_keys),
                (3, "set_bank", self.set_keys))

    @property
    def engine_stamp(self) -> str:
        """The wire stamp of this engine's sketch pair — what the
        forwarders send and the import paths compare against."""
        return sketches.engine_stamp(self._heng, self._seng)

    def engines_describe(self) -> dict:
        """JSON-ready sketch-engine description (/debug/flush),
        including which kernel arm (fused/xla/interpret) each engine's
        executables were built with (ISSUE 15 satellite) — bench rows
        and operator triage read the arm here instead of guessing from
        the platform, and the process-wide fallback count sits next to
        it so a probe-refused backend is visible."""
        d = sketches.describe(self._heng, self._seng)
        arms = getattr(self, "_kernel_arms", None) \
            or {"histogram": "xla", "set": "xla"}
        d["kernels"] = {
            "requested": getattr(self.cfg, "fused_kernels", "auto"),
            "histogram_arm": arms["histogram"],
            "set_arm": arms["set"],
            "fallback_total": kernels.fallback_total(),
        }
        return d

    def bank_leaf_names(self, kind: int) -> tuple:
        """The durability leaf order for one bank kind — engine-aware
        (the histogram and set banks' leaves are the selected engine's;
        counter/gauge leaves are engine-independent)."""
        if kind == 0:
            return self._heng.bank_leaves
        if kind == 3:
            return self._seng.bank_leaves
        from ..durability import records as drecords
        return drecords.BANK_LEAVES[kind]

    def enable_dirty_tracking(self, delta_threshold: float = 0.5):
        """Arm per-bank dirty-slot bitmaps for the CHECKPOINT consumer
        (the Server calls this when durability_engine_snapshot is on).
        The incremental flush arms the same bitmaps in __init__ by
        default; existing marks are preserved — rebuilding them here
        would desync both consumers from rows already landed.
        `delta_threshold` is the dirty fraction above which
        checkpoint_state fetches whole leaves and slices on host
        instead of a device-side row gather (a near-full gather costs
        more than the contiguous fetch)."""
        with self.lock:
            self._delta_threshold = float(delta_threshold)
            if self._dirty is None:
                self._dirty = [
                    np.zeros(getattr(self, attr).num_slots, bool)
                    for _kind, attr, _ki in self._bank_table()]

    def _mark_dirty(self, kind: int, slots):
        """Record device-landing touches on the LIVE bitmap. Call
        sites guard on self._dirty so the untracked case costs one
        attribute load."""
        self._mark_dirty_into(self._dirty, kind, slots)

    @staticmethod
    def _mark_dirty_into(dirty, kind: int, slots):
        """Record device-landing touches on an explicit bitmap set —
        the live one, or a retired interval's (the double-buffered
        flush lands retired stages/imports AFTER the swap; their
        touches belong to the retiring flush's dirty set, never the
        new banks' checkpoint bitmap)."""
        d = dirty[kind]
        s = np.asarray(slots)
        if s.size:
            d[s[(s >= 0) & (s < d.size)]] = True

    def checkpoint_state(self) -> dict:
        """One engine's flush-boundary checkpoint, taken under the
        ingest lock so it is a consistent cut: dirty bank rows (banks
        are interval-scoped, so fresh init + these rows IS the state),
        the full interner tables, the staged-but-unlanded import
        accumulators, the gauge sequence, and the applied-op watermark
        — everything restore_checkpoint needs, as numpy arrays (the
        byte encoding lives in durability/records.py)."""
        from ..durability import records as drecords
        with self.lock:
            banks: dict = {}
            piles_total = piles_dirty = 0
            for kind, attr, _ki in self._bank_table():
                bank = getattr(self, attr)
                d = self._dirty[kind]
                ids = np.nonzero(d)[0].astype(np.int32)
                piles_total += d.size
                piles_dirty += ids.size
                leaves: dict = {}
                if ids.size:
                    gather = ids.size < self._delta_threshold * d.size
                    for name in self.bank_leaf_names(kind):
                        leaf = getattr(bank, name)
                        if gather:
                            leaves[name] = np.asarray(
                                jax.device_get(leaf[ids]))
                        else:
                            leaves[name] = np.asarray(leaf)[ids]
                banks[kind] = (ids, leaves)
            interner = {
                kind: (ki.interval, ki.snapshot_entries())
                for kind, _attr, ki in self._bank_table()}
            staged = {
                "centroids": list(self._import_centroids),
                "sets": list(self._import_sets),
                "counters": list(self._import_counter_acc.items()),
                "gauges": list(self._import_gauge_acc.items()),
            }
            return {
                "fingerprint": drecords.engine_fingerprint(
                    self.cfg, self.histo_bank.num_centroids),
                "gauge_seq": self._gauge_seq,
                "last_import_op": self.last_import_op,
                "interner": interner,
                "banks": banks,
                "staged": staged,
                "leaf_names": {
                    kind: self.bank_leaf_names(kind)
                    for kind, _attr, _ki in self._bank_table()},
                "piles_total": piles_total,
                "piles_dirty": piles_dirty,
            }

    def restore_checkpoint(self, fingerprint, gauge_seq: int,
                           watermark: int, interner: dict, banks: dict,
                           staged: dict):
        """Rebuild this (freshly constructed) engine from a decoded
        checkpoint group: leaves are composed on host from the exact
        fresh-init baseline plus the journaled rows, then committed to
        the device in one device_put per leaf. Raises ValueError on a
        shape-fingerprint mismatch — the Server refuses the whole
        recovery loudly rather than scattering rows into wrong slots."""
        from ..durability import records as drecords
        want = drecords.engine_fingerprint(self.cfg,
                                           self.histo_bank.num_centroids)
        if tuple(fingerprint) != want:
            raise ValueError(
                f"engine checkpoint fingerprint {tuple(fingerprint)} "
                f"does not match this engine's shape {want}")
        with self.lock:
            new_banks = {}
            for kind, attr, _ki in self._bank_table():
                bank = getattr(self, attr)
                ids, leaves = banks.get(kind, (np.zeros(0, np.int32), {}))
                if len(ids) == 0:
                    new_banks[attr] = bank     # fresh rows, already right
                    continue
                host = {}
                for name in self.bank_leaf_names(kind):
                    # fetch the fresh-init baseline (exact: vmin=+inf
                    # rows etc. come from the same _fresh_fn output the
                    # live process swapped in), overlay the rows
                    full = np.array(np.asarray(getattr(bank, name)))
                    full[ids] = leaves[name]
                    host[name] = jax.device_put(full, self._device)
                new_banks[attr] = type(bank)(**host)
            # SR02 invariant note: the histo rows restored above are
            # bit-exact copies of rows an invariant-holding compress
            # wrote before the checkpoint — restore preserves whatever
            # cluster order the owning kernel produced
            self.histo_bank = new_banks["histo_bank"]
            self.counter_bank = new_banks["counter_bank"]
            self.gauge_bank = new_banks["gauge_bank"]
            self.set_bank = new_banks["set_bank"]
            for kind, _attr, ki in self._bank_table():
                interval, entries = interner.get(kind, (0, []))
                ki.restore(interval, entries)
                # restored rows deviate from fresh: the next checkpoint
                # must serialize them again
                ids, _leaves = banks.get(kind,
                                         (np.zeros(0, np.int32), {}))
                if self._dirty is not None and len(ids):
                    self._dirty[kind][ids] = True
            self._import_centroids = [
                (int(s), np.asarray(m, np.float32),
                 np.asarray(w, np.float32), float(a), float(b),
                 float(c), float(d), float(e))
                for s, m, w, a, b, c, d, e in staged.get("centroids", [])]
            self._import_centroid_total = sum(
                len(m) for _s, m, *_rest in self._import_centroids)
            self._import_sets = [(int(s), np.asarray(r, np.uint8))
                                 for s, r in staged.get("sets", [])]
            self._import_counter_acc = {
                int(s): float(v) for s, v in staged.get("counters", [])}
            self._import_gauge_acc = {
                int(s): float(v) for s, v in staged.get("gauges", [])}
            self._gauge_seq = int(gauge_seq)
            self.last_import_op = int(watermark)

    def dirty_stats(self) -> tuple:
        """(dirty piles, total piles) across the four banks — the
        veneur.durability.engine_snapshot_piles_* gauges."""
        if self._dirty is None:
            return (0, 0)
        with self.lock:
            return (sum(int(d.sum()) for d in self._dirty),
                    sum(d.size for d in self._dirty))
