"""AWS X-Ray span sink: SSF spans → X-Ray daemon UDP segments.

Parity: sinks/xray/xray.go (sym: XRaySpanSink — each span becomes one
JSON "segment" datagram sent to the local X-Ray daemon, prefixed with
the daemon's `{"format": "json", "version": 1}` header line; trace ids
are rendered in X-Ray's `1-<epoch hex8>-<hex24>` form).
"""

from __future__ import annotations

import json
import logging
import socket

from . import SpanSink

log = logging.getLogger("veneur_tpu.sinks.xray")

_HEADER = b'{"format": "json", "version": 1}\n'


def xray_trace_id(trace_id: int, start_ns: int) -> str:
    """X-Ray trace id: version-epoch-96bit, derived deterministically
    from the SSF trace id so all spans of a trace land together."""
    epoch = (start_ns // 1_000_000_000) & 0xFFFFFFFF
    return f"1-{epoch:08x}-{trace_id & ((1 << 96) - 1):024x}"


def span_to_segment(span) -> dict:
    seg = {
        "name": (span.service or "unknown")[:200],
        "id": f"{span.id & ((1 << 64) - 1):016x}",
        "trace_id": xray_trace_id(span.trace_id, span.start_timestamp),
        "start_time": span.start_timestamp / 1e9,
        "end_time": span.end_timestamp / 1e9,
        "error": bool(span.error),
        "annotations": {k: v for k, v in span.tags.items()},
    }
    if span.parent_id:
        seg["parent_id"] = f"{span.parent_id & ((1 << 64) - 1):016x}"
        seg["type"] = "subsegment"
    if span.name:
        seg["annotations"]["span_name"] = span.name
    return seg


class XRaySpanSink(SpanSink):
    """Deliberately NOT behind an Egress: ingest() runs once per span
    on the span-worker hot path, and a UDP sendto to the local daemon
    is fire-and-forget — a dropped datagram is the protocol's loss
    model and retrying a connectionless send has nothing to wait for.
    Failures still surface per destination in veneur.resilience.*
    (error path only, no per-span locking)."""

    def __init__(self, daemon_address: str = "127.0.0.1:2000",
                 registry=None):
        from ..resilience import DEFAULT_REGISTRY
        host, _, port = daemon_address.rpartition(":")
        host = host.strip("[]") or "127.0.0.1"
        self._dest = (host, int(port))
        self._dest_name = f"xray://{daemon_address}"
        self._registry = registry or DEFAULT_REGISTRY
        family = socket.AF_INET6 if ":" in host else socket.AF_INET
        self._sock = socket.socket(family, socket.SOCK_DGRAM)
        self.sent_total = 0
        self.dropped_total = 0

    def name(self) -> str:
        return "xray"

    def ingest(self, span) -> None:
        payload = _HEADER + json.dumps(span_to_segment(span)).encode()
        try:
            self._sock.sendto(payload, self._dest)
            self.sent_total += 1
        except OSError as e:
            self.dropped_total += 1
            self._registry.incr(self._dest_name, "failures")
            log.debug("xray send failed: %s", e)

    def stop(self) -> None:
        self._sock.close()
