"""New Relic sink: flushed metrics → Metric API, events → Event API.

Parity: sinks/newrelic/ (sym: NewRelicMetricSink — converts InterMetrics
to New Relic's metric payloads via the telemetry SDK, and DogStatsD
events to custom events on the account event stream). The vendor SDK is
replaced with the two public JSON ingest surfaces it wraps:
  * POST {metric_url}/metric/v1 — [{"metrics": [{name, type, value,
    timestamp, attributes}]}] with an Api-Key header; counters carry
    interval.ms like the SDK's count type.
  * POST {event_url}/v1/accounts/{id}/events — custom "VeneurEvent"
    records.
Tests point both URLs at a loopback http.server.
"""

from __future__ import annotations

import json
import logging
import urllib.request

from ..metrics import InterMetric, MetricType
from . import MetricSink

log = logging.getLogger("veneur_tpu.sinks.newrelic")


def _attrs(m: InterMetric, common_tags: list[str]) -> dict:
    out = {}
    for t in list(common_tags) + list(m.tags):
        k, _, v = t.partition(":")
        out[k] = v
    if m.hostname:
        out["hostname"] = m.hostname
    return out


class NewRelicMetricSink(MetricSink):
    def __init__(self, insert_key: str, account_id: int = 0,
                 metric_url: str = "https://metric-api.newrelic.com",
                 event_url: str = "https://insights-collector.newrelic.com",
                 tags: list[str] | None = None, interval_s: float = 10.0,
                 timeout_s: float = 10.0, egress=None,
                 egress_policy=None):
        from ..resilience import Egress
        self.insert_key = insert_key
        self.account_id = account_id
        self.metric_url = metric_url.rstrip("/") + "/metric/v1"
        self.event_url = (event_url.rstrip("/")
                          + f"/v1/accounts/{account_id}/events")
        self.tags = tags or []
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self._egress = egress or Egress("newrelic",
                                        policy=egress_policy)
        self.flushed_total = 0

    def name(self) -> str:
        return "newrelic"

    def _metric(self, m: InterMetric) -> dict:
        rec = {
            "name": m.name,
            "value": m.value,
            "timestamp": m.timestamp,
            "attributes": _attrs(m, self.tags),
        }
        if m.type == MetricType.COUNTER:
            rec["type"] = "count"
            rec["interval.ms"] = max(1, int(self.interval_s * 1000))
        else:
            rec["type"] = "gauge"
        return rec

    def _post(self, url: str, payload) -> bool:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "Api-Key": self.insert_key})
        try:
            self._egress.post(req, timeout_s=self.timeout_s)
            return True
        except Exception as e:
            log.error("newrelic post to %s failed: %s", url, e)
            return False

    def flush(self, metrics):
        metrics = [m for m in metrics
                   if m.type != MetricType.STATUS]  # datadog-shaped
        if not metrics:
            return
        payload = [{"metrics": [self._metric(m) for m in metrics]}]
        if self._post(self.metric_url, payload):
            self.flushed_total += len(metrics)

    def flush_other(self, events, checks):
        if not self.account_id:
            # the event API is per-account; without an id the POST can
            # only 4xx every interval
            if events or checks:
                log.warning("newrelic: dropping %d events/checks — "
                            "newrelic_account_id is not configured",
                            len(events) + len(checks))
            return
        records = [{
            "eventType": "VeneurEvent",
            "title": e.title, "text": e.text,
            "timestamp": e.timestamp or 0,
            "alertType": e.alert_type,
            "aggregationKey": e.aggregation_key,
        } for e in events]
        records += [{
            "eventType": "VeneurServiceCheck",
            "name": c.name, "status": c.status,
            "timestamp": c.timestamp or 0,
            "message": c.message,
        } for c in checks]
        if records:
            self._post(self.event_url, records)
