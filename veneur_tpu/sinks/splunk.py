"""Splunk span sink: SSF spans → Splunk HTTP Event Collector.

Parity: sinks/splunk/splunk.go (sym: splunkSpanSink — buffers ingested
spans, serialises each as an HEC JSON event `{"time": ..., "host": ...,
"event": {...}}`, POSTs batches to /services/collector/event with an
`Authorization: Splunk <token>` header). Transport is stdlib urllib so
tests can point `hec_address` at a loopback http.server.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request

from . import SpanSink

log = logging.getLogger("veneur_tpu.sinks.splunk")


def span_to_event(span, hostname: str) -> dict:
    """One SSFSpan → one HEC event dict (the serialized shape the
    reference posts per span)."""
    return {
        "time": span.start_timestamp / 1e9,
        "host": hostname,
        "event": {
            "trace_id": f"{span.trace_id:x}",
            "id": f"{span.id:x}",
            "parent_id": f"{span.parent_id:x}",
            "start_timestamp": span.start_timestamp,
            "end_timestamp": span.end_timestamp,
            "duration_ns": max(0, span.end_timestamp
                               - span.start_timestamp),
            "error": bool(span.error),
            "service": span.service,
            "indicator": bool(span.indicator),
            "name": span.name,
            "tags": dict(span.tags),
        },
    }


class SplunkSpanSink(SpanSink):
    def __init__(self, hec_address: str, token: str, hostname: str = "",
                 max_buffer: int = 16384, timeout_s: float = 10.0,
                 egress=None, egress_policy=None):
        from ..resilience import Egress
        self.url = hec_address.rstrip("/") + "/services/collector/event"
        self.token = token
        self.hostname = hostname
        self.max_buffer = max_buffer
        self.timeout_s = timeout_s
        self._egress = egress or Egress("splunk", policy=egress_policy)
        self._buf: list = []
        self._lock = threading.Lock()
        self.flushed_total = 0
        self.dropped_total = 0

    def name(self) -> str:
        return "splunk"

    def ingest(self, span) -> None:
        with self._lock:
            if len(self._buf) >= self.max_buffer:
                self.dropped_total += 1
                return
            self._buf.append(span)

    def flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if not batch:
            return
        # HEC batching: newline-delimited JSON events in one body
        body = "\n".join(
            json.dumps(span_to_event(s, self.hostname)) for s in batch
        ).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "Authorization": f"Splunk {self.token}"})
        try:
            self._egress.post(req, timeout_s=self.timeout_s)
            self.flushed_total += len(batch)
        except Exception as e:
            self.dropped_total += len(batch)
            log.error("splunk HEC flush failed: %s", e)
