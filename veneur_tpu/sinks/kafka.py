"""Kafka sinks: flushed metrics and/or SSF spans → Kafka topics.

Parity: sinks/kafka/kafka.go (sym: KafkaMetricSink — JSON-encoded
InterMetrics to `kafka_metric_topic`, partition-keyed so one series
always lands on one partition; KafkaSpanSink — spans to
`kafka_span_topic` as protobuf or JSON, keyed by trace id).

No Kafka client library ships in this image, so the producer is
injectable: anything callable as `produce(topic, key: bytes,
value: bytes)`. `start()` tries to build one from `kafka-python` if
installed; without a client the sink stays up but drops (counted),
mirroring veneur's treat-egress-as-lossy stance rather than crashing
the pipeline.
"""

from __future__ import annotations

import json
import logging
import threading

from ..metrics import InterMetric, MetricType
from . import MetricSink, SpanSink

log = logging.getLogger("veneur_tpu.sinks.kafka")


def metric_to_payload(m: InterMetric) -> dict:
    """The JSON shape the reference's sarama encoder produces per
    InterMetric."""
    return {
        "name": m.name,
        "timestamp": m.timestamp,
        "value": m.value,
        "tags": list(m.tags),
        "type": m.type.name.lower(),
        "hostname": m.hostname,
    }


def _produce_batch(egress, producer, msgs):
    """Whole-batch produce under the egress treatment (shared by both
    kafka sinks): the batch retries as a unit, so re-produced messages
    are at-least-once — the reference's sarama producer semantics."""
    def _all():
        for topic, key, value in msgs:
            producer(topic, key, value)

    egress.call(_all)


def _default_producer(broker: str):
    """Build a producer from kafka-python if present, else None."""
    try:
        from kafka import KafkaProducer  # type: ignore
    except ImportError:
        return None
    producer = KafkaProducer(bootstrap_servers=broker)

    def produce(topic: str, key: bytes, value: bytes):
        producer.send(topic, key=key, value=value)

    return produce


class KafkaMetricSink(MetricSink):
    def __init__(self, broker: str, metric_topic: str, producer=None,
                 egress=None, egress_policy=None):
        from ..resilience import Egress
        self.broker = broker
        self.metric_topic = metric_topic
        self.producer = producer
        self._egress = egress or Egress(f"kafka://{broker}",
                                        policy=egress_policy)
        self.dropped_total = 0
        self._lock = threading.Lock()

    def name(self) -> str:
        return "kafka"

    def start(self):
        if self.producer is None:
            self.producer = _default_producer(self.broker)
            if self.producer is None:
                log.warning("kafka: no client library available; "
                            "metrics to %s will be dropped (counted)",
                            self.metric_topic)

    def flush(self, metrics):
        if self.producer is None:
            with self._lock:
                self.dropped_total += len(metrics)
            return
        msgs = []
        for m in metrics:
            if m.type == MetricType.STATUS:
                continue  # service checks are Datadog-shaped; skip
            # key by series identity: one series → one partition, so
            # per-series ordering survives (the reference's partition key)
            key = f"{m.name}|{','.join(m.tags)}".encode()
            value = json.dumps(metric_to_payload(m)).encode()
            msgs.append((self.metric_topic, key, value))
        if not msgs:
            return
        try:
            _produce_batch(self._egress, self.producer, msgs)
        except Exception:
            with self._lock:
                self.dropped_total += len(msgs)
            raise


class KafkaSpanSink(SpanSink):
    def __init__(self, broker: str, span_topic: str, producer=None,
                 encoding: str = "protobuf", max_buffer: int = 16384,
                 egress=None, egress_policy=None):
        from ..resilience import Egress
        if encoding not in ("protobuf", "json"):
            raise ValueError(f"bad kafka span encoding {encoding!r}")
        self.broker = broker
        self.span_topic = span_topic
        self.producer = producer
        self.encoding = encoding
        self.max_buffer = max_buffer
        self._egress = egress or Egress(f"kafka://{broker}",
                                        policy=egress_policy)
        self._buf: list = []
        self._lock = threading.Lock()
        self.dropped_total = 0

    def name(self) -> str:
        return "kafka"

    def start(self):
        if self.producer is None:
            self.producer = _default_producer(self.broker)
            if self.producer is None:
                log.warning("kafka: no client library available; spans "
                            "to %s will be dropped (counted)",
                            self.span_topic)

    def ingest(self, span):
        with self._lock:
            if len(self._buf) >= self.max_buffer:
                self.dropped_total += 1
                return
            self._buf.append(span)

    def _encode(self, span) -> bytes:
        if self.encoding == "protobuf":
            return span.SerializeToString()
        return json.dumps({
            "trace_id": span.trace_id, "id": span.id,
            "parent_id": span.parent_id, "name": span.name,
            "service": span.service, "error": bool(span.error),
            "start_timestamp": span.start_timestamp,
            "end_timestamp": span.end_timestamp,
            "tags": dict(span.tags),
        }).encode()

    def flush(self):
        with self._lock:
            spans, self._buf = self._buf, []
        if self.producer is None:
            with self._lock:
                self.dropped_total += len(spans)
            return
        if not spans:
            return
        msgs = [(self.span_topic, str(s.trace_id).encode(),
                 self._encode(s)) for s in spans]
        try:
            _produce_batch(self._egress, self.producer, msgs)
        except Exception as e:
            with self._lock:
                self.dropped_total += len(msgs)
            log.warning("kafka span flush failed (%d dropped): %s",
                        len(msgs), e)
