"""Datadog metric/span sink — the reference's default egress.

Parity: sinks/datadog/datadog.go (sym: DatadogMetricSink.Flush — POST
/api/v1/series with JSON bodies chunked by `flush_max_per_body`;
events + service checks; DatadogSpanSink → APM traces API).

Semantics carried over:
  * counters are emitted as Datadog "rate": value / interval, with the
    interval attached (how the reference reports DogStatsD counters).
  * gauges emit as "gauge"; metric hostname/device overrides via the
    magic `host:` / `device:` tags.
  * chunking: bodies hold at most `flush_max_per_body` series.

Transport is stdlib urllib (zlib-deflated JSON like the reference), so the
sink has no third-party deps; tests point `api_url` at a loopback
http.server.
"""

from __future__ import annotations

import json
import logging
import urllib.request
import zlib

from ..metrics import InterMetric, MetricType
from . import MetricSink

log = logging.getLogger("veneur_tpu.sinks.datadog")


class DatadogMetricSink(MetricSink):
    def __init__(self, api_key: str, api_url: str = "https://app.datadoghq.com",
                 hostname: str = "", tags: list[str] | None = None,
                 interval_s: int = 10, flush_max_per_body: int = 25_000,
                 timeout_s: float = 10.0):
        self.api_key = api_key
        self.api_url = api_url.rstrip("/")
        self.hostname = hostname
        self.tags = tags or []
        self.interval_s = interval_s
        self.flush_max_per_body = flush_max_per_body
        self.timeout_s = timeout_s
        self._tag_memo: dict = {}

    def name(self) -> str:
        return "datadog"

    def _series(self, m: InterMetric) -> dict:
        if m.type == MetricType.COUNTER:
            mtype, value = "rate", m.value / max(self.interval_s, 1)
        else:
            mtype, value = "gauge", m.value
        host, device, tags = self._split_tags(m.tags)
        s = {
            "metric": m.name,
            "points": [[m.timestamp, value]],
            "type": mtype,
            "host": host or m.hostname or self.hostname,
            "tags": tags,
            "interval": self.interval_s,
        }
        if device:
            s["device_name"] = device
        return s

    def _post(self, path: str, body: dict):
        data = zlib.compress(json.dumps(body).encode())
        req = urllib.request.Request(
            f"{self.api_url}{path}?api_key={self.api_key}",
            data=data,
            headers={"Content-Type": "application/json",
                     "Content-Encoding": "deflate"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if resp.status >= 400:
                raise RuntimeError(
                    f"datadog POST {path}: HTTP {resp.status}")

    def flush(self, metrics):
        series = [self._series(m) for m in metrics]
        self._post_series(series)

    def _post_series(self, series):
        for i in range(0, len(series), self.flush_max_per_body):
            self._post("/api/v1/series",
                       {"series": series[i:i + self.flush_max_per_body]})

    def _split_tags(self, tg: list) -> tuple:
        """(host_override, device, merged_tags) for one key's shared tag
        list. Memoized by identity: tag lists are interned per key in the
        engine's presentation cache and shared across flushes, so the
        host:/device: scan runs once per key, not once per metric. The
        memo holds a reference to the list, keeping the id stable."""
        memo = self._tag_memo.get(id(tg))
        if memo is not None and memo[0] is tg:
            return memo[1]
        host, device, tags = "", "", list(self.tags)
        for t in tg:
            if t.startswith("host:"):
                host = t[5:]
            elif t.startswith("device:"):
                device = t[7:]
            else:
                tags.append(t)
        if len(self._tag_memo) > 1_000_000:
            self._tag_memo.clear()
        out = (host, device, tags)
        self._tag_memo[id(tg)] = (tg, out)
        return out

    def flush_frames(self, frames):
        """Frame-native flush: build the series bodies straight from the
        columnar blocks (same wire output as flush(), without
        materializing InterMetric objects)."""
        iv = self.interval_s
        div = max(iv, 1)  # divide (not multiply-by-reciprocal) so the
        # rate values match _series() bit-for-bit
        series = []
        app = series.append
        for fr in frames.frames:
            ts = fr.timestamp
            fr_host = fr.hostname or self.hostname
            for names, tags, values, types in fr.blocks:
                is_rate = [t == MetricType.COUNTER for t in types]
                m = values.shape[1]
                rows = values.tolist()
                for nm, tg, row in zip(names, tags, rows):
                    host, device, dtags = self._split_tags(tg)
                    h = host or fr_host
                    cols = (nm,) if m == 1 and isinstance(nm, str) else nm
                    for j in range(m):
                        s = {
                            "metric": cols[j],
                            "points": [[ts, row[j] / div
                                        if is_rate[j] else row[j]]],
                            "type": "rate" if is_rate[j] else "gauge",
                            "host": h,
                            "tags": dtags,
                            "interval": iv,
                        }
                        if device:
                            s["device_name"] = device
                        app(s)
        name = self.name()
        for x in frames.extra:
            if not x.sinks or name in x.sinks:
                app(self._series(x))
        self._post_series(series)

    def flush_other(self, events, checks):
        for e in events:
            body = {
                "title": e.title, "text": e.text,
                "aggregation_key": e.aggregation_key,
                "priority": e.priority or "normal",
                "source_type_name": e.source_type,
                "alert_type": e.alert_type or "info",
                "tags": e.tags,
            }
            if e.timestamp:
                body["date_happened"] = e.timestamp
            if e.hostname:
                body["host"] = e.hostname
            try:
                self._post("/api/v1/events", body)
            except Exception as ex:  # one bad event must not stop the rest
                log.warning("datadog event post failed: %s", ex)
        for c in checks:
            body = {"check": c.name, "status": c.status,
                    "tags": c.tags, "message": c.message}
            if c.timestamp:
                body["timestamp"] = c.timestamp
            if c.hostname:
                body["host_name"] = c.hostname
            try:
                self._post("/api/v1/check_run", body)
            except Exception as ex:
                log.warning("datadog check post failed: %s", ex)
