"""Datadog metric/span sink — the reference's default egress.

Parity: sinks/datadog/datadog.go (sym: DatadogMetricSink.Flush — POST
/api/v1/series with JSON bodies chunked by `flush_max_per_body`;
events + service checks; DatadogSpanSink → APM traces API).

Semantics carried over:
  * counters are emitted as Datadog "rate": value / interval, with the
    interval attached (how the reference reports DogStatsD counters).
  * gauges emit as "gauge"; metric hostname/device overrides via the
    magic `host:` / `device:` tags.
  * chunking: bodies hold at most `flush_max_per_body` series.

Transport is stdlib urllib (zlib-deflated JSON like the reference), so the
sink has no third-party deps; tests point `api_url` at a loopback
http.server.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
import zlib

from ..metrics import InterMetric, MetricType
from ..resilience import Egress, EgressPolicy, is_retryable
from . import MetricSink, SpanSink

log = logging.getLogger("veneur_tpu.sinks.datadog")


class DatadogMetricSink(MetricSink):
    def __init__(self, api_key: str, api_url: str = "https://app.datadoghq.com",
                 hostname: str = "", tags: list[str] | None = None,
                 interval_s: int = 10, flush_max_per_body: int = 25_000,
                 timeout_s: float = 10.0, egress: Egress | None = None,
                 egress_policy: EgressPolicy | None = None):
        self.api_key = api_key
        self.api_url = api_url.rstrip("/")
        self.hostname = hostname
        self.tags = tags or []
        self.interval_s = interval_s
        self.flush_max_per_body = flush_max_per_body
        self.timeout_s = timeout_s
        self._egress = egress or Egress("datadog", policy=egress_policy)
        self._tag_memo: dict = {}

    def name(self) -> str:
        return "datadog"

    def _series(self, m: InterMetric) -> dict:
        if m.type == MetricType.COUNTER:
            mtype, value = "rate", m.value / max(self.interval_s, 1)
        else:
            mtype, value = "gauge", m.value
        host, device, tags = self._split_tags(m.tags)
        s = {
            "metric": m.name,
            "points": [[m.timestamp, value]],
            "type": mtype,
            "host": host or m.hostname or self.hostname,
            "tags": tags,
            "interval": self.interval_s,
        }
        if device:
            s["device_name"] = device
        return s

    def _post(self, path: str, body: dict, deadline=None):
        data = zlib.compress(json.dumps(body).encode())
        req = urllib.request.Request(
            f"{self.api_url}{path}?api_key={self.api_key}",
            data=data,
            headers={"Content-Type": "application/json",
                     "Content-Encoding": "deflate"},
            method="POST")
        self._egress.post(req, timeout_s=self.timeout_s,
                          deadline=deadline)

    def flush(self, metrics):
        series, checks = [], []
        for m in metrics:
            if m.type == MetricType.STATUS:
                checks.append(m)
            else:
                series.append(self._series(m))
        # chunked bodies + checks share ONE flush deadline budget
        deadline = self._egress.deadline()
        self._post_series(series, deadline)
        self._post_status(checks, deadline)

    def _post_series(self, series, deadline=None):
        for i in range(0, len(series), self.flush_max_per_body):
            self._post("/api/v1/series",
                       {"series": series[i:i + self.flush_max_per_body]},
                       deadline=deadline)

    def _split_tags(self, tg: list) -> tuple:
        """(host_override, device, merged_tags) for one key's shared tag
        list. Memoized by value (tuple of the tags) so ephemeral lists —
        per-flush self-metrics, extras — share entries with the interned
        frame lists instead of growing the memo per list instance; the
        bound keeps worst-case retention to ~64k entries."""
        key = tuple(tg)
        out = self._tag_memo.get(key)
        if out is not None:
            return out
        host, device, tags = "", "", list(self.tags)
        for t in tg:
            if t.startswith("host:"):
                host = t[5:]
            elif t.startswith("device:"):
                device = t[7:]
            else:
                tags.append(t)
        if len(self._tag_memo) >= 65536:
            self._tag_memo.clear()
        out = (host, device, tags)
        self._tag_memo[key] = out
        return out

    def flush_frames(self, frames):
        """Frame-native flush: build the series bodies straight from the
        columnar blocks (same wire output as flush(), without
        materializing InterMetric objects)."""
        iv = self.interval_s
        div = max(iv, 1)  # divide (not multiply-by-reciprocal) so the
        # rate values match _series() bit-for-bit
        series = []
        app = series.append
        for fr in frames.frames:
            ts = fr.timestamp
            fr_host = fr.hostname or self.hostname
            for names, tags, values, types in fr.blocks:
                is_rate = [t == MetricType.COUNTER for t in types]
                m = values.shape[1]
                rows = values.tolist()
                for nm, tg, row in zip(names, tags, rows):
                    host, device, dtags = self._split_tags(tg)
                    h = host or fr_host
                    cols = (nm,) if m == 1 and isinstance(nm, str) else nm
                    for j in range(m):
                        s = {
                            "metric": cols[j],
                            "points": [[ts, row[j] / div
                                        if is_rate[j] else row[j]]],
                            "type": "rate" if is_rate[j] else "gauge",
                            "host": h,
                            "tags": dtags,
                            "interval": iv,
                        }
                        if device:
                            s["device_name"] = device
                        app(s)
        name = self.name()
        checks = []
        for x in frames.extra:
            if not x.sinks or name in x.sinks:
                if x.type == MetricType.STATUS:
                    checks.append(x)
                else:
                    app(self._series(x))
        deadline = self._egress.deadline()
        self._post_series(series, deadline)
        self._post_status(checks, deadline)
        return len(series) + len(checks)

    def _post_status(self, status_metrics, deadline=None):
        """Status-typed InterMetrics (the StatusCheck sampler's flush
        shape) become Datadog service checks — the reference's datadog
        sink does the same conversion at flush."""
        for m in status_metrics:
            body = {"check": m.name, "status": int(m.value),
                    "tags": list(m.tags), "message": m.message}
            if m.timestamp:
                body["timestamp"] = m.timestamp
            if m.hostname:
                body["host_name"] = m.hostname
            try:
                self._post("/api/v1/check_run", body, deadline=deadline)
            except Exception as ex:
                log.warning("datadog check post failed: %s", ex)

    def flush_other(self, events, checks):
        for e in events:
            body = {
                "title": e.title, "text": e.text,
                "aggregation_key": e.aggregation_key,
                "priority": e.priority or "normal",
                "source_type_name": e.source_type,
                "alert_type": e.alert_type or "info",
                "tags": e.tags,
            }
            if e.timestamp:
                body["date_happened"] = e.timestamp
            if e.hostname:
                body["host"] = e.hostname
            try:
                self._post("/api/v1/events", body)
            except Exception as ex:  # one bad event must not stop the rest
                log.warning("datadog event post failed: %s", ex)
        for c in checks:
            body = {"check": c.name, "status": c.status,
                    "tags": c.tags, "message": c.message}
            if c.timestamp:
                body["timestamp"] = c.timestamp
            if c.hostname:
                body["host_name"] = c.hostname
            try:
                self._post("/api/v1/check_run", body)
            except Exception as ex:
                log.warning("datadog check post failed: %s", ex)


class DatadogSpanSink(SpanSink):
    """SSF spans → Datadog APM traces (sinks/datadog/datadog.go sym:
    DatadogSpanSink): buffer ingested spans, group by trace id, and PUT
    them to a local Datadog trace agent's /v0.3/traces endpoint as the
    agent's JSON list-of-traces format. Nanosecond SSF timestamps map
    straight onto the agent's start/duration fields."""

    def __init__(self, trace_api_address: str = "http://127.0.0.1:8126",
                 buffer_size: int = 16384, timeout_s: float = 10.0,
                 egress: Egress | None = None,
                 egress_policy: EgressPolicy | None = None):
        self.trace_api_address = trace_api_address.rstrip("/")
        self.buffer_size = buffer_size
        self.timeout_s = timeout_s
        self._egress = egress or Egress("datadog-traces",
                                        policy=egress_policy)
        self._spans: list = []
        self._lock = threading.Lock()
        self.dropped_total = 0
        self.flushed_total = 0
        self.requeued_total = 0

    def name(self) -> str:
        return "datadog"

    def ingest(self, span):
        # spans with no timing/ids are metric carriers, not traces
        if not span.trace_id or not span.id or not span.start_timestamp:
            return
        with self._lock:
            if len(self._spans) >= self.buffer_size:
                self.dropped_total += 1
                return
            self._spans.append(span)

    @staticmethod
    def _convert(span) -> dict:
        dur = max(0, (span.end_timestamp or span.start_timestamp)
                  - span.start_timestamp)
        d = {
            "trace_id": span.trace_id,
            "span_id": span.id,
            "parent_id": span.parent_id,
            "name": span.name,
            "resource": span.tags.get("resource", span.name),
            "service": span.service,
            "start": span.start_timestamp,
            "duration": dur,
            "error": 1 if span.error else 0,
            "meta": dict(span.tags),
        }
        return d

    def flush(self):
        with self._lock:
            spans, self._spans = self._spans, []
        if not spans:
            return
        traces: dict[int, list] = {}
        for s in spans:
            traces.setdefault(s.trace_id, []).append(self._convert(s))
        body = json.dumps(list(traces.values())).encode()
        req = urllib.request.Request(
            f"{self.trace_api_address}/v0.3/traces", data=body,
            headers={"Content-Type": "application/json"}, method="PUT")
        try:
            self._egress.post(req, timeout_s=self.timeout_s)
            self.flushed_total += len(spans)
        except Exception as e:
            if not is_retryable(e):
                # terminal (4xx: the batch itself is refused) —
                # requeueing would re-PUT the same doomed body forever,
                # pinning the ring and starving new spans
                with self._lock:
                    self.dropped_total += len(spans)
                log.warning("datadog trace flush terminally failed "
                            "(%d spans dropped): %s", len(spans), e)
                return
            # transient: requeue the failed spans into the ring up to
            # buffer_size (next flush retries them); only what the ring
            # cannot hold is dropped — ring semantics, OLDEST overflow
            # goes first
            with self._lock:
                room = max(0, self.buffer_size - len(self._spans))
                keep = spans[-room:] if room else []
                self._spans[:0] = keep
                self.requeued_total += len(keep)
                evicted = len(spans) - len(keep)
                self.dropped_total += evicted
            log.warning(
                "datadog trace flush failed (%d spans requeued, %d "
                "evicted): %s", len(keep), evicted, e)
