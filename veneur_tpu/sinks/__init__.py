"""Egress layer: metric/span sinks and plugins.

Parity: sinks/sinks.go (sym: MetricSink — Name/Start/Flush/
FlushOtherSamples; SpanSink — Name/Start/Ingest/Flush) and plugins/
(sym: Plugin). Sinks are independent: one slow or failing sink must never
stall the others, so the server fans flushes out with per-sink timeouts
(veneur runs one goroutine per sink; here a thread per sink).
"""

from __future__ import annotations

import abc
from typing import Iterable

from ..metrics import FrameSet, InterMetric


class MetricSink(abc.ABC):
    """Destination for flushed metrics (sinks.MetricSink)."""

    @abc.abstractmethod
    def name(self) -> str: ...

    def start(self) -> None:
        """One-time setup; raise to disable the sink."""

    @abc.abstractmethod
    def flush(self, metrics: list[InterMetric]) -> None:
        """Deliver one interval's metrics. Called once per flush tick."""

    def flush_frames(self, frames: FrameSet) -> int | None:
        """Frame-aware delivery: the server hands every sink the flush's
        columnar FrameSet. The default materializes InterMetrics (lazily,
        in this sink's thread, shared across legacy sinks) and calls
        flush(); frame-native sinks override this to serialize straight
        from the blocks and never build 600k Python objects.

        Returns the number of metrics actually delivered (after sink
        routing / type drops) so veneur.sink.metrics_flushed_total counts
        what went out, not what was offered; None means "all of them"."""
        routed = filter_for_sink(self.name(), frames.to_list())
        self.flush(routed)
        return len(routed)

    def flush_other(self, events, checks) -> None:
        """Deliver events / service checks (FlushOtherSamples)."""

    def stop(self) -> None:
        """Graceful shutdown."""


class SpanSink(abc.ABC):
    """Destination for ingested SSF spans (sinks.SpanSink)."""

    @abc.abstractmethod
    def name(self) -> str: ...

    def start(self) -> None: ...

    @abc.abstractmethod
    def ingest(self, span) -> None: ...

    def flush(self) -> None: ...

    def stop(self) -> None: ...


class Plugin(abc.ABC):
    """Whole-interval dump plugins (plugins.Plugin: s3, localfile)."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def flush(self, metrics: list[InterMetric], hostname: str) -> None: ...

    def flush_frames(self, frames: FrameSet, hostname: str) -> None:
        """Frame-aware variant; default materializes lazily."""
        self.flush(frames.to_list(), hostname)


def filter_for_sink(sink_name: str, metrics: Iterable[InterMetric]):
    """Honor InterMetric.sinks routing (empty = deliver everywhere)."""
    return [m for m in metrics if not m.sinks or sink_name in m.sinks]
