"""gRPC span sink: stream SSF spans to an arbitrary gRPC span service.

Parity: sinks/grpsink/ (sym: GRPCStreamingSpanSink — the "Falconer"
egress: a long-lived gRPC connection over which every ingested span is
sent as an SSFSpan protobuf). The service contract here is a unary
`/ssfspans.SpanSink/SendSpan(SSFSpan) -> SSFSpan-empty`; the reference
uses a client-streaming RPC, but the wire payload (one SSFSpan message
per span) is the same. `serve_capture()` provides the loopback
test-double the reference's sink tests build with a fake gRPC server.
"""

from __future__ import annotations

import logging
from concurrent import futures

import grpc

from . import SpanSink
from ..resilience import Egress, grpc_channel
from ..ssf.protos import ssf_pb2

log = logging.getLogger("veneur_tpu.sinks.grpsink")

SEND_SPAN = "/ssfspans.SpanSink/SendSpan"


class GrpcSpanSink(SpanSink):
    """Sends happen on a private sender thread behind a bounded queue so
    a slow/hung endpoint stalls only this sink, never the span worker
    (the sink-independence contract of sinks/__init__.py). Each send
    rides the resilience layer: retried on transient gRPC codes, and a
    dead endpoint trips the breaker so the sender drains the queue with
    fast rejections instead of a timeout per span."""

    def __init__(self, address: str, timeout_s: float = 5.0,
                 capacity: int = 8192, egress: Egress | None = None,
                 egress_policy=None):
        import queue
        import threading

        self.address = address
        self.timeout_s = timeout_s
        self._egress = egress or Egress(f"grpc://{address}",
                                        policy=egress_policy)
        self._channel = None
        self._send = None
        self.sent_total = 0
        self.dropped_total = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._queue_mod = queue
        self._stopping = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="grpsink-sender", daemon=True)

    def name(self) -> str:
        return "grpsink"

    def start(self) -> None:
        self._channel = grpc_channel(self.address)
        self._send = self._channel.unary_unary(
            SEND_SPAN,
            request_serializer=ssf_pb2.SSFSpan.SerializeToString,
            response_deserializer=ssf_pb2.SSFSpan.FromString)
        if not self._thread.is_alive():
            self._thread.start()

    def ingest(self, span) -> None:
        if self._send is None:
            self.start()
        try:
            self._q.put_nowait(span)
        except self._queue_mod.Full:
            self.dropped_total += 1

    def _run(self):
        while True:
            try:
                span = self._q.get(timeout=0.25)
            except self._queue_mod.Empty:
                if self._stopping.is_set():
                    return
                continue
            if span is None:
                return
            try:
                self._egress.call(self._send, span,
                                  timeout_s=self.timeout_s)
                self.sent_total += 1
            except Exception as e:
                # never let the sender thread die — a dead thread would
                # silently disable the sink for the process lifetime
                self.dropped_total += 1
                log.debug("grpsink send failed: %s", e)

    def stop(self) -> None:
        self._stopping.set()
        try:
            self._q.put_nowait(None)
        except self._queue_mod.Full:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)
        if self._channel is not None:
            self._channel.close()


def serve_capture(address: str = "127.0.0.1:0"):
    """Loopback span-sink service for tests: returns (server, port,
    captured_list)."""
    captured: list = []

    class Handler(grpc.GenericRpcHandler):
        def service(self, details):
            if details.method != SEND_SPAN:
                return None

            def send_span(request, context):
                captured.append(request)
                return ssf_pb2.SSFSpan()

            return grpc.unary_unary_rpc_method_handler(
                send_span,
                request_deserializer=ssf_pb2.SSFSpan.FromString,
                response_serializer=ssf_pb2.SSFSpan.SerializeToString)

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((Handler(),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port, captured
