"""The simple sinks: blackhole, debug, localfile.

Parity: sinks/blackhole/ (discard, for tests/benchmarks), sinks/debug/
(log flushed values), plugins/localfile/ (append TSV rows — the same TSV
schema the s3 plugin writes).
"""

from __future__ import annotations

import logging
import sys
import threading

from ..metrics import InterMetric
from . import MetricSink, Plugin, SpanSink

log = logging.getLogger("veneur_tpu.sinks")


class BlackholeMetricSink(MetricSink):
    def __init__(self):
        self.flushed_total = 0

    def name(self) -> str:
        return "blackhole"

    def flush(self, metrics):
        self.flushed_total += len(metrics)

    def flush_frames(self, frames):
        # frame-native: count without materializing a single InterMetric
        n = len(frames)
        self.flushed_total += n
        return n


class BlackholeSpanSink(SpanSink):
    def __init__(self):
        self.ingested_total = 0

    def name(self) -> str:
        return "blackhole"

    def ingest(self, span):
        self.ingested_total += 1


class DebugMetricSink(MetricSink):
    """Log every flushed metric (sinks/debug)."""

    def __init__(self, stream=None):
        self.stream = stream or sys.stderr

    def name(self) -> str:
        return "debug"

    def flush(self, metrics):
        for m in metrics:
            print(f"DEBUG METRIC {m.name} {m.value} "
                  f"tags={','.join(m.tags)} type={m.type.name.lower()}",
                  file=self.stream)

    def flush_other(self, events, checks):
        for e in events:
            print(f"DEBUG EVENT {e.title!r}", file=self.stream)
        for c in checks:
            print(f"DEBUG CHECK {c.name} status={c.status}",
                  file=self.stream)


class CaptureMetricSink(MetricSink):
    """Test helper: record everything (the reference's capturing fake
    sink pattern in server_test.go)."""

    def __init__(self):
        self.flushes: list[list[InterMetric]] = []
        self.events = []
        self.checks = []
        self._cv = threading.Condition()

    def name(self) -> str:
        return "capture"

    def flush(self, metrics):
        with self._cv:
            self.flushes.append(list(metrics))
            self._cv.notify_all()

    def flush_other(self, events, checks):
        self.events.extend(events)
        self.checks.extend(checks)

    def wait_for_flush(self, n=1, timeout=10.0) -> bool:
        with self._cv:
            return self._cv.wait_for(lambda: len(self.flushes) >= n,
                                     timeout)

    @property
    def all_metrics(self):
        return [m for fl in self.flushes for m in fl]


def tsv_line(m: InterMetric, hostname: str, interval_s: int) -> str:
    """One TSV row per metric — the localfile/s3 plugin schema
    (plugins/s3/s3.go: name, tags, type, hostname, timestamp, value,
    interval)."""
    return "\t".join([
        m.name, ",".join(m.tags), m.type.name.lower(),
        m.hostname or hostname, str(m.timestamp), repr(m.value),
        str(interval_s)]) + "\n"


def tsv_from_frames(frames, hostname: str, interval_s: int):
    """Yield TSV rows straight from the FrameSet's blocks — byte-for-byte
    what tsv_line produces over the materialized list, minus the 600k
    InterMetric objects."""
    iv = str(interval_s)
    for fr in frames.frames:
        ts = str(fr.timestamp)
        host = fr.hostname or hostname
        for names, tags, values, types in fr.blocks:
            tnames = [t.name.lower() for t in types]
            m = values.shape[1]
            rows = values.tolist()
            if m == 1:
                t0 = tnames[0]
                for nm, tg, row in zip(names, tags, rows):
                    if not isinstance(nm, str):
                        nm = nm[0]
                    yield (f"{nm}\t{','.join(tg)}\t{t0}\t{host}\t{ts}"
                           f"\t{row[0]!r}\t{iv}\n")
            else:
                for nms, tg, row in zip(names, tags, rows):
                    jt = ",".join(tg)
                    for j in range(m):
                        yield (f"{nms[j]}\t{jt}\t{tnames[j]}\t{host}"
                               f"\t{ts}\t{row[j]!r}\t{iv}\n")
    for x in frames.extra:
        yield tsv_line(x, hostname, interval_s)


class LocalFilePlugin(Plugin):
    """Append one interval's metrics as TSV (plugins/localfile)."""

    def __init__(self, path: str, interval_s: int = 10):
        self.path = path
        self.interval_s = interval_s

    def name(self) -> str:
        return "localfile"

    def flush(self, metrics, hostname):
        with open(self.path, "a") as f:
            for m in metrics:
                f.write(tsv_line(m, hostname, self.interval_s))

    def flush_frames(self, frames, hostname):
        with open(self.path, "a") as f:
            f.writelines(tsv_from_frames(frames, hostname,
                                         self.interval_s))
