"""LightStep span sink: SSF spans → a LightStep collector.

Parity: sinks/lightstep/lightstep.go (sym: LightStepSpanSink — wraps the
LightStep tracer, converting each SSFSpan into an OpenTracing span with
trace/span/parent ids and tags, reported to a collector with an access
token). The vendor tracer library isn't available here, so the sink
speaks the collector's JSON report surface directly: buffered spans are
POSTed as one report body per flush with the access token attached —
the same buffer-then-report lifecycle the tracer performs internally.
Tests point `collector_url` at a loopback http.server.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request

from . import SpanSink

log = logging.getLogger("veneur_tpu.sinks.lightstep")


def span_to_record(span) -> dict:
    """One SSFSpan → one LightStep span record (the field mapping the
    reference performs via the OpenTracing API: ot.ParentSpanID /
    lightstep.TraceIDField etc.)."""
    return {
        "span_guid": f"{span.id:x}",
        "trace_guid": f"{span.trace_id:x}",
        "span_name": span.name,
        "oldest_micros": span.start_timestamp // 1000,
        "youngest_micros": span.end_timestamp // 1000,
        "attributes": [
            {"Key": "component", "Value": span.service},
            {"Key": "parent_span_guid", "Value": f"{span.parent_id:x}"},
            {"Key": "error", "Value": str(bool(span.error)).lower()},
        ] + [{"Key": k, "Value": v} for k, v in sorted(span.tags.items())],
    }


class LightStepSpanSink(SpanSink):
    def __init__(self, access_token: str, collector_url: str,
                 hostname: str = "", max_buffer: int = 16384,
                 timeout_s: float = 10.0, egress=None,
                 egress_policy=None):
        from ..resilience import Egress
        # no default collector here: config.lightstep_collector_host is
        # the single source of truth for the endpoint
        self.access_token = access_token
        self.url = collector_url.rstrip("/") + "/api/v0/reports"
        self.hostname = hostname
        self.max_buffer = max_buffer
        self.timeout_s = timeout_s
        self._egress = egress or Egress("lightstep",
                                        policy=egress_policy)
        self._buf: list = []
        self._lock = threading.Lock()
        self.flushed_total = 0
        self.dropped_total = 0

    def name(self) -> str:
        return "lightstep"

    def ingest(self, span):
        with self._lock:
            if len(self._buf) >= self.max_buffer:
                self.dropped_total += 1
                return
            self._buf.append(span)

    def flush(self):
        with self._lock:
            spans, self._buf = self._buf, []
        if not spans:
            return
        body = json.dumps({
            "auth": {"access_token": self.access_token},
            "runtime": {"group_name": "veneur", "guid": self.hostname},
            "span_records": [span_to_record(s) for s in spans],
        }).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            self._egress.post(req, timeout_s=self.timeout_s)
            self.flushed_total += len(spans)
        except Exception as e:
            self.dropped_total += len(spans)
            log.error("lightstep report failed (%d spans dropped): %s",
                      len(spans), e)
