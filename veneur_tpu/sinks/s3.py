"""S3 plugin: archive each interval's full flush as gzipped TSV objects.

Parity: plugins/s3/s3.go (sym: S3Plugin.Flush — encodes the interval's
[]InterMetric as TSV, gzips, and PutObjects under
`<hostname>/<date>/<timestamp>.tsv.gz`).

The AWS SDK is not available in this image, so the uploader is
injectable: anything callable as `put(bucket, key, body_bytes)`.
`start()` builds one from boto3 when importable; without it the plugin
drops (counted) instead of failing the flush fan-out — egress is lossy,
the pipeline is not.
"""

from __future__ import annotations

import gzip
import io
import logging
import time

from . import Plugin
from .basic import tsv_from_frames, tsv_line

log = logging.getLogger("veneur_tpu.sinks.s3")


def _default_uploader(region: str, access_key: str, secret_key: str):
    try:
        import boto3  # type: ignore
    except ImportError:
        return None
    kw = {}
    if region:
        kw["region_name"] = region
    if access_key:
        kw["aws_access_key_id"] = access_key
        kw["aws_secret_access_key"] = secret_key
    client = boto3.client("s3", **kw)

    def put(bucket: str, key: str, body: bytes):
        client.put_object(Bucket=bucket, Key=key, Body=body)

    return put


def object_key(hostname: str, ts: float | None = None) -> str:
    """`<hostname>/<yyyy>/<mm>/<dd>/veneur-<epoch>.tsv.gz` — the
    reference's date-partitioned layout."""
    t = time.time() if ts is None else ts
    tm = time.gmtime(t)
    return (f"{hostname or 'unknown'}/{tm.tm_year:04d}/{tm.tm_mon:02d}/"
            f"{tm.tm_mday:02d}/veneur-{int(t)}.tsv.gz")


class S3Plugin(Plugin):
    def __init__(self, bucket: str, region: str = "",
                 access_key: str = "", secret_key: str = "",
                 interval_s: int = 10, uploader=None, egress=None,
                 egress_policy=None):
        from ..resilience import Egress
        self.bucket = bucket
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.interval_s = interval_s
        self.uploader = uploader
        self._egress = egress or Egress(f"s3://{bucket}",
                                        policy=egress_policy)
        self.uploaded_total = 0
        self.dropped_total = 0
        if self.uploader is None:
            self.uploader = _default_uploader(region, access_key,
                                              secret_key)
            if self.uploader is None:
                log.warning("s3: boto3 unavailable; interval archives "
                            "to bucket %r will be dropped (counted)",
                            bucket)

    def name(self) -> str:
        return "s3"

    def _upload(self, lines, n: int, hostname: str):
        """Gzip `lines` (TSV rows) and PutObject; shared by the legacy
        and frame-native flush paths."""
        if not n:
            return
        if self.uploader is None:
            self.dropped_total += n
            return
        buf = io.BytesIO()
        with gzip.GzipFile(fileobj=buf, mode="wb") as gz:
            for line in lines:
                gz.write(line.encode())
        try:
            self._egress.call(self.uploader, self.bucket,
                              object_key(hostname), buf.getvalue())
            self.uploaded_total += n
        except Exception as e:
            self.dropped_total += n
            log.error("s3 upload failed (%d metrics dropped): %s", n, e)

    def flush(self, metrics, hostname):
        self._upload((tsv_line(m, hostname, self.interval_s)
                      for m in metrics), len(metrics), hostname)

    def flush_frames(self, frames, hostname):
        self._upload(tsv_from_frames(frames, hostname, self.interval_s),
                     len(frames), hostname)
