"""Prometheus sink: expose each interval's flush for scraping.

Parity: sinks/prometheus/ (the egress direction of veneur's Prometheus
integration; the ingest direction is the veneur-prometheus CLI). The
reference repeats statsd to a prometheus exporter; here the sink IS the
exporter: it holds the latest flush and serves it in the text
exposition format (0.0.4) on an embedded HTTP listener, with metric
names sanitized to the Prometheus grammar and tags become labels.

Counters are exposed as `counter` with a cumulative value accumulated
across flushes (Prometheus semantics: counters are cumulative, while
veneur counters are per-interval deltas); everything else is a `gauge`.
"""

from __future__ import annotations

import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..metrics import InterMetric, MetricType
from . import MetricSink

log = logging.getLogger("veneur_tpu.sinks.prometheus")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label(name: str) -> str:
    name = _LABEL_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def render(metrics: list[InterMetric],
           counter_totals: dict | None = None) -> str:
    """Text exposition (0.0.4) for one flush's metrics."""
    by_name: dict[str, list[InterMetric]] = {}
    for m in metrics:
        by_name.setdefault(sanitize_name(m.name), []).append(m)
    lines: list[str] = []
    for name in sorted(by_name):
        group = by_name[name]
        is_counter = group[0].type == MetricType.COUNTER
        lines.append(f"# TYPE {name} "
                     f"{'counter' if is_counter else 'gauge'}")
        for m in group:
            labels = []
            for t in m.tags:
                k, _, v = t.partition(":")
                labels.append(f'{sanitize_label(k)}="{_escape_value(v)}"')
            if m.hostname:
                labels.append(f'hostname="{_escape_value(m.hostname)}"')
            lstr = "{" + ",".join(labels) + "}" if labels else ""
            value = m.value
            if is_counter and counter_totals is not None:
                key = (name, lstr)
                value = counter_totals.get(key, 0.0) + m.value
                counter_totals[key] = value
            lines.append(f"{name}{lstr} {value:g}")
    return "\n".join(lines) + ("\n" if lines else "")


class _TtlCounterTotals(dict):
    """Cumulative counter state with idle eviction: a series not flushed
    for `idle_flushes` intervals is dropped, so unbounded metric-name
    churn cannot grow the dict forever. `render` touches every live key
    via `__setitem__`, which doubles as the liveness signal; an evicted
    series that reappears restarts from its next delta (a counter reset,
    which Prometheus clients already handle via staleness/rate())."""

    def __init__(self, idle_flushes: int = 60):
        super().__init__()
        self.idle_flushes = idle_flushes
        self._last_seen: dict = {}
        self._flush_idx = 0

    def __setitem__(self, key, value):
        self._last_seen[key] = self._flush_idx
        super().__setitem__(key, value)

    def advance(self):
        self._flush_idx += 1
        horizon = self._flush_idx - self.idle_flushes
        if horizon <= 0:
            return
        dead = [k for k, t in self._last_seen.items() if t < horizon]
        for k in dead:
            del self._last_seen[k]
            self.pop(k, None)


def render_registries(registries, exclude_names=frozenset()) -> str:
    """One scrape's view of the unified telemetry registries
    (observe/registry.py): cumulative counters (native Prometheus
    counter semantics — no delta reconstruction needed), live gauges,
    and levels. Families already present in `exclude_names` (sanitized)
    are skipped so a flush body that carries the same self-metrics
    can't produce duplicate TYPE families in one exposition."""
    import time as _time

    ts = int(_time.time())
    metrics = []
    for reg in registries:
        metrics.extend(m for m in reg.snapshot(ts)
                       if sanitize_name(m.name) not in exclude_names)
    # registry values are already cumulative: render without the
    # flush-path counter_totals accumulator
    return render(metrics, None)


class PrometheusMetricSink(MetricSink):
    """The exposition server. With `registries` (the server wires its
    telemetry spine + the process default), /metrics is ONE scrape
    surface for every veneur.* self-metric — including the counters
    that would otherwise only be visible inside a flush body (or not at
    all when stats_address diverts self-metrics onto the wire)."""

    def __init__(self, listen_address: str = "127.0.0.1:9125",
                 counter_idle_flushes: int = 60, registries=()):
        # parsed in start() so a malformed address disables this sink
        # (the server catches start() errors per-sink) instead of
        # aborting server construction
        self.listen_address = listen_address
        self.host = ""
        self.port = -1
        self._body = b""
        self._body_names: frozenset = frozenset()
        self._lock = threading.Lock()
        self._counter_totals = _TtlCounterTotals(counter_idle_flushes)
        self._registries = tuple(registries)
        self._server: ThreadingHTTPServer | None = None

    def name(self) -> str:
        return "prometheus"

    def start(self):
        host, _, port = self.listen_address.rpartition(":")
        self.host = host.strip("[]") or "0.0.0.0"
        self.port = int(port)
        sink = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                with sink._lock:
                    body = sink._body
                    names = sink._body_names
                if sink._registries:
                    # registry state renders at scrape time (fresh),
                    # minus families the flush body already carries
                    body = body + render_registries(
                        sink._registries, names).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         name="prometheus-sink", daemon=True).start()

    def flush(self, metrics):
        metrics = [m for m in metrics
                   if m.type != MetricType.STATUS]  # datadog-shaped
        with self._lock:
            self._body = render(metrics, self._counter_totals).encode()
            self._body_names = frozenset(
                sanitize_name(m.name) for m in metrics)
            self._counter_totals.advance()

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
