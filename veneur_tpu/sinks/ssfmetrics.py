"""ssfmetrics: the internal span→metrics bridge sink.

Parity: sinks/ssfmetrics/metrics.go (sym: metricExtractionSink) plus the
sample conversion of samplers/parser.go (sym: samplers.ParseMetricSSF) and
the indicator-span timer derivation (config key
`indicator_span_timer_name`). Every SSFSample embedded in an ingested
span is converted to a UDPMetric and re-submitted to the metric workers,
so applications that only emit spans still get their metrics aggregated;
indicator spans additionally produce a duration timer.
"""

from __future__ import annotations

from . import SpanSink
from ..ingest.parser import (GLOBAL_ONLY, LOCAL_ONLY, MIXED_SCOPE,
                             MetricKey, ServiceCheck, UDPMetric)
from ..ssf import TIME_UNITS
from ..ssf.protos import ssf_pb2
from ..utils.hashing import metric_digest

_SSF_TYPE = {
    ssf_pb2.SSFSample.COUNTER: "counter",
    ssf_pb2.SSFSample.GAUGE: "gauge",
    ssf_pb2.SSFSample.HISTOGRAM: "histogram",
    ssf_pb2.SSFSample.SET: "set",
    ssf_pb2.SSFSample.STATUS: "status",
}
_SSF_SCOPE = {
    ssf_pb2.SSFSample.DEFAULT: MIXED_SCOPE,
    ssf_pb2.SSFSample.LOCAL: LOCAL_ONLY,
    ssf_pb2.SSFSample.GLOBAL: GLOBAL_ONLY,
}

# derived from the client-side table so the unit set lives in one place
_TIME_SCALE_NS = {u: s * 1e9 for u, s in TIME_UNITS.items()}


def sample_to_check(s: ssf_pb2.SSFSample) -> ServiceCheck | None:
    """STATUS samples → service checks (the reference converts these in
    samplers.ParseMetricSSF rather than dropping them)."""
    if s.metric != ssf_pb2.SSFSample.STATUS or not s.name:
        return None
    tags = sorted(f"{k}:{v}" if v else k for k, v in s.tags.items())
    return ServiceCheck(
        name=s.name, status=int(s.status),
        timestamp=int(s.timestamp // 1_000_000_000) or None,
        message=s.message, tags=tags)


def sample_to_metric(s: ssf_pb2.SSFSample,
                     host_tag: str = "") -> UDPMetric | None:
    """samplers.ParseMetricSSF: one embedded sample → one UDPMetric."""
    mtype = _SSF_TYPE.get(s.metric)
    if mtype is None or mtype == "status" or not s.name:
        return None
    tags = sorted(f"{k}:{v}" if v else k for k, v in s.tags.items())
    joined = ",".join(tags)
    value: float | str = s.message if mtype == "set" else float(s.value)
    # timers arrive as HISTOGRAM samples carrying a time unit; normalise
    # to ms so the same duration aggregates identically whatever unit
    # the client chose
    if mtype == "histogram" and s.unit in _TIME_SCALE_NS:
        mtype = "timer"
        value = float(value) * _TIME_SCALE_NS[s.unit] / 1e6
    key = MetricKey(name=s.name, type=mtype, joined_tags=joined)
    return UDPMetric(
        key=key,
        digest=metric_digest(s.name, mtype, joined),
        value=value,
        sample_rate=s.sample_rate or 1.0,
        scope=_SSF_SCOPE.get(s.scope, MIXED_SCOPE),
        tags=tags,
    )


def indicator_timer(span: ssf_pb2.SSFSpan,
                    timer_name: str) -> UDPMetric | None:
    """Indicator spans → a duration timer named `timer_name`, tagged
    with the span's service and error status (metricExtractionSink's
    indicator-span handling)."""
    if not (timer_name and span.indicator and span.start_timestamp
            and span.end_timestamp):
        return None
    dur_ns = max(0, span.end_timestamp - span.start_timestamp)
    tags = sorted([f"service:{span.service}",
                   f"error:{'true' if span.error else 'false'}"])
    joined = ",".join(tags)
    key = MetricKey(name=timer_name, type="timer", joined_tags=joined)
    return UDPMetric(
        key=key,
        digest=metric_digest(timer_name, "timer", joined),
        value=dur_ns / 1e6,   # report in ms, like DogStatsD timers
        sample_rate=1.0,
        scope=MIXED_SCOPE,
        tags=tags,
    )


class SSFMetricsSink(SpanSink):
    """SpanSink that feeds embedded samples back into the metric
    pipeline via `submit(UDPMetric)` (the server's worker router)."""

    def __init__(self, submit, indicator_span_timer_name: str = ""):
        self._submit = submit
        self._timer_name = indicator_span_timer_name
        self.samples_extracted = 0

    def name(self) -> str:
        return "ssfmetrics"

    def ingest(self, span: ssf_pb2.SSFSpan) -> None:
        for s in span.metrics:
            item = sample_to_metric(s)
            if item is None:
                item = sample_to_check(s)
            if item is not None:
                self._submit(item)
                self.samples_extracted += 1
        t = indicator_timer(span, self._timer_name)
        if t is not None:
            self._submit(t)
            self.samples_extracted += 1
