"""SignalFx metric sink.

Parity: sinks/signalfx/signalfx.go (sym: SignalFxSink.Flush — datapoints
POSTed to /v2/datapoint; per-key API-token routing via `vary_key_by` tag).
JSON body instead of the sfx protobuf (the ingest API accepts both); same
datapoint model: gauge/counter with dimensions.
"""

from __future__ import annotations

import json
import logging
import urllib.request

from ..metrics import MetricType
from ..resilience import Egress, EgressPolicy
from . import MetricSink

log = logging.getLogger("veneur_tpu.sinks.signalfx")


class SignalFxMetricSink(MetricSink):
    def __init__(self, api_key: str,
                 endpoint: str = "https://ingest.signalfx.com",
                 hostname: str = "", tags: list[str] | None = None,
                 vary_key_by: str = "", per_tag_keys: dict | None = None,
                 timeout_s: float = 10.0, egress: Egress | None = None,
                 egress_policy: EgressPolicy | None = None):
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.tags = tags or []
        self.vary_key_by = vary_key_by
        self.per_tag_keys = per_tag_keys or {}
        self.timeout_s = timeout_s
        self._egress = egress or Egress("signalfx",
                                        policy=egress_policy)

    def name(self) -> str:
        return "signalfx"

    def _dims(self, m):
        dims = {"host": m.hostname or self.hostname}
        for t in self.tags + m.tags:
            k, _, v = t.partition(":")
            dims[k] = v
        return dims

    def _token_for(self, m) -> str:
        if self.vary_key_by:
            prefix = self.vary_key_by + ":"
            for t in m.tags:
                if t.startswith(prefix):
                    return self.per_tag_keys.get(t[len(prefix):],
                                                 self.api_key)
        return self.api_key

    def flush(self, metrics):
        by_token: dict[str, dict] = {}
        for m in metrics:
            if m.type == MetricType.STATUS:
                continue  # service checks are Datadog-shaped; skip
            dp = {"metric": m.name, "timestamp": m.timestamp * 1000,
                  "value": m.value, "dimensions": self._dims(m)}
            kind = ("counter" if m.type == MetricType.COUNTER else "gauge")
            by_token.setdefault(self._token_for(m), {}).setdefault(
                kind, []).append(dp)
        deadline = self._egress.deadline()   # one budget, all tokens
        for token, body in by_token.items():
            req = urllib.request.Request(
                f"{self.endpoint}/v2/datapoint",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         "X-SF-Token": token},
                method="POST")
            self._egress.post(req, timeout_s=self.timeout_s,
                              deadline=deadline)
